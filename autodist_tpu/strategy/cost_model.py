"""Analytical cost model for strategy selection (chief-side planning).

The reference ships no selector: its performance page *claims* the best
strategy differs per model (``/root/reference/docs/usage/performance.md:14``)
but users pick builders by hand, and the only machine-readable resource hint
is per-node ``network_bandwidth`` (``resource_spec.py:209-215``). This module
closes that loop for the TPU build: given a built :class:`Strategy`, a
:class:`ModelItem` and a :class:`ResourceSpec`, it estimates

- **synchronization time** per step — gradient bytes over ICI / DCN
  bandwidths, with ring / hierarchical all-reduce cost formulas and
  PS-destination NIC serialization;
- **weight-update time** per step — optimizer HBM traffic (params + grads +
  slots, divided by each variable's residency shard count);
- **per-chip memory** — params + optimizer slots + a transient gradient
  buffer, checked against the chip generation's HBM capacity.

Compute (forward/backward) time is deliberately *excluded*: under pure data
parallelism every candidate strategy runs identical per-chip FLOPs, so it
cannot change the ranking. Parameter sharding is charged by its rendering:
on the data axis (pure-DP meshes) it is ZeRO — parameter all-gathers in
forward and backward plus a gradient reduce-scatter, 1.5× the plain
all-reduce wire, traded for 1/n residency; on a non-trivial model axis it
is tensor parallelism — per-shard gradients reduced over the data group
plus an activation all-gather over the model group per use
(``batch_size × shape[-1] × 2`` bytes when the ModelItem captured a batch,
an explicit ``act_bytes`` calibration when given, else
:data:`DEFAULT_ACT_BYTES`). All estimates mirror the lowering
semantics in ``kernel/lowering.py`` (which mesh axis shards a variable, when
divisibility forces replication, ZeRO-1 vs ZeRO-3 residency for PS vars).

Units are bytes and seconds throughout; bandwidths come from the
ResourceSpec (Gbps on the wire, GB/s for HBM).
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from autodist_tpu.model_item import ModelItem, VarItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.utils import logging
from autodist_tpu.strategy.ir import (
    AllReduceSynchronizer,
    NodeConfig,
    PSSynchronizer,
    Strategy,
)

# Dispatch latency per collective (seconds). ICI collectives are
# compiler-scheduled; DCN ones cross host NICs.
ICI_LATENCY_S = 5e-6
DCN_LATENCY_S = 100e-6

# Analytic prior for bucketed backward-overlap collectives
# (GraphConfig.bucket_bytes > 0): the fraction of an *overlappable* bucket
# collective's wire time still expected to show up on the critical path
# (scheduler imperfections, VMEM pressure, ICI contention with the matmuls
# it hides under). StrategyCost.overlap_s carries the overlappable seconds
# raw; total_s charges this fraction of them. The per-topology calibration
# (plan/calibrate.py "overlap_s" component) replaces the prior with a
# measured coefficient — near 0 when XLA's latency-hiding scheduler truly
# hides the wire, near 1 when it doesn't.
OVERLAP_EXPOSED_FRACTION = 0.25

# Predictions closer than this are a tie, not a ranking: the analytical
# model's per-family deltas (collective-count latency, chunking constants)
# sit well below both its own fidelity and measured run-to-run variance
# (~4% on the bench chip, xla_flag_ab base_again control). Within a tie the
# slate's preference order decides — it is ordered simplest-mechanism-first,
# and unmodeled overhead (resharding copies, PS residency juggling) only
# grows with mechanism. TPU-calibrated: the r5 device sweep measured
# TensorParallel 14% slower than AllReduce on a single chip while the model
# priced it 0.6% cheaper (docs/measured/resnet.json). On a single chip ALL
# inter-strategy deltas are unmodeled overhead, hence the wide band; on
# real meshes the collective terms are the model's actual claim and only
# sub-percent deltas are noise.
NEAR_TIE_REL = 0.05          # single-chip meshes
NEAR_TIE_REL_MULTI = 0.01    # multi-chip meshes

# Canonical preference order on prediction ties: candidate_slate() order
# (simplest mechanism first), shared by CostModel.rank and
# preferred_prediction so the two surfaces cannot drift. Names absent from
# this tuple rank last, alphabetically.
SLATE_PREFERENCE = (
    "AllReduce", "Zero1", "PartitionedAR", "TensorParallel",
    "PSLoadBalancing", "PS(zero3)", "PS(zero1)", "Parallax",
    "RandomAxisPartitionAR", "PartitionedPS", "UnevenPartitionedPS",
    "AllReduce+bf16", "AllReduce+topk",
)


def _tie_winner(times: Dict[str, float], order: Sequence[str],
                rel: float,
                memory: Optional[Dict[str, float]] = None) -> str:
    """Cheapest entry, except entries within ``rel`` of it form a tie.

    The tie breaks DETERMINISTICALLY, in a way no caller can perturb:
    position in ``order`` first (the canonical mechanism preference), then
    — for names ``order`` does not distinguish, e.g. planner-generated
    candidates — lower per-chip ``memory``, then stable name order. Input
    ordering of ``times`` never matters, so a near-tie can't flap between
    runs (tests/test_cost_model.py pins this)."""
    t0 = min(times.values())
    tied = [n for n, t in times.items() if t <= t0 * (1.0 + rel)]
    rank_of = {n: i for i, n in enumerate(order)}
    mem = memory or {}
    return min(tied, key=lambda n: (rank_of.get(n, len(order)),
                                    mem.get(n, float("inf")), n))

# Activation bytes synchronized per tensor-parallel (partitioned) variable per
# step (forward + backward each pay one collective). Fallback when the
# ModelItem carries no captured batch size; with one, the estimate becomes
# batch_size × var.shape[-1] × 2 (bf16 activations).
DEFAULT_ACT_BYTES = 1 << 20
ACT_BYTES_PER_ELEMENT = 2  # bf16 activations

# Fraction of an embedding table's rows a step touches (sparse PS wire bytes).
DEFAULT_SPARSE_TOUCH = 0.05

# Fraction of HBM usable for state; the rest is reserved for activations,
# XLA scratch and infeed buffers.
HBM_USABLE_FRACTION = 0.75

def _shard_weights(var: VarItem, node, n_dests: int) -> List[float]:
    """Fraction of ``var``'s wire each shard destination carries.

    Mirrors the floor/ceil row split the partitioner applies along the
    active axis: dim rows over k shards gives ``dim % k`` shards one extra
    row. Falls back to an even split when the axis is unknown (e.g. a
    hand-built table on an unpartitioned node).
    """
    axis = node.active_partition_axis
    if axis is None or axis >= len(var.shape) or n_dests <= 0:
        return [1.0 / max(n_dests, 1)] * max(n_dests, 1)
    dim = int(var.shape[axis])
    base, rem = divmod(dim, n_dests)
    rows = [base + 1 if i < rem else base for i in range(n_dests)]
    total = float(sum(rows)) or 1.0
    return [r / total for r in rows]


def compressor_wire_factor(name: Optional[str], shape, nshards: int = 1,
                           traced_shape=None) -> float:
    """Wire-size multiplier for a gradient of ``shape`` under a compressor
    synced over ``nshards`` data shards.

    Delegates to ``Compressor.wire_factor`` (kernel/compressor.py) so the
    priced payload is computed from the same rank/shape arithmetic as the
    collectives the compressor actually emits — e.g. PowerSGD's
    ``(m+k)·r / (m·k)`` instead of a flat guess (VERDICT r2 #9);
    ``tests/test_compressor.py`` pins the factor to real HLO payloads.
    ``nshards`` matters only for gather-shaped compressors (TopK), whose
    payload grows with the group size.
    """
    from autodist_tpu.kernel.compressor import canonical_compressor_name

    if not name or canonical_compressor_name(name) == "NoneCompressor":
        return 1.0
    from autodist_tpu.kernel.compressor import get_compressor

    try:
        comp = get_compressor(name)
    except ValueError:
        # A hand-built/deserialized IR may name a compressor this build
        # doesn't know; rank it conservatively as dense rather than
        # crashing the whole tune()/explain() candidate pass. Warn once
        # per name — tune sweeps call this per var x candidate.
        if name not in _warned_compressors:
            _warned_compressors.add(name)
            logging.warning("unknown compressor %r: pricing wire as dense", name)
        return 1.0
    try:
        return float(comp.wire_factor(
            tuple(shape), max(nshards, 1),
            traced_shape=tuple(traced_shape) if traced_shape else None))
    except TypeError:
        # Third-party Compressor subclasses predating the traced_shape
        # parameter.
        return float(comp.wire_factor(tuple(shape), max(nshards, 1)))


_warned_compressors: set = set()

# Optimizer-slot count per parameter byte (optax state residency). Unknown
# optimizers — including "custom" (a raw optax transform whose state shape we
# cannot see) — assume the adam-class worst case of 2 so the HBM feasibility
# check stays conservative.
OPTIMIZER_SLOT_FACTOR = {
    "sgd": 0.0,
    "momentum": 1.0,
    "adam": 2.0,
    "adamw": 2.0,
    "adagrad": 1.0,
    "rmsprop": 1.0,
    "lamb": 2.0,
    "lion": 1.0,
    "adafactor": 1.0,  # row/col factors are near-free; count conservatively
}


def preferred_prediction(predicted_s: Dict[str, float],
                         rel: float = NEAR_TIE_REL) -> str:
    """Auto's selection rule applied to a ``name → predicted seconds`` table.

    The cheapest prediction wins unless other candidates sit within ``rel``
    of it, in which case the earliest :data:`SLATE_PREFERENCE` name among
    the tied wins (unknown names: stable name order — this helper sees no
    memory column; :meth:`CostModel.rank` additionally prefers lower
    per-chip memory for them). The default ``rel`` is the single-chip
    band, matching the calibrate sweep artifacts this helper exists to
    interpret.
    """
    return _tie_winner(predicted_s, SLATE_PREFERENCE, rel)


def candidate_slate(
    chunk_size: int = 128, include_sparse: bool = True, full: bool = False
) -> List[Tuple[str, object]]:
    """The shared candidate list behind Auto, ``AutoDist.tune`` and the
    explain CLI — one definition so the three surfaces can never recommend
    from different slates. ``include_sparse`` adds Parallax (Auto handles
    sparse structurally and omits it); ``full=True`` appends the remaining
    builders (random-axis / PS-partitioning variants) for exhaustive
    explain tables."""
    from autodist_tpu.strategy.all_reduce_strategy import AllReduce
    from autodist_tpu.strategy.parallax_strategy import Parallax
    from autodist_tpu.strategy.partitioned_all_reduce_strategy import PartitionedAR
    from autodist_tpu.strategy.partitioned_ps_strategy import PartitionedPS
    from autodist_tpu.strategy.ps_lb_strategy import PSLoadBalancing
    from autodist_tpu.strategy.ps_strategy import PS
    from autodist_tpu.strategy.random_axis_partition_all_reduce_strategy import (
        RandomAxisPartitionAR,
    )
    from autodist_tpu.strategy.tensor_parallel_strategy import TensorParallel
    from autodist_tpu.strategy.uneven_partition_ps_strategy import UnevenPartitionedPS
    from autodist_tpu.strategy.zero1_strategy import Zero1

    slate: List[Tuple[str, object]] = [
        ("AllReduce", AllReduce(chunk_size=chunk_size)),
        # Weight-update sharding (ZeRO-1, Xu et al. arXiv 2004.13336):
        # identical wire bytes to the ring all-reduce (rs + ag IS the
        # ring), optimizer slots + update time ÷ data-axis size; wins on
        # big dense models, ties (and then loses the tie to AllReduce's
        # simpler mechanism) on tiny ones. docs/zero.md.
        ("Zero1", Zero1(chunk_size=chunk_size)),
        ("PartitionedAR", PartitionedAR(chunk_size=chunk_size)),
        # Megatron axis pairing: the winner on model-axis meshes for
        # transformer-shaped models; degrades to ZeRO-style data-axis
        # sharding on pure-DP meshes, where the ranking judges it like the
        # PS variants.
        ("TensorParallel", TensorParallel()),
        ("PSLoadBalancing", PSLoadBalancing()),
        ("PS(zero3)", PS(local_proxy_variable=False)),
        ("PS(zero1)", PS(local_proxy_variable=True)),
    ]
    if include_sparse:
        slate.append(("Parallax", Parallax(chunk_size=chunk_size)))
    if full:
        slate.extend([
            ("RandomAxisPartitionAR", RandomAxisPartitionAR(chunk_size=chunk_size)),
            ("PartitionedPS", PartitionedPS()),
            ("UnevenPartitionedPS", UnevenPartitionedPS()),
            # Compressed wires appear only in the exhaustive explain table:
            # they change numerics (lossy), so Auto/tune must never pick
            # one silently — the user opts in by naming the compressor.
            ("AllReduce+bf16", AllReduce(chunk_size=chunk_size,
                                         compressor="bf16")),
            ("AllReduce+topk", AllReduce(chunk_size=chunk_size,
                                         compressor="topk")),
        ])
    return slate


@dataclass
class Calibration:
    """Measured correction on top of the analytical model.

    The closed-form estimates deliberately exclude compute time and assume
    wire/HBM run at peak; a short measured sweep (``AutoDist.tune``) fits

        measured_step_s ≈ base_s + scale × predicted_total_s

    where ``base_s`` absorbs the strategy-invariant compute floor (every
    candidate runs the same per-chip FLOPs) and ``scale`` the achieved
    fraction of peak. Ranking is unchanged (the map is monotonic for
    ``scale > 0``); what calibration buys is *absolute* step-time
    prediction, shown by ``explain`` next to the analytical column
    (VERDICT r1 next #10).
    """

    base_s: float = 0.0
    scale: float = 1.0
    device: str = ""        # accelerator kind measured on
    n_points: int = 0       # candidates the fit saw

    @classmethod
    def fit(
        cls, predicted: Sequence[float], measured: Sequence[float],
        device: str = "",
    ) -> "Calibration":
        """Least-squares fit over (predicted, measured) candidate pairs.

        One point pins ``base_s`` only; degenerate spreads (all candidates
        predicted equal) keep ``scale = 1``. A non-positive fitted scale
        (measurement noise dominating) also falls back to ``scale = 1`` so
        calibrated predictions never invert the analytical ranking.
        """
        pred = np.asarray(predicted, np.float64)
        meas = np.asarray(measured, np.float64)
        ok = np.isfinite(pred) & np.isfinite(meas)
        pred, meas = pred[ok], meas[ok]
        if pred.size == 0:
            return cls(device=device)
        if pred.size == 1 or float(np.ptp(pred)) < 1e-12:
            return cls(
                base_s=float(np.mean(meas - pred)), scale=1.0,
                device=device, n_points=int(pred.size),
            )
        scale, base = np.polyfit(pred, meas, 1)
        if scale <= 0:
            scale, base = 1.0, float(np.mean(meas - pred))
        return cls(
            base_s=float(base), scale=float(scale),
            device=device, n_points=int(pred.size),
        )

    def predict_s(self, cost: "StrategyCost") -> float:
        return self.base_s + self.scale * cost.total_s

    # ------------------------------------------------------------ persistence
    def save(self, path: Optional[str] = None) -> str:
        from autodist_tpu import const

        if path is None:
            path = os.path.join(const.DEFAULT_WORKING_DIR, "calibration.json")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # Atomic replace: a concurrent reader (or a second writer) never
        # observes a truncated file.
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(
                {"base_s": self.base_s, "scale": self.scale,
                 "device": self.device, "n_points": self.n_points},
                f, indent=2, sort_keys=True,
            )
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, path: Optional[str] = None) -> Optional["Calibration"]:
        from autodist_tpu import const

        if path is None:
            path = os.path.join(const.DEFAULT_WORKING_DIR, "calibration.json")
        if not os.path.exists(path):
            return None
        try:
            with open(path, "r", encoding="utf-8") as f:
                d = json.load(f)
        except (json.JSONDecodeError, OSError):
            # A torn file from a killed writer degrades to "no calibration"
            # rather than crashing explain.
            return None
        return cls(
            base_s=float(d.get("base_s", 0.0)),
            scale=float(d.get("scale", 1.0)),
            device=str(d.get("device", "")),
            n_points=int(d.get("n_points", 0)),
        )


@dataclass
class StrategyCost:
    """Estimated per-step cost of one strategy on one cluster."""

    comm_s: float          # gradient/param synchronization (wire) time
    update_s: float        # optimizer HBM traffic time
    latency_s: float       # per-collective dispatch latency
    act_sync_s: float      # tensor-parallel activation synchronization
    per_chip_bytes: float  # resident state: params + slots + grad buffer
    hbm_bytes: float       # usable per-chip capacity (already derated)
    n_collectives: int
    # Param re-gather wire of weight-update-sharded (zero1) vars — the
    # all-gather leg of rs → sharded update → ag. A separate component (not
    # folded into comm_s) so the planner's per-topology calibration can fit
    # its achieved bandwidth independently (plan/calibrate.py COMPONENTS).
    gather_s: float = 0.0
    # Per-chip optimizer-slot residency (a subset of per_chip_bytes): the
    # number zero1 divides by ~N, surfaced as explain's opt/chip column.
    opt_bytes: float = 0.0
    # Wire seconds moved OUT of comm_s because bucketed backward-overlap
    # emission (GraphConfig.bucket_bytes) lets the latency-hiding scheduler
    # run them under backward compute: every bucket's grad collective
    # except the last-closing one. total_s charges only
    # OVERLAP_EXPOSED_FRACTION of it (analytic prior); calibration fits
    # the real coefficient per topology.
    overlap_s: float = 0.0

    @property
    def total_s(self) -> float:
        return (self.comm_s + self.update_s + self.latency_s
                + self.act_sync_s + self.gather_s
                + OVERLAP_EXPOSED_FRACTION * self.overlap_s)

    @property
    def feasible(self) -> bool:
        return self.per_chip_bytes <= self.hbm_bytes

    def describe(self) -> str:
        return (
            f"total {self.total_s * 1e3:.3f} ms "
            f"(comm {self.comm_s * 1e3:.3f}, update {self.update_s * 1e3:.3f}, "
            f"lat {self.latency_s * 1e3:.3f}, act {self.act_sync_s * 1e3:.3f}, "
            f"gather {self.gather_s * 1e3:.3f}, "
            f"overlap {self.overlap_s * 1e3:.3f}) "
            f"mem {self.per_chip_bytes / 1e9:.2f}/{self.hbm_bytes / 1e9:.2f} GB "
            f"(opt {self.opt_bytes / 1e9:.2f}) "
            f"{'ok' if self.feasible else 'OVER'}"
        )


class CostModel:
    """Estimate per-step time and memory for candidate strategies.

    Mirrors ``kernel/lowering.py`` residency rules: a partition request
    shards over the mesh's model axis when the spec's ``mesh:`` override
    makes it non-trivial, else ZeRO-style over the data axis (Auto's meshes
    are pure-DP); gradients reduce over the data axis; PS dense vars get
    ZeRO-1 (proxy) or ZeRO-3 (no-proxy) residency; PS sparse vars are
    row-sharded (pad-and-mask when rows don't divide).
    """

    def __init__(
        self,
        model_item: ModelItem,
        resource_spec: ResourceSpec,
        *,
        act_bytes: Optional[float] = None,
        sparse_touch_fraction: float = DEFAULT_SPARSE_TOUCH,
    ):
        self.model_item = model_item
        self.spec = resource_spec
        # None = derive from the captured batch (or DEFAULT_ACT_BYTES); an
        # explicit calibration always wins.
        self.act_bytes = float(act_bytes) if act_bytes is not None else None
        self.sparse_touch = float(sparse_touch_fraction)

        self.n = max(resource_spec.num_chips, 1)
        self.m = max(resource_spec.num_nodes, 1)
        self.chips_per_node = max(self.n // self.m, 1)
        # Mesh-aware group sizes (identical to self.n on pure-DP meshes,
        # which is what Auto builds): gradients reduce over the DATA axis;
        # variable partitioning rides the MODEL axis when the spec's mesh
        # override makes it non-trivial (lowering `_shard_axis_name`),
        # else it is ZeRO-style over the data axis.
        mesh_shape = resource_spec.mesh_shape(("data", "model"))
        self.n_data = max(int(mesh_shape.get("data", 1)), 1)
        self.n_model = max(int(mesh_shape.get("model", 1)), 1)
        self.n_expert = max(int(mesh_shape.get("expert", 1)), 1)
        self.n_shard = self.n_model if self.n_model > 1 else self.n_data
        self.bw_ici = resource_spec.ici_bandwidth * 1e9 / 8.0
        self.bw_dcn = resource_spec.network_bandwidth * 1e9 / 8.0
        self.hbm_bw = resource_spec.tpu.hbm_bandwidth_bytes
        self.hbm_cap = resource_spec.tpu.hbm_bytes * HBM_USABLE_FRACTION
        # One chip emits no collectives at all (XLA elides them), so the
        # per-collective dispatch term must not break prediction ties there.
        self.latency = (0.0 if self.n <= 1
                        else ICI_LATENCY_S if self.m == 1
                        else DCN_LATENCY_S)
        self.slot_factor = OPTIMIZER_SLOT_FACTOR.get(
            model_item.optimizer_spec.name, 2.0
        )

    # ----------------------------------------------------------- primitives
    def allreduce_s(self, nbytes: float, participants: Optional[int] = None) -> float:
        """Ring all-reduce of ``nbytes`` over the gradient-reduction group
        (the data axis by default); hierarchical (reduce-scatter on ICI,
        all-reduce shards on DCN) across hosts."""
        p = participants if participants is not None else self.n_data
        if p <= 1:
            return 0.0
        if self.m == 1 or p <= self.chips_per_node:
            # Single host, or a group small enough to live inside one host
            # (mesh_utils maps minor axes onto intra-node ICI): pure ICI ring.
            return 2.0 * nbytes * (p - 1) / p / self.bw_ici
        c = max(p // self.m, 1)
        intra = 2.0 * nbytes * (c - 1) / c / self.bw_ici if c > 1 else 0.0
        inter = 2.0 * (nbytes / c) * (self.m - 1) / self.m / self.bw_dcn
        return intra + inter

    def _group_latency(self, participants: int) -> float:
        """Dispatch latency for a collective over ``participants`` chips:
        ICI-class when the group fits inside one host."""
        if self.m == 1 or participants <= self.chips_per_node:
            return ICI_LATENCY_S
        return DCN_LATENCY_S

    def _oneway_s(self, nbytes: float, participants: Optional[int] = None) -> float:
        """All-gather / reduce-scatter (half an all-reduce)."""
        return self.allreduce_s(nbytes, participants) / 2.0

    def _sharded(self, var: VarItem, axis: Optional[int]) -> int:
        """Residency shard count the lowering would realize: the shard-axis
        size when the requested (or fallback) axis divides evenly, else 1."""
        k = self.n_shard
        if k <= 1 or not var.shape or axis is None:
            return 1
        if var.shape[axis] % k == 0 and var.shape[axis] >= k:
            return k
        # lowering `_fallback_axis`: largest evenly-divisible axis; then
        # pad-and-mask on the requested axis when it exceeds the mesh degree.
        if any(d % k == 0 and d >= k for d in var.shape) or var.shape[axis] > k:
            return k
        return 1

    def _residency_bytes(self, var: VarItem, axis: Optional[int], shards: int) -> float:
        """Stored bytes of the variable: the zero-padded storage size when
        pad-and-mask sharding applies (lowering stores ceil-multiples of the
        shard axis), else the logical size."""
        B = float(var.byte_size)
        if shards <= 1 or axis is None or not var.shape:
            return B
        if var.shape[axis] % shards == 0 or any(
            d % shards == 0 and d >= shards for d in var.shape
        ):
            return B  # exact shard or divisible-fallback axis: no padding
        padded = -(-var.shape[axis] // shards) * shards
        return B * padded / var.shape[axis]

    def _act_bytes_for(self, var: VarItem) -> float:
        """Activation bytes one TP collective moves for this variable: the
        sharded matmul's output is ~(batch, var.shape[-1]). An explicit
        ``act_bytes`` calibration wins; otherwise derive from the captured
        batch, falling back to the fixed planning default."""
        if self.act_bytes is not None:
            return self.act_bytes
        bs = self.model_item.batch_size
        if bs and var.shape:
            return float(bs) * float(var.shape[-1]) * ACT_BYTES_PER_ELEMENT
        return DEFAULT_ACT_BYTES

    def _update_axis_shards(self, var: VarItem) -> int:
        """`_weight_update_spec` parity: slot sharding for PS vars rides the
        data axis."""
        k = self.n_data
        if k <= 1 or not var.shape:
            return 1
        cands = [d for d in var.shape if d % k == 0 and d >= k]
        return k if cands else 1

    def _zero1_degradations(self, var: VarItem, part_axis, compressor):
        """The shared quiet-degradation predicate (kernel/degrade.py) on
        this model's mesh degrees — ONE list for lowering, pricing, and the
        static analyzer; ``tests/test_cost_model.py`` pins the parity."""
        from autodist_tpu.kernel.degrade import zero1_degradation_reasons

        return zero1_degradation_reasons(
            var.shape,
            sparse_update=var.sparse_update,
            expert=var.expert,
            part_axis=part_axis,
            compressor=compressor,
            n_data=self.n_data,
            n_model=self.n_model,
            n_expert=self.n_expert,
        )

    def _sparse_cost(
        self, var: VarItem, update_traffic_factor: float
    ) -> Tuple[float, float, float, float, float, int]:
        """(comm_s, update_s, param_bytes, extra_bytes, opt_bytes, shards)
        for a row-sharded sparse table — the lowering's sparse branch, which
        applies under both PS and AllReduce synchronizers.

        Wire: forward row gather + backward scatter-add of touched rows.
        Residency: row-sharded (over the shard axis, padding if needed)
        whenever the table has at least axis-size rows, else the dense
        weight-update axis decides residency.
        """
        B = float(var.byte_size)
        wire = B * self.sparse_touch
        comm = 2.0 * self._oneway_s(wire)
        if var.shape and self.n_shard > 1 and var.shape[0] >= self.n_shard:
            shards = self.n_shard
            res = self._residency_bytes(var, 0, shards)
        else:
            shards = self._update_axis_shards(var)
            res = B
        update = update_traffic_factor * B * self.sparse_touch / shards / self.hbm_bw
        params = res / shards
        opt = self.slot_factor * res / shards
        extra = opt + wire
        return comm, update, params, extra, opt, shards

    # ------------------------------------------------------------ node costs
    def _node_cost(self, node: NodeConfig, var: VarItem) -> Tuple[
        float, float, float, float, float, float, float, int, bool,
        Dict[str, float]
    ]:
        """(comm_s, update_s, act_s, gather_s, param_bytes, slot+grad bytes,
        opt_bytes, n_collectives, shard_update_active, ps_host_loads) for
        one variable."""
        B = float(var.byte_size)
        sync = node.synchronizer
        update_traffic_factor = 3.0 + 2.0 * self.slot_factor  # param rw + grad r + slots rw
        ps_loads: Dict[str, float] = {}

        if (
            var.expert and var.shape and self.n_expert > 1
            and var.shape[0] % self.n_expert == 0
        ):
            # Lowering parity (the expert branch outranks everything in
            # _lower_node): the leading expert dim shards over the expert
            # axis, so residency is 1/n_expert and the expert-sharded
            # gradient reduces over the DATA group only — tokens reach the
            # experts via the all_to_all GSPMD inserts, which is activation
            # traffic, not parameter sync (ADVICE r1).
            res = B / self.n_expert
            comm = self.allreduce_s(res)
            update = update_traffic_factor * res / self.hbm_bw
            params = res
            opt = self.slot_factor * res
            extra = opt + res
            return (comm, update, 0.0, 0.0, params, extra, opt, 1, False,
                    ps_loads)

        if isinstance(sync, AllReduceSynchronizer):
            part_axis = node.active_partition_axis
            if var.sparse_update and part_axis is None:
                from autodist_tpu.kernel.compressor import is_active_compressor

                compressed = (
                    is_active_compressor(sync.compressor) and self.n_model == 1
                )
                if compressed:
                    # Lowering parity for the compressed path: an active
                    # compressor routes the whole grad computation through
                    # the data-manual shard_map, which feeds every param in
                    # REPLICATED — the table all-gathers in and its dense
                    # gradient psums at full size (_manual_sync_grads),
                    # erasing the sparse wire savings. Price that honestly
                    # rather than reporting tokens-scaled comm for a
                    # table-scaled program. (On non-pure-DP meshes
                    # compression is disabled and the sparse path below
                    # applies.)
                    comm = self._oneway_s(B) + self.allreduce_s(B)
                    update = update_traffic_factor * B / self.hbm_bw
                    params = B  # materialized replicated inside the step
                    opt = self.slot_factor * B
                    extra = opt + B
                    return (comm, update, 0.0, 0.0, params, extra, opt, 1,
                            False, ps_loads)
                # Lowering parity: the sparse branch row-shards under
                # AllReduce exactly like PS (kernel/lowering.py sparse
                # branch), so the wire is tokens-scaled gather/scatter —
                # never a dense full-table all-reduce.
                comm, update, params, extra, opt, _ = self._sparse_cost(
                    var, update_traffic_factor
                )
                return (comm, update, 0.0, 0.0, params, extra, opt, 1,
                        False, ps_loads)
            shards = self._sharded(var, part_axis)
            res = self._residency_bytes(var, part_axis, shards)
            act = 0.0
            if shards <= 1:
                upd_shards = self._update_axis_shards(var)
                if sync.shard_update and not self._zero1_degradations(
                        var, part_axis, sync.compressor):
                    # zero1 weight-update sharding (lowering parity via the
                    # ONE shared kernel/degrade.py predicate; compressed,
                    # claimed-elsewhere or non-divisible vars fall through
                    # to plain AR below). Wire bytes equal the ring
                    # all-reduce (rs + ag IS the ring decomposition), but
                    # split across the comm (reduce-scatter) and gather
                    # (all-gather) components; the optimizer update and
                    # slots shard 1/N. Two collectives per fusion group
                    # (rs + ag) vs the plain AR's one — the latency term
                    # that makes tiny vars lose.
                    comm = self._oneway_s(B)
                    gather = self._oneway_s(B)
                    update = (update_traffic_factor * B / upd_shards
                              / self.hbm_bw)
                    params = B
                    opt = self.slot_factor * B / upd_shards
                    extra = opt + B  # sharded slots + full grad buffer
                    return (comm, update, 0.0, gather, params, extra, opt,
                            2, True, ps_loads)
                # Plain DP: one gradient all-reduce over the data group,
                # compressed at the full gradient shape.
                comm = self.allreduce_s(
                    res * compressor_wire_factor(
                        sync.compressor, var.shape, self.n_data))
            elif self.n_model > 1:
                # Model-axis tensor parallelism (lowering _shard_axis_name:
                # any non-trivial model axis wins): each chip holds a
                # 1/shards gradient slice, reduced over the data group; the
                # compressor runs ON THE SLICE, so its factor is computed
                # from the slice shape (for PowerSGD that factor is worse
                # than the full-shape one — the m+k term doesn't shrink
                # with k/shards). The split matmul pays an activation
                # all-gather over the model group in forward and backward.
                slice_shape = list(var.shape)
                if part_axis is not None and part_axis < len(slice_shape):
                    slice_shape[part_axis] = max(
                        1, -(-slice_shape[part_axis] // shards))
                comm = self.allreduce_s(
                    (res / shards)
                    * compressor_wire_factor(
                        sync.compressor, slice_shape, self.n_data,
                        traced_shape=var.shape))
                act = 2.0 * (
                    self._group_latency(self.n_shard)
                    + self._oneway_s(self._act_bytes_for(var), self.n_shard)
                )
            else:
                # Data-axis parameter sharding (ZeRO rendering): params are
                # all-gathered for compute at FULL size, forward + backward,
                # and grads reduce-scattered. Compressors DO NOT apply here
                # — lowering skips them for data-axis-sharded vars
                # (_resolve_compressors warns and compresses nothing), so
                # pricing a compressed wire would make tune prefer a
                # compressed-ZeRO candidate whose real wire is the dense
                # 1.5x all-reduce cost.
                comm = 3.0 * self._oneway_s(res)
            update = update_traffic_factor * res / shards / self.hbm_bw
            params = res / shards
            opt = self.slot_factor * res / shards
            extra = opt + res  # slots + grad buffer
            n_coll = 1
            return (comm, update, act, 0.0, params, extra, opt, n_coll,
                    False, ps_loads)

        assert isinstance(sync, PSSynchronizer)
        if var.sparse_update:
            comm, update, params, extra, opt, shards = self._sparse_cost(
                var, update_traffic_factor
            )
        else:
            part_axis = node.active_partition_axis
            if part_axis is not None:
                # Explicitly partitioned PS var (PartitionedPS /
                # UnevenPartitionedPS): lowering shards param + update on
                # the requested axis (padding when nothing divides), taking
                # precedence over the proxy residency knob.
                upd_shards = self._sharded(var, part_axis)
                res = self._residency_bytes(var, part_axis, upd_shards)
            else:
                upd_shards = self._update_axis_shards(var)
                res = B
            if sync.local_replication and part_axis is None:
                # ZeRO-1: replicated param, sharded update; grads all-reduce
                # then the owner shard's update is re-broadcast.
                comm = self.allreduce_s(B) + self._oneway_s(B)
                params = B
            else:
                # ZeRO-3 / partitioned: sharded param; reduce-scatter grads
                # + all-gather params on use (forward + backward).
                comm = 3.0 * self._oneway_s(res)
                params = res / upd_shards
            update = update_traffic_factor * res / upd_shards / self.hbm_bw
            opt = self.slot_factor * res / upd_shards
            extra = opt + res
        # Multi-node PS: the destination host's NIC serializes this var's
        # cross-host traffic (reference: all workers push to one PS CPU).
        # A partitioned var's shards may reduce at different hosts
        # (PartitionedPS bin-packing, strategy.proto:46-50): each shard
        # destination carries its 1/num_shards slice of the wire, so a
        # well-spread shard table genuinely relieves the per-host NIC term.
        if self.m > 1:
            wire_dcn = (B * self.sparse_touch) if var.sparse_update else B
            load = 2.0 * (self.m - 1) * wire_dcn / self.bw_dcn
            node_dest = sync.reduction_destination or "chief"
            if node.part_config and len(node.part_config) != node.num_shards:
                # Same contract the lowering enforces (_fold_part_config):
                # a mismatched shard table must not silently skew per-host
                # load estimates for a strategy that could never lower.
                raise ValueError(
                    f"{node.var_name!r}: {len(node.part_config)} part "
                    f"configs but partitioner {node.partitioner!r} implies "
                    f"{node.num_shards}"
                )
            shard_dests = [
                p.synchronizer.reduction_destination or node_dest
                for p in node.part_config
                if isinstance(p.synchronizer, PSSynchronizer)
            ]
            if shard_dests:
                # Each destination's NIC carries its shard's actual slice
                # of the wire. Shards can be uneven (UnevenPartitionedPS
                # splits a non-divisible axis floor/ceil), so weight by the
                # shard's row count rather than splitting evenly.
                weights = _shard_weights(var, node, len(shard_dests))
                for d, w in zip(shard_dests, weights):
                    host = d.split(":", 1)[0]
                    ps_loads[host] = ps_loads.get(host, 0.0) + load * w
            else:
                host = node_dest.split(":", 1)[0]
                ps_loads[host] = ps_loads.get(host, 0.0) + load
        act = 0.0
        n_coll = 2  # push + pull round
        return (comm, update, act, 0.0, params, extra, opt, n_coll, False,
                ps_loads)

    def _bucketable(self, node: NodeConfig, var: VarItem) -> bool:
        """Backward-overlap bucket eligibility for one AR node — the ONE
        shared predicate (kernel/bucketing.py), on this model's mesh
        degrees, so pricing can never bucket a var the lowering would not
        (``tests/test_bucketing.py`` pins the three-way parity)."""
        from autodist_tpu.kernel.bucketing import bucket_exclusion_reasons

        try:
            part_axis = node.active_partition_axis
        except ValueError:
            part_axis = None
        return not bucket_exclusion_reasons(
            var.shape,
            trainable=var.trainable,
            is_ps=not isinstance(node.synchronizer, AllReduceSynchronizer),
            sparse_update=var.sparse_update,
            expert=var.expert,
            part_axis=part_axis,
            compressor=getattr(node.synchronizer, "compressor",
                               "NoneCompressor"),
            n_data=self.n_data,
            n_model=self.n_model,
            n_expert=self.n_expert,
        )

    # -------------------------------------------------------------- strategy
    def strategy_cost(self, strategy: Strategy) -> StrategyCost:
        comm = update = act = gather = params_bytes = extra_bytes = 0.0
        opt_bytes = 0.0
        groups: set = set()
        su_groups: set = set()
        n_ps_coll = 0
        host_loads: Dict[str, float] = {}
        bucket_bytes = int(getattr(
            strategy.graph_config, "bucket_bytes", 0) or 0)
        # (name, var bytes, comm contribution, shard_update) per bucketed
        # var, in node (model) order — mirrors the lowering's assignment
        # input exactly.
        bucket_rows: List[Tuple[str, float, float, bool]] = []
        for node in strategy.node_config:
            try:
                var = self.model_item.var(node.var_name)
            except KeyError:
                continue
            (c, u, a, g, p, e, ob, n_coll, su_active,
             loads) = self._node_cost(node, var)
            comm += c
            update += u
            act += a
            gather += g
            params_bytes += p
            extra_bytes += e
            opt_bytes += ob
            for h, load in loads.items():
                host_loads[h] = host_loads.get(h, 0.0) + load
            sync = node.synchronizer
            if isinstance(sync, AllReduceSynchronizer):
                if bucket_bytes > 0 and self._bucketable(node, var):
                    # Bucketed vars leave the fusion-group accounting: the
                    # bucket partition decides their dispatch count below.
                    bucket_rows.append(
                        (var.name, float(var.byte_size), c, su_active))
                    continue
                leaf_groups = (
                    [p.synchronizer.group for p in node.part_config
                     if isinstance(p.synchronizer, AllReduceSynchronizer)]
                    or [sync.group]
                )
                # zero1 fusion groups dispatch TWO collectives (rs + ag)
                # where a plain AR group dispatches one; keep them apart so
                # the latency term reflects the extra dispatch (this is
                # what makes shard_update lose on a model of tiny vars).
                (su_groups if su_active else groups).update(leaf_groups)
            else:
                n_ps_coll += n_coll
        # Bucketed backward-overlap emission (kernel/bucketing.py): the SAME
        # reverse-order greedy assignment the lowering renders. Every
        # bucket's grad collective except the LAST-closing one (the first
        # model variables, whose grads the backward produces at its very
        # end) overlaps remaining backward compute — its wire moves from
        # comm_s to overlap_s (total_s charges OVERLAP_EXPOSED_FRACTION of
        # it; calibration fits the real coefficient). The zero1 param
        # all-gather (gather_s) happens after the update and stays exposed.
        overlap = 0.0
        n_bucket_coll = 0
        if bucket_rows:
            from autodist_tpu.kernel.bucketing import assign_buckets

            buckets = assign_buckets(
                [(nm, b) for nm, b, _, _ in bucket_rows], bucket_bytes)
            comm_of = {nm: c for nm, _, c, _ in bucket_rows}
            per_bucket = [sum(comm_of[nm] for nm in names)
                          for names in buckets]
            overlap = sum(per_bucket[:-1])
            # One grad collective dispatch per bucket, plus one param
            # all-gather when any bucketed var shards its update.
            n_bucket_coll = len(buckets) + (
                1 if any(su for *_, su in bucket_rows) else 0)
        # PS destination NIC serialization dominates the hierarchical
        # all-reduce estimate for those vars; charge the slower of the two
        # — against the PRE-overlap comm, then move the overlappable wire
        # out (subtracting after the max would let a dominating host load
        # void the subtraction while total_s still charges the overlap
        # prior, double-counting the bucketed wire on mixed AR+PS plans).
        if host_loads:
            comm = max(comm, max(host_loads.values()))
        comm = max(comm - overlap, 0.0)
        n_collectives = (len(groups) + 2 * len(su_groups) + n_ps_coll
                         + n_bucket_coll)
        latency = n_collectives * self.latency
        per_chip = params_bytes + extra_bytes
        return StrategyCost(
            comm_s=comm,
            update_s=update,
            latency_s=latency,
            act_sync_s=act,
            gather_s=gather,
            overlap_s=overlap,
            per_chip_bytes=per_chip,
            hbm_bytes=self.hbm_cap,
            n_collectives=n_collectives,
            opt_bytes=opt_bytes,
        )

    def rank(
        self, candidates: Sequence[Tuple[str, Strategy]]
    ) -> List[Tuple[str, StrategyCost]]:
        """Cost each candidate; feasible ones first, each tier by time.

        When nothing fits, the least-over-budget candidate ranks first so the
        caller still gets the best available answer (with a warning upstream).
        """
        costed = [(name, self.strategy_cost(s)) for name, s in candidates]
        ranked = sorted(
            costed,
            key=lambda nc: (
                not nc[1].feasible,
                nc[1].total_s if nc[1].feasible else nc[1].per_chip_bytes,
            ),
        )
        # Near-tie break: predictions within the mesh's tie band of the
        # feasible best are indistinguishable; among them the CANONICAL
        # preference order (SLATE_PREFERENCE, simplest-mechanism-first)
        # picks the winner — never the caller's candidate ordering, which
        # may come from a dict/set and silently flip between runs. Names
        # the canon doesn't know (planner-generated candidates, custom
        # slates) break by lower per-chip memory, then stable name order,
        # so the choice is deterministic for ANY candidate list.
        if ranked and ranked[0][1].feasible:
            rel = NEAR_TIE_REL if self.n <= 1 else NEAR_TIE_REL_MULTI
            feas = {name: c.total_s for name, c in ranked if c.feasible}
            mem = {name: c.per_chip_bytes for name, c in ranked if c.feasible}
            win_name = _tie_winner(feas, SLATE_PREFERENCE, rel, memory=mem)
            winner = next(nc for nc in ranked if nc[0] == win_name)
            ranked.remove(winner)
            ranked.insert(0, winner)
        return ranked
