"""The ONE quiet-degradation predicate for zero1 weight-update sharding.

``shard_update`` (ZeRO-1, arXiv 2004.13336) is a *capability request*: a
variable claimed by a more specific rendering (expert sharding, explicit
partitioning, sparse row-sharding), carried by a compressed wire, or with
no data-axis-divisible dimension keeps its usual rendering instead of
erroring. Three subsystems must agree on that list exactly:

- ``kernel/lowering.py`` decides whether the reduce-scatter → sharded
  update → all-gather rendering is ACTIVE for a variable;
- ``strategy/cost_model.py`` prices zero1 only where the lowering would
  actually render it (a priced-but-not-rendered var would desync the
  ranking from the program);
- ``analysis/passes.py`` treats exactly these reasons as *declared*
  degradations — anything else that silently differs from the strategy's
  request is a finding.

Before this module each side mirrored the list by hand (PR 5); the parity
regression lives in ``tests/test_cost_model.py`` next to
``TestWeightUpdateSpecParity``. Pure arithmetic on shapes and mesh
degrees — no jax imports — so the chief-side cost model stays light.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

#: Every reason this predicate can emit, in emission order. The analyzer
#: treats exactly this vocabulary as "declared"; an unknown reason string
#: anywhere in a plan is itself a finding (docs/analysis.md, SLH003).
DEGRADATION_REASONS = (
    "scalar",          # rank-0 var: nothing to scatter
    "compressed",      # active compressor owns the wire (full-grad psum)
    "expert",          # expert-axis sharding claims the var first
    "partitioned",     # explicit partition request lands (incl. fallback/pad)
    "sparse",          # sparse-update row-sharding claims the var first
    "non_divisible",   # no dimension divides the data axis: nothing shards
)


def _compressor_active(compressor: Optional[str]) -> bool:
    from autodist_tpu.kernel.compressor import is_active_compressor

    return is_active_compressor(compressor or "")


def zero1_degradation_reasons(
    shape: Sequence[int],
    *,
    sparse_update: bool = False,
    expert: bool = False,
    part_axis: Optional[int] = None,
    compressor: str = "NoneCompressor",
    n_data: int = 1,
    n_model: int = 1,
    n_expert: int = 1,
) -> Tuple[str, ...]:
    """Why a ``shard_update`` request would NOT actively render for a var.

    Returns every applicable reason (ordered as
    :data:`DEGRADATION_REASONS`); empty tuple = the zero1 rendering is
    active. Mirrors ``kernel/lowering.py::GraphTransformer._lower_node``'s
    branch precedence: expert > explicit partition (divisible, largest
    divisible fallback, or pad-and-mask) > sparse row-sharding > zero1.
    """
    shape = tuple(int(d) for d in (shape or ()))
    n_data = max(int(n_data), 1)
    n_model = max(int(n_model), 1)
    n_expert = max(int(n_expert), 1)
    # The shard axis variable partitioning rides (lowering _shard_axis_name):
    # the model axis when non-trivial, else ZeRO-style over the data axis.
    n_shard = n_model if n_model > 1 else n_data

    reasons = []
    if not shape:
        reasons.append("scalar")
    if _compressor_active(compressor):
        reasons.append("compressed")
    if shape and expert and n_expert > 1 and shape[0] % n_expert == 0:
        reasons.append("expert")
    if shape and part_axis is not None and part_axis < len(shape):
        # Does the partition request LAND (exact divide, largest-divisible
        # fallback axis, or pad-and-mask on an over-degree axis)? A landed
        # partition already shards the update; a request that cannot land
        # at all falls through to the zero1 branch in the lowering.
        d = shape[part_axis]
        divisible = d % n_shard == 0 and d >= n_shard
        fallback = any(x % n_shard == 0 and x >= n_shard for x in shape)
        if divisible or fallback or d > n_shard:
            reasons.append("partitioned")
    if shape and sparse_update and "partitioned" not in reasons:
        # Sparse row-sharding (axis 0, padding when rows don't divide)
        # claims the var under both PS and AllReduce whenever the table has
        # enough rows; n_shard == 1 row-"shards" trivially.
        if (shape[0] % n_shard == 0 and shape[0] >= n_shard) or shape[0] > n_shard:
            reasons.append("sparse")
    if shape and (
        n_data <= 1
        or not any(d % n_data == 0 and d >= n_data for d in shape)
    ):
        # _weight_update_spec parity: nothing to scatter over the data axis
        # (a single-chip data axis renders no wire at all).
        reasons.append("non_divisible")
    return tuple(r for r in DEGRADATION_REASONS if r in reasons)
