"""Gradient compressors: fewer bits on the wire for the gradient sync.

TPU-native rebuild of the reference's compressor layer
(``/root/reference/autodist/kernel/synchronization/compressor.py``): there a
``Compressor`` wrapped the explicit ``collective_ops.all_reduce`` call
(``compressor.py:146-201``), with an error-feedback mixin (``:120-143``) and a
drafted-but-disabled PowerSGD (``:208-284``). Here the gradient all-reduce is
the data-axis ``lax.psum`` inside a partially-manual ``shard_map`` (manual
over the data axis, GSPMD-auto over model axes), and each compressor owns the
full compress → psum → decompress pattern:

- ``NoneCompressor`` — plain ``psum`` average, full precision.
- ``HorovodCompressor`` — dtype-cast transport (bf16 on TPU, replacing the
  reference's fp16/fp32 casting): the collective itself runs on half-width
  payloads, halving ICI bytes.
- ``HorovodCompressorEF`` — same cast plus per-worker error feedback: the
  rounding error of each step is carried in a residual and re-injected, so
  compression error accumulates to zero instead of biasing the trajectory.
- ``PowerSGDCompressor`` — rank-r low-rank approximation (arXiv 1905.13727)
  with power-iteration warm start and error feedback; syncs two rank-r
  factors instead of the full matrix.
- ``TopKCompressor`` — magnitude sparsification with error feedback (the
  Deep-Gradient-Compression recipe, arXiv 1712.01887; beyond the
  reference, which drafted no sparsifier): each worker contributes only
  its top-k entries, synced by all-gathering (value, index) pairs and
  scatter-adding — the wire scales with k·nshards instead of the tensor
  size.

Per-worker state (EF residuals) is carried in ``TrainState.comp_state`` with
a leading data-axis dimension so each mesh data-shard keeps its own residual
— the analog of each reference worker holding its own ``error`` tensor.
"""
from __future__ import annotations

import math
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from autodist_tpu.model_item import VarItem

State = Dict[str, jnp.ndarray]


class Compressor:
    """One gradient leaf's compress → all-reduce → decompress policy.

    ``step`` runs inside the data-axis-manual ``shard_map``: ``grad`` is the
    local (per-data-shard) gradient of the local-mean loss; the result must
    be the synchronized global-mean gradient, identical on every shard.
    """

    name = "Compressor"

    def init_local(self, var: VarItem) -> State:
        """Per-worker persistent state (one copy per data shard)."""
        return {}

    def init_shared(self, var: VarItem) -> State:
        """Cross-worker persistent state (identical on all shards)."""
        return {}

    def step(
        self, grad: jnp.ndarray, local: State, shared: State, *, axis: str, nshards: int
    ) -> Tuple[jnp.ndarray, State, State]:
        raise NotImplementedError

    def wire_factor(self, shape: Tuple[int, ...], nshards: int = 1) -> float:
        """Collective payload bytes under this compressor / dense fp32
        psum payload bytes, for a gradient of ``shape`` synced over
        ``nshards`` data shards. The cost model's wire term
        (strategy/cost_model.py) uses this, so the formula lives next to
        the ``step`` whose collectives it prices;
        ``tests/test_compressor.py`` pins it to the actual HLO payloads.
        ``nshards`` only matters for compressors whose collective is a
        gather (payload grows with the group) — psum-shaped compressors
        ignore it.
        """
        return 1.0


class NoneCompressor(Compressor):
    """Identity: full-precision psum average (compressor.py:146-166)."""

    name = "NoneCompressor"

    def step(self, grad, local, shared, *, axis, nshards):
        return lax.psum(grad, axis) / nshards, local, shared


class HorovodCompressor(Compressor):
    """Cast-for-transport: the collective runs on bf16 payloads
    (compressor.py:169-201, retargeted fp16→bf16 for the MXU/ICI)."""

    name = "HorovodCompressor"
    wire_dtype = jnp.bfloat16

    def step(self, grad, local, shared, *, axis, nshards):
        compressed = grad.astype(self.wire_dtype)
        summed = lax.psum(compressed, axis)
        return summed.astype(grad.dtype) / nshards, local, shared

    def wire_factor(self, shape, nshards=1):
        return jnp.dtype(self.wire_dtype).itemsize / jnp.dtype(jnp.float32).itemsize


class HorovodCompressorEF(HorovodCompressor):
    """Cast transport + error feedback (CompressorEF mixin,
    compressor.py:120-143): residual_{t+1} = input - decompress(compress(input))
    accumulated per worker."""

    name = "HorovodCompressorEF"

    def init_local(self, var):
        return {"residual": jnp.zeros(var.shape, jnp.dtype(var.dtype))}

    def step(self, grad, local, shared, *, axis, nshards):
        inp = grad + local["residual"].astype(grad.dtype)
        compressed = inp.astype(self.wire_dtype)
        residual = inp - compressed.astype(grad.dtype)
        summed = lax.psum(compressed, axis)
        return (
            summed.astype(grad.dtype) / nshards,
            {"residual": residual},
            shared,
        )


class PowerSGDCompressor(Compressor):
    """Rank-r PowerSGD (arXiv 1905.13727; reference draft
    compressor.py:208-284) with error feedback.

    For a gradient reshaped to M (m×k): P = M·Q (psum, orthonormalize via QR),
    Qn = Mᵀ·P (psum, averaged), M̂ = P·Qnᵀ. Wire cost per step is
    (m+k)·r instead of m·k. Q persists across steps (warm-started power
    iteration); the per-worker residual carries the approximation error.
    Rank-0/1 tensors are too small to benefit — plain full-precision psum.
    """

    name = "PowerSGDCompressor"

    def __init__(self, rank: int = 2, seed: int = 0):
        self.rank = rank
        self.seed = seed

    def _matrix_shape(self, shape) -> Tuple[int, int]:
        return shape[0], math.prod(shape[1:])

    def init_local(self, var):
        if len(var.shape) < 2:
            return {}
        return {"residual": jnp.zeros(var.shape, jnp.dtype(var.dtype))}

    def init_shared(self, var):
        if len(var.shape) < 2:
            return {}
        _, k = self._matrix_shape(var.shape)
        r = min(self.rank, k, var.shape[0])
        q = jax.random.normal(
            jax.random.PRNGKey(self.seed), (k, r), jnp.dtype(var.dtype)
        )
        q, _ = jnp.linalg.qr(q)
        return {"q": q}

    def step(self, grad, local, shared, *, axis, nshards):
        if grad.ndim < 2:
            return lax.psum(grad, axis) / nshards, local, shared
        m_rows, k = self._matrix_shape(grad.shape)
        inp = grad + local["residual"]
        mat = inp.reshape(m_rows, k)
        q = shared["q"]
        # Left factor: aggregate across workers, then orthonormalize.
        p = lax.psum(mat @ q, axis)
        p, _ = jnp.linalg.qr(p)
        # Right factor: aggregate of Mᵀ·P, averaged.
        qn = lax.psum(mat.T @ p, axis) / nshards
        approx = (p @ qn.T).reshape(grad.shape)
        residual = inp - approx
        return approx, {"residual": residual}, {"q": qn}

    def wire_factor(self, shape, nshards=1):
        """(m+k)·r over m·k: the two rank-r factor psums in :meth:`step`
        (P is m×r, Qn is k×r) replace the dense m×k payload. Rank-0/1
        gradients take the plain psum path — factor 1. Deliberately NOT
        clamped at 1: for tiny matrices the factor payloads really do
        exceed the dense gradient, and the cost model should see that
        honestly rather than reward compressing tensors it shouldn't."""
        if len(shape) < 2:
            return 1.0
        m_rows, k = self._matrix_shape(shape)
        r = min(self.rank, k, m_rows)
        return (m_rows + k) * r / (m_rows * k)


class TopKCompressor(Compressor):
    """Magnitude top-k sparsification with error feedback (Deep Gradient
    Compression, arXiv 1712.01887). Beyond the reference: its compressor
    layer drafted casts and PowerSGD but no sparsifier.

    Each worker adds its EF residual, keeps its ``ratio`` largest-magnitude
    entries, and contributes ``(values, indices)`` pairs; the sync is an
    all-gather of both arrays over the data axis followed by a local
    scatter-add and mean. Overlapping index choices across workers sum
    naturally (the dense-psum semantics restricted to the union support).
    Everything not selected stays in the per-worker residual, so the
    compression error accumulates to zero over steps instead of biasing
    the trajectory.

    Tensors smaller than ``min_size`` take the plain full-precision psum —
    at that size the (value, index) pairs would rival the dense payload.
    ``k`` is static (computed from the shape at trace time), so the
    program stays fixed-shape for XLA.
    """

    name = "TopKCompressor"

    def __init__(self, ratio: float = 0.01, min_size: int = 4096):
        if not 0.0 < ratio <= 1.0:
            raise ValueError(f"ratio must be in (0, 1], got {ratio}")
        self.ratio = ratio
        self.min_size = min_size

    def _k(self, shape) -> int:
        return max(1, int(math.prod(shape) * self.ratio))

    def init_local(self, var):
        if math.prod(var.shape) < self.min_size:
            return {}
        return {"residual": jnp.zeros(var.shape, jnp.dtype(var.dtype))}

    def step(self, grad, local, shared, *, axis, nshards):
        n_elems = math.prod(grad.shape)
        if n_elems < self.min_size:
            return lax.psum(grad, axis) / nshards, local, shared
        k = self._k(grad.shape)
        inp = grad + local["residual"]
        flat = inp.reshape(-1)
        _, idx = lax.top_k(jnp.abs(flat), k)
        vals = flat[idx]
        # Residual: everything this worker did NOT contribute this step —
        # the input with its selected entries zeroed in place.
        residual = flat.at[idx].set(0.0).reshape(grad.shape)
        # Wire: one (k,) value gather + one (k,) index gather per worker.
        all_vals = lax.all_gather(vals, axis)   # [nshards, k]
        all_idx = lax.all_gather(idx, axis)     # [nshards, k]
        dense = (
            jnp.zeros_like(flat)
            .at[all_idx.reshape(-1)]
            .add(all_vals.reshape(-1))
            / nshards
        )
        return dense.reshape(grad.shape), {"residual": residual}, shared

    def wire_factor(self, shape, nshards=1, traced_shape=None):
        """k·nshards / N: the two k-element all-gathers (values f32 +
        indices i32, 8 bytes/entry) move ≈ 8k·(n−1) bytes per chip, vs a
        ring psum's ≈ 2·(n−1)/n·payload — equating the two gives an
        equivalent psum payload of 4·k·n bytes against the dense 4·N.
        Below ``min_size`` the dense psum path runs — factor 1.

        ``traced_shape``: the shape ``step`` actually traces at. On mixed
        data×model meshes the cost model prices the per-chip SLICE
        (``shape``) while the compressor gates and sizes k on the FULL
        tensor (model axes are GSPMD-auto inside the data-manual region)
        — passing the full shape here keeps the priced wire consistent
        with the collectives actually emitted. Like PowerSGD, the factor
        is deliberately not clamped at 1: with enough workers the
        gathered pairs really can exceed the dense wire, and the cost
        model should see that honestly."""
        gate = traced_shape if traced_shape is not None else shape
        if math.prod(gate) < self.min_size:
            return 1.0
        return self._k(gate) * max(nshards, 1) / math.prod(shape)


_REGISTRY = {
    "NoneCompressor": NoneCompressor,
    "HorovodCompressor": HorovodCompressor,
    "HorovodCompressorEF": HorovodCompressorEF,
    "PowerSGDCompressor": PowerSGDCompressor,
    "TopKCompressor": TopKCompressor,
}

# Friendly strategy-IR aliases (builder knob: AllReduce(compressor="bf16")).
_ALIASES = {
    "none": "NoneCompressor",
    "bf16": "HorovodCompressor",
    "ef": "HorovodCompressorEF",
    "powersgd": "PowerSGDCompressor",
    "topk": "TopKCompressor",
}


def canonical_compressor_name(name: str) -> str:
    """Resolve IR-level aliases to registry names. Every consumer that
    string-compares compressor names (lowering's no-op skip, the cost
    model's compressed-path branch) must normalize through here, or
    ``compressor="none"`` would behave differently from
    ``"NoneCompressor"`` (active-but-identity compressed region)."""
    return _ALIASES.get(name, name)


def is_active_compressor(name: str) -> bool:
    """True when ``name`` (IR string, alias or canonical) denotes a real
    wire transformation — i.e. not empty and not the identity
    NoneCompressor. The single predicate behind lowering's no-op skip,
    the cost model's compressed-path pricing, and explain's lossy
    classification; string-comparing anywhere else invites drift."""
    return canonical_compressor_name(name or "") not in ("", "NoneCompressor")


def get_compressor(name: str) -> Compressor:
    """Instantiate by strategy-IR name (AllReduceSynchronizer.compressor);
    lowercase aliases accepted (``bf16``/``ef``/``powersgd``/``topk``)."""
    name = canonical_compressor_name(name)
    if name not in _REGISTRY:
        raise ValueError(
            f"unknown compressor {name!r}; known: "
            f"{sorted(_REGISTRY)} (aliases: {sorted(_ALIASES)})")
    return _REGISTRY[name]()
