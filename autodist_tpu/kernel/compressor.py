"""Gradient compressors: fewer bits on the wire for the gradient sync.

TPU-native rebuild of the reference's compressor layer
(``/root/reference/autodist/kernel/synchronization/compressor.py``): there a
``Compressor`` wrapped the explicit ``collective_ops.all_reduce`` call
(``compressor.py:146-201``), with an error-feedback mixin (``:120-143``) and a
drafted-but-disabled PowerSGD (``:208-284``). Here the gradient all-reduce is
the data-axis ``lax.psum`` inside a partially-manual ``shard_map`` (manual
over the data axis, GSPMD-auto over model axes), and each compressor owns the
full compress → psum → decompress pattern:

- ``NoneCompressor`` — plain ``psum`` average, full precision.
- ``HorovodCompressor`` — dtype-cast transport (bf16 on TPU, replacing the
  reference's fp16/fp32 casting): the collective itself runs on half-width
  payloads, halving ICI bytes.
- ``HorovodCompressorEF`` — same cast plus per-worker error feedback: the
  rounding error of each step is carried in a residual and re-injected, so
  compression error accumulates to zero instead of biasing the trajectory.
- ``PowerSGDCompressor`` — rank-r low-rank approximation (arXiv 1905.13727)
  with power-iteration warm start and error feedback; syncs two rank-r
  factors instead of the full matrix.

Per-worker state (EF residuals) is carried in ``TrainState.comp_state`` with
a leading data-axis dimension so each mesh data-shard keeps its own residual
— the analog of each reference worker holding its own ``error`` tensor.
"""
from __future__ import annotations

import math
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from autodist_tpu.model_item import VarItem

State = Dict[str, jnp.ndarray]


class Compressor:
    """One gradient leaf's compress → all-reduce → decompress policy.

    ``step`` runs inside the data-axis-manual ``shard_map``: ``grad`` is the
    local (per-data-shard) gradient of the local-mean loss; the result must
    be the synchronized global-mean gradient, identical on every shard.
    """

    name = "Compressor"

    def init_local(self, var: VarItem) -> State:
        """Per-worker persistent state (one copy per data shard)."""
        return {}

    def init_shared(self, var: VarItem) -> State:
        """Cross-worker persistent state (identical on all shards)."""
        return {}

    def step(
        self, grad: jnp.ndarray, local: State, shared: State, *, axis: str, nshards: int
    ) -> Tuple[jnp.ndarray, State, State]:
        raise NotImplementedError

    def wire_factor(self, shape: Tuple[int, ...]) -> float:
        """Collective payload bytes under this compressor / dense fp32
        payload bytes, for a gradient of ``shape``. The cost model's wire
        term (strategy/cost_model.py) uses this, so the formula lives next
        to the ``step`` whose collectives it prices;
        ``tests/test_compressor.py`` pins it to the actual HLO payloads.
        """
        return 1.0


class NoneCompressor(Compressor):
    """Identity: full-precision psum average (compressor.py:146-166)."""

    name = "NoneCompressor"

    def step(self, grad, local, shared, *, axis, nshards):
        return lax.psum(grad, axis) / nshards, local, shared


class HorovodCompressor(Compressor):
    """Cast-for-transport: the collective runs on bf16 payloads
    (compressor.py:169-201, retargeted fp16→bf16 for the MXU/ICI)."""

    name = "HorovodCompressor"
    wire_dtype = jnp.bfloat16

    def step(self, grad, local, shared, *, axis, nshards):
        compressed = grad.astype(self.wire_dtype)
        summed = lax.psum(compressed, axis)
        return summed.astype(grad.dtype) / nshards, local, shared

    def wire_factor(self, shape):
        return jnp.dtype(self.wire_dtype).itemsize / jnp.dtype(jnp.float32).itemsize


class HorovodCompressorEF(HorovodCompressor):
    """Cast transport + error feedback (CompressorEF mixin,
    compressor.py:120-143): residual_{t+1} = input - decompress(compress(input))
    accumulated per worker."""

    name = "HorovodCompressorEF"

    def init_local(self, var):
        return {"residual": jnp.zeros(var.shape, jnp.dtype(var.dtype))}

    def step(self, grad, local, shared, *, axis, nshards):
        inp = grad + local["residual"].astype(grad.dtype)
        compressed = inp.astype(self.wire_dtype)
        residual = inp - compressed.astype(grad.dtype)
        summed = lax.psum(compressed, axis)
        return (
            summed.astype(grad.dtype) / nshards,
            {"residual": residual},
            shared,
        )


class PowerSGDCompressor(Compressor):
    """Rank-r PowerSGD (arXiv 1905.13727; reference draft
    compressor.py:208-284) with error feedback.

    For a gradient reshaped to M (m×k): P = M·Q (psum, orthonormalize via QR),
    Qn = Mᵀ·P (psum, averaged), M̂ = P·Qnᵀ. Wire cost per step is
    (m+k)·r instead of m·k. Q persists across steps (warm-started power
    iteration); the per-worker residual carries the approximation error.
    Rank-0/1 tensors are too small to benefit — plain full-precision psum.
    """

    name = "PowerSGDCompressor"

    def __init__(self, rank: int = 2, seed: int = 0):
        self.rank = rank
        self.seed = seed

    def _matrix_shape(self, shape) -> Tuple[int, int]:
        return shape[0], math.prod(shape[1:])

    def init_local(self, var):
        if len(var.shape) < 2:
            return {}
        return {"residual": jnp.zeros(var.shape, jnp.dtype(var.dtype))}

    def init_shared(self, var):
        if len(var.shape) < 2:
            return {}
        _, k = self._matrix_shape(var.shape)
        r = min(self.rank, k, var.shape[0])
        q = jax.random.normal(
            jax.random.PRNGKey(self.seed), (k, r), jnp.dtype(var.dtype)
        )
        q, _ = jnp.linalg.qr(q)
        return {"q": q}

    def step(self, grad, local, shared, *, axis, nshards):
        if grad.ndim < 2:
            return lax.psum(grad, axis) / nshards, local, shared
        m_rows, k = self._matrix_shape(grad.shape)
        inp = grad + local["residual"]
        mat = inp.reshape(m_rows, k)
        q = shared["q"]
        # Left factor: aggregate across workers, then orthonormalize.
        p = lax.psum(mat @ q, axis)
        p, _ = jnp.linalg.qr(p)
        # Right factor: aggregate of Mᵀ·P, averaged.
        qn = lax.psum(mat.T @ p, axis) / nshards
        approx = (p @ qn.T).reshape(grad.shape)
        residual = inp - approx
        return approx, {"residual": residual}, {"q": qn}

    def wire_factor(self, shape):
        """(m+k)·r over m·k: the two rank-r factor psums in :meth:`step`
        (P is m×r, Qn is k×r) replace the dense m×k payload. Rank-0/1
        gradients take the plain psum path — factor 1. Deliberately NOT
        clamped at 1: for tiny matrices the factor payloads really do
        exceed the dense gradient, and the cost model should see that
        honestly rather than reward compressing tensors it shouldn't."""
        if len(shape) < 2:
            return 1.0
        m_rows, k = self._matrix_shape(shape)
        r = min(self.rank, k, m_rows)
        return (m_rows + k) * r / (m_rows * k)


_REGISTRY = {
    "NoneCompressor": NoneCompressor,
    "HorovodCompressor": HorovodCompressor,
    "HorovodCompressorEF": HorovodCompressorEF,
    "PowerSGDCompressor": PowerSGDCompressor,
}


def get_compressor(name: str) -> Compressor:
    """Instantiate by strategy-IR name (AllReduceSynchronizer.compressor)."""
    if name not in _REGISTRY:
        raise ValueError(f"unknown compressor {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name]()
