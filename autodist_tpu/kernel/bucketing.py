"""Bucketed backward-overlap gradient synchronization (the "hide the wire"
mechanism, GSPMD §latency-hiding / arXiv 2105.04663; ZeRO weight-update
sharding assumes exactly this overlap, arXiv 2004.13336).

Without bucketing, ``DistributedTrainStep`` emits every gradient collective
(``psum`` for plain AllReduce vars, ``psum_scatter`` for zero1
``shard_update`` vars) *after* the full backward pass — communication and
compute are serialized on the hot path, and ``obs.StepProfiler`` shows the
wire as exposed step time. This module makes the sync overlap the backward:

- **assignment** (:func:`assign_buckets`): eligible variables are grouped
  into size-targeted buckets in REVERSE model order — the backward pass
  produces gradients for the last layers first, so the bucket holding the
  last layers' variables closes earliest and has the most remaining
  backward compute to hide under;
- **emission** (:func:`make_bucket_hook`): each bucket is an identity
  ``jax.custom_vjp`` applied to the bucket's parameters inside the
  differentiated function. Autodiff calls the hook's backward rule exactly
  when ALL of the bucket's cotangents are available — i.e. at the bucket's
  layer-group boundary in the backward — and the rule emits the bucket's
  collectives there, under a ``gradsync.bucket_{i}`` named scope, so XLA's
  latency-hiding scheduler can run bucket k's reduce-scatter concurrently
  with layer k-1's backward compute.

Eligibility mirrors the quiet-degradation discipline of
``kernel/degrade.py``: variables claimed by a more specific wire
(compressed, sparse row-sharded, expert-sharded, explicitly partitioned)
keep their rendering and sync after the backward as before. THREE
subsystems must agree on that list exactly — the lowering (which vars get
hooks), the cost model (which wire seconds count as overlappable), and the
static analyzer (which collectives attribute to which bucket) — so the
predicate lives here, once, as pure shape/mesh arithmetic
(:func:`bucket_exclusion_reasons`; ``tests/test_bucketing.py`` pins the
three-way parity).

The collective-emission helpers at the bottom are the ONE place the
gradient-sync ``lax.psum`` / ``lax.psum_scatter`` calls live
(``tools/check_patterns.py`` bans them elsewhere in ``kernel/lowering.py``
so a future change cannot silently reintroduce the monolithic post-backward
sync path). jax imports stay inside the emission functions so the
chief-side cost model can import the pure half without pulling jax
(the ``kernel/degrade.py`` convention).

Zero1 shape note: a ``custom_vjp`` backward rule must return cotangents
shaped like its primals, but ``psum_scatter`` produces the 1/N shard. The
hook therefore re-embeds the shard into a zero-filled full-shape buffer at
this instance's offset (``dynamic_update_slice``), and
:func:`slice_update_shard` extracts exactly that slice again after the
gradient exits autodiff — values round-trip bit-exactly, and XLA folds the
update/slice pair away.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

#: Every reason this predicate can emit, in emission order. Mirrors the
#: ``kernel/degrade.py`` vocabulary where the same mechanism excludes a var
#: from the zero1 rendering; ``nontrainable``/``ps`` are bucketing-specific
#: (PS vars sync through their own push/pull wire, never the AR psum path).
EXCLUSION_REASONS = (
    "nontrainable",    # no gradient, nothing to sync
    "ps",              # PS synchronizer: reduction rides the PS wire
    "compressed",      # active compressor owns the wire (full-grad psum)
    "expert",          # expert-axis sharding claims the var first
    "partitioned",     # explicit partition request lands (sharded param)
    "sparse",          # sparse-update row-sharding claims the var first
)

#: Default bucket size target (bytes) when a caller enables bucketing
#: without picking a size; the planner searches the gene instead
#: (plan/search.py BUCKET_GENE_CHOICES).
DEFAULT_BUCKET_BYTES = 4 << 20

# ----------------------------------------------------- named-scope join keys
# The gradient-sync named scopes are the JOIN KEY between a device profile
# and the plan: measured-wire attribution (obs/attrib.py) resolves a traced
# collective to its bucket/vars through the compiled program's op_name
# metadata, which carries exactly these strings. They are pinned here —
# next to the emission that stamps them — and tests/test_attrib.py pins
# the literals, so renaming one is a deliberate, test-visible act.
#: Prefix of the per-bucket backward-overlap scope; bucket i's collectives
#: fire under :func:`bucket_scope`\ ``(i)``.
GRADSYNC_BUCKET_SCOPE = "gradsync.bucket_"
#: Scope of the post-hook shard extraction (bit-exact re-slice).
GRADSYNC_SHARD_SLICE_SCOPE = "gradsync.shard_slice"
#: Scope of the unbucketed zero1 gradient reduce-scatter.
ZERO1_REDUCE_SCATTER_SCOPE = "zero1.reduce_scatter_grads"
#: Scope of the zero1 param re-gather after the sharded update.
ZERO1_ALL_GATHER_SCOPE = "zero1.all_gather_params"


def bucket_scope(bucket_index: int) -> str:
    """Named scope bucket ``bucket_index``'s collectives are emitted under."""
    return f"{GRADSYNC_BUCKET_SCOPE}{bucket_index}"


def bucket_exclusion_reasons(
    shape: Sequence[int],
    *,
    trainable: bool = True,
    is_ps: bool = False,
    sparse_update: bool = False,
    expert: bool = False,
    part_axis: Optional[int] = None,
    compressor: str = "NoneCompressor",
    n_data: int = 1,
    n_model: int = 1,
    n_expert: int = 1,
) -> Tuple[str, ...]:
    """Why a variable would NOT enter a gradient bucket, as pure shape/mesh
    arithmetic (the cost model's entry point — no jax, no VarPlan).

    Empty tuple = the var is bucket-eligible: its gradient sync is a plain
    data-axis ``psum`` (replicated AR var, including scalars and vars whose
    zero1 request quietly degraded on divisibility) or a zero1
    ``psum_scatter`` — both of which the bucketed emission renders
    identically to the monolithic path. Mirrors the branch precedence of
    ``kernel/lowering.py::GraphTransformer._lower_node``.
    """
    from autodist_tpu.kernel.degrade import zero1_degradation_reasons

    shape = tuple(int(d) for d in (shape or ()))
    reasons = []
    if not trainable:
        reasons.append("nontrainable")
    if is_ps:
        reasons.append("ps")
    # Reuse the ONE shared degradation predicate for the renderings that
    # claim a var away from the plain-AR/zero1 psum path; its scalar /
    # non_divisible reasons do NOT exclude from bucketing (those vars still
    # sync via a plain psum, which buckets fine).
    shared = zero1_degradation_reasons(
        shape, sparse_update=sparse_update, expert=expert,
        part_axis=part_axis, compressor=compressor,
        n_data=n_data, n_model=n_model, n_expert=n_expert,
    )
    for r in ("compressed", "expert", "partitioned", "sparse"):
        if r in shared:
            reasons.append(r)
    return tuple(r for r in EXCLUSION_REASONS if r in reasons)


def plan_exclusion_reasons(var_plan) -> Tuple[str, ...]:
    """:func:`bucket_exclusion_reasons` read off a lowered
    :class:`~autodist_tpu.kernel.lowering.VarPlan` — the lowering/analyzer
    entry point. Derives the same answer from the plan's resolved facts
    (no mesh arithmetic: the plan already folded it) so the two entry
    points cannot disagree on a rendered plan."""
    from autodist_tpu.kernel.compressor import is_active_compressor
    from autodist_tpu.kernel.lowering import SyncKind

    reasons = []
    if not var_plan.var.trainable:
        reasons.append("nontrainable")
    if var_plan.kind is SyncKind.PS:
        reasons.append("ps")
    if is_active_compressor(var_plan.compressor):
        reasons.append("compressed")
    # A sharded parameter (expert / partitioned / sparse row-sharded) syncs
    # through its sharded wire, not the plain data-axis psum — EXCEPT the
    # zero1 rendering, whose param stays replicated (update_pspec shards).
    if not var_plan.shard_update and tuple(var_plan.pspec):
        sharded = any(e is not None for e in tuple(var_plan.pspec))
        if sharded:
            if var_plan.var.expert:
                reasons.append("expert")
            elif var_plan.var.sparse_update:
                reasons.append("sparse")
            else:
                reasons.append("partitioned")
    return tuple(r for r in EXCLUSION_REASONS if r in reasons)


def assign_buckets(
    sized_names: Sequence[Tuple[str, int]],
    bucket_bytes: int,
) -> Tuple[Tuple[str, ...], ...]:
    """Partition eligible variables into size-targeted buckets.

    ``sized_names`` is ``(name, byte_size)`` in MODEL order (the plan's
    variable order); the assignment walks it in REVERSE so bucket 0 holds
    the last variables — whose gradients the backward pass produces first —
    and closes early. Greedy fill: a bucket closes once its accumulated
    bytes reach ``bucket_bytes`` (an oversized single variable gets its own
    bucket). Deterministic and order-stable: the same input always yields
    the same partition, every input name lands in exactly one bucket.
    """
    if bucket_bytes <= 0 or not sized_names:
        return ()
    buckets = []
    current: list = []
    acc = 0
    for name, nbytes in reversed(list(sized_names)):
        current.append(name)
        acc += max(int(nbytes), 0)
        if acc >= bucket_bytes:
            buckets.append(tuple(current))
            current, acc = [], 0
    if current:
        buckets.append(tuple(current))
    return tuple(buckets)


# --------------------------------------------------------------- emission
# The ONE home of the gradient-sync collectives. tools/check_patterns.py
# bans lax.psum / lax.psum_scatter in kernel/lowering.py so the monolithic
# sync path cannot silently come back outside this helper.

def psum_mean(x, axis_name: str, n: int):
    """Data-axis mean reduction: the plain AllReduce gradient (and loss /
    aux) wire — ``psum(x) / n``."""
    from jax import lax

    return lax.psum(x, axis_name) / n


def reduce_scatter_grad(g, axis_name: str, n: int, dim: int):
    """The zero1 gradient wire: reduce-scatter of the mean gradient over
    the data axis; this instance keeps its 1/n slice along ``dim``
    (arXiv 2004.13336)."""
    from jax import lax

    return lax.psum_scatter(g / n, axis_name, scatter_dimension=dim,
                            tiled=True)


def slice_update_shard(g, axis_name: str, n: int, dim: int):
    """Extract this instance's 1/n shard of a full-shape gradient along
    ``dim`` — the inverse of the bucket hook's zero-embed, so a bucketed
    zero1 gradient exits the manual region shaped exactly like the
    unbucketed ``psum_scatter`` result (bit-equal values)."""
    from jax import lax

    idx = lax.axis_index(axis_name)
    size = g.shape[dim] // n
    return lax.dynamic_slice_in_dim(g, idx * size, size, dim)


def make_bucket_hook(
    bucket_index: int,
    names: Sequence[str],
    su_dims: Dict[str, int],
    axis_name: str,
    n: int,
):
    """Identity ``custom_vjp`` over one bucket's parameter leaves whose
    backward rule emits the bucket's gradient collectives.

    Autodiff invokes the rule when every cotangent in the bucket is ready —
    the bucket's layer-group boundary in the backward — so the collectives
    land mid-backward where XLA's latency-hiding scheduler can overlap them
    with the remaining backward compute. Plain AR vars get
    :func:`psum_mean`; zero1 (``shard_update``) vars get
    :func:`reduce_scatter_grad` with the shard re-embedded full-shape (see
    module docstring); the caller re-slices via :func:`slice_update_shard`.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    names = tuple(names)

    @jax.custom_vjp
    def hook(*leaves):
        return leaves

    def fwd(*leaves):
        return leaves, None

    def bwd(_, grads):
        out = []
        with jax.named_scope(bucket_scope(bucket_index)):
            for name, g in zip(names, grads):
                dim = su_dims.get(name)
                if dim is None:
                    out.append(psum_mean(g, axis_name, n))
                    continue
                shard = reduce_scatter_grad(g, axis_name, n, dim)
                idx = lax.axis_index(axis_name)
                size = g.shape[dim] // n
                out.append(lax.dynamic_update_slice_in_dim(
                    jnp.zeros(g.shape, shard.dtype), shard, idx * size,
                    dim))
        return tuple(out)

    hook.defvjp(fwd, bwd)
    return hook
