"""Strategy lowering (L2): Strategy IR → sharding plan → compiled train step.

This is the TPU-native replacement for the reference's entire kernel layer —
``GraphTransformer`` + partitioner + replicator + synchronizers
(``/root/reference/autodist/kernel/graph_transformer.py:55-92``,
``partitioner.py``, ``replicator.py``, ``synchronization/*.py``). Where the
reference rewrote a TF graph op-by-op (replicating it per device, splicing
accumulators, queues and collective ops), this layer emits
``jax.sharding.NamedSharding`` annotations per variable and lets XLA GSPMD
insert the collectives:

- ``AllReduceSynchronizer`` → parameter replicated over the mesh; with the
  batch sharded over the "data" axis, autodiff of the mean loss makes XLA
  emit the gradient all-reduce over ICI (the ``lax.psum`` path) — replacing
  the reference's explicit ``collective_ops.all_reduce`` splicing
  (``all_reduce_synchronizer.py:100-126``).
- ``PSSynchronizer`` (unpartitioned, dense) → parameter replicated, but
  optimizer slots *sharded*: weight-update sharding (the ZeRO-style scheme of
  arXiv 2004.13336), so the "server-side" update computation and optimizer
  memory are distributed exactly where the reference placed them on PS
  devices. ``reduction_destination`` degrees of freedom collapse onto mesh
  coordinates.
- ``partitioner: "1,k,1"`` → the parameter itself is sharded on the active
  axis (``NamedSharding``); XLA all-gathers on use and reduce-scatters the
  gradient — a *true* tensor-parallel upgrade of the reference's
  variable-only partitioning (``docs/design/kernels.md:11-17``).
- sparse-update variables (embeddings) → row-sharded on axis 0 under both
  PS and AllReduce, keeping the PS sparse-path capability
  (``ps_synchronizer.py:473-532``) and the sparse-AllReduce wire contract
  (``all_reduce_synchronizer.py:129-169``: sync cost scales with touched
  rows) with gather/scatter collectives instead of
  SparseConditionalAccumulators / collective all-gathers.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, replace as _dc_replace
from enum import Enum
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from autodist_tpu import const
from autodist_tpu.chaos import hooks as chaos_hooks
from autodist_tpu.kernel import bucketing
from autodist_tpu.kernel.mesh import data_axis
from autodist_tpu.obs import recorder as flight
from autodist_tpu.model_item import ModelItem, VarItem, _path_to_name
from autodist_tpu.strategy.ir import (
    AllReduceSynchronizer,
    NodeConfig,
    PSSynchronizer,
    Strategy,
)
from autodist_tpu.utils import is_broadcast_leaf, logging


class SyncKind(Enum):
    ALL_REDUCE = "all_reduce"
    PS = "ps"


@dataclass
class VarPlan:
    """Resolved per-variable lowering decision."""

    var: VarItem
    kind: SyncKind
    pspec: P                       # parameter sharding
    update_pspec: P                # optimizer-slot / weight-update sharding
    compressor: str = "NoneCompressor"
    group: int = 0
    staleness: int = 0
    reduction_destination: str = ""
    local_replication: bool = False
    num_shards: int = 1
    # Store the parameter (and its optimizer slots) in pinned host memory,
    # streaming through HBM inside the step — the TPU rendering of the
    # reference parking PS variables on host CPUs (ps_strategy.py:38-55).
    offload: bool = False
    # Per-shard PS destination table (reference strategy.proto:46-50, as
    # emitted by the PartitionedPS load balancer): shard i of the variable
    # reduces at shard_destinations[i]. Under SPMD the *identity* of each
    # destination collapses onto mesh coordinates (shard i lives at position
    # i of the shard axis — uniform by construction), but the table is part
    # of the plan: explain prints it, the cost model prices it, and
    # ``host_offload="from_strategy"`` reads the destinations' device type
    # to pick the memory kind.
    shard_destinations: Tuple[str, ...] = ()
    # Pad-and-mask sharding (SURVEY §7.4 item 5): when a requested shard
    # axis divides no axis evenly (e.g. GPT-2's prime vocab 50257), the
    # parameter is STORED zero-padded to this shape so XLA's equal-shard
    # requirement holds; the loss sees the sliced logical view, so padded
    # entries get zero gradients and elementwise optimizers keep them at
    # zero. None = storage is the logical shape.
    storage_shape: Optional[Tuple[int, ...]] = None
    # ZeRO-1 weight-update sharding for an AllReduce var (arXiv 2004.13336,
    # strategy.ir.AllReduceSynchronizer.shard_update): param replicated,
    # optimizer slots + update sharded per ``update_pspec`` over the data
    # axis, gradient sync rendered reduce-scatter → sharded update →
    # all-gather. True only when the rendering is ACTIVE (update_pspec is
    # genuinely sharded) — the step keys its manual grad sync off this.
    shard_update: bool = False
    # Declared quiet degradations: why a requested capability (today:
    # shard_update) did NOT render for this var, in the shared
    # ``kernel.degrade.zero1_degradation_reasons`` vocabulary. The static
    # analyzer (autodist_tpu.analysis) treats exactly these as declared;
    # a plan whose flags disagree with the predicate is a finding.
    degradations: Tuple[str, ...] = ()


@struct.dataclass
class TrainState:
    """Minimal functional train state (the reference's mutable-graph state —
    variables + optimizer slots — as an explicit pytree). ``.replace`` comes
    from the struct.dataclass decorator. ``comp_state`` carries gradient-
    compressor persistence (EF residuals per data shard, PowerSGD bases);
    empty dict when no compressor is active."""

    step: jax.Array
    params: Any
    opt_state: Any
    comp_state: Any = struct.field(default_factory=dict)
    # Bounded-staleness gradient buffers ({var: [K, ...]}): the SPMD
    # rendering of the reference's staleness queues (ps_synchronizer.py:
    # 384-455) — gradients apply with a fixed K-step delay instead of a
    # nondeterministic ≤K-step one. Empty when no var has staleness.
    stale_state: Any = struct.field(default_factory=dict)


def _spec_with_axis(rank: int, dim: int, mesh_axis: str) -> P:
    entries: List[Optional[str]] = [None] * rank
    entries[dim] = mesh_axis
    return P(*entries)


def _is_cpu_device(dest: str) -> bool:
    """True when a DeviceSpec string (``host:TYPE:index``) names a host CPU.

    Delegates the parse to :class:`resource_spec.DeviceSpec` so there is one
    implementation of the device-string grammar; unparseable destinations
    read as non-CPU (stay in HBM) rather than raising — a strategy artifact
    with a malformed destination should still lower.
    """
    from autodist_tpu.resource_spec import DeviceSpec, DeviceType

    try:
        return DeviceSpec.from_string(dest).device_type is DeviceType.CPU
    except (ValueError, KeyError):
        return False


def _memory_kinds_supported(mesh: Mesh) -> bool:
    """True when the runtime can stream pinned-host leaves inside jit.

    Requires (a) a pinned_host memory space, and (b) a compile path that
    accepts in-jit memory-space transfers: the TPU toolchain, or any
    single-device mesh (the SPMD partitioner — which rejects
    ``annotate_device_placement`` custom calls — only runs multi-device).
    """
    try:
        dev = mesh.devices.flat[0]
        kinds = {m.kind for m in dev.addressable_memories()}
        if "pinned_host" not in kinds:
            raise ValueError("no pinned_host memory space")
        if dev.platform != "tpu":
            # The CPU runtime has no annotate_device_placement kernel and
            # the non-TPU SPMD partitioner rejects the custom call.
            raise ValueError("in-jit host streaming needs the TPU toolchain")
        return True
    except Exception as e:  # noqa: BLE001 - older runtimes lack the API
        logging.warning("host offload requested but unsupported (%s); disabled", e)
        return False


class GraphTransformer:
    """Lower a compiled Strategy over a mesh into a :class:`ShardingPlan`.

    Keeps the reference's pass-manager name (graph_transformer.py:45-92); the
    passes here are sharding-assignment rules instead of graph rewrites.
    """

    #: host_offload modes: False (never), True (every PS variable), or
    #: "from_strategy" (PS variables whose reduction destination — node- or
    #: shard-level — names a host CPU device, the reference's literal
    #: placement; ps_strategy.py:38-55).
    OFFLOAD_MODES = (False, True, "from_strategy")

    def __init__(
        self,
        strategy: Strategy,
        model_item: ModelItem,
        mesh: Mesh,
        host_offload: "bool | str" = False,
    ):
        if host_offload not in self.OFFLOAD_MODES:
            raise ValueError(
                f"host_offload={host_offload!r}: expected one of "
                f"{self.OFFLOAD_MODES}"
            )
        self.strategy = strategy
        self.model_item = model_item
        self.mesh = mesh
        if host_offload and not _memory_kinds_supported(mesh):
            host_offload = False
        self.host_offload = host_offload

    def transform(self) -> "ShardingPlan":
        from autodist_tpu.obs import spans as _spans

        t_wall, t0 = time.time(), time.perf_counter()
        plans: Dict[str, VarPlan] = {}
        for node in self.strategy.node_config:
            var = self.model_item.var(node.var_name)
            plans[var.name] = self._lower_node(node, var)
        # Non-trainable vars: replicated.
        for var in self.model_item.variables:
            if var.name not in plans:
                plans[var.name] = VarPlan(
                    var=var, kind=SyncKind.ALL_REDUCE, pspec=P(), update_pspec=P()
                )
        # Retroactive span (obs timeline): how long lowering took and how
        # many vars carry the zero1 reduce-scatter/all-gather rendering.
        _spans.add_span(
            "lowering.transform", t_wall, time.perf_counter() - t0,
            n_nodes=len(self.strategy.node_config),
            shard_update_vars=sum(1 for p in plans.values() if p.shard_update),
        )
        return ShardingPlan(
            mesh=self.mesh, var_plans=plans,
            bucket_bytes=int(getattr(
                self.strategy.graph_config, "bucket_bytes", 0) or 0),
        )

    # ------------------------------------------------------------------ rules
    def _shard_axis_name(self) -> str:
        """Mesh axis carrying variable partitioning: the "model" axis when it
        is non-trivial, else the data axis (ZeRO-style sharding)."""
        shape = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        model_ax = const.MESH_AXIS_MODEL
        if shape.get(model_ax, 1) > 1:
            return model_ax
        return data_axis(self.mesh)

    @staticmethod
    def _fold_part_config(node: NodeConfig) -> dict:
        """Fold per-shard sync configs (strategy.proto:46-50) into the plan.

        The reference rendered each shard of a partitioned variable as an
        independent variable with its own synchronizer, so shards could
        legitimately differ (partitioned_ps_strategy.py:104-121 gives each a
        different reduction destination). Under SPMD one variable lowers to
        ONE NamedSharding and one gradient wire, so the per-shard degrees of
        freedom fold: settings that must be uniform across a single wire
        (synchronizer kind, sync/staleness, compressor, local_replication)
        are validated uniform — heterogeneous values have no SPMD rendering
        and raise — and the uniform value *overrides* the node-level one
        (shard configs are the more specific contract). Exception: ``sync``
        is validated, never overridden — async PS is rejected loudly whether
        it appears at node or shard level (a shard-level ``sync=True`` does
        not resurrect an async node config).
        Per-shard destinations survive as the plan's ``shard_destinations``
        table. Per-shard ``group`` ids are advisory (see
        AllReduceSynchronizer.group) and are not required to agree.
        """
        parts = node.part_config
        folded: dict = {}
        if not parts:
            return folded
        if len(parts) != node.num_shards:
            # StrategyCompiler checks this too, but GraphTransformer also
            # lowers hand-built / deserialized strategies directly — a
            # mismatched table must not silently skew shard_destinations.
            raise ValueError(
                f"{node.var_name!r}: {len(parts)} part configs but "
                f"partitioner {node.partitioner!r} implies {node.num_shards}"
            )
        kinds = {type(p.synchronizer) for p in parts} | {type(node.synchronizer)}
        if len(kinds) > 1:
            raise ValueError(
                f"{node.var_name!r}: per-shard synchronizers mix "
                f"{sorted(k.__name__ for k in kinds)} — shards of one "
                f"variable share a single gradient wire under SPMD, so "
                f"heterogeneous synchronizer kinds have no rendering"
            )

        def uniform(field_name: str):
            vals = {getattr(p.synchronizer, field_name) for p in parts}
            if len(vals) > 1:
                raise ValueError(
                    f"{node.var_name!r}: per-shard {field_name} differs "
                    f"across shards ({sorted(map(str, vals))}) — one "
                    f"variable has one gradient wire under SPMD, so "
                    f"per-shard {field_name} must be uniform"
                )
            return vals.pop()

        if isinstance(node.synchronizer, PSSynchronizer):
            if not uniform("sync"):
                from autodist_tpu.strategy.base import check_sync_supported

                check_sync_supported(False)
            folded["staleness"] = uniform("staleness")
            folded["proxy"] = uniform("local_replication")
            folded["shard_destinations"] = tuple(
                p.synchronizer.reduction_destination for p in parts
            )
        else:
            # The schema has no "unset" sentinel for compressor, so a shard
            # table left at the default is indistinguishable from one that
            # explicitly chose NoneCompressor; treat default-valued parts as
            # deferring to the node-level choice (overriding would silently
            # strip an explicitly configured node-level compressor). A
            # non-default uniform part compressor wins as usual.
            part_comp = uniform("compressor")
            if part_comp != "NoneCompressor":
                folded["compressor"] = part_comp
            # Same default-ambiguity contract for shard_update (default
            # False): a uniform True overrides; uniform False defers to the
            # node level. One variable = one gradient wire, so a mixed
            # table raises in uniform().
            if uniform("shard_update"):
                folded["shard_update"] = True
        return folded

    def _lower_node(self, node: NodeConfig, var: VarItem) -> VarPlan:
        sync = node.synchronizer
        shard_ax = self._shard_axis_name()
        rank = len(var.shape)
        folded = self._fold_part_config(node)

        if isinstance(sync, AllReduceSynchronizer):
            kind = SyncKind.ALL_REDUCE
            compressor, group = folded.get("compressor", sync.compressor), sync.group
            staleness, dest, proxy = 0, "", False
            shard_update = folded.get("shard_update", sync.shard_update)
        else:
            assert isinstance(sync, PSSynchronizer)
            if not sync.sync:
                # Builders already reject async PS (base.check_sync_supported);
                # this guards hand-built / deserialized strategies so the knob
                # is never silently ignored.
                from autodist_tpu.strategy.base import check_sync_supported

                check_sync_supported(False)
            kind = SyncKind.PS
            compressor, group = "NoneCompressor", 0
            staleness = folded.get("staleness", sync.staleness)
            dest = sync.reduction_destination
            proxy = folded.get("proxy", sync.local_replication)
            shard_update = False

        mesh_shape = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        n_shard = mesh_shape[shard_ax]

        def divisible(axis: int) -> bool:
            # jax NamedSharding requires exact divisibility; non-divisible
            # axes (incl. UnevenPartitionedPS's deliberate non-divisor shard
            # counts) fall back to replication until pad-and-mask sharding
            # lands (SURVEY.md §7.4 item 5).
            ok = var.shape[axis] % n_shard == 0 and var.shape[axis] >= n_shard
            if not ok:
                logging.debug(
                    "var %s axis %d (size %d) not divisible by mesh axis %s=%d; "
                    "replicating instead",
                    var.name, axis, var.shape[axis], shard_ax, n_shard,
                )
            return ok

        def padded_storage(axis: int) -> Tuple[int, ...]:
            shape = list(var.shape)
            shape[axis] = -(-shape[axis] // n_shard) * n_shard  # ceil multiple
            return tuple(shape)

        storage_shape: Optional[Tuple[int, ...]] = None
        expert_ax = const.MESH_AXIS_EXPERT
        n_expert = mesh_shape.get(expert_ax, 1)
        part_axis = node.active_partition_axis
        if (
            var.expert and rank > 0 and n_expert > 1
            and var.shape[0] % n_expert == 0
        ):
            # Expert parallelism: the leading (expert) dim shards over the
            # expert axis; the expert einsums then keep tokens local after
            # the all_to_all dispatch GSPMD inserts.
            pspec = _spec_with_axis(rank, 0, expert_ax)
            update_pspec = pspec
        elif part_axis is not None and rank > 0 and divisible(part_axis):
            # Explicit partitioning: shard the parameter itself.
            pspec = _spec_with_axis(rank, part_axis, shard_ax)
            update_pspec = pspec
        elif part_axis is not None and rank > 0 and self._fallback_axis(var, n_shard) is not None:
            # Requested axis not divisible (UnevenPartitionedPS deliberately
            # picks non-divisor counts, uneven_partition_ps_strategy.py:
            # 128-137). XLA shardings must divide evenly, so the *intent*
            # (shard this variable) is honored on the largest divisible
            # axis instead of falling all the way back to replication.
            fb = self._fallback_axis(var, n_shard)
            logging.debug(
                "var %s: partition axis %d (size %d) not divisible by %d; "
                "sharding axis %d instead",
                var.name, part_axis, var.shape[part_axis], n_shard, fb,
            )
            pspec = _spec_with_axis(rank, fb, shard_ax)
            update_pspec = pspec
        elif part_axis is not None and rank > 0 and var.shape[part_axis] > n_shard:
            # No axis divides at all (e.g. a prime-sized dim): pad-and-mask
            # on the requested axis — store the parameter zero-padded to the
            # next multiple of the mesh axis, shard that, slice the logical
            # view for compute (SURVEY §7.4 item 5). Axes smaller than the
            # mesh degree keep replicating: padding them yields degenerate
            # sub-element shards for pure overhead.
            storage_shape = padded_storage(part_axis)
            logging.debug(
                "var %s: no divisible axis for %d shards; padding axis %d "
                "%d→%d and sharding it",
                var.name, n_shard, part_axis, var.shape[part_axis],
                storage_shape[part_axis],
            )
            pspec = _spec_with_axis(rank, part_axis, shard_ax)
            update_pspec = pspec
        elif var.sparse_update and rank > 0 and divisible(0):
            # Sparse path (PS *and* AllReduce): row-sharded embedding
            # (axis 0). Under PS this is the reference's sharded sparse
            # table (ps_synchronizer.py:473-532); under AllReduce it is the
            # TPU rendering of the reference's sparse all-gather sync
            # (all_reduce_synchronizer.py:129-169) — GSPMD turns the lookup
            # and its scatter-add gradient into tokens-sized collectives,
            # so sync wire scales with touched rows, never with the table
            # (a dense psum of the full table gradient is what a replicated
            # sparse var would cost).
            pspec = _spec_with_axis(rank, 0, shard_ax)
            update_pspec = pspec
        elif var.sparse_update and rank > 0 and var.shape[0] > n_shard:
            # Sparse tables need axis-0 (row) sharding for the gather/scatter
            # path regardless of divisibility — pad the rows (the GPT-2
            # prime-vocab case: 50257 rows divide nothing).
            storage_shape = padded_storage(0)
            pspec = _spec_with_axis(rank, 0, shard_ax)
            update_pspec = pspec
        elif kind is SyncKind.PS and rank > 0:
            # Dense PS: the proxy-variable knob (reference
            # proxy_variable.py:96-114) picks the parameter's residency.
            # With a proxy the reference cached a worker-local replica →
            # replicated param + sharded weight update (ZeRO-1,
            # arXiv 2004.13336). Without one, workers read the variable
            # from the PS on every use → fully sharded param with
            # all-gather on use (ZeRO-3), the SPMD rendering of that
            # remote-read-per-step placement.
            update_pspec = self._weight_update_spec(var)
            pspec = P() if proxy else update_pspec
        elif kind is SyncKind.ALL_REDUCE and shard_update and rank > 0:
            # ZeRO-1 for an AllReduce var (shard_update capability): the
            # parameter stays replicated — its uses are untouched — but the
            # optimizer slots and the update computation shard over the
            # data axis. The step's manual grad sync renders the gradient
            # reduction as reduce-scatter and the fresh values all-gather
            # back (arXiv 2004.13336; docs/zero.md).
            pspec = P()
            update_pspec = self._weight_update_spec(var)
        else:
            pspec = P()
            update_pspec = P()

        # shard_update activation: the ONE shared degradation predicate
        # (kernel/degrade.py) decides whether the request renders — the same
        # predicate the cost model prices by and the static analyzer
        # (autodist_tpu.analysis) treats as the declared-degradation list.
        # The structural rendering above must agree with it; divergence is a
        # lowering bug and fails loudly rather than desyncing the three.
        su_active = False
        degradations: Tuple[str, ...] = ()
        if kind is SyncKind.ALL_REDUCE and shard_update:
            from autodist_tpu.kernel.degrade import zero1_degradation_reasons

            degradations = zero1_degradation_reasons(
                var.shape,
                sparse_update=var.sparse_update,
                expert=var.expert,
                part_axis=part_axis,
                compressor=compressor,
                n_data=mesh_shape.get(data_axis(self.mesh), 1),
                n_model=mesh_shape.get(const.MESH_AXIS_MODEL, 1),
                n_expert=mesh_shape.get(expert_ax, 1),
            )
            su_active = not degradations
            structural = pspec == P() and update_pspec != P()
            if su_active != (structural and "compressed" not in degradations):
                raise RuntimeError(
                    f"var {var.name!r}: zero1 rendering "
                    f"(pspec={pspec}, update={update_pspec}) disagrees with "
                    f"degradation_reasons={degradations!r} — "
                    f"kernel/degrade.py and _lower_node have drifted"
                )
            if structural and "compressed" in degradations:
                # The compressed wire psums the FULL gradient inside its
                # manual region (_manual_sync_grads) — there is no
                # reduce-scatter to render, and a silently ineffective
                # shard_update would desync pricing from the program. The
                # compressor is the explicit opt-in; it wins.
                logging.warning(
                    "var %s: shard_update ignored — compressor %s syncs the "
                    "full gradient (no reduce-scatter rendering); optimizer "
                    "state stays replicated for this var",
                    var.name, compressor,
                )
                update_pspec = P()
            elif degradations:
                logging.debug(
                    "var %s: shard_update has no effect (%s)",
                    var.name, ", ".join(degradations),
                )

        shard_dests = folded.get("shard_destinations", ())
        # Reference parity: PS destinations are host CPUs; offload is opt-in
        # (True = every PS var) because HBM residency is usually faster on
        # TPU, or destination-driven ("from_strategy" = follow the strategy's
        # placement: offload exactly the vars whose reduction destination
        # names a CPU device).
        if kind is SyncKind.PS and self.host_offload:
            if self.host_offload == "from_strategy":
                # Shard destinations are the more specific contract: when
                # the table exists it decides placement (the node-level
                # destination may be stale relative to it, and the cost
                # model prices the shard table too); empty shard entries
                # fall back to the node-level destination.
                dests = [d or dest for d in shard_dests] if shard_dests else [dest]
                offload = any(_is_cpu_device(d) for d in dests if d)
            else:
                offload = True
        else:
            offload = False
        return VarPlan(
            var=var,
            kind=kind,
            pspec=pspec,
            update_pspec=update_pspec,
            compressor=compressor,
            group=group,
            staleness=staleness,
            reduction_destination=dest,
            local_replication=proxy,
            num_shards=node.num_shards,
            offload=offload,
            shard_destinations=shard_dests,
            storage_shape=storage_shape,
            shard_update=su_active,
            degradations=degradations,
        )

    @staticmethod
    def _fallback_axis(var: VarItem, n_shard: int):
        """Largest axis evenly divisible by ``n_shard``, or None."""
        cands = [
            i for i, d in enumerate(var.shape) if d % n_shard == 0 and d >= n_shard
        ]
        return max(cands, key=lambda i: var.shape[i]) if cands else None

    def _weight_update_spec(self, var: VarItem) -> P:
        """Largest axis divisible by the data-axis size, else replicated."""
        ax_name = data_axis(self.mesh)
        n = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))[ax_name]
        if n <= 1 or not var.shape:
            return P()
        candidates = [i for i, d in enumerate(var.shape) if d % n == 0 and d >= n]
        if not candidates:
            return P()
        best = max(candidates, key=lambda i: var.shape[i])
        return _spec_with_axis(len(var.shape), best, ax_name)


@dataclass(frozen=True)
class VarWire:
    """One variable's slice of the plan's promised collective wire — what
    the lowering COMMITS the compiled program to carrying for this var (see
    :meth:`ShardingPlan.promised_wire`). Consumed by the static analyzer's
    wire-conformance pass (``autodist_tpu.analysis.passes``)."""

    var: str
    rendering: str                      # zero1|sparse|expert|partitioned|...
    require: Tuple[str, ...] = ()       # op kinds that MUST appear
    allow: Tuple[str, ...] = ()         # kinds allowed at up-to-full payload
    storage_elements: int = 0
    storage_bytes: int = 0
    shard_update: bool = False
    sparse_row_sharded: bool = False
    compressor: str = "NoneCompressor"
    degradations: Tuple[str, ...] = ()
    # Backward-overlap bucket this var's gradient collective is emitted in
    # (kernel/bucketing.py; None = unbucketed post-backward sync), and the
    # bucket's summed payload — the per-bucket allowance the analyzer
    # attributes a combined/fused collective against.
    bucket: Optional[int] = None
    bucket_elements: int = 0


@dataclass
class ShardingPlan:
    """The lowered strategy: mesh + per-variable shardings."""

    mesh: Mesh
    var_plans: Dict[str, VarPlan]
    # Backward-overlap gradient bucketing target (bytes, 0 = disabled):
    # carried from Strategy.graph_config.bucket_bytes by the lowering; the
    # step, the cost model and the analyzer all derive the SAME assignment
    # from it via bucket_assignment().
    bucket_bytes: int = 0

    # --------------------------------------------------------------- lookups
    def plan_for(self, name: str) -> VarPlan:
        return self.var_plans[name]

    def bucket_assignment(self) -> Tuple[Tuple[str, ...], ...]:
        """Deterministic backward-overlap bucket partition of this plan's
        bucket-eligible variables (kernel/bucketing.py): reverse model
        order, greedy fill to ``bucket_bytes``. Empty when bucketing is
        disabled or nothing is eligible. The ONE assignment the step's
        emission, the analyzer's attribution and the cost model's overlap
        pricing share."""
        from autodist_tpu.kernel.bucketing import (
            assign_buckets,
            plan_exclusion_reasons,
        )

        if self.bucket_bytes <= 0:
            return ()
        sized = []
        for name, p in self.var_plans.items():
            if plan_exclusion_reasons(p):
                continue
            elems = 1
            for d in (p.storage_shape or tuple(p.var.shape) or (1,)):
                elems *= int(d)
            sized.append((name, elems * int(np.dtype(p.var.dtype).itemsize)))
        return assign_buckets(sized, self.bucket_bytes)

    @property
    def has_sparse_ps(self) -> bool:
        return any(
            p.kind is SyncKind.PS and p.var.sparse_update for p in self.var_plans.values()
        )

    def _sharding(self, pspec: P, offload: bool = False) -> NamedSharding:
        if offload:
            return NamedSharding(self.mesh, pspec, memory_kind="pinned_host")
        return NamedSharding(self.mesh, pspec)

    @property
    def has_offload(self) -> bool:
        return any(p.offload for p in self.var_plans.values())

    @property
    def has_padding(self) -> bool:
        return any(p.storage_shape is not None for p in self.var_plans.values())

    def _resize_state_tree(self, tree, to_storage: bool) -> Any:
        """Map padded↔logical shapes across any state-like pytree.

        Leaves are matched by var-name path suffix (the same rule
        ``opt_shardings`` uses, so params, optax slots and staleness buffers
        all match); a matched leaf whose *trailing* dims equal the source
        shape is padded/sliced on those dims, leading (buffer) dims pass
        through. Trace-safe (jnp.pad / lax.slice), so the storage→logical
        direction runs inside the jitted step. Identity without padding.
        """
        if not self.has_padding:
            return tree
        names = sorted(self.var_plans, key=len, reverse=True)

        def leaf_fn(path, leaf):
            leaf_name = _path_name(path)
            for n in names:
                if leaf_name != n and not leaf_name.endswith("/" + n):
                    continue
                plan = self.var_plans[n]
                if plan.storage_shape is None:
                    return leaf
                logical, storage = tuple(plan.var.shape), tuple(plan.storage_shape)
                src = logical if to_storage else storage
                dst = storage if to_storage else logical
                shape = tuple(getattr(leaf, "shape", ()))
                r = len(src)
                if len(shape) < r or shape[-r:] != src:
                    return leaf
                lead = shape[:-r]
                if to_storage:
                    pads = [(0, 0)] * len(lead) + [
                        (0, d - s) for d, s in zip(dst, src)
                    ]
                    return jnp.pad(jnp.asarray(leaf), pads)
                return lax.slice(
                    jnp.asarray(leaf),
                    [0] * len(shape),
                    list(lead) + list(dst),
                )
            return leaf

        return jax.tree_util.tree_map_with_path(leaf_fn, tree)

    def pad_params(self, params) -> Any:
        """Logical → storage view: zero-pad every leaf whose plan shards a
        non-divisible axis. No-op (identity tree) without padding."""
        return self._resize_state_tree(params, to_storage=True)

    def unpad_params(self, params) -> Any:
        """Storage → logical view: slice padded leaves back to the shapes the
        user's model defines."""
        return self._resize_state_tree(params, to_storage=False)

    def pad_state(self, state) -> Any:
        """Logical → storage view across a full state tree (params, optimizer
        slots, staleness buffers)."""
        return self._resize_state_tree(state, to_storage=True)

    def unpad_state(self, state) -> Any:
        """Storage → logical view across a full state tree — what checkpoints
        should contain so they restore into any sharding (the reference's
        original-name/shape contract, checkpoint/saver.py:50-57)."""
        return self._resize_state_tree(state, to_storage=False)

    # ------------------------------------------------------------- shardings
    def params_shardings(self, params, device_view: bool = False) -> Any:
        """Pytree of NamedShardings matching ``params`` (matched by path).

        ``device_view=True`` ignores host-offload markers — the sharding the
        parameter has *inside* the step after streaming into HBM.
        """
        leaves, treedef = jax.tree_util.tree_flatten_with_path(params)
        out = []
        for path, leaf in leaves:
            name = _path_name(path)
            plan = self.var_plans.get(name)
            pspec = plan.pspec if plan is not None else P()
            offload = plan.offload if plan is not None and not device_view else False
            out.append(self._sharding(pspec, offload))
        return jax.tree_util.tree_unflatten(treedef, out)

    def opt_shardings(self, opt_state_shapes, device_view: bool = False) -> Any:
        """Shardings for an optimizer-state pytree.

        Slot leaves are matched to variables by path suffix (optax states
        embed the params tree, e.g. ``0/mu/dense/kernel``); matched slots get
        the variable's ``update_pspec`` (weight-update sharding for PS vars,
        the param sharding for partitioned vars) and the variable's
        host-offload placement (slots are 1-2x the param bytes — leaving
        them in HBM would defeat the offload); unmatched leaves (step
        counts, scalars) are replicated on device.
        """
        names = sorted(self.var_plans, key=len, reverse=True)
        leaves, treedef = jax.tree_util.tree_flatten_with_path(opt_state_shapes)
        out = []
        for path, leaf in leaves:
            leaf_name = _path_name(path)
            spec, offload = P(), False
            for n in names:
                if leaf_name == n or leaf_name.endswith("/" + n):
                    plan = self.var_plans[n]
                    # Slots mirror the *storage* shape when the param is
                    # padded (optax init runs on the padded tree).
                    expect = plan.storage_shape or tuple(plan.var.shape)
                    if tuple(getattr(leaf, "shape", ())) == tuple(expect):
                        spec = plan.update_pspec
                        offload = plan.offload and not device_view
                    break
            out.append(self._sharding(spec, offload))
        return jax.tree_util.tree_unflatten(treedef, out)

    def batch_shardings(self, batch, strict: bool = True) -> Any:
        """Batch leaves sharded along the data axis on dim 0 (the remapper's
        feed-splitting contract, remapper.py:81-123). With ``strict=False``,
        non-divisible leading dims replicate instead of raising."""
        ax = data_axis(self.mesh)
        n = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))[ax]

        def leaf_sharding(leaf):
            shape = tuple(getattr(leaf, "shape", ()))
            if not is_broadcast_leaf(shape) and shape[0] % n == 0:
                return self._sharding(P(ax))
            # Broadcast leaves (attention masks, per-feature constants —
            # see is_broadcast_leaf) replicate without complaint.
            if not is_broadcast_leaf(shape) and shape[0] % n != 0 and strict:
                raise ValueError(
                    f"global batch dim {shape[0]} not divisible by data-parallel "
                    f"degree {n}"
                )
            return self._sharding(P())

        return jax.tree_util.tree_map(leaf_sharding, batch)

    def global_batch_from_local(self, local_batch, broadcast=None) -> Any:
        """Assemble per-process batch shards into global arrays (multi-host
        feed path — the remapper's feed-splitting contract in reverse,
        reference remapper.py:81-123: each host loads only its slice of the
        global batch, dim 0 concatenates across processes).

        ``broadcast`` optionally disambiguates leaves whose LOCAL leading dim
        is 1: a pytree of bools (same structure as ``local_batch``) marking
        leaves every process holds whole (replicated) rather than as a slice.
        Without it, local leading dim <= 1 is taken as broadcast — the
        framework convention (``is_broadcast_leaf``) — which mis-classifies a
        genuinely batched leaf whose per-process batch is exactly 1; callers
        that know the global shapes (e.g. the fleet-tune feed) should pass
        the mask.

        Single-process: equivalent to ``device_put`` with batch shardings.
        """
        if jax.process_count() == 1:
            return jax.device_put(local_batch, self.batch_shardings(local_batch, strict=False))

        n_proc = jax.process_count()
        if broadcast is None:
            broadcast = jax.tree_util.tree_map(
                lambda x: is_broadcast_leaf(np.shape(x)), local_batch
            )

        def global_shape_of(x, is_bcast) -> Tuple[int, ...]:
            shape = tuple(np.shape(x))
            # Broadcast (and rank-0) leaves are replicated: every process
            # holds the same value, so the global shape is the local shape.
            if not shape or is_bcast:
                return shape
            return (shape[0] * n_proc,) + shape[1:]

        def leaf_to_global(leaf, sharding, is_bcast):
            arr = np.asarray(leaf)
            if arr.ndim == 0:
                # Replicated scalar: every process holds the same value;
                # make_array_from_process_local_data has no dim to concat.
                return jax.make_array_from_callback((), sharding, lambda _: arr)
            return jax.make_array_from_process_local_data(
                sharding, arr, global_shape_of(arr, is_bcast))

        shardings = self.batch_shardings(
            jax.tree_util.tree_map(
                lambda x, b: jax.ShapeDtypeStruct(
                    global_shape_of(x, b),
                    getattr(x, "dtype", None) or np.asarray(x).dtype,
                ),
                local_batch, broadcast,
            ),
            strict=False,
        )
        return jax.tree_util.tree_map(
            leaf_to_global, local_batch, shardings, broadcast)

    def window_shardings(self, stacked_batch, strict: bool = True) -> Any:
        """Shardings for a prefetched data window: every leaf carries a
        leading (scan-step) axis that stays unsharded, and each per-step
        slice shards exactly as :meth:`batch_shardings` would shard it —
        including the strict default: a window is always TRAINING data, so
        a non-divisible slice dim should fail loudly, not silently
        replicate 8x redundant work per device."""
        slice_struct = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(
                tuple(x.shape)[1:], getattr(x, "dtype", None) or np.asarray(x).dtype
            ),
            stacked_batch,
        )
        slice_sh = self.batch_shardings(slice_struct, strict=strict)
        return jax.tree_util.tree_map(
            lambda s: self._sharding(P(None, *s.spec)), slice_sh)

    def window_from_local(self, stacked_local) -> Any:
        """Per-process stacked host window → device-resident global window.

        ``stacked_local`` leaves are ``[num_steps, local_batch, ...]`` (this
        process's slices of ``num_steps`` consecutive batches, stacked on a
        new leading axis). One transfer ships the whole window — the bridge
        between the DataLoader and ``run(stacked=True)``'s device-side scan,
        instead of paying per-step dispatch+transfer latency
        (docs/performance.md measures that pattern at ~11× slower here).

        Window leaves are batched by construction, so no broadcast-leaf
        ambiguity exists: dim 1 (after the step axis) always concatenates
        across processes.
        """
        if jax.process_count() == 1:
            return jax.device_put(
                stacked_local, self.window_shardings(stacked_local))

        n_proc = jax.process_count()

        def global_shape_of(x) -> Tuple[int, ...]:
            shape = tuple(np.shape(x))
            return (shape[0], shape[1] * n_proc) + shape[2:]

        shardings = self.window_shardings(
            jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(
                    global_shape_of(x),
                    getattr(x, "dtype", None) or np.asarray(x).dtype,
                ),
                stacked_local,
            )
        )
        return jax.tree_util.tree_map(
            lambda leaf, sh: jax.make_array_from_process_local_data(
                sh, np.asarray(leaf), global_shape_of(leaf)),
            stacked_local, shardings,
        )

    def comp_shardings(self, comp_state) -> Any:
        """Compressor-state shardings: per-worker ("local") leaves carry a
        leading data-axis dim and shard over it; "shared" leaves replicate."""
        ax = data_axis(self.mesh)
        leaves, treedef = jax.tree_util.tree_flatten_with_path(comp_state)
        out = []
        for path, _leaf in leaves:
            name = _path_name(path)
            spec = P(ax) if "/local/" in f"/{name}/" else P()
            out.append(self._sharding(spec))
        return jax.tree_util.tree_unflatten(treedef, out)

    def stale_shardings(self, stale_state) -> Any:
        """Gradient-delay buffers: the var's sharding behind a replicated
        leading (delay-depth) dim. (Staleness is a PS-only capability —
        the AR arm of ``_lower_node`` pins staleness=0 — so zero1
        shard_update vars never appear here.)"""
        out = {}
        for name, leaf in stale_state.items():
            pspec = self.var_plans[name].pspec if name in self.var_plans else P()
            out[name] = self._sharding(P(None, *pspec))
        return out

    def state_shardings(self, state_shapes: TrainState, device_view: bool = False) -> TrainState:
        return TrainState(
            step=self._sharding(P()),
            params=self.params_shardings(state_shapes.params, device_view=device_view),
            opt_state=self.opt_shardings(state_shapes.opt_state, device_view=device_view),
            comp_state=self.comp_shardings(state_shapes.comp_state),
            stale_state=self.stale_shardings(state_shapes.stale_state),
        )

    # -------------------------------------------------------- promised wire
    def promised_wire(self) -> Dict[str, "VarWire"]:
        """The collective wire this plan PROMISES, per variable — the
        contract the static analyzer (``autodist_tpu.analysis``) checks the
        compiled program against. Exported from the lowering (not re-derived
        in the analyzer) so the promise and the rendering can never drift:
        each :class:`VarWire` names the op kinds that must appear
        (``require``), the kinds this var's sync can legitimately emit at up
        to its full payload (``allow``), and the declared degradations.

        Renderings (mirroring ``_lower_node`` precedence):

        - ``zero1`` (shard_update active): reduce-scatter + all-gather are
          REQUIRED; an all-reduce carrying this var's full gradient is the
          regression GSPMD re-fusion produces (docs/zero.md);
        - ``sparse``: row-sharded table — wire must stay tokens-scale, so
          NOTHING is allowed at full-table payload;
        - ``expert`` / ``partitioned``: sharded param; gathers/reduces up to
          the storage size are the planned TP/EP wire (activation-scale
          all-to-all / collective-permute ride the activation allowance);
        - ``zero3`` (data-axis-sharded param): all-gather on use is
          required; this toolchain's GSPMD renders the grad reduce-scatter
          as all-reduce + slice, so full-size all-reduce is allowed;
        - ``ps1`` / ``replicated``: dense all-reduce wire at full payload.
        """
        ax_d = data_axis(self.mesh)
        sizes = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        # Bucket attribution: which backward-overlap bucket carries each
        # var's gradient collective, and the bucket's summed payload (the
        # allowance a combined per-bucket collective is checked against).
        bucket_of: Dict[str, int] = {}
        for bi, names in enumerate(self.bucket_assignment()):
            for n in names:
                bucket_of[n] = bi

        def axes_of(pspec: P):
            out = set()
            for e in tuple(pspec):
                if e is None:
                    continue
                for name in (e if isinstance(e, tuple) else (e,)):
                    out.add(name)
            return out

        wires: Dict[str, VarWire] = {}
        for name, p in self.var_plans.items():
            elems = 1
            for d in (p.storage_shape or tuple(p.var.shape) or (1,)):
                elems *= int(d)
            axes = {a for a in axes_of(p.pspec) if sizes.get(a, 1) > 1}
            if not p.var.trainable:
                rendering, require, allow = "nontrainable", (), ()
            elif p.shard_update:
                rendering = "zero1"
                require = ("reduce-scatter", "all-gather")
                allow = ("reduce-scatter", "all-gather")
            elif p.var.sparse_update and axes:
                rendering, require, allow = "sparse", (), ()
            elif const.MESH_AXIS_EXPERT in axes:
                rendering, require = "expert", ()
                allow = ("all-reduce", "all-gather", "all-to-all")
            elif ax_d in axes:
                rendering = "zero3"
                require = ("all-gather",) if sizes.get(ax_d, 1) > 1 else ()
                allow = ("all-gather", "reduce-scatter", "all-reduce")
            elif axes:
                rendering, require = "partitioned", ()
                allow = ("all-gather", "reduce-scatter", "all-reduce")
            elif p.kind is SyncKind.PS:
                rendering, require = "ps1", ()
                allow = ("all-reduce", "all-gather")
            else:
                rendering, require = "replicated", ()
                allow = ("all-reduce", "all-gather")
            wires[name] = VarWire(
                var=name,
                rendering=rendering,
                require=require,
                allow=allow,
                storage_elements=elems,
                storage_bytes=elems * int(np.dtype(p.var.dtype).itemsize),
                shard_update=p.shard_update,
                sparse_row_sharded=(p.var.sparse_update and bool(axes)),
                compressor=p.compressor,
                degradations=p.degradations,
                bucket=bucket_of.get(name),
            )
        if bucket_of:
            # Per-bucket summed payload: a combined collective for bucket i
            # may legitimately carry up to this many elements.
            bucket_sums: Dict[int, int] = {}
            for name, bi in bucket_of.items():
                bucket_sums[bi] = (bucket_sums.get(bi, 0)
                                   + wires[name].storage_elements)
            for name, bi in bucket_of.items():
                wires[name] = _dc_replace(
                    wires[name], bucket_elements=bucket_sums[bi])
        return wires

    def describe(self) -> str:
        lines = [f"ShardingPlan(mesh={dict(zip(self.mesh.axis_names, self.mesh.devices.shape))})"]
        for name, p in self.var_plans.items():
            lines.append(
                f"  {name}: {p.kind.value} param={p.pspec} update={p.update_pspec}"
                + (" shard_update=zero1" if p.shard_update else "")
                + (f" dest={p.reduction_destination}" if p.reduction_destination else "")
                + (f" shard_dests={list(p.shard_destinations)}"
                   if p.shard_destinations else "")
                + (" offload=pinned_host" if p.offload else "")
            )
        return "\n".join(lines)


# Param names are matched by string equality against ModelItem's names, so
# both sides must use the one path-to-name implementation.
_path_name = _path_to_name


def _stream(tree, marker_shardings, target_shardings):
    """device_put only the leaves whose marker sharding is host-placed."""
    def leaf(x, marker, target):
        if getattr(marker, "memory_kind", None) == "pinned_host":
            return jax.device_put(x, target)
        return x

    return jax.tree_util.tree_map(leaf, tree, marker_shardings, target_shardings)


class DistributedTrainStep:
    """Compiled distributed train step — the ``WrappedSession`` analog
    (reference runner.py:117-132): users call it like the single-device step;
    sharding, collectives and device placement are invisible.
    """

    def __init__(
        self,
        plan: ShardingPlan,
        loss_fn: Callable,
        optimizer: optax.GradientTransformation,
        has_aux: bool = False,
        donate_state: bool = True,
        grad_accum_steps: int = 1,
        record_norms: bool = False,
    ):
        self.plan = plan
        # Under pad-and-mask sharding the step's param tree is the padded
        # STORAGE view; the user's loss always sees the sliced logical view.
        # Slicing's autodiff transpose zero-pads the gradients, so padded
        # entries never move (elementwise optimizers; factored ones like
        # adafactor mix padding zeros into their row/col statistics — a
        # small, documented perturbation).
        if plan.has_padding:
            self.loss_fn = lambda p, b: loss_fn(plan.unpad_params(p), b)
        else:
            self.loss_fn = loss_fn
        self.tx = optimizer
        self.has_aux = has_aux
        self._donate = donate_state
        # Flight-recorder telemetry (docs/observability.md): global grad /
        # update norms in the step metrics — two extra reductions per step
        # (cheap next to the backward), opt-in because they change the
        # metrics pytree shape callers may have pinned.
        self._record_norms = bool(record_norms)
        if grad_accum_steps < 1:
            raise ValueError(f"grad_accum_steps must be >= 1, got {grad_accum_steps}")
        self._accum = grad_accum_steps
        self._compiled = None
        self._compiled_runs: Dict[Any, Any] = {}
        self._compiled_eval: Dict[Any, Any] = {}
        # Fresh-program first-call latencies (compile happens synchronously
        # inside that call): the obs StepProfiler's compile count/time feed.
        self.compile_log: List[Dict[str, Any]] = []
        self._state_shardings = None
        self._compressors = self._resolve_compressors(plan)
        # ZeRO-1 (shard_update) vars: gradient sync rendered manually as
        # reduce-scatter inside the shard_map region (the toolchain's GSPMD
        # pass renders a psum + sliced consumer as all-reduce +
        # dynamic-slice, which pays full wire AND forfeits the pinned
        # reduce-scatter evidence), update computed on the 1/N shard,
        # params re-gathered by the output shardings.
        self._shard_update = {
            name: p for name, p in plan.var_plans.items() if p.shard_update
        }
        self._stale = {
            name: p.staleness
            for name, p in plan.var_plans.items()
            if p.staleness > 0
        }
        # Backward-overlap gradient bucketing (kernel/bucketing.py): the
        # plan's deterministic assignment, emitted as per-bucket collectives
        # INSIDE the backward via custom_vjp hooks so XLA's latency-hiding
        # scheduler can overlap the wire with backward compute. Disabled
        # under gradient accumulation: per-microbatch emission would
        # multiply the wire by k and reassociate the mean.
        self._buckets: Tuple[Tuple[str, ...], ...] = ()
        if plan.bucket_bytes > 0:
            if self._accum > 1:
                logging.warning(
                    "bucketed grad sync (bucket_bytes=%d) disabled under "
                    "grad_accum_steps=%d: collectives must fire once per "
                    "step, after accumulation", plan.bucket_bytes,
                    self._accum)
            else:
                self._buckets = plan.bucket_assignment()

    @staticmethod
    def _resolve_compressors(plan: ShardingPlan):
        """var name → Compressor for vars whose strategy asks for one.

        Compression wraps the data-axis gradient psum, so it applies only to
        vars not sharded over the data axis (matching the reference, where
        compressors exist only on the dense AllReduce path,
        compressor.py:146-201); others are skipped with a warning.
        Model/seq/expert-sharded vars compress fine: the compressed sync is
        manual over the data axis only, with other mesh axes left to GSPMD
        (partial-manual shard_map).
        """
        from autodist_tpu.kernel.compressor import (
            get_compressor,
            is_active_compressor,
        )

        ax = data_axis(plan.mesh)
        sizes = dict(zip(plan.mesh.axis_names, plan.mesh.devices.shape))
        mixed_mesh = any(v > 1 for k, v in sizes.items() if k != ax)
        platform = plan.mesh.devices.flat[0].platform
        out = {}
        for name, p in plan.var_plans.items():
            if not is_active_compressor(p.compressor):
                continue
            if any(e == ax or (isinstance(e, tuple) and ax in e) for e in p.pspec):
                logging.warning(
                    "compressor %s on %s ignored: var is sharded over the data "
                    "axis (sparse/ZeRO path has no gradient all-reduce to "
                    "compress). NOTE: with any compressor active this var "
                    "enters the compressed grad region replicated, so its "
                    "sync pays full-size (table-scale) wire — avoid "
                    "compressors on embedding-heavy AllReduce models",
                    p.compressor, name,
                )
                continue
            comp = get_compressor(p.compressor)
            if (
                mixed_mesh
                and platform == "cpu"
                and getattr(comp, "wire_dtype", None) not in (None, jnp.float32)
            ):
                # XLA's CPU pipeline (AllReducePromotion/ChangeOpDataType)
                # check-fails cloning a bf16 all-reduce inside a
                # partial-manual region ("Invalid binary instruction opcode
                # copy"). TPU handles bf16 collectives natively; on the CPU
                # test backend keep the semantics and drop only the wire
                # narrowing.
                logging.warning(
                    "compressor %s on %s: bf16 collective unsupported by the "
                    "CPU backend inside a partial-manual region; wire stays "
                    "f32 here (TPU runs the narrow wire)", p.compressor, name,
                )
                comp.wire_dtype = jnp.float32
            out[name] = comp
        return out

    # ------------------------------------------------------------------ init
    def init(self, params) -> TrainState:
        """Build + shard the initial state (runs the reference's "run
        initializers on session creation", runner.py:86-100).

        Copies param leaves: the returned state's buffers are donated on each
        step, and ``device_put`` may alias the caller's arrays when shardings
        already match — donation must never invalidate user-held arrays.
        """
        params = jax.tree.map(
            lambda x: jnp.array(x, copy=True) if isinstance(x, jax.Array) else jnp.asarray(x),
            params,
        )
        # Pad-and-mask storage view (no-op without padded plans). jnp.pad
        # also makes a copy, satisfying the donation-safety contract above.
        params = self.plan.pad_params(params)
        state = TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=self.tx.init(params),
            comp_state=self._init_comp_state(),
            stale_state=self._init_stale_state(params),
        )
        shardings = self.plan.state_shardings(jax.eval_shape(lambda: state))
        self._state_shardings = shardings
        return jax.device_put(state, shardings)

    def logical_params(self, state: TrainState):
        """The user-shaped parameter view of a train state — identical to
        ``state.params`` except under pad-and-mask sharding, where the padded
        storage is sliced back to the model's shapes."""
        return self.plan.unpad_params(state.params)

    def logical_state(self, state: TrainState) -> TrainState:
        """Checkpoint view of a train state: every leaf (params, optimizer
        slots, staleness buffers) in its logical shape. Identity when the
        plan has no padding, so ``saver.save(step.logical_state(state))`` is
        always the right call — the written checkpoint restores into any
        sharding, padded or not (the reference's original-name/shape
        contract, checkpoint/saver.py:50-57). ``init_or_restore`` re-pads on
        the way back in."""
        return self.plan.unpad_state(state)

    def _init_comp_state(self):
        """Compressor persistence: {"<var>": {"local": ..., "shared": ...}}.
        Local (per-worker) entries are stacked with a leading data-axis dim —
        one residual per data shard (each reference worker kept its own
        ``error`` tensor)."""
        if not self._compressors:
            return {}
        n = dict(zip(self.plan.mesh.axis_names, self.plan.mesh.devices.shape))[
            data_axis(self.plan.mesh)
        ]
        comp_state = {}
        for name, comp in self._compressors.items():
            var = self.plan.var_plans[name].var
            local = comp.init_local(var)
            comp_state[name] = {
                "local": jax.tree.map(
                    lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), local
                ),
                "shared": comp.init_shared(var),
            }
        return comp_state

    # ------------------------------------------------------------------ step
    def _init_stale_state(self, params):
        """Zero-filled [K, ...] delay buffer per stale var."""
        if not self._stale:
            return {}
        buffers = {}
        leaves, _ = jax.tree_util.tree_flatten_with_path(params)
        by_name = {_path_name(p): leaf for p, leaf in leaves}
        for name, k in self._stale.items():
            leaf = by_name[name]
            buffers[name] = jnp.zeros((k,) + tuple(leaf.shape), leaf.dtype)
        return buffers

    def _apply_staleness(self, grads, stale_state):
        """Swap each stale var's fresh gradient for the K-step-old one.

        The fresh grad enters the buffer tail; the head (computed K steps
        ago) is what the optimizer sees — so updates lag exactly
        ``staleness`` steps, the deterministic rendering of the reference's
        ≤K bound (its staleness queues let the chief run ahead by at most K
        tokens). The first K steps apply zero gradient (buffers start
        empty), matching "workers proceed before the server has aggregated".
        """
        leaves, treedef = jax.tree_util.tree_flatten_with_path(grads)
        new_bufs = dict(stale_state)
        out = []
        for path, g in leaves:
            name = _path_name(path)
            if name in new_bufs:
                buf = new_bufs[name]
                delayed = buf[0]
                new_bufs[name] = jnp.concatenate([buf[1:], g[None]], axis=0)
                g = delayed
            out.append(g)
        return jax.tree_util.tree_unflatten(treedef, out), new_bufs

    def _step(self, state: TrainState, batch):
        host_shardings = None
        if self.plan.has_offload:
            # Weight streaming: offloaded leaves live in pinned host memory
            # between steps; stream them into HBM for compute and back out
            # after the update. Only offloaded leaves get device_put —
            # annotating already-on-device leaves (e.g. the step scalar)
            # trips the SPMD partitioner's side-effect sharding check.
            shapes = jax.eval_shape(lambda: state)
            host_shardings = self.plan.state_shardings(shapes)
            device_shardings = self.plan.state_shardings(shapes, device_view=True)
            state = _stream(state, host_shardings, device_shardings)
        if self._compressors or self._shard_update or self._buckets:
            loss, aux, grads, new_comp = self._manual_sync_grads(state, batch)
        elif self._accum > 1:
            loss, aux, grads = self._accumulated_grads(state.params, batch)
            new_comp = state.comp_state
        else:
            if self.has_aux:
                (loss, aux), grads = jax.value_and_grad(self.loss_fn, has_aux=True)(
                    state.params, batch
                )
            else:
                loss, grads = jax.value_and_grad(self.loss_fn)(state.params, batch)
                aux = None
            new_comp = state.comp_state
        new_stale = state.stale_state
        if self._stale:
            grads, new_stale = self._apply_staleness(grads, state.stale_state)
        updates, new_opt = self.tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        if self._shard_update:
            new_params = self._gather_updated_params(new_params)
        new_state = TrainState(
            step=state.step + 1, params=new_params, opt_state=new_opt,
            comp_state=new_comp, stale_state=new_stale,
        )
        if host_shardings is not None:
            new_state = _stream(new_state, host_shardings, host_shardings)
        metrics = {"loss": loss}
        if aux is not None:
            metrics["aux"] = aux
        if self._record_norms:
            # Global (all-leaf) L2 norms: the NaN/explosion signal the obs
            # sentry watches (SNT002). optax.global_norm handles ragged
            # pytrees; sharded leaves are fine — the norm is computed under
            # the same shardings as the update itself.
            metrics["grad_norm"] = optax.global_norm(grads)
            metrics["update_norm"] = optax.global_norm(updates)
        return new_state, metrics

    def _gather_updated_params(self, params):
        """Re-gather zero1 (shard_update) parameters to their replicated
        residency after the sharded update — the all-gather leg of
        reduce-scatter → sharded update → all-gather (arXiv 2004.13336).
        The explicit constraint (under a named scope, so profiles attribute
        the collective) pins the gather HERE; without it the output
        shardings would still force one, but anonymously at program exit."""
        leaves, treedef = jax.tree_util.tree_flatten_with_path(params)
        out = []
        with jax.named_scope(bucketing.ZERO1_ALL_GATHER_SCOPE):
            for path, leaf in leaves:
                plan = self._shard_update.get(_path_name(path))
                if plan is not None:
                    leaf = lax.with_sharding_constraint(
                        leaf, NamedSharding(self.plan.mesh, plan.pspec))
                out.append(leaf)
        return jax.tree_util.tree_unflatten(treedef, out)

    # --------------------------------------------- gradient accumulation
    def _accumulated_grads(self, params, batch):
        """Microbatched gradients: split the batch dim into ``_accum``
        slices, scan, and average — activation memory drops ~k× while the
        update equals the full-batch step exactly (for batch-mean losses,
        the zoo's convention). Loss and aux metrics come back averaged over
        microbatches (so sum-style aux reports the per-micro mean, in f32). This is the memory side of what the
        reference's per-variable ``ConditionalAccumulator`` did across
        workers (ps_synchronizer.py:553-630), rendered as a deterministic
        on-device loop.
        """
        k = self._accum
        ax = data_axis(self.plan.mesh)
        n = dict(zip(self.plan.mesh.axis_names, self.plan.mesh.devices.shape))[ax]

        for leaf in jax.tree.leaves(batch):
            shape = getattr(leaf, "shape", ())
            # Broadcast leaves replicate (is_broadcast_leaf — the same
            # tolerance as batch_shardings); batched leaves must split
            # evenly.
            if not is_broadcast_leaf(shape) and shape[0] % k != 0:
                raise ValueError(
                    f"grad_accum_steps={k} requires every batched leaf's "
                    f"leading dim to be divisible by {k}; got shape {shape}")

        def to_micro(x):
            # [B, ...] -> [k, B/k, ...]; keep the micro batch dim sharded on
            # the data axis exactly where the plan would shard the full
            # batch (one all-to-all on the feed, versus resharding the
            # whole activation set every micro-step). Rank-0 and broadcast
            # leaves ride along whole, one copy per micro-step.
            shape = tuple(getattr(x, "shape", ()))
            if is_broadcast_leaf(shape):
                m = jnp.broadcast_to(jnp.asarray(x)[None], (k,) + shape)
                return lax.with_sharding_constraint(
                    m, NamedSharding(self.plan.mesh, P()))
            m = x.reshape((k, x.shape[0] // k) + x.shape[1:])
            if m.shape[1] % n == 0 and m.shape[1] > 0:
                spec = P(None, ax)
            else:
                logging.warning(
                    "grad_accum_steps=%d: micro batch dim %d not divisible "
                    "by data-parallel degree %d — micro batches replicate "
                    "and every device computes the full gradient redundantly",
                    k, m.shape[1], n,
                )
                spec = P()
            return lax.with_sharding_constraint(
                m, NamedSharding(self.plan.mesh, spec))

        micro_batches = jax.tree.map(to_micro, batch)

        def grads_fn(p, mb):
            if self.has_aux:
                (loss, aux), grads = jax.value_and_grad(
                    self.loss_fn, has_aux=True)(p, mb)
                return loss, aux, grads
            loss, grads = jax.value_and_grad(self.loss_fn)(p, mb)
            return loss, None, grads

        return self._scan_accumulate(grads_fn, params, micro_batches, k)

    def _scan_accumulate(self, grads_fn, params, micro_batches, k):
        """Shared microbatch-accumulation core (plain and compressed paths):
        scan ``grads_fn`` over the leading ``k`` dim, averaging loss, aux
        (promoted to ≥f32 — ``a + x/k`` needs a dtype-stable carry) and
        grads."""
        zero_grads = jax.tree.map(jnp.zeros_like, params)
        if self.has_aux:
            micro0 = jax.tree.map(lambda x: x[0], micro_batches)
            aux_shape = jax.eval_shape(lambda: self.loss_fn(params, micro0)[1])
            zero_aux = jax.tree.map(
                lambda s: jnp.zeros(s.shape, jnp.promote_types(s.dtype, jnp.float32)),
                aux_shape)
        else:
            zero_aux = None

        def body(carry, mb):
            loss_acc, grads_acc, aux_acc = carry
            loss, aux, grads = grads_fn(params, mb)
            grads_acc = jax.tree.map(lambda a, g: a + g / k, grads_acc, grads)
            if aux is not None:
                aux_acc = jax.tree.map(lambda a, x: a + x / k, aux_acc, aux)
            return (loss_acc + loss / k, grads_acc, aux_acc), None

        (loss, grads, aux), _ = lax.scan(
            body, (jnp.zeros((), jnp.float32), zero_grads, zero_aux),
            micro_batches,
        )
        return loss, aux, grads

    # ---------------------------------------------- manual gradient sync
    def _manual_sync_grads(self, state: TrainState, batch):
        """Gradient sync with an explicit per-variable wire: compression,
        zero1 reduce-scatter, and/or bucketed backward-overlap emission
        around the data-axis psum.

        Runs the loss/grad computation inside a ``shard_map`` that is manual
        over the data axis only: each instance sees its local batch shard,
        computes local-mean grads, and each var picks its wire —

        - bucketed vars (``plan.bucket_assignment()`` non-empty): the
          collective is emitted INSIDE the backward pass by the bucket's
          ``custom_vjp`` hook (kernel/bucketing.py, ``gradsync.bucket_{i}``
          named scopes) — same per-var op (psum / psum_scatter), moved to
          the bucket's layer-group boundary so XLA's latency-hiding
          scheduler overlaps it with the remaining backward compute; the
          trailing loop only re-slices zero1 shards;
        - compressed vars: the compressor's compress → psum → decompress
          sequence (the collective runs on compressed payloads — the
          reference wrapped ``collective_ops.all_reduce`` the same way);
        - ``shard_update`` (zero1) vars: ``lax.psum_scatter`` over the data
          axis, so each instance exits with its 1/N reduce-scattered
          gradient slice (arXiv 2004.13336) — the optimizer update outside
          the region then runs sharded and the output shardings all-gather
          the fresh params;
        - everything else: a plain ``lax.psum``.

        Model/other mesh axes stay GSPMD-auto (partial-manual mode), so
        tensor-parallel vars keep their shardings; on a pure-DP mesh the
        region runs fully manual over a flat data-only mesh view (identical
        device order), which keeps the long-tested full-manual lowering on
        the bench path.

        Assumes ``loss_fn`` computes a *mean* over the batch (the reference's
        merge=Add final=Div semantics, all_reduce_synchronizer.py:100-126).
        """
        from autodist_tpu.utils.compat import shard_map

        mesh = self.plan.mesh
        ax = data_axis(mesh)
        n = dict(zip(mesh.axis_names, mesh.devices.shape))[ax]
        if n == mesh.devices.size:
            # Pure DP: flat full-manual view, device order unchanged.
            mesh = Mesh(mesh.devices.reshape(-1), (ax,))
        compressors = self._compressors
        # zero1 vars: data-axis index of their scatter dimension, taken from
        # the plan's update spec (always divisible — _weight_update_spec
        # only picks divisible axes).
        su_dims = {
            name: list(p.update_pspec).index(ax)
            for name, p in self._shard_update.items()
        }

        # Every parameter enters the manual region REPLICATED over the data
        # axis (shard_map all-gathers data-sharded leaves at entry): the
        # user's loss indexes and matmuls against full-shaped parameters, so
        # feeding a data-row-sliced leaf (e.g. a row-sharded embedding, or a
        # ZeRO-sharded kernel) would silently compute garbage — jnp.take
        # clamps out-of-range ids instead of failing. Grads exit replicated
        # too (each instance psums the full gradient) EXCEPT zero1 vars,
        # whose reduce-scattered slice exits sharded on its scatter dim;
        # GSPMD reshards everything onto the plan's update shardings at the
        # region boundary.
        param_specs = jax.tree_util.tree_map(lambda _: P(), state.params)
        g_spec_leaves, g_spec_treedef = jax.tree_util.tree_flatten_with_path(
            state.params)
        grad_specs = jax.tree_util.tree_unflatten(
            g_spec_treedef,
            [
                (self._shard_update[_path_name(path)].update_pspec
                 if _path_name(path) in self._shard_update else P())
                for path, _ in g_spec_leaves
            ],
        )

        def spec_for_batch(leaf):
            shape = tuple(getattr(leaf, "shape", ()))
            return P(ax) if len(shape) >= 1 and shape[0] % n == 0 and shape[0] > 0 else P()

        batch_specs = jax.tree_util.tree_map(spec_for_batch, batch)

        c_leaves, c_treedef = jax.tree_util.tree_flatten_with_path(state.comp_state)
        comp_specs = jax.tree_util.tree_unflatten(
            c_treedef,
            [
                P(ax) if "/local/" in f"/{_path_name(path)}/" else P()
                for path, _ in c_leaves
            ],
        )

        loss_fn, has_aux, k = self.loss_fn, self.has_aux, self._accum

        # Backward-overlap buckets: wrap the loss so each bucket's params
        # pass through an identity custom_vjp whose backward rule emits the
        # bucket's collectives mid-backward (kernel/bucketing.py). Names
        # are filtered to leaves actually present in the params tree so a
        # hook's arg list always zips exactly with its cotangents.
        p_leaves, _ = jax.tree_util.tree_flatten_with_path(state.params)
        present = {_path_name(path) for path, _ in p_leaves}
        buckets = tuple(
            b for b in (
                tuple(nm for nm in names if nm in present)
                for names in self._buckets)
            if b)
        bucketed = {nm for names in buckets for nm in names}
        if buckets:
            hooks = [
                bucketing.make_bucket_hook(i, names, su_dims, ax, n)
                for i, names in enumerate(buckets)
            ]
            inner_loss_fn = loss_fn

            def loss_fn(p, b):  # noqa: F811 - deliberate hooked rebind
                leaves, treedef = jax.tree_util.tree_flatten_with_path(p)
                vals = [leaf for _, leaf in leaves]
                idx_of = {
                    _path_name(path): j for j, (path, _) in enumerate(leaves)
                }
                for hook, names in zip(hooks, buckets):
                    idxs = [idx_of[nm] for nm in names]
                    outs = hook(*[vals[j] for j in idxs])
                    for j, o in zip(idxs, outs):
                        vals[j] = o
                return inner_loss_fn(
                    jax.tree_util.tree_unflatten(treedef, vals), b)

        if k > 1:
            # Validate (and later microbatch) ONLY the leaves the region
            # data-shards; replicated leaves (broadcast masks, scalars —
            # the spec_for_batch P() cases) ride through whole.
            for leaf in jax.tree.leaves(batch):
                shape = tuple(getattr(leaf, "shape", ()))
                if (
                    len(shape) >= 1 and shape[0] > 0 and shape[0] % n == 0
                    and (shape[0] // n) % k != 0
                ):
                    raise ValueError(
                        f"grad_accum_steps={k} with a manual gradient sync "
                        f"(compression and/or zero1 shard_update) requires "
                        f"each data shard's batch slice (global {shape[0]} "
                        f"/ {n} shards) to split into {k} microbatches; "
                        f"got shape {shape}")

        def local_grads(params, local_batch):
            if has_aux:
                (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                    params, local_batch
                )
            else:
                loss, grads = jax.value_and_grad(loss_fn)(params, local_batch)
                aux = None
            return loss, aux, grads

        # Which leaves arrive data-sliced inside the manual region (the
        # others — broadcast masks, scalars — arrive whole and must not be
        # split along their leading dim).
        sharded_leaf = jax.tree_util.tree_map(
            lambda s: s == P(ax), batch_specs
        )

        def local_fn(params, local_batch, comp_state):
            if k > 1:
                # Microbatch INSIDE the manual region: accumulate local-mean
                # grads over a scan (the shared _scan_accumulate core), then
                # compress + psum once — activation memory ÷ k with a single
                # compressed collective per step.
                def to_micro(x, is_sharded):
                    if is_sharded and getattr(x, "ndim", 0) >= 1:
                        return x.reshape((k, x.shape[0] // k) + x.shape[1:])
                    return jnp.broadcast_to(
                        jnp.asarray(x)[None],
                        (k,) + tuple(getattr(x, "shape", ())))

                micro = jax.tree.map(to_micro, local_batch, sharded_leaf)
                loss, aux, grads = self._scan_accumulate(
                    local_grads, params, micro, k)
            else:
                loss, aux, grads = local_grads(params, local_batch)
            loss = bucketing.psum_mean(loss, ax, n)
            if aux is not None:
                aux = jax.tree.map(
                    lambda x: bucketing.psum_mean(x, ax, n), aux)
            g_leaves, g_treedef = jax.tree_util.tree_flatten_with_path(grads)
            new_comp = dict(comp_state)
            synced = []
            for path, g in g_leaves:
                name = _path_name(path)
                if name in bucketed:
                    if name in su_dims:
                        # Bucketed zero1: the reduce-scatter already fired
                        # inside the backward (gradsync.bucket_i scope);
                        # extract this instance's shard from the hook's
                        # re-embedded full-shape buffer (bit-exact).
                        with jax.named_scope(
                                bucketing.GRADSYNC_SHARD_SLICE_SCOPE):
                            synced.append(bucketing.slice_update_shard(
                                g, ax, n, su_dims[name]))
                    else:
                        # Plain AR bucketed var: already psum'd mid-backward.
                        synced.append(g)
                    continue
                if name in su_dims:
                    # zero1: one reduce-scatter replaces the all-reduce —
                    # this instance keeps only its 1/n gradient slice, which
                    # is exactly what its optimizer-state shard consumes.
                    with jax.named_scope(
                            bucketing.ZERO1_REDUCE_SCATTER_SCOPE):
                        synced.append(bucketing.reduce_scatter_grad(
                            g, ax, n, su_dims[name]))
                    continue
                comp = compressors.get(name)
                if comp is None:
                    synced.append(bucketing.psum_mean(g, ax, n))
                    continue
                # Local state arrives as the (1, ...) slice of the stacked
                # per-shard leaves; unwrap, step, rewrap.
                local = jax.tree.map(lambda x: x[0], comp_state[name]["local"])
                g_hat, new_local, new_shared = comp.step(
                    g, local, comp_state[name]["shared"], axis=ax, nshards=n
                )
                new_comp[name] = {
                    "local": jax.tree.map(lambda x: x[None], new_local),
                    "shared": new_shared,
                }
                synced.append(g_hat)
            grads = jax.tree_util.tree_unflatten(g_treedef, synced)
            return loss, aux, grads, new_comp

        sm = shard_map(
            local_fn,
            mesh=mesh,
            in_specs=(param_specs, batch_specs, comp_specs),
            out_specs=(P(), P(), grad_specs, comp_specs),
            axis_names={ax},
            check_vma=False,
        )
        return sm(state.params, batch, state.comp_state)

    def _compile(self, state: TrainState, batch):
        if self._state_shardings is None:
            self._state_shardings = self.plan.state_shardings(jax.eval_shape(lambda: state))
        in_shardings = (self._state_shardings, self.plan.batch_shardings(batch))
        out_shardings = (self._state_shardings, None)
        self._compiled = jax.jit(
            self._step,
            in_shardings=in_shardings,
            out_shardings=out_shardings,
            donate_argnums=(0,) if self._donate else (),
        )
        if const.ENV.AUTODIST_DUMP_HLO.val:
            # Per-stage compile snapshots (the reference dumped its graph to
            # TensorBoard at each transform stage, graph_transformer.py:62-90).
            from autodist_tpu.utils import tracing

            lowered = self._compiled.lower(state, batch)
            tracing.dump_compiled("train_step", lowered, lowered.compile())
        return self._compiled

    # ------------------------------------------------------------- multi-step
    def run(self, state: TrainState, batch, num_steps: int,
            stacked: bool = False, _force_unroll: bool = False):
        """Execute ``num_steps`` train steps as ONE compiled device program
        (``lax.scan`` over the step body).

        The reference's per-step ``session.run`` was cheap because its hot
        loop lived inside TF's C++ runtime (SURVEY §3.4); the TPU analog is
        keeping the loop on device — one dispatch per *window*, amortizing
        host latency and param transfers that per-step dispatch pays every
        step.

        ``stacked=False`` (default): ``batch`` is a single batch pytree,
        re-used each step (benchmarking / steady-state input).
        ``stacked=True``: every ``batch`` leaf carries a leading
        ``num_steps`` axis — a prefetched data window, one slice per step.
        The flag is explicit because shape inference is ambiguous (a batch
        whose leading dim happens to equal ``num_steps`` is a valid single
        batch). Returns ``(state, metrics)`` with per-step stacked metric
        leaves (``metrics["loss"].shape == (num_steps,)``).
        """
        if stacked:
            for leaf in jax.tree.leaves(batch):
                if getattr(leaf, "ndim", 0) < 1 or leaf.shape[0] != num_steps:
                    raise ValueError(
                        f"stacked=True requires every batch leaf to have "
                        f"leading dim num_steps={num_steps}; got shape "
                        f"{getattr(leaf, 'shape', ())}")
        key = (int(num_steps), stacked, _force_unroll)
        fresh = key not in self._compiled_runs
        program = f"run[{num_steps}{'/stacked' if stacked else ''}]"
        try:
            fn = self._window_program(state, batch, num_steps, stacked,
                                      _force_unroll)
            batch = self._chaos_batch(batch, num_steps, stacked)
            if fresh:
                # The first call of a fresh program compiles synchronously
                # before dispatching; its latency is the compile-time signal
                # the obs StepProfiler reports.
                t0 = time.perf_counter()
                out = fn(state, batch)
                entry = {
                    "program": program,
                    "first_call_s": time.perf_counter() - t0,
                }
                self.compile_log.append(entry)
                # Flight-record the compile (no-op without a recorder): a
                # run that dies mid-compile leaves "compiling X" as its
                # last event — exactly what the postmortem doctor needs.
                flight.record_event("compile", critical=False, **entry)
            else:
                out = fn(state, batch)
            return self._chaos_metrics(out, num_steps)
        except Exception as e:
            # Black-box the failure before re-raising: an XLA OOM
            # (RESOURCE_EXHAUSTED) or runtime error recorded here is the
            # doctor's primary oom/crash evidence (docs/observability.md).
            flight.record_event(
                "error", program=program,
                error=f"{type(e).__name__}: {e}"[:500])
            raise

    def _window_program(self, state: TrainState, batch, num_steps: int,
                        stacked: bool, _force_unroll: bool):
        """Build-or-fetch the jitted window program for one ``run`` shape
        (shared by :meth:`run` and :meth:`window_cost`)."""
        key = (int(num_steps), stacked, _force_unroll)
        fn = self._compiled_runs.get(key)
        if fn is None:
            if self._state_shardings is None:
                self._state_shardings = self.plan.state_shardings(
                    jax.eval_shape(lambda: state))
            # device_put streaming (host offload) inside a scan body is not
            # supported by the SPMD partitioner; unroll those windows instead
            # — same one-dispatch amortization, longer compile.
            unroll = self.plan.has_offload or _force_unroll

            def unrolled(st, get_batch):
                ms = []
                for i in range(num_steps):
                    st, m = self._step(st, get_batch(i))
                    ms.append(m)
                return st, jax.tree.map(lambda *xs: jnp.stack(xs), *ms)

            if stacked:
                batch_sh = self.plan.window_shardings(batch)

                def multi(st, bs):
                    if unroll:
                        return unrolled(st, lambda i: jax.tree.map(
                            lambda x: x[i], bs))
                    return lax.scan(lambda s, b: self._step(s, b), st, bs,
                                    length=num_steps)
            else:
                batch_sh = self.plan.batch_shardings(batch)

                def multi(st, b):
                    if unroll:
                        return unrolled(st, lambda i: b)
                    return lax.scan(lambda s, _: self._step(s, b), st, None,
                                    length=num_steps)
            fn = jax.jit(
                multi,
                in_shardings=(self._state_shardings, batch_sh),
                out_shardings=(self._state_shardings, None),
                donate_argnums=(0,) if self._donate else (),
            )
            self._compiled_runs[key] = fn
        return fn

    def window_cost(self, state: TrainState, batch, num_steps: int = 1,
                    stacked: bool = False) -> Dict[str, float]:
        """FLOPs / HBM traffic of the compiled window program, from XLA's
        own per-executable cost analysis (not an analytical model) — the
        measured-over-measured MFU numerator the obs
        :class:`~autodist_tpu.obs.profiler.StepProfiler` reports.

        ``state``/``batch`` supply shapes only (nothing executes). Returns
        ``{"flops", "bytes_accessed"}`` plus ``memory_analysis`` sizes when
        the backend exposes them. See the in-body note on scan-body
        counting: request ``num_steps=1`` for per-step numbers.
        """
        fn = self._window_program(state, batch, num_steps, stacked, False)
        compiled = fn.lower(state, batch).compile()
        ca = compiled.cost_analysis()
        d = ca[0] if isinstance(ca, (list, tuple)) and ca else (ca or {})
        # NB: XLA's cost analysis counts a while/scan body ONCE regardless
        # of trip count, so for a scanned window these numbers are per-BODY
        # (≈ per step), not per window. Per-step consumers should ask for
        # ``num_steps=1`` explicitly (the obs StepProfiler does) rather
        # than divide a window's numbers by its length.
        out = {
            "flops": float(d.get("flops", 0.0)),
            "bytes_accessed": float(d.get("bytes accessed", 0.0)),
        }
        try:
            mem = compiled.memory_analysis()
        except Exception:  # noqa: BLE001 - optional backend API
            mem = None
        if mem is not None:
            out["argument_bytes"] = float(
                getattr(mem, "argument_size_in_bytes", 0))
            out["output_bytes"] = float(
                getattr(mem, "output_size_in_bytes", 0))
            out["temp_bytes"] = float(getattr(mem, "temp_size_in_bytes", 0))
        return out

    # ------------------------------------------------------------------ fit
    def fit(
        self,
        state: TrainState,
        batches,
        steps: Optional[int] = None,
        eval_batch=None,
        eval_every: int = 0,
        log_every: int = 0,
        window: int = 0,
        eval_metrics_fn=None,
    ):
        """Keras-``model.fit``-shaped training loop over an iterable of
        batches (a :class:`~autodist_tpu.data.DataLoader` or any batch
        iterator) — parity for the reference's patched ``model.fit`` path
        (``patch.py:96-116``, exercised by its integration case c7).

        Returns ``(state, history)`` where ``history["loss"]`` is the
        per-step loss and ``history["eval_loss"]`` the periodic eval losses
        (``eval_every`` > 0 with ``eval_batch``). ``eval_metrics_fn`` — a
        ``(params, batch) -> {name: value}`` function (see
        ``autodist_tpu.metrics`` factories) — adds ``history["eval_<name>"]``
        series computed at the same eval points against the logical
        parameter view.

        ``window=k`` (k > 1) bridges fit to the windowed hot loop: ``k``
        consecutive batches are stacked host-side and executed as ONE device
        program (``run(stacked=True)`` — a ``lax.scan`` over fresh data),
        paying one dispatch+transfer per window instead of per step — the
        per-step dispatch pattern is ~11× slower on the remote-tunnel
        platform (docs/performance.md). Windows are chopped so eval/steps
        boundaries land exactly between windows; per-step history is
        identical to ``window=0``.
        """
        import itertools

        if window and window > 1:
            return self._fit_windowed(
                state, batches, steps, eval_batch, eval_every, log_every,
                window, eval_metrics_fn)

        history = {"loss": []}
        eval_metrics = self._make_eval_metrics(eval_metrics_fn)
        if eval_every and eval_batch is not None:
            history["eval_loss"] = []
        # islice, not a break-on-index loop: breaking after enumerate() has
        # pulled the batch would silently consume (and discard) one extra
        # batch from a shared iterator per capped fit() call.
        if steps is not None:
            batches = itertools.islice(batches, steps)
        for i, batch in enumerate(batches):
            state, metrics = self(state, batch)
            loss = float(metrics["loss"])
            history["loss"].append(loss)
            if log_every and (i + 1) % log_every == 0:
                logging.info("fit step %d: loss=%.6f", i + 1, loss)
            if eval_every and eval_batch is not None and (i + 1) % eval_every == 0:
                ev_loss = float(self.evaluate(state, eval_batch)["loss"])
                history["eval_loss"].append(ev_loss)
                eval_metrics(state, eval_batch, history)
                if log_every:
                    logging.info("fit step %d: eval_loss=%.6f", i + 1, ev_loss)
        return state, history

    def compile_metrics(self, metrics_fn, state: "TrainState"):
        """Jit a ``(params, batch) -> {name: value}`` task-metric function
        against this step's parameter handling: host-offloaded leaves
        stream into HBM INSIDE the jitted program (the same `_stream`
        evaluate uses — no eager whole-tree device_put per call) and
        pad-and-mask storage is sliced back to logical shapes under the
        trace. The ONE way to run user metrics on live state
        (autodist_tpu.metrics.evaluate_dataset and fit's eval hook both
        come through here). ``state`` supplies shapes only."""
        if self.plan.has_offload:
            shaped = jax.eval_shape(lambda: state).params
            host_sh = self.plan.params_shardings(shaped)
            dev_sh = self.plan.params_shardings(shaped, device_view=True)
        else:
            host_sh = dev_sh = None

        def fn(params, batch):
            if host_sh is not None:
                params = _stream(params, host_sh, dev_sh)
            params = self.plan.unpad_params(params)
            return metrics_fn(params, batch)

        return jax.jit(fn)

    def _make_eval_metrics(self, eval_metrics_fn):
        """Task-metric hook for fit's eval points: appends ``eval_<name>``
        series to the history. ``<name>__weight`` entries (the masked-
        metric convention of autodist_tpu.metrics.evaluate_dataset) are
        stripped — a point-in-time series has no cross-batch weighting —
        and a metric named ``loss`` records as ``eval_metrics_loss`` so it
        can never interleave with the built-in ``eval_loss`` series."""
        if eval_metrics_fn is None:
            return lambda state, batch, history: None
        compiled = None

        def run(state, batch, history):
            nonlocal compiled
            if compiled is None:
                compiled = self.compile_metrics(eval_metrics_fn, state)
            out = compiled(state.params, batch)
            for k, v in out.items():
                if k.endswith("__weight"):
                    continue
                name = "eval_metrics_loss" if k == "loss" else f"eval_{k}"
                history.setdefault(name, []).append(float(v))

        return run

    def _fit_windowed(self, state, batches, steps, eval_batch, eval_every,
                      log_every, window, eval_metrics_fn=None):
        """The ``fit(window=k)`` body: stack host batches, one dispatch per
        window. See :meth:`fit` for the contract.

        Batch source: a DataLoader exposes ``host_batches()`` (raw
        per-process numpy batches — stacking must happen BEFORE the device
        transfer); any other iterable is consumed as-is and stacked via
        ``np.asarray``, which is single-process only (a generic iterator's
        leaves can't be assembled into multi-host global windows).

        A batch whose leaf shapes differ from the current window's (ragged
        final batch with ``drop_remainder=False``) flushes the window and
        runs alone. Look-ahead never over-consumes a shared iterator: a
        shape-mismatched pull is carried as ``pending`` into the next
        window, and since a window that defers a pull always ran fewer
        than ``steps - step_i`` batches, the loop always comes back around
        to run it — consumed == ran, pinned by
        ``tests/test_lowering.py::test_fit_windowed_consumes_exactly_ran``.
        """
        from_loader = hasattr(batches, "host_batches")
        if from_loader:
            it = iter(batches.host_batches())
        else:
            if jax.process_count() > 1:
                raise ValueError(
                    "fit(window>1) on a multi-process fleet requires a "
                    "DataLoader: generic iterator batches cannot be "
                    "assembled into global windows")
            it = iter(batches)

        history = {"loss": []}
        eval_metrics = self._make_eval_metrics(eval_metrics_fn)
        if eval_every and eval_batch is not None:
            history["eval_loss"] = []

        def sig(b):
            return tuple(tuple(np.shape(leaf)) for leaf in jax.tree.leaves(b))

        _end = object()
        pending = None
        step_i = 0
        while True:
            if steps is not None and step_i >= steps:
                break
            # Chop the window so steps/eval boundaries land between windows.
            chunk = window
            if steps is not None:
                chunk = min(chunk, steps - step_i)
            if eval_every and eval_batch is not None:
                chunk = min(chunk, eval_every - (step_i % eval_every))
            buf = []
            while len(buf) < chunk:
                if pending is not None:
                    b, pending = pending, None
                else:
                    b = next(it, _end)
                    if b is _end:
                        break
                if buf and sig(b) != sig(buf[0]):
                    pending = b  # ragged/shape-change batch: next window
                    break
                buf.append(b)
            if not buf:
                break
            if len(buf) == 1:
                batch = buf[0]
                if from_loader:
                    batch = self.plan.global_batch_from_local(
                        batch, broadcast=jax.tree.map(lambda _: False, batch))
                state, metrics = self(state, batch)
                losses = [float(metrics["loss"])]
            else:
                stacked = jax.tree.map(
                    lambda *xs: np.stack([np.asarray(x) for x in xs]), *buf)
                wnd = (self.plan.window_from_local(stacked) if from_loader
                       else stacked)
                state, metrics = self.run(state, wnd, len(buf), stacked=True)
                losses = [float(x) for x in np.asarray(metrics["loss"])]
            for loss in losses:
                step_i += 1
                history["loss"].append(loss)
                if log_every and step_i % log_every == 0:
                    logging.info("fit step %d: loss=%.6f", step_i, loss)
            if (eval_every and eval_batch is not None
                    and step_i % eval_every == 0):
                ev_loss = float(self.evaluate(state, eval_batch)["loss"])
                history["eval_loss"].append(ev_loss)
                eval_metrics(state, eval_batch, history)
                if log_every:
                    logging.info("fit step %d: eval_loss=%.6f", step_i, ev_loss)
        return state, history

    # ------------------------------------------------------------ evaluation
    def evaluate(self, state: TrainState, batch):
        """Loss (+aux) on a batch without gradients or state mutation — the
        reference's "fetch tensors without train ops" path
        (remapper.py:125-185: non-train fetches ran against the master
        replica). Params stay in their plan shardings; the batch shards on
        the data axis (replicating ragged leaves — eval tails needn't
        divide the mesh); nothing is donated. Compiles are cached per batch
        structure/shape.
        """
        key = (jax.tree.structure(batch), tuple(
            (getattr(x, "shape", ()), str(getattr(x, "dtype", type(x))))
            for x in jax.tree.leaves(batch)))
        fn = self._compiled_eval.get(key)
        if fn is None:
            if self._state_shardings is None:
                self._state_shardings = self.plan.state_shardings(
                    jax.eval_shape(lambda: state))

            if self.plan.has_offload:
                # Host view == the plan shardings already frozen in
                # _state_shardings; only the device view needs computing.
                host_sh = self._state_shardings.params
                dev_sh = self.plan.params_shardings(
                    jax.eval_shape(lambda: state).params, device_view=True)
            else:
                host_sh = dev_sh = None

            def eval_fn(params, b):
                if host_sh is not None:
                    params = _stream(params, host_sh, dev_sh)
                out = self.loss_fn(params, b)
                if self.has_aux:
                    loss, aux = out
                    return {"loss": loss, "aux": aux}
                return {"loss": out}

            fn = jax.jit(
                eval_fn,
                in_shardings=(self._state_shardings.params,
                              self.plan.batch_shardings(batch, strict=False)),
                out_shardings=None,
            )
            self._compiled_eval[key] = fn
        return fn(state.params, batch)

    def save(self, saver, state: TrainState, path: Optional[str] = None,
             step: Optional[int] = None, block: bool = True) -> str:
        """Checkpoint ``state`` in its LOGICAL shapes — the safe way to save
        a train state (ADVICE r1: a plain ``saver.save(state)`` under a
        pad-and-mask plan would write padded storage shapes that no other
        plan could restore). Defaults the checkpoint step to the state's
        own step counter. ``init_or_restore`` is the matching load."""
        if step is None:
            step = int(state.step)
        return saver.save(self.logical_state(state), path=path, step=step,
                          block=block)

    def init_or_restore(self, params, saver=None, restore_fn=None) -> TrainState:
        """Fresh state, or the latest checkpoint when one exists — the
        crash-resume entry point (the reference's closest fault-tolerance
        mechanism was checkpoint/resume, SURVEY §5). The restored state is
        re-sharded onto this run's plan, so resuming onto a different mesh
        or strategy works like any cross-sharding restore. Checkpoints hold
        *logical* shapes (write them with
        ``saver.save(step.logical_state(state))``); a padded plan re-pads
        the loaded leaves into its storage view here.

        ``restore_fn(target=..., shardings=...)`` overrides where the state
        comes from (default: ``saver.restore_latest``) — the ft subsystem
        passes ``SnapshotManager.restore_latest_valid`` so elastic resume
        rides this exact path with integrity-verified snapshots.
        """
        if restore_fn is None:
            restore_fn = saver.restore_latest
        state = self.init(params)
        if not self.plan.has_padding:
            restored = restore_fn(
                target=jax.eval_shape(lambda: state), shardings=self._state_shardings
            )
            return restored if restored is not None else state
        logical_shapes = jax.eval_shape(self.plan.unpad_state, state)
        restored = restore_fn(target=logical_shapes)
        if restored is None:
            return state
        return jax.device_put(self.plan.pad_state(restored), self._state_shardings)

    def trace_step(self, state: TrainState, batch, name: str = "train_step"):
        """One profiled step -> TensorBoard trace dir (runner.py:64-75 analog).

        Returns ``(new_state, metrics), trace_dir``."""
        from autodist_tpu.utils import tracing

        fn = self._compiled or self._compile(state, batch)
        with tracing.trace(name) as trace_dir:
            out = fn(state, batch)
            jax.block_until_ready(out)
        return out, trace_dir

    @staticmethod
    def _chaos_batch(batch, num_steps: int, stacked: bool):
        """Chaos seam (docs/chaos.md): an installed plant may poison the
        batch (NaN gradients, loss spikes) before dispatch. Inert — one
        predicate call — without a plant. ONE helper for the windowed
        (:meth:`run`) and per-step (:meth:`__call__`) paths."""
        if chaos_hooks.active():
            batch = chaos_hooks.apply(chaos_hooks.SEAM_TRAIN_BATCH, batch,
                                      num_steps=num_steps, stacked=stacked)
        return batch

    @staticmethod
    def _chaos_metrics(out, num_steps: int):
        """Post-step chaos seam: advances the plant's step cursor (and may
        transform metrics). Same inertness contract as _chaos_batch."""
        if chaos_hooks.active():
            new_state, metrics = out
            out = (new_state, chaos_hooks.apply(
                chaos_hooks.SEAM_TRAIN_METRICS, metrics,
                num_steps=num_steps))
        return out

    def __call__(self, state: TrainState, batch) -> Tuple[TrainState, Dict[str, Any]]:
        fresh = self._compiled is None
        fn = self._compiled or self._compile(state, batch)
        batch = self._chaos_batch(batch, num_steps=1, stacked=False)
        if fresh:
            t0 = time.perf_counter()
            out = fn(state, batch)
            self.compile_log.append(
                {"program": "step", "first_call_s": time.perf_counter() - t0})
        else:
            out = fn(state, batch)
        return self._chaos_metrics(out, num_steps=1)

    def lower_text(self, state: TrainState, batch) -> str:
        """Stable-HLO dump of the compiled step — the TPU analog of the
        reference's per-stage TensorBoard graph snapshots
        (visualization_util.py:24-36)."""
        fn = self._compiled or self._compile(state, batch)
        return fn.lower(state, batch).as_text()
