"""Kernel layer (L2): lowering from Strategy IR to XLA sharding plans.

Replaces the reference's graph-rewriting kernel passes
(``/root/reference/autodist/kernel/``) with GSPMD sharding emission.
"""
from autodist_tpu.kernel.lowering import (
    DistributedTrainStep,
    GraphTransformer,
    ShardingPlan,
    SyncKind,
    TrainState,
    VarPlan,
)
from autodist_tpu.kernel.mesh import build_mesh, data_axis, data_sharding

__all__ = [
    "DistributedTrainStep",
    "GraphTransformer",
    "ShardingPlan",
    "SyncKind",
    "TrainState",
    "VarPlan",
    "build_mesh",
    "data_axis",
    "data_sharding",
]
