"""Device-mesh construction from a ResourceSpec.

Replaces the reference's device resolver + ClusterSpec
(``/root/reference/autodist/kernel/device/resolver.py:26-67``,
``cluster.py:70-82``): AutoDist device strings resolved into a
``jax.sharding.Mesh`` instead of TF ``DeviceSpecV2`` job/task strings. On real
TPU slices the mesh uses ``mesh_utils.create_device_mesh`` so logical axes map
onto physical ICI rings; on the host-platform (tests) it falls back to a plain
reshape.
"""
from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh

from autodist_tpu import const
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.utils import logging

DEFAULT_AXES = (const.MESH_AXIS_DATA, const.MESH_AXIS_MODEL)


def build_mesh(
    resource_spec: Optional[ResourceSpec] = None,
    axes: Sequence[str] = DEFAULT_AXES,
    devices=None,
    slice_of=None,
) -> Mesh:
    """Build the logical mesh the strategy lowers onto.

    The axis sizes come from the resource spec (``mesh:`` override or
    all-chips-on-data default); the concrete devices come from the local JAX
    runtime. The spec's chip count must match the visible device count —
    the analog of the reference's cluster_spec/worker agreement.

    ``slice_of`` maps a device to its slice/ICI-domain id (None = single
    domain). Defaults to the runtime's ``slice_index`` attribute; tests and
    the driver dryrun inject a fake assignment to exercise the multi-slice
    hybrid layout on the host-platform mesh.
    """
    if devices is None:
        devices = jax.devices()
    injected_slices = slice_of is not None
    if slice_of is None:
        slice_of = lambda d: getattr(d, "slice_index", None)  # noqa: E731
    if resource_spec is None:
        shape: Dict[str, int] = {ax: 1 for ax in axes}
        shape[list(axes)[0]] = len(devices)
    else:
        shape = resource_spec.mesh_shape(tuple(axes))
    n = math.prod(shape.values())
    if n != len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {n} devices but the runtime has "
            f"{len(devices)} — resource spec and runtime disagree"
        )
    axis_names = tuple(shape.keys())
    dims = [shape[ax] for ax in axis_names]

    slice_ids = {slice_of(d) for d in devices}
    slice_ids.discard(None)
    n_slices = max(len(slice_ids), 1)
    if n_slices > 1:
        # The DCN-crossing axis is the DATA axis *by role*, not positionally:
        # a mesh override may list axes in any order. Resolved only when
        # multi-slice placement needs it — a role-only mesh (no batch-capable
        # axis) must still build on a single slice.
        data_ix = None
        try:
            data_ix = axis_names.index(_data_axis_name(axis_names, shape))
        except ValueError:
            logging.warning(
                "multi-slice runtime (%d slices) but the mesh has no "
                "data-capable axis — collectives may cross DCN", n_slices,
            )
        if data_ix is not None and dims[data_ix] % n_slices == 0:
            # Multi-slice pod: only the DATA axis crosses DCN — its
            # gradient all-reduce tolerates the slower hops via
            # hierarchical reduce-scatter — while model/seq/expert
            # axes stay inside a slice so their per-layer collectives
            # ride ICI (the scaling-book layout; the reference's analog
            # was `network_bandwidth` steering PS placement).
            try:
                return Mesh(
                    _hybrid_arrangement(
                        devices, dims, data_ix, n_slices, slice_of,
                        honor_slice_of=injected_slices,
                    ),
                    axis_names,
                )
            except Exception as e:  # noqa: BLE001 - ICI-aware path still next
                logging.warning(
                    "hybrid mesh arrangement failed (%s); falling back to "
                    "create_device_mesh", e,
                )
        elif data_ix is not None:
            logging.warning(
                "multi-slice runtime (%d slices) but data axis %d does "
                "not divide by the slice count — model-axis collectives "
                "may cross DCN", n_slices, dims[data_ix],
            )
    if devices and devices[0].platform == "tpu":
        from jax.experimental import mesh_utils

        try:
            mesh_devices = mesh_utils.create_device_mesh(dims, devices=devices)
            return Mesh(mesh_devices, axis_names)
        except Exception as e:  # noqa: BLE001 - fall back to naive order
            logging.warning("create_device_mesh failed (%s); using naive order", e)
    return Mesh(np.asarray(devices).reshape(dims), axis_names)


def _hybrid_arrangement(devices, dims, data_ix: int, n_slices: int, slice_of,
                        honor_slice_of: bool = False):
    """Device array for a multi-slice mesh: DCN-major along the data axis.

    The data axis splits into ``n_slices`` contiguous DCN blocks, each filled
    by exactly one slice's devices, so fixing a data coordinate pins a slice
    (model/seq/expert fibers never leave their ICI domain) and the gradient
    all-reduce decomposes into in-slice reduce-scatter + cross-slice
    exchange + in-slice all-gather (XLA does this given the layout). On TPU
    with the runtime's own slice notion the arrangement delegates to
    ``mesh_utils.create_hybrid_device_mesh`` (physical-topology-aware within
    each slice); with a caller-injected ``slice_of`` (``honor_slice_of``) or
    off-TPU, each slice block is ordered by a plain reshape — the injected
    assignment is the contract, so it must not be silently re-derived from
    hardware attributes that may disagree.
    """
    groups: Dict[object, list] = {}
    for d in devices:
        groups.setdefault(slice_of(d), []).append(d)
    sizes = {len(g) for g in groups.values()}
    if len(sizes) != 1:
        raise ValueError(
            f"uneven slices: {sorted((k, len(v)) for k, v in groups.items())}"
        )
    if devices[0].platform == "tpu" and not honor_slice_of:
        from jax.experimental import mesh_utils

        dcn = [1] * len(dims)
        dcn[data_ix] = n_slices
        ici = list(dims)
        ici[data_ix] = dims[data_ix] // n_slices
        return mesh_utils.create_hybrid_device_mesh(ici, dcn, devices=devices)
    per_slice = list(dims)
    per_slice[data_ix] //= n_slices
    blocks = [
        np.asarray(groups[sid]).reshape(per_slice) for sid in sorted(groups)
    ]
    return np.concatenate(blocks, axis=data_ix)


def _data_axis_name(names: Sequence[str], sizes: Dict[str, int]) -> str:
    """Resolve which axis carries the batch (shared by :func:`data_axis`
    and :func:`build_mesh`'s DCN-placement logic).

    ``data`` when present with degree > 1. When a mesh override uses a
    custom axis name (e.g. ``{"x": 8}``), ``mesh_shape`` still setdefaults a
    size-1 ``data`` axis — there, the batch axis is the custom-named axis
    (degree > 1, not a known model/seq/expert/pipe role), not the vestigial
    ``data``. Known non-data roles are never picked even when ``data`` has
    degree 1: ``{"model": 8}`` means the user asked for pure model
    parallelism with a replicated batch.
    """
    non_data_roles = set(const.ALL_MESH_AXES) - {const.MESH_AXIS_DATA}
    if const.MESH_AXIS_DATA not in names:
        for ax in names:
            if ax not in non_data_roles:
                return ax
        # Every axis is a known non-data role (e.g. axes=("model",)):
        # putting the batch on any of them would silently corrupt training
        # (each model shard would see different examples). Pure model
        # parallelism is spelled with a size-1 data axis — the default
        # mesh_axes includes one automatically.
        raise ValueError(
            f"mesh axes {tuple(names)} contain no axis that can carry the "
            f"batch; include '{const.MESH_AXIS_DATA}' (size 1 for pure "
            f"model parallelism) in mesh_axes"
        )
    if sizes[const.MESH_AXIS_DATA] > 1:
        return const.MESH_AXIS_DATA
    for ax in names:
        if ax not in non_data_roles and sizes[ax] > 1:
            return ax
    return const.MESH_AXIS_DATA


def data_axis(mesh: Mesh) -> str:
    """The batch axis name (see :func:`_data_axis_name`)."""
    return _data_axis_name(
        mesh.axis_names, dict(zip(mesh.axis_names, mesh.devices.shape))
    )


def data_sharding(mesh: Mesh, rank: int, dim: int = 0):
    """NamedSharding for a rank-``rank`` array batch-sharded on ``dim``.

    The generic "this dimension is per-example/per-slot work" placement:
    training batches use dim 0 (``ShardingPlan.batch_shardings``), the
    serving engine's KV-cache pools use dim 1 (``[layers, slots, ...]``).
    Replicates when the data axis is trivial — a size-1 axis in the spec
    would be legal but noisier to read in sharding dumps.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    ax = data_axis(mesh)
    if dict(zip(mesh.axis_names, mesh.devices.shape))[ax] <= 1:
        return NamedSharding(mesh, PartitionSpec())
    spec = [None] * rank
    spec[dim] = ax
    return NamedSharding(mesh, PartitionSpec(*spec))
