"""Device-mesh construction from a ResourceSpec.

Replaces the reference's device resolver + ClusterSpec
(``/root/reference/autodist/kernel/device/resolver.py:26-67``,
``cluster.py:70-82``): AutoDist device strings resolved into a
``jax.sharding.Mesh`` instead of TF ``DeviceSpecV2`` job/task strings. On real
TPU slices the mesh uses ``mesh_utils.create_device_mesh`` so logical axes map
onto physical ICI rings; on the host-platform (tests) it falls back to a plain
reshape.
"""
from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh

from autodist_tpu import const
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.utils import logging

DEFAULT_AXES = (const.MESH_AXIS_DATA, const.MESH_AXIS_MODEL)


def build_mesh(
    resource_spec: Optional[ResourceSpec] = None,
    axes: Sequence[str] = DEFAULT_AXES,
    devices=None,
) -> Mesh:
    """Build the logical mesh the strategy lowers onto.

    The axis sizes come from the resource spec (``mesh:`` override or
    all-chips-on-data default); the concrete devices come from the local JAX
    runtime. The spec's chip count must match the visible device count —
    the analog of the reference's cluster_spec/worker agreement.
    """
    if devices is None:
        devices = jax.devices()
    if resource_spec is None:
        shape: Dict[str, int] = {ax: 1 for ax in axes}
        shape[list(axes)[0]] = len(devices)
    else:
        shape = resource_spec.mesh_shape(tuple(axes))
    n = math.prod(shape.values())
    if n != len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {n} devices but the runtime has "
            f"{len(devices)} — resource spec and runtime disagree"
        )
    axis_names = tuple(shape.keys())
    dims = [shape[ax] for ax in axis_names]
    if devices and devices[0].platform == "tpu":
        from jax.experimental import mesh_utils

        slice_ids = {getattr(d, "slice_index", None) for d in devices}
        slice_ids.discard(None)
        n_slices = max(len(slice_ids), 1)
        if n_slices > 1 and dims[0] % n_slices == 0:
            # Multi-slice pod: only the DATA (outermost) axis crosses
            # DCN — its gradient all-reduce tolerates the slower hops
            # via hierarchical reduce-scatter — while model/seq/expert
            # axes stay inside a slice so their per-layer collectives
            # ride ICI (the scaling-book layout; the reference's analog
            # was `network_bandwidth` steering PS placement).
            try:
                dcn = [n_slices] + [1] * (len(dims) - 1)
                ici = [dims[0] // n_slices] + list(dims[1:])
                mesh_devices = mesh_utils.create_hybrid_device_mesh(
                    ici, dcn, devices=devices
                )
                return Mesh(mesh_devices, axis_names)
            except Exception as e:  # noqa: BLE001 - ICI-aware path still next
                logging.warning(
                    "create_hybrid_device_mesh failed (%s); falling back to "
                    "create_device_mesh", e,
                )
        elif n_slices > 1:
            logging.warning(
                "multi-slice runtime (%d slices) but data axis %d does "
                "not divide by the slice count — model-axis collectives "
                "may cross DCN", n_slices, dims[0],
            )
        try:
            mesh_devices = mesh_utils.create_device_mesh(dims, devices=devices)
            return Mesh(mesh_devices, axis_names)
        except Exception as e:  # noqa: BLE001 - fall back to naive order
            logging.warning("create_device_mesh failed (%s); using naive order", e)
    return Mesh(np.asarray(devices).reshape(dims), axis_names)


def data_axis(mesh: Mesh) -> str:
    """The batch axis name (first axis by convention)."""
    return mesh.axis_names[0]
