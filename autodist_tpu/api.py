"""User API (L5): the ``AutoDist`` entry point.

Mirrors the reference's user lifecycle (``/root/reference/autodist/
autodist.py``): construct ``AutoDist(resource_spec_file, strategy_builder)``,
then turn a single-device model into a distributed one. In the TF reference
that meant graph capture inside ``scope()`` + a wrapped session; here the
single-device artifact is a pure ``loss_fn`` + params pytree, and the result
is a compiled :class:`DistributedTrainStep` that runs sharded over the mesh.

Minimal usage (the ≤3-line diff contract, reference README.md:39-54)::

    import autodist_tpu as ad

    autodist = ad.AutoDist(resource_spec_file="spec.yml",
                           strategy_builder=ad.strategy.AllReduce())
    step = autodist.build(loss_fn, params, example_batch)   # <- the diff
    state = step.init(params)
    for batch in data:
        state, metrics = step(state, batch)

Lifecycle parity:
- one AutoDist per process (``autodist.py:46-57``);
- default builder is ``PSLoadBalancing`` (``autodist.py:70``);
- chief builds + serializes the strategy, workers deserialize by
  ``AUTODIST_STRATEGY_ID`` (``autodist.py:100-109``);
- ``build`` = capture → strategy → compile → transform
  (``autodist.py:139-150``).
"""
from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Any, Callable, Optional, Sequence, TYPE_CHECKING, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax

from autodist_tpu import const
from autodist_tpu.const import ENV
from autodist_tpu.kernel import DistributedTrainStep, GraphTransformer, ShardingPlan, build_mesh
from autodist_tpu.model_item import ModelItem, OptimizerSpec
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy import PSLoadBalancing, Strategy, StrategyBuilder, StrategyCompiler
from autodist_tpu.utils import is_broadcast_leaf, logging

if TYPE_CHECKING:  # circular at runtime: async_ps imports nothing from api
    from autodist_tpu.ft import FTConfig, FTRuntime
    from autodist_tpu.obs import ObsConfig, ObsRuntime
    from autodist_tpu.runtime.async_ps import AsyncPSTrainer

_default_autodist: Optional["AutoDist"] = None


# Windows per tune() trial, dispatched back-to-back with one trailing sync
# (see tune's timing loop). 4 amortizes the device->host fetch latency to
# ~2 ms/step-window on the axon tunnel while keeping the sweep short.
_TUNE_TRIAL_WINDOWS = 4

# Non-factory jax.checkpoint_policies usable directly as a remat policy
# (factories like save_only_these_names need arguments and are out of scope
# for the string shorthand).
_REMAT_POLICIES = (
    "everything_saveable",
    "nothing_saveable",
    "dots_saveable",
    "checkpoint_dots",
    "dots_with_no_batch_dims_saveable",
    "checkpoint_dots_with_no_batch_dims",
)


def _cast_compute(loss_fn: Callable, compute_dtype: str) -> Callable:
    """Mixed-precision wrapper: params enter the loss in ``compute_dtype``
    while the train state stays fp32 (master weights). Autodiff through
    ``astype`` upcasts gradients back to the parameter dtype, so the
    optimizer update runs full precision — the standard TPU policy (MXU
    eats bf16, accumulation and weight updates stay fp32). Non-floating
    leaves (embedding id tables etc.) pass through untouched.
    """
    dtype = jnp.dtype(compute_dtype)
    if not jnp.issubdtype(dtype, jnp.floating):
        raise ValueError(f"compute_dtype must be floating, got {compute_dtype!r}")

    def cast(leaf):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf.astype(dtype)
        return leaf

    def wrapped(params, batch):
        return loss_fn(jax.tree.map(cast, params), batch)

    return wrapped


def _remat_policy(remat: Union[bool, str]):
    if remat is True:
        return None
    if remat in _REMAT_POLICIES:
        return getattr(jax.checkpoint_policies, remat)
    raise ValueError(
        f"unknown remat policy {remat!r}; use True or one of {_REMAT_POLICIES}")


def _resolve_optimizer(optimizer):
    """(OptimizerSpec, optax transform) from the user's optimizer argument.

    One resolution path for :meth:`AutoDist.build` and
    :meth:`AutoDist.build_pipeline`: an :class:`OptimizerSpec` is
    materialized; ``None`` gets the default spec; a raw optax transform is
    wrapped as the opaque ``"custom"`` spec (planners then assume the
    conservative worst-case slot count).
    """
    if isinstance(optimizer, OptimizerSpec):
        return optimizer, optimizer.make()
    if optimizer is None:
        spec = OptimizerSpec("sgd", {"learning_rate": 0.01})
        return spec, spec.make()
    return OptimizerSpec("custom"), optimizer


def get_default_autodist() -> Optional["AutoDist"]:
    return _default_autodist


class AutoDist:
    """Distributed-training entry point bound to one cluster description."""

    def __init__(
        self,
        resource_spec_file: Optional[str] = None,
        strategy_builder: Union[StrategyBuilder, str, None] = None,
        resource_spec: Optional[ResourceSpec] = None,
        mesh_axes: Sequence[str] = ("data", "model"),
        fault_tolerance: "Optional[FTConfig]" = None,
        observability: "Optional[ObsConfig]" = None,
    ):
        global _default_autodist
        if _default_autodist is not None:
            # Parity: one AutoDist per process (autodist.py:46-57; the
            # reference test asserts the second construction raises).
            raise RuntimeError(
                "Only one AutoDist instance is supported per process; "
                "call AutoDist.reset_default() first if you really need another."
            )
        # Join the multi-controller runtime if this process was launched by
        # the coordinator/launcher (the reference's _setup stage,
        # autodist.py:120-128). Must happen before any ResourceSpec device
        # query initializes the XLA backend; idempotent, no-op single-process.
        from autodist_tpu.runtime.launcher import initialize_from_env

        initialize_from_env()

        if resource_spec is not None:
            self.resource_spec = resource_spec
        elif resource_spec_file:
            self.resource_spec = ResourceSpec(resource_spec_file)
        elif ENV.AUTODIST_RESOURCE_SPEC.val:
            self.resource_spec = ResourceSpec(ENV.AUTODIST_RESOURCE_SPEC.val)
        else:
            self.resource_spec = ResourceSpec.from_local_devices()
        # Default strategy builder (autodist.py:70). A string names a
        # builder class ("AllReduce", "Auto", ...) or the search-based
        # auto-planner ("plan" — docs/planner.md).
        if isinstance(strategy_builder, str):
            from autodist_tpu.strategy import from_name

            strategy_builder = from_name(strategy_builder)
        self.strategy_builder = strategy_builder or PSLoadBalancing()
        self.mesh_axes = tuple(mesh_axes)
        self._mesh = None
        self._built: Optional[DistributedTrainStep] = None
        self._strategy: Optional[Strategy] = None
        self._model_item: Optional[ModelItem] = None
        # Filled by tune(): {"table": {name: {measured_s, predicted_s}},
        # "calibration": Calibration, "calibration_path": str}.
        self.last_tune_results: Optional[dict] = None
        # Fault tolerance (docs/fault_tolerance.md): a started HealthMonitor
        # + SnapshotManager bundle, or None when the knob is off (zero
        # overhead on the default path).
        self.ft: "Optional[FTRuntime]" = None
        if fault_tolerance is not None:
            from autodist_tpu.ft import FTRuntime

            self.ft = FTRuntime(fault_tolerance)
        # Observability (docs/observability.md): spans + exporters +
        # cross-host aggregation, or None when the knob is off (zero
        # overhead on the default path — mirrors the ft pattern).
        self.obs: "Optional[ObsRuntime]" = None
        if observability is not None:
            from autodist_tpu.obs import ObsRuntime

            self.obs = ObsRuntime(observability)
            if self.ft is not None:
                # Straggler scores escalate through the ft HealthMonitor.
                self.obs.attach_monitor(self.ft.monitor)
        _default_autodist = self

    @classmethod
    def reset_default(cls) -> None:
        """Testing hook — the reference isolates per-process state by forking
        (tests/integration/test_all.py:20-75); we allow explicit reset."""
        global _default_autodist
        _default_autodist = None

    # ------------------------------------------------------------------ mesh
    @property
    def mesh(self):
        if self._mesh is None:
            self._mesh = build_mesh(self.resource_spec, axes=self.mesh_axes)
        return self._mesh

    @property
    def is_chief(self) -> bool:
        return const.is_chief_process()

    # ----------------------------------------------------------------- build
    def _build_or_load_strategy(self, model_item: ModelItem) -> Strategy:
        """Chief builds + serializes; workers receive it
        (autodist.py:100-109, strategy/base.py:89-99).

        Two handoff paths:
        - connected multi-controller runtime (all hosts started together,
          the TPU launch model): the chief broadcasts the strategy bytes
          over the distributed runtime — no shared filesystem or
          launch-time env needed;
        - coordinator-launched workers (reference SSH-relaunch model):
          load by ``AUTODIST_STRATEGY_ID`` from the shipped file.
        """
        shipped_id = ENV.AUTODIST_STRATEGY_ID.val
        if jax.process_count() > 1 and not (not self.is_chief and shipped_id):
            # Connected fleet without a coordinator-shipped strategy file:
            # broadcast. A worker that *was* shipped an id (Coordinator
            # relaunch, possibly with a hand-tuned strategy) must honor the
            # file — rebuilding from the local builder could silently train
            # a different strategy.
            return self._sync_strategy_multihost(model_item)
        if self.is_chief:
            strategy = self.strategy_builder.build(model_item, self.resource_spec)
            strategy.serialize()
            # Child/worker processes launched from here inherit the id.
            os.environ[ENV.AUTODIST_STRATEGY_ID.name] = strategy.id
        else:
            strategy_id = ENV.AUTODIST_STRATEGY_ID.val
            if not strategy_id:
                raise RuntimeError(
                    "AUTODIST_WORKER is set but AUTODIST_STRATEGY_ID is empty — "
                    "workers must be launched with the chief's strategy id "
                    "(the coordinator does this automatically)"
                )
            logging.info("worker loading strategy %s", strategy_id)
            strategy = self._wait_for_strategy(strategy_id)
        return strategy

    def _sync_strategy_multihost(self, model_item: ModelItem) -> Strategy:
        """Chief builds; everyone else receives the bytes via the runtime.

        Replaces the reference's SFTP strategy shipping
        (coordinator.py:84-88) with a payload broadcast riding the already-
        connected jax.distributed cluster: length first (fixed shape), then
        the zero-padded JSON bytes.
        """
        import json as _json

        from jax.experimental import multihost_utils

        if jax.process_index() == 0:
            try:
                strategy = self.strategy_builder.build(model_item, self.resource_spec)
            except Exception:
                # Only the chief builds — a build failure here is NOT
                # SPMD-deterministic, and the workers are already waiting in
                # the length broadcast below. Ship a -1 sentinel so every
                # process raises in lockstep instead of the workers pairing
                # this broadcast with some later one (protocol desync).
                multihost_utils.broadcast_one_to_all(np.int32(-1))
                raise
            strategy.serialize()  # audit trail on the chief host
            # Children forked from the chief later (coordinator relaunch
            # pattern) inherit the id, same as the single-process path.
            os.environ[ENV.AUTODIST_STRATEGY_ID.name] = strategy.id
            payload = _json.dumps(strategy.to_json()).encode()
        else:
            payload = b""
        n = int(multihost_utils.broadcast_one_to_all(np.int32(len(payload))))
        if n < 0:
            raise RuntimeError(
                "strategy build failed on the chief — see the chief's "
                "traceback for the cause")
        buf = np.zeros(n, np.uint8)
        if payload:
            buf[: len(payload)] = np.frombuffer(payload, np.uint8)
        buf = np.asarray(multihost_utils.broadcast_one_to_all(buf))
        strategy = Strategy.from_json(_json.loads(buf.tobytes().decode()))
        if jax.process_index() != 0:
            # The serialized path references the chief's filesystem; blank
            # it on receivers so nothing tries to read a nonexistent file.
            strategy.path = ""
        logging.info(
            "strategy %s synced across %d processes", strategy.id, jax.process_count()
        )
        return strategy

    @staticmethod
    def _wait_for_strategy(strategy_id: str, timeout_s: float = 60.0) -> Strategy:
        """Load the chief's serialized strategy, waiting for it to appear.

        Covers concurrent multi-process starts on a shared filesystem; on
        disjoint filesystems the runtime coordinator broadcasts the strategy
        instead (runtime/coordinator.py)."""
        from autodist_tpu.utils import retry as _retry

        path = os.path.join(const.DEFAULT_STRATEGY_DIR, strategy_id)
        if not _retry.wait_until(lambda: os.path.exists(path), timeout_s,
                                 interval_s=0.2):
            raise FileNotFoundError(
                f"strategy {strategy_id!r} not found at {path} after "
                f"{timeout_s:.0f}s — was the chief's strategy shipped to "
                f"this host? (AUTODIST_STRATEGY_ID contract)"
            )
        return Strategy.deserialize(strategy_id)

    def build(
        self,
        loss_fn: Callable,
        params: Any,
        example_batch: Any = None,
        optimizer: Union[OptimizerSpec, optax.GradientTransformation, None] = None,
        has_aux: bool = False,
        sparse_names: Sequence[str] = (),
        expert_names: Sequence[str] = (),
        donate_state: bool = True,
        host_offload: Union[bool, str] = False,
        grad_accum_steps: int = 1,
        remat: Union[bool, str] = False,
        compute_dtype: Union[str, None] = None,
        record_norms: bool = False,
    ) -> "Union[DistributedTrainStep, AsyncPSTrainer]":
        """Capture → strategy → compile → lower (autodist.py:139-150).

        Returns a :class:`DistributedTrainStep` (SPMD path), or — when the
        strategy carries ``sync=False`` PS nodes — a host-driven
        :class:`autodist_tpu.runtime.async_ps.AsyncPSTrainer`, whose
        ``run(state, next_batch_callable, n_pushes)`` signature differs
        from the SPMD step's ``run(state, batch, n_steps)`` (asynchronous
        pulls need a batch *source*, not one batch). See docs/async_ps.md.

        ``optimizer`` may be an :class:`OptimizerSpec` (serializable, lets
        builders see the optimizer) or a raw optax transform.
        ``host_offload=True`` parks PS-synchronized parameters + optimizer
        slots in pinned host memory, streaming through HBM per step (the
        reference's params-on-CPU placement, ps_strategy.py:38-55);
        ``host_offload="from_strategy"`` follows the strategy's own
        placement instead — only variables whose ``reduction_destination``
        (node- or shard-level) names a host CPU device are offloaded.
        ``grad_accum_steps=k`` microbatches each step k-ways (activation
        memory ÷ k, same update for batch-mean losses).
        ``remat`` rematerializes the forward pass during backward
        (``jax.checkpoint``): ``True`` saves nothing (max memory savings,
        ~+1/3 FLOPs), or pass a ``jax.checkpoint_policies`` name (e.g.
        ``"dots_saveable"``) to keep MXU outputs and recompute the rest —
        the HBM-vs-FLOPs trade the TPU guide recommends.
        ``record_norms=True`` adds global gradient/update L2 norms to the
        step metrics (two cheap reductions) — the flight recorder persists
        them and the obs sentry's SNT002 non-finite-norm check watches
        them (docs/observability.md).
        ``compute_dtype="bfloat16"`` is the mixed-precision master-weight
        policy: floating-point parameters are cast to the compute dtype on
        entry to the loss (XLA fuses the casts into the consuming
        matmuls, so the MXU sees bf16 operands and param HBM reads
        halve), while the stored parameters, gradients, and optimizer
        update stay full fp32 — autodiff through the cast upcasts the
        gradient automatically. Zoo models already cast activations
        internally; this knob brings user-supplied fp32 models onto the
        same MXU contract without touching their code.
        """
        opt_spec, tx = _resolve_optimizer(optimizer)

        model_item = ModelItem.from_params(
            params,
            # "custom" (raw optax) flows through so planners know the slot
            # count is unknown and must assume the conservative worst case.
            optimizer_spec=opt_spec,
            loss_fn=loss_fn,
            example_batch=example_batch,
            sparse_names=sparse_names,
            expert_names=expert_names,
        )
        strategy = self._build_or_load_strategy(model_item)
        compiled = StrategyCompiler(model_item).compile(strategy)
        if compute_dtype is not None:
            # Wrap AFTER ModelItem capture (like remat below): sparse
            # detection must run on the bare loss_fn. Only floating leaves
            # cast — integer tables/embedding ids pass through. BEFORE the
            # async route, so mixed precision composes with sync=False
            # (workers compute in bf16, the server's master weights stay
            # fp32) and an invalid dtype fails fast on every path.
            loss_fn = _cast_compute(loss_fn, compute_dtype)
        async_trainer = self._maybe_build_async(
            compiled, model_item, loss_fn, tx, has_aux=has_aux,
            host_offload=host_offload, grad_accum_steps=grad_accum_steps,
            remat=remat)
        if async_trainer is not None:
            return async_trainer
        plan = GraphTransformer(
            compiled, model_item, self.mesh, host_offload=host_offload
        ).transform()
        logging.debug("sharding plan:\n%s", plan.describe())
        if remat:
            # Wrap AFTER ModelItem capture: _trace_analysis cannot see through
            # a remat2 equation, so sparse-update detection must run on the
            # bare loss_fn.
            loss_fn = jax.checkpoint(loss_fn, policy=_remat_policy(remat))
        step = DistributedTrainStep(
            plan, loss_fn, tx, has_aux=has_aux, donate_state=donate_state,
            grad_accum_steps=grad_accum_steps, record_norms=record_norms,
        )
        self._built, self._strategy, self._model_item = step, compiled, model_item
        return step

    # -------------------------------------------------------------- async
    def _maybe_build_async(self, compiled, model_item, loss_fn, tx, *,
                           has_aux, host_offload, grad_accum_steps, remat):
        """Route ``sync=False`` strategies to the host-driven async PS.

        The reference's asynchronous training mode (synchronizers.proto:28,
        ps_synchronizer.py:553-630) has no SPMD rendering — lockstep jitted
        programs cannot express "a worker that doesn't wait" — so the
        asynchrony lives in the host dispatch schedule instead
        (runtime/async_ps.py, docs/async_ps.md). Returns None for fully
        synchronous strategies.
        """
        from autodist_tpu.strategy.ir import PSSynchronizer
        from autodist_tpu.strategy.ir import iter_synchronizers as _syncs

        async_nodes = [
            n for n in compiled.node_config
            if any(isinstance(s, PSSynchronizer) and not s.sync
                   for s in _syncs(n))
        ]
        if not async_nodes:
            return None
        if len(async_nodes) != len(compiled.node_config):
            raise NotImplementedError(
                "strategies mixing sync and async synchronizers have no "
                "rendering: under the host-driven async loop every "
                "variable's update applies per push. Make the strategy "
                "uniformly sync or uniformly async (sync=False)."
            )
        unsupported = []
        if host_offload:
            unsupported.append("host_offload")
        if grad_accum_steps != 1:
            unsupported.append("grad_accum_steps")
        if remat:
            unsupported.append("remat")
        if unsupported:
            raise NotImplementedError(
                f"async PS (sync=False) does not compose with "
                f"{', '.join(unsupported)}; these knobs belong to the SPMD "
                f"lowering path."
            )
        from autodist_tpu.runtime.async_ps import AsyncPSTrainer

        staleness = max(
            (s.staleness for n in async_nodes for s in _syncs(n)
             if isinstance(s, PSSynchronizer)),
            default=0,
        )
        n_workers = max(1, len(compiled.graph_config.replicas))
        trainer = AsyncPSTrainer(
            loss_fn, tx, n_workers=n_workers, staleness=staleness,
            has_aux=has_aux,
        )
        self._built, self._strategy, self._model_item = (
            trainer, compiled, model_item)
        logging.info(
            "sync=False strategy: routed to host-driven AsyncPSTrainer "
            "(%d workers, staleness=%d)", n_workers, staleness)
        return trainer

    # ------------------------------------------------------------ inference
    def build_inference(
        self,
        params: Any,
        apply_fn: Optional[Callable] = None,
        decode_model=None,
        checkpoint: Optional[str] = None,
        n_slots: int = 8,
        max_len: Optional[int] = None,
        page_len: int = 16,
        n_pages: Optional[int] = None,
        prefill_chunk: Optional[int] = None,
        draft_params: Any = None,
        draft_decode_model=None,
        draft_checkpoint: Optional[str] = None,
        spec_k: int = 4,
        draft_n_pages: Optional[int] = None,
        prefix_cache: bool = False,
    ):
        """Compile a sharded *inference* engine over this AutoDist's mesh —
        the serving counterpart of :meth:`build` (same capture → strategy →
        compile → lower pipeline, a forward/decode step instead of a train
        step; docs/serving.md).

        ``apply_fn(params, batch)`` enables one-shot inference
        (:meth:`~autodist_tpu.serve.InferenceEngine.infer`); ``decode_model``
        (e.g. ``autodist_tpu.models.transformer.decode_model(cfg)``) enables
        autoregressive paged KV-cache decode behind the continuous batcher:
        ``n_slots`` decode rows over a fixed pool of ``page_len``-token KV
        pages (``n_pages`` overrides the pool size; the default funds it
        from this AutoDist's ResourceSpec HBM headroom), with prompts
        prefilled in ``prefill_chunk``-token chunks (default: one page)
        interleaved with decode. ``checkpoint`` restores parameters from a
        ``checkpoint/saver.py`` checkpoint directly into the plan's
        shardings (partial parallel reads — no host ever holds the full
        logical arrays). The strategy comes from this AutoDist's builder
        with the usual chief-builds/workers-receive handoff, so a fleet
        serves one consistent plan.

        ``draft_params`` + ``draft_decode_model`` turn the engine into a
        :class:`~autodist_tpu.serve.spec.SpecDecodeEngine` — speculative
        decode with a small draft model (same mesh, its own ShardingPlan
        compiled through the same builder, its own paged KV pool of
        ``draft_n_pages``; ``draft_checkpoint`` restores it through the
        same Saver path), proposing ``spec_k`` tokens per slot per round
        with lossless greedy verification (docs/serving.md § speculative
        decode).

        ``prefix_cache=True`` enables copy-on-write prefix sharing over
        the page pool (``serve/prefix.py``): admissions whose prompts
        share cached block prefixes map onto the same physical pages and
        prefill only their suffix (docs/serving.md § prefix sharing); a
        spec-decode engine shares one tree across its target and draft
        pools.
        """
        from autodist_tpu.serve.engine import InferenceEngine

        model_item = ModelItem.from_params(params)
        strategy = self._build_or_load_strategy(model_item)
        compiled = StrategyCompiler(model_item).compile(strategy)
        plan = GraphTransformer(compiled, model_item, self.mesh).transform()
        logging.debug("inference sharding plan:\n%s", plan.describe())
        if checkpoint is not None:
            params = InferenceEngine.restore_params(checkpoint, params, plan)
        engine_kwargs = dict(
            n_slots=n_slots, page_len=page_len, n_pages=n_pages,
            prefill_chunk=prefill_chunk, max_len=max_len,
            resource_spec=self.resource_spec,
            prefix_cache=prefix_cache,
        )
        if draft_params is not None:
            from autodist_tpu.serve.spec import (
                SpecDecodeEngine, build_draft_plan)

            # The draft rides the same builder over the same mesh but
            # skips the strategy-id handoff: its build is deterministic
            # per (builder, model, spec), so every process of a fleet
            # derives the identical draft plan locally while the TARGET
            # plan still travels the normal chief->worker channel.
            draft_plan = build_draft_plan(
                draft_params, self.mesh, resource_spec=self.resource_spec,
                strategy_builder=self.strategy_builder)
            if draft_checkpoint is not None:
                draft_params = InferenceEngine.restore_params(
                    draft_checkpoint, draft_params, draft_plan)
            engine = SpecDecodeEngine(
                params, plan, draft_params, draft_plan,
                apply_fn=apply_fn, decode_model=decode_model,
                draft_decode_model=draft_decode_model, spec_k=spec_k,
                draft_n_pages=draft_n_pages, **engine_kwargs)
        else:
            engine = InferenceEngine(
                params, plan, apply_fn=apply_fn, decode_model=decode_model,
                **engine_kwargs)
        self._strategy, self._model_item = compiled, model_item
        return engine

    # ------------------------------------------------------------- pipeline
    def build_pipeline(
        self,
        stage_fn: Callable,
        loss_head: Callable,
        n_microbatches: int,
        optimizer: Union[OptimizerSpec, optax.GradientTransformation, None] = None,
        donate_state: bool = True,
    ):
        """Pipeline-parallel train step over this AutoDist's mesh.

        The pipelined counterpart of :meth:`build` for stage-stack models
        (``stage_fn(stage_params, h) -> h`` shape-preserving, params given
        stacked ``[S, ...]`` to ``init``): returns a
        :class:`~autodist_tpu.parallel.PipelineTrainStep` with the same
        ``init / __call__ / run / evaluate`` contract, running the
        interleaved-1F1B schedule over the mesh ``pipe`` axis while the
        batch shards over ``data`` (beyond-reference capability;
        SURVEY.md §2.2 lists pipeline parallelism as absent upstream).
        """
        from autodist_tpu.parallel import PipelineTrainStep

        _, tx = _resolve_optimizer(optimizer)
        return PipelineTrainStep(
            stage_fn, loss_head, tx, n_microbatches,
            mesh=self.mesh, donate_state=donate_state,
        )

    # -------------------------------------------------------------- elastic
    def elastic_rebuild(
        self,
        loss_fn: Callable,
        params: Any,
        example_batch: Any = None,
        devices: Optional[Sequence] = None,
        optimizer: Union[OptimizerSpec, optax.GradientTransformation, None] = None,
        **recompile_kwargs,
    ):
        """Elastic restart onto the SURVIVING devices: re-derive the
        resource spec from whatever is still alive, recompile the
        Strategy→ShardingPlan on the resized mesh, and restore the newest
        integrity-verified snapshot into the new shardings
        (``ft/elastic.py``; requires ``fault_tolerance=FTConfig(...)``).

        Returns ``(step, state)``. This AutoDist's ``resource_spec`` /
        ``mesh`` are repointed at the surviving cluster so subsequent
        ``build``/``build_inference`` calls compile for the same resized
        mesh the restored state lives on.
        """
        if self.ft is None:
            raise RuntimeError(
                "elastic_rebuild needs fault tolerance enabled: construct "
                "AutoDist(fault_tolerance=FTConfig(...))")
        from autodist_tpu.ft.elastic import surviving_resource_spec

        devices = list(devices) if devices is not None else jax.devices()
        recompile_kwargs.setdefault("mesh_axes", self.mesh_axes)
        step, state = self.ft.elastic.resume(
            loss_fn, params, example_batch,
            devices=devices,
            strategy_builder=self.strategy_builder,
            optimizer=optimizer,
            spec_template=self.resource_spec,
            **recompile_kwargs,
        )
        self.resource_spec = surviving_resource_spec(
            devices, template=self.resource_spec)
        self._mesh = step.plan.mesh
        self._built = step
        return step, state

    # ----------------------------------------------------------------- tune
    def tune(
        self,
        loss_fn: Callable,
        params: Any,
        example_batch: Any,
        candidates: Optional[Sequence] = None,
        window: int = 8,
        **build_kwargs,
    ) -> DistributedTrainStep:
        """Measured strategy selection: build each candidate strategy, time
        ``_TUNE_TRIAL_WINDOWS`` (4) back-to-back device-side windows of
        ``window`` real training steps each (plus one warmup window), keep
        the fastest.

        The analytical :class:`~autodist_tpu.strategy.cost_model.CostModel`
        behind :class:`~autodist_tpu.strategy.Auto` *predicts*; ``tune``
        *measures* — the empirical complement the reference project pointed
        at (its performance page shows the best strategy differs per model,
        ``docs/usage/performance.md:14``, but ships no way to find it).
        Compiles every candidate, so expect ~N× the normal build latency;
        infeasible or non-compiling candidates are skipped with a warning.

        ``candidates``: ``[(name, StrategyBuilder), ...]``; defaults to the
        Auto dense slate (+ Parallax, which degenerates to AllReduce on
        dense-only models). On a multi-process fleet every process times
        every candidate in lockstep (the candidates' collectives keep the
        fleet synchronized), the CHIEF's measurements decide, and the
        winner's index is broadcast over the runtime — so the election is
        both *measured* and fleet-consistent, the same broadcast contract
        the strategy handoff uses (``_sync_strategy_multihost``).
        """
        import time

        from autodist_tpu.strategy.cost_model import CostModel, candidate_slate

        if candidates is None:
            candidates = candidate_slate()
        multi = jax.process_count() > 1
        if multi:
            # The feed contract depends only on (batch, process count) —
            # fail it once, loudly, before paying any candidate builds.
            self._check_fleet_batch(example_batch)

        def _sync(tree) -> None:
            # Scalar fetch, not block_until_ready: reliable on every
            # platform including tunneled devices (docs/performance.md).
            leaf = jax.tree_util.tree_leaves(tree)[0]
            float(jnp.asarray(leaf).ravel()[0])

        results = []  # (name, dt) per candidate; inf when it failed here
        predicted = {}  # name -> analytical StrategyCost of the strategy timed
        best = None   # single-process: (name, dt, builder, step, strategy, item)
        for name, builder in candidates:
            self.strategy_builder = builder
            try:
                step = self.build(loss_fn, params, example_batch, **build_kwargs)
                if multi:
                    # Already device-resident global arrays (assembled via
                    # plan.global_batch_from_local).
                    bench_batch = self._fleet_bench_batch(step.plan, example_batch)
                else:
                    # Pin ONCE in HBM, synced before the warmup run
                    # (mirroring bench.py's measure()): the pipelined
                    # windows below dispatch back-to-back, and re-uploading
                    # a host batch against an in-flight dispatch is the
                    # documented tunnel-deadlock trigger (train.py fed-path
                    # note) — besides serializing the transfer into the
                    # timed region and skewing calibration absolutes.
                    bench_batch = jax.device_put(
                        example_batch, step.plan.batch_shardings(example_batch))
                jax.block_until_ready(bench_batch)
                state = step.init(params)
                state, _ = step.run(state, bench_batch, window)  # compile+warm
                _sync(state.params)
                # Back-to-back windows with one trailing sync: run() returns
                # immediately and the programs pipeline on the device, so the
                # platform's device->host fetch latency (~64 ms through the
                # axon tunnel) is paid once, not per window — it biased
                # every candidate's absolute ms/step equally (fair ranking,
                # skewed calibration). 4 windows amortize it ~4x.
                t0 = time.perf_counter()
                for _ in range(_TUNE_TRIAL_WINDOWS):
                    state, _ = step.run(state, bench_batch, window)
                _sync(state.params)
                dt = (time.perf_counter() - t0) / (_TUNE_TRIAL_WINDOWS * window)
            except Exception as e:  # noqa: BLE001 - candidate-level isolation
                # Fleet alignment: chief-only build failures ship a sentinel
                # through the strategy broadcast so every process raises (and
                # lands here) for the same candidate; compile/run failures
                # are SPMD-deterministic (same program everywhere). Either
                # way the results lists stay index-aligned, and the
                # election below only considers candidates that succeeded
                # on every process.
                logging.warning("tune: candidate %s failed (%s); skipped", name, e)
                results.append((name, float("inf")))
                continue
            finally:
                # Free this candidate's device train state before the next
                # one's init(): holding both transiently doubles HBM and
                # would make near-capacity models fail every candidate after
                # the first (electing the first, not the fastest).
                state = None  # noqa: F841
            logging.info("tune: %-16s %.3f ms/step", name, dt * 1e3)
            results.append((name, dt))
            try:
                # Cost the exact strategy just timed (self._strategy is the
                # one build() compiled — on a fleet, the chief-broadcast one).
                predicted[name] = CostModel(
                    self._model_item, self.resource_spec
                ).strategy_cost(self._strategy)
            except Exception:  # noqa: BLE001 - calibration is best-effort
                pass
            if multi:
                # The winner is rebuilt after the election; holding every
                # candidate's compiled programs would waste HBM meanwhile.
                step = None  # noqa: F841
            elif best is None or dt < best[1]:
                # Keep only the running best — a losing step's compiled
                # device programs are dead weight for the rest of the sweep.
                best = (name, dt, builder, step, self._strategy, self._model_item)

        self._record_calibration(results, predicted)

        if multi:
            from jax.experimental import multihost_utils

            dts = np.array([dt for _, dt in results], np.float64)
            # Fleet-wide election in one collective: allgather every
            # process's timing vector (identical result everywhere), keep
            # only candidates that succeeded on EVERY process, then pick
            # the chief's fastest among those. Deterministic on all
            # processes with no follow-up broadcast, and a candidate that
            # failed anywhere can never be elected — so the winner rebuild
            # below cannot diverge. (A failure *inside* a candidate's
            # collectives still hangs like any SPMD program would; this
            # protects the host-side stages around them.)
            all_dts = np.asarray(
                multihost_utils.process_allgather(dts)
            ).reshape(jax.process_count(), len(results))
            fleet_valid = np.isfinite(all_dts).all(axis=0)
            if not fleet_valid.any():
                raise RuntimeError(
                    "tune(): every candidate strategy failed to build/run "
                    "on at least one process")
            chief_dts = np.where(fleet_valid, all_dts[0], np.inf)
            idx = int(np.argmin(chief_dts))
            best_name = results[idx][0]
            logging.info(
                "tune (fleet) selected %s — chief-measured; local %.3f ms/step",
                best_name, results[idx][1] * 1e3,
            )
            self._record_tune_obs(results, best_name)
            self.strategy_builder = dict(candidates)[best_name]
            return self.build(loss_fn, params, example_batch, **build_kwargs)

        if best is None:
            raise RuntimeError("tune(): every candidate strategy failed to build/run")
        best_name, best_dt, best_builder, best_step, best_strategy, best_item = best
        logging.info("tune selected %s (%.3f ms/step)", best_name, best_dt * 1e3)
        self._record_tune_obs(results, best_name)
        # Leave every selection-visible surface pointing at the WINNER, not
        # the last candidate tried: the builder (future build() calls) and
        # the strategy id env (coordinator-relaunched workers load by it).
        self.strategy_builder = best_builder
        os.environ[ENV.AUTODIST_STRATEGY_ID.name] = best_strategy.id
        self._built, self._strategy, self._model_item = (
            best_step, best_strategy, best_item,
        )
        return best_step

    def _record_tune_obs(self, results, selected: str) -> None:
        """Auditable strategy selection: every candidate's name and measured
        seconds (inf = failed) plus the winner land in the process metrics
        registry and the obs span timeline, and ride
        ``last_tune_results["measured"]/["selected"]`` — so *why this
        strategy* is answerable after the fact from any export surface,
        not just the tune call's log lines. Best-effort: never fails a tune.
        """
        import time as _time

        try:
            from autodist_tpu import metrics as M
            from autodist_tpu.obs import spans as _spans

            reg = M.registry
            reg.counter("tune_runs_total").inc()
            reg.gauge("tune_candidates").set(len(results))
            now = _time.time()
            for name, dt in results:
                failed = not (dt < float("inf"))
                if not failed:
                    reg.gauge(f"tune_measured_ms_{name}").set(dt * 1e3)
                _spans.add_span(
                    "tune.candidate", now, 0.0 if failed else dt,
                    candidate=name, failed=failed,
                    selected=(name == selected))
            sel_dt = dict(results).get(selected)
            if sel_dt is not None and sel_dt < float("inf"):
                reg.gauge("tune_selected_ms").set(sel_dt * 1e3)
            self.last_tune_results = {
                **(self.last_tune_results or {}),
                "measured": {n: dt for n, dt in results},
                "selected": selected,
            }
        except Exception:  # noqa: BLE001 - diagnostics must not break tune
            logging.warning("tune: obs audit recording failed", exc_info=True)

    def _record_calibration(self, results, predicted) -> None:
        """Close the predict→measure loop (VERDICT r1 next #10): pair each
        candidate's measured step time with the analytical cost of the
        strategy actually timed (computed in the sweep loop), fit a
        :class:`~autodist_tpu.strategy.cost_model.Calibration`
        (measured ≈ base + scale × predicted), and persist it so
        ``explain`` can show calibrated absolute step times next to the
        analytical column. On a fleet, only the chief writes (atomic
        replace inside ``Calibration.save``), so the persisted fit is the
        chief's timings — the ones that decide elections. Best-effort:
        never fails a tune."""
        try:
            from autodist_tpu.strategy.cost_model import Calibration

            meas, pred, table = [], [], {}
            for name, dt in results:
                cost = predicted.get(name)
                if cost is None or not (dt < float("inf")):
                    continue
                meas.append(dt)
                pred.append(cost.total_s)
                table[name] = {"measured_s": dt, "predicted_s": cost.total_s}
            if not meas:
                return
            device = ""
            try:
                device = str(jax.devices()[0].device_kind)
            except Exception:  # noqa: BLE001
                pass
            calib = Calibration.fit(pred, meas, device=device)
            path = calib.save() if jax.process_index() == 0 else None
            plan_calib = None
            if jax.process_index() == 0:
                # The same sweep feeds the planner's per-topology
                # per-component calibration (docs/planner.md): every
                # measured candidate becomes a CalibrationRecord, so a
                # later `strategy_builder="plan"` run prices THIS topology
                # instead of nominal constants.
                try:
                    from autodist_tpu.plan.calibrate import (
                        CalibrationRecord, calibrate_from_records)

                    plan_calib = calibrate_from_records(
                        [CalibrationRecord.from_cost(
                            predicted[n], dt, name=n)
                         for n, dt in results
                         if n in predicted and dt < float("inf")],
                        self.resource_spec, device_kind=device)
                except Exception:  # noqa: BLE001 - planner feed is optional
                    logging.warning(
                        "tune: plan calibration recording failed",
                        exc_info=True)
            self.last_tune_results = {
                "table": table,
                "calibration": calib,
                "calibration_path": path,
                "plan_calibration": plan_calib,
            }
            logging.info(
                "tune calibration: measured ≈ %.3fms + %.2f × predicted "
                "(%d candidates, %s)%s",
                calib.base_s * 1e3, calib.scale, calib.n_points, device,
                f" -> {path}" if path else "",
            )
        except Exception as e:  # noqa: BLE001 - diagnostics must not break tune
            logging.warning("tune: calibration recording failed (%s)", e)

    @staticmethod
    def _check_fleet_batch(example_batch) -> None:
        """Pre-sweep validation of the fleet feed contract (see
        :meth:`_fleet_bench_batch`), so a bad batch fails once with the
        real cause instead of failing every candidate after a full build."""
        pc = jax.process_count()
        for leaf in jax.tree.leaves(example_batch):
            shape = tuple(np.shape(leaf))
            # Broadcast leaves (is_broadcast_leaf — masks, per-feature
            # constants) replicate and are exempt from the per-process
            # divisibility contract.
            if not is_broadcast_leaf(shape) and shape[0] % pc != 0:
                raise ValueError(
                    f"tune() on a {pc}-process fleet needs every batched "
                    f"leaf's leading dim divisible by {pc}; got {shape}"
                )

    @staticmethod
    def _fleet_bench_batch(plan: ShardingPlan, example_batch):
        """Global example batch → fleet-fed global arrays for timing.

        On a multi-process fleet a raw host batch cannot be fed to a
        sharded jit (numpy + non-addressable shardings is rejected); the
        feed contract is per-process local slices assembled via
        ``plan.global_batch_from_local``. Every process holds the same
        global example, so each takes its row slice.
        (:meth:`_check_fleet_batch` owns the divisibility validation.)
        """
        pi, pc = jax.process_index(), jax.process_count()
        AutoDist._check_fleet_batch(example_batch)

        # The broadcast mask comes from the GLOBAL example shapes — after
        # slicing, a genuinely batched leaf with global batch == pc also has
        # local leading dim 1 and could not be told apart.
        broadcast = jax.tree.map(
            lambda x: is_broadcast_leaf(np.shape(x)), example_batch)

        def to_local(x, is_bcast):
            arr = np.asarray(x)
            # Broadcast leaves stay whole on every process; slicing them
            # would hand k=0 rows to each host.
            if not is_bcast:
                k = arr.shape[0] // pc
                return arr[pi * k:(pi + 1) * k]
            return arr

        return plan.global_batch_from_local(
            jax.tree.map(to_local, example_batch, broadcast), broadcast)

    # ------------------------------------------------------------- accessors
    @property
    def strategy(self) -> Optional[Strategy]:
        return self._strategy

    @property
    def plan(self) -> Optional[ShardingPlan]:
        # AsyncPSTrainer has no sharding plan (host-driven engine): None,
        # same as "not built yet", so function()'s guidance path still fires.
        return getattr(self._built, "plan", None)

    @property
    def model_item(self) -> Optional[ModelItem]:
        return self._model_item

    # ------------------------------------------------------------- tf2-style
    def function(self, fn: Callable) -> Callable:
        """``autodist.function`` analog (autodist.py:269-289): wrap an
        arbitrary step function so its array arguments are sharded along the
        mesh data axis on first call, then executed jitted.

        Unlike the TF2 path (which replayed ndarrays through placeholders),
        JAX functions are already traceable — this only adds sharding
        constraints + compile caching.
        """
        jitted = jax.jit(fn)

        def wrapper(*args):
            plan = self.plan
            if plan is None:
                raise RuntimeError("call AutoDist.build(...) before .function(...)")
            args = jax.device_put(args, plan.batch_shardings(args, strict=False))
            return jitted(*args)

        return wrapper

    @contextmanager
    def scope(self):
        """Model-definition scope (autodist.py:309-322). JAX needs no graph
        capture; the scope exists for lifecycle parity and future hooks."""
        yield self
