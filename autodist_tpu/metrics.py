"""Evaluation metrics over sharded models.

The reference's vendored benchmark trainers tracked task metrics
(top-1/top-5 for ImageNet, masked-LM accuracy for BERT, HR/NDCG for NCF)
inside ~12.9k LoC of official-models code; the framework itself shipped
none. Here metrics are a thin functional layer over the same contract
the rest of the stack uses: a jitted ``(params, batch) -> {name: value}``
function evaluated under the plan's parameter shardings, plus a
weighted-average aggregator for dataset-scale evaluation.

Usage::

    from autodist_tpu import metrics

    mfn = metrics.classification_metrics(model.apply, top_k=(1, 5))
    results = metrics.evaluate_dataset(step, state, loader, metrics_fn=mfn)
    # {"loss": 1.93, "top1": 0.71, "top5": 0.90, "examples": 50000}
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Iterable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "accuracy",
    "top_k_accuracy",
    "perplexity",
    "classification_metrics",
    "lm_metrics",
    "ranking_metrics",
    "evaluate_dataset",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "registry",
]


# ------------------------------------------------------------- pure metrics
def accuracy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Fraction of rows whose argmax matches the integer label."""
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))


def top_k_accuracy(logits: jnp.ndarray, labels: jnp.ndarray, k: int) -> jnp.ndarray:
    """Fraction of rows whose label lands in the k highest logits."""
    _, top = jax.lax.top_k(logits, k)
    hit = jnp.any(top == labels[..., None], axis=-1)
    return jnp.mean(hit.astype(jnp.float32))


def perplexity(mean_loss) -> float:
    """exp(cross-entropy) — the LM-convention view of a token loss."""
    return float(np.exp(np.asarray(mean_loss)))


# -------------------------------------------------------- metric factories
def classification_metrics(
    apply_fn: Callable[[Any, Any], Any],
    input_key: str = "images",
    label_key: str = "labels",
    top_k: Sequence[int] = (1,),
) -> Callable[[Any, Any], Dict[str, jnp.ndarray]]:
    """(params, batch) -> {top1, top5, ...} for dict image/label batches
    via the model's ``apply`` (every CNN zoo model exposes one)."""

    def metrics_fn(params, batch):
        logits = apply_fn(params, batch[input_key])
        labels = batch[label_key]
        out = {}
        for k in top_k:
            name = f"top{k}"
            out[name] = (accuracy(logits, labels) if k == 1
                         else top_k_accuracy(logits, labels, k))
        return out

    return metrics_fn


def lm_metrics(
    apply_fn: Callable[[Any, Any], Any],
    token_key: str = "tokens",
    shift: bool = True,
    pad_id: Optional[int] = None,
) -> Callable[[Any, Any], Dict[str, jnp.ndarray]]:
    """(params, batch) -> {token_accuracy} for next-token LMs: the model's
    logits at position t predict token t+1 (``shift=True``); ``pad_id``
    positions are masked out of the average."""

    def metrics_fn(params, batch):
        tokens = batch[token_key]
        logits = apply_fn(params, tokens)
        if shift:
            logits, targets = logits[:, :-1], tokens[:, 1:]
        else:
            targets = tokens
        correct = (jnp.argmax(logits, axis=-1) == targets).astype(jnp.float32)
        if pad_id is not None:
            # Masked mean PLUS its weight: a per-batch mean over valid
            # tokens must aggregate across batches weighted by the valid
            # count, not the row count (the __weight convention
            # evaluate_dataset consumes).
            mask = (targets != pad_id).astype(jnp.float32)
            n_valid = jnp.sum(mask)
            return {
                "token_accuracy": jnp.sum(correct * mask)
                / jnp.maximum(n_valid, 1.0),
                "token_accuracy__weight": n_valid,
            }
        return {"token_accuracy": jnp.mean(correct)}

    return metrics_fn


def ranking_metrics(
    score_fn: Callable[[Any, Any, Any], Any],
    user_key: str = "users",
    item_key: str = "candidates",
    k: int = 10,
) -> Callable[[Any, Any], Dict[str, jnp.ndarray]]:
    """HR@k / NDCG@k for implicit-feedback recommenders (the reference
    NCF benchmark's metrics, utils/recommendation eval layout): each row
    is one user with candidate items ``[C]`` whose POSITIVE sits in
    column 0 and the rest are sampled negatives. ``score_fn(params,
    users, items)`` scores equal-length user/item vectors (for the zoo
    NeuMF: ``lambda p, u, i: model.apply(p, {"users": u, "items": i})``).

    The positive's rank is the number of negatives scored strictly
    higher (ties resolve in the positive's favor — matching argsort-less
    hand counting); HR@k = rank < k, NDCG@k = 1/log2(rank+2) when hit.
    """

    def metrics_fn(params, batch):
        users = batch[user_key]                    # [B]
        cands = batch[item_key]                    # [B, C]
        scores = jax.vmap(
            lambda u, items: score_fn(
                # Broadcast u in ITS OWN dtype: casting user ids to the
                # candidate dtype could silently wrap when the user vocab
                # outgrows the item dtype.
                params, jnp.full(items.shape, u, u.dtype), items)
        )(users, cands)                            # [B, C]
        pos = scores[:, :1]
        rank = jnp.sum((scores[:, 1:] > pos).astype(jnp.int32), axis=1)
        hit = (rank < k).astype(jnp.float32)
        ndcg = jnp.where(rank < k,
                         1.0 / jnp.log2(rank.astype(jnp.float32) + 2.0),
                         0.0)
        return {f"hr@{k}": jnp.mean(hit), f"ndcg@{k}": jnp.mean(ndcg)}

    return metrics_fn


# ----------------------------------------------------------- aggregation
def _batch_size(batch) -> int:
    for leaf in jax.tree.leaves(batch):
        if getattr(leaf, "ndim", 0) >= 1:
            return int(leaf.shape[0])
    return 0


def _logical_params(step, state):
    """The user-shaped parameter view — the step's own definition when
    available (``DistributedTrainStep.logical_params`` handles pad-and-
    mask storage), raw params otherwise. Offload streaming is handled by
    ``step.compile_metrics`` inside the jitted program, not here."""
    if hasattr(step, "logical_params"):
        return step.logical_params(state)
    return getattr(state, "params", state)


def _compile(step, state, metrics_fn):
    """Prefer the step's jit (streams offloaded leaves + unpads storage
    inside the trace — lowering.compile_metrics); plain jit for foreign
    step objects (tests, custom engines)."""
    if hasattr(step, "compile_metrics"):
        return step.compile_metrics(metrics_fn, state), True
    return jax.jit(metrics_fn), False


def evaluate_dataset(
    step,
    state,
    batches: Iterable[Any],
    metrics_fn: Optional[Callable[[Any, Any], Dict[str, Any]]] = None,
    max_batches: Optional[int] = None,
) -> Dict[str, float]:
    """Weighted-average ``step.evaluate`` loss (+ optional task metrics)
    over an iterable of batches (a DataLoader or any batch iterator).

    Each metric's contribution is weighted by the batch's leading
    dimension, so ragged tails average correctly; a metrics_fn may
    override the weight for metric ``k`` by also returning
    ``"<k>__weight"`` (masked metrics — ``lm_metrics(pad_id=...)`` counts
    valid tokens this way). ``metrics_fn`` runs jitted against the
    LOGICAL parameter view (unpadded, HBM-resident — the same handling
    the step's own loss path applies). Returns
    ``{"loss": ..., <metrics...>, "examples": N}``.

    Multi-host: aggregation here is per-process (host-side Python). On a
    fleet either feed every process the same eval batches (replicated
    evaluation — results identical everywhere), or give each process a
    disjoint shard and combine externally: per-metric sums are
    recoverable as ``result[k] * result["examples"]`` (row-weighted
    metrics), so they add across processes.
    """
    compiled_metrics = step_jit = None
    if metrics_fn is not None:
        compiled_metrics, step_jit = _compile(step, state, metrics_fn)
    sums: Dict[str, float] = {}
    weights: Dict[str, float] = {}
    n_total = 0
    logical = None
    for i, batch in enumerate(batches):
        if max_batches is not None and i >= max_batches:
            break
        n = _batch_size(batch)
        if n == 0:
            continue
        out = step.evaluate(state, batch)
        vals = {"loss": float(out["loss"])}
        batch_weights = {}
        if compiled_metrics is not None:
            if step_jit:
                # The step's jit streams/unpads internally: raw params in.
                metric_params = getattr(state, "params", state)
            else:
                if logical is None:
                    logical = _logical_params(step, state)
                metric_params = logical
            m = {k: float(v) for k, v in
                 compiled_metrics(metric_params, batch).items()}
            batch_weights = {k[: -len("__weight")]: m.pop(k)
                             for k in list(m) if k.endswith("__weight")}
            vals.update(m)
        for k, v in vals.items():
            w = batch_weights.get(k, float(n))
            sums[k] = sums.get(k, 0.0) + v * w
            weights[k] = weights.get(k, 0.0) + w
        n_total += n
    if n_total == 0:
        return {"examples": 0}
    result = {k: (sums[k] / weights[k]) if weights[k] else 0.0 for k in sums}
    result["examples"] = n_total
    return result


# ------------------------------------------------------- operational registry
# The functions above evaluate *task* metrics (accuracy, perplexity) over a
# dataset. Serving needs *operational* metrics — latency percentiles, queue
# depth, token throughput — observed from hot host threads. This registry is
# the process-wide export surface the serve subsystem (and anything else
# host-driven, e.g. the async PS trainer) publishes through: prometheus-style
# named counters/gauges/histograms, thread-safe, renderable as text or a
# snapshot dict.


class Counter:
    """Monotonic counter (requests served, tokens generated)."""

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Point-in-time value (queue depth, active slots, tokens/sec)."""

    def __init__(self):
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = float(v)

    def add(self, n: float = 1.0) -> None:
        self._value += n

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Latency-style distribution with exact count/sum and sampled quantiles.

    Keeps up to ``max_samples`` observations; past that, reservoir sampling
    (Vitter's algorithm R) keeps the retained set a uniform sample of the
    stream, so percentiles stay unbiased at serving volumes while memory
    stays bounded.

    The retained reservoir is maintained **sorted** (``bisect.insort`` on
    observe — an O(max_samples) memmove of doubles, microseconds at the
    4096 default) so :meth:`percentile` is an O(1) index + interpolation
    instead of a full ``np.percentile`` pass over every retained
    observation per quantile per render: ``GET /metrics`` under serve load
    renders every histogram in O(quantiles), not O(samples·log·quantiles).
    The interpolation replicates numpy's ``linear`` method bit-for-bit
    (including its t≥0.5 lerp branch), so the rendered exposition is
    byte-identical to the previous implementation — pinned by the
    existing byte-parity golden tests.
    """

    def __init__(self, max_samples: int = 4096):
        self._samples: list = []   # SORTED retained reservoir
        self._max = max_samples
        self._count = 0
        self._sum = 0.0
        self._lock = threading.Lock()
        self._rng = np.random.default_rng(0)

    def observe(self, v: float) -> None:
        import bisect

        v = float(v)
        with self._lock:
            self._count += 1
            self._sum += v
            if len(self._samples) < self._max:
                bisect.insort(self._samples, v)
            else:
                j = int(self._rng.integers(0, self._count))
                if j < self._max:
                    # Evicting the j-th order statistic for uniform random
                    # j evicts a uniform-random retained sample — same
                    # algorithm-R distribution as the unsorted variant.
                    del self._samples[j]
                    bisect.insort(self._samples, v)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def percentile(self, p: float) -> float:
        """p in [0, 100]; nan when nothing was observed. O(1): index math
        over the sorted reservoir, numpy-'linear'-exact interpolation."""
        with self._lock:
            xs = self._samples
            if not xs:
                return float("nan")
            rank = (len(xs) - 1) * (float(p) / 100.0)
            lo = int(rank)
            hi = min(lo + 1, len(xs) - 1)
            t = rank - lo
            a, b = xs[lo], xs[hi]
            # numpy's _lerp computes b - (b-a)(1-t) for t >= 0.5 (monotone
            # guard); mirror it exactly for byte parity through %.6g.
            if t >= 0.5:
                return float(b - (b - a) * (1.0 - t))
            return float(a + (b - a) * t)

    def summary(self) -> Dict[str, float]:
        return {
            "count": float(self._count),
            "sum": self._sum,
            "mean": (self._sum / self._count) if self._count else float("nan"),
            "p50": self.percentile(50.0),
            "p90": self.percentile(90.0),
            "p99": self.percentile(99.0),
        }


class MetricsRegistry:
    """Named metric table: get-or-create by name, snapshot/render for export.

    One process-wide default lives at ``metrics.registry``; components take a
    registry argument so tests can isolate (the serve selftest passes its
    own to keep its numbers clean of earlier runs).
    """

    def __init__(self):
        self._metrics: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls()
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def snapshot(self) -> Dict[str, Any]:
        """{name: value | histogram summary dict} for JSON export."""
        with self._lock:
            items = list(self._metrics.items())
        out: Dict[str, Any] = {}
        for name, m in items:
            out[name] = m.summary() if isinstance(m, Histogram) else m.value
        return out

    def render_text(self) -> str:
        """Text exposition of this registry — delegates to THE renderer
        (``autodist_tpu.obs.exporter.render_openmetrics``) so every export
        surface emits one format; kept as a convenience method (lazy
        import: obs imports metrics at module load)."""
        from autodist_tpu.obs.exporter import render_openmetrics

        return render_openmetrics(self)


#: Process-default registry (the serve subsystem's export surface).
registry = MetricsRegistry()
