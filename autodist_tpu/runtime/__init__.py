"""Runtime (L1): multi-host bootstrap, coordination and process lifecycle.

TPU-native replacement for the reference's runtime layer — ``SSHCluster`` +
``Coordinator`` + ``server_starter`` (``/root/reference/autodist/cluster.py``,
``coordinator.py``, ``utils/server_starter.py``). The reference started a TF
grpc server on every node over SSH and re-executed the user script per worker;
here the native JAX multi-controller model plays that role: every host runs
the same script, ``jax.distributed.initialize`` forms the cluster, and XLA
ICI/DCN collectives replace grpc.

What survives from the reference (the capability contract):
- chief/worker role dispatch via the ``AUTODIST_WORKER`` env contract;
- chief builds + serializes the strategy, workers receive it by id;
- "re-run the same script on every host" launch model;
- worker monitoring with chief fail-fast on worker death;
- stale-process cleanup on node start.
"""
from autodist_tpu.runtime.cluster import Cluster
from autodist_tpu.runtime.coordinator import Coordinator
from autodist_tpu.runtime.launcher import launch, main

__all__ = ["Cluster", "Coordinator", "launch", "main"]
