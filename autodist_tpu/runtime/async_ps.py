"""Host-driven asynchronous parameter server (``sync=False`` rendering).

The reference's async PS let every worker push its gradient into the
server's optimizer the moment it was ready, with no barrier against the
other workers, and pull whatever parameters the server currently held
(ps_synchronizer.py:553-630; synchronizers.proto:28). That machine model
has no rendering *inside* an SPMD program — every device in a jitted
program is lockstep by construction — but the asynchrony never lived in
the kernels in the reference either: it lived in the host-side dispatch
schedule. This module renders exactly that part:

- One canonical parameter store (:class:`ParamServer`) owns params +
  optimizer slots behind a lock, with a monotonically increasing
  ``version`` (one bump per applied push).
- ``n_workers`` logical workers each loop pull → grad → push. A push
  applies immediately through the jitted optimizer update — no
  accumulation, no waiting for peers — so updates interleave and every
  worker computes gradients against parameters that may be stale by the
  other workers' pushes. This is the reference's async semantics.
- ``staleness=K > 0`` bounds the lag (SSP): a push whose snapshot is more
  than K versions behind is REJECTED (the gradient is dropped) and the
  worker re-pulls and recomputes on fresh parameters — stale work is
  discarded, never applied. ``staleness=0`` means unbounded (pure async),
  matching the reference's default.

Compute still runs on the device through ordinary jitted functions —
gradients ride the MXU; only the *schedule* is host-driven. On a single
chip, worker dispatches serialize on the device queue (the semantics —
interleaved, stale updates — are unchanged); on a multi-device host each
worker is pinned round-robin to a device. Multi-host asynchrony would
need a parameter RPC service, which this framework deliberately does not
ship — the SPMD collectives path (``sync=True``) is the scalable product
path; async PS exists for semantic parity and staleness research. See
docs/async_ps.md.

Two schedules:

- ``schedule="threads"`` (production): real OS threads, genuinely
  nondeterministic interleaving (jax dispatch releases the GIL).
- ``schedule="round_robin"`` (tests/debug): the same pull/push loop run
  deterministically on the calling thread — all workers pull a snapshot,
  then push in worker order. Reproducible stale-gradient dynamics.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np
import optax

from autodist_tpu import metrics as M
from autodist_tpu.utils import logging


@dataclass
class AsyncServerState:
    """Canonical server-side training state (params live HERE, not on the
    workers — the defining PS property; reference ps_strategy.py:38-55)."""

    params: Any
    opt_state: Any
    version: int = 0


@dataclass
class AsyncMetrics:
    """Per-push records, in apply order."""

    losses: List[float] = field(default_factory=list)
    lags: List[int] = field(default_factory=list)       # version - snapshot
    workers: List[int] = field(default_factory=list)    # who pushed
    wall_s: float = 0.0

    @property
    def max_lag(self) -> int:
        return max(self.lags) if self.lags else 0

    def summary(self) -> Dict[str, float]:
        return {
            "pushes": len(self.losses),
            "last_loss": self.losses[-1] if self.losses else float("nan"),
            "max_lag": self.max_lag,
            "pushes_per_sec": (len(self.losses) / self.wall_s)
            if self.wall_s > 0 else float("nan"),
        }


class ParamServer:
    """The shared store. ``pull`` returns a snapshot + its version;
    ``push`` applies one worker's gradient immediately (async apply)."""

    def __init__(self, params, tx: optax.GradientTransformation,
                 staleness: int = 0, device=None,
                 state: Optional[AsyncServerState] = None):
        self._tx = tx
        self._lock = threading.Lock()
        # The server owns ONE device; params + slots live there, and every
        # push transfers the worker's gradient onto it — that transfer IS
        # the worker→server wire of the reference's PS.
        self._device = device if device is not None else jax.local_devices()[0]
        if state is not None:
            # Adopt a restored state as-is (checkpoint resume): no fresh
            # tx.init / params copy — Adam-sized slot allocations on resume
            # would be pure waste.
            self.state = state
        else:
            params = jax.device_put(params, self._device)
            self.state = AsyncServerState(
                params=params, opt_state=tx.init(params))
        self.staleness = int(staleness)
        self.metrics = AsyncMetrics()
        # One jitted update shared by every push. NO buffer donation here:
        # pulled snapshots alias the server's buffers, so donating would
        # delete arrays workers are still computing against (async pulls
        # outlive the next apply by design).
        def _apply(params, opt_state, grads):
            updates, new_opt = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_opt

        self._apply = jax.jit(_apply)

    # ------------------------------------------------------------- protocol
    def pull(self):
        with self._lock:
            return self.state.params, self.state.version

    def push(self, grads, snapshot_version: int, worker: int,
             loss: Optional[float] = None) -> int:
        """Apply ``grads`` computed against ``snapshot_version``. Returns
        the new version, or -1 if the snapshot exceeds the staleness bound
        (SSP): the gradient is dropped and the caller must re-pull and
        recompute. With ``staleness=0`` every push applies (pure async,
        the reference default)."""
        with self._lock:
            lag = self.state.version - snapshot_version
            if self.staleness > 0 and lag > self.staleness:
                # Too stale to apply: in SSP the slow worker REFRESHES
                # rather than poisoning the model with an ancient gradient.
                # The caller re-pulls and recomputes; we record the drop.
                logging.debug(
                    "async-ps: worker %d snapshot v%d is %d > K=%d behind; "
                    "re-pull", worker, snapshot_version, lag, self.staleness)
                return -1
            self.state.params, self.state.opt_state = self._apply(
                self.state.params, self.state.opt_state,
                jax.device_put(grads, self._device))
            self.state.version += 1
            if loss is not None:
                self.metrics.losses.append(float(loss))
            self.metrics.lags.append(lag)
            self.metrics.workers.append(worker)
            return self.state.version


class AsyncPSTrainer:
    """User-facing async trainer; returned by ``AutoDist.build`` when the
    compiled strategy carries ``sync=False`` PS nodes.

    API mirrors the synchronous :class:`DistributedTrainStep` where the
    concepts map: ``init`` builds server state, ``run`` executes a fixed
    number of *pushes* (the async analog of steps), returning
    ``(state, metrics)``.
    """

    def __init__(
        self,
        loss_fn: Callable,
        tx: optax.GradientTransformation,
        n_workers: int,
        staleness: int = 0,
        schedule: str = "threads",
        has_aux: bool = False,
        devices: Optional[Sequence] = None,
        registry: Optional[M.MetricsRegistry] = None,
    ):
        if schedule not in ("threads", "round_robin"):
            raise ValueError(f"unknown schedule {schedule!r}")
        if n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        self.loss_fn = loss_fn
        self.tx = tx
        self.n_workers = n_workers
        self.staleness = int(staleness)
        self.schedule = schedule
        self.has_aux = has_aux
        self.devices = list(devices) if devices else jax.local_devices()
        self._vg = jax.jit(jax.value_and_grad(loss_fn, has_aux=has_aux))
        self._server: Optional[ParamServer] = None
        # Operational export surface: the trainer publishes through the
        # SAME registry serve does, so the one OpenMetrics renderer
        # (obs/exporter.py — serve /metrics, headless file exporter) covers
        # async-PS training without any bespoke text path.
        reg = registry or M.registry
        self._c_pushes = reg.counter("async_ps_pushes_total")
        self._g_version = reg.gauge("async_ps_version")
        self._g_loss = reg.gauge("async_ps_last_loss")
        self._g_pps = reg.gauge("async_ps_pushes_per_sec")
        self._h_lag = reg.histogram("async_ps_push_lag")
        # Pushes already exported for the CURRENT server (its per-push lists
        # restart at zero whenever a fresh ParamServer is adopted, while the
        # registry counter — possibly shared process-wide — never resets).
        self._published = 0

    # ------------------------------------------------------------------ api
    def init(self, params) -> AsyncServerState:
        self._server = ParamServer(params, self.tx, staleness=self.staleness)
        self._published = 0
        return self._server.state

    def _worker_loop(self, server: ParamServer, worker: int,
                     next_batch: Callable[[int], Any], budget: List[int],
                     budget_lock: threading.Lock):
        dev = self.devices[worker % len(self.devices)]
        while True:
            with budget_lock:
                if budget[0] <= 0:
                    return
                budget[0] -= 1
                tick = budget[0]
            params, version = server.pull()
            batch = next_batch(tick)
            out = self._vg(jax.device_put(params, dev),
                           jax.device_put(batch, dev))
            loss, grads = (out[0][0], out[1]) if self.has_aux else out
            # Scalar fetch doubles as the device barrier (tunnel-safe).
            loss = float(loss)
            if server.push(grads, version, worker, loss=loss) < 0:
                # Snapshot exceeded the staleness bound: SSP refresh —
                # the gradient is dropped, the tick returns to the budget.
                with budget_lock:
                    budget[0] += 1

    def run(self, state: AsyncServerState, next_batch: Callable[[int], Any],
            n_pushes: int):
        """Execute ``n_pushes`` asynchronous updates.

        ``next_batch(tick)`` supplies each worker pull's batch (tick is a
        decreasing budget counter — deterministic batches per tick let
        tests replay schedules). Returns ``(state, metrics_dict)``.
        """
        server = self._server
        if server is None or server.state is not state:
            # Accept externally-restored state (checkpoint resume); adopts
            # the state without re-initializing optimizer slots.
            server = ParamServer(None, self.tx, staleness=self.staleness,
                                 state=state)
            self._server = server
            self._published = 0
        t0 = time.perf_counter()
        if self.schedule == "round_robin":
            self._run_round_robin(server, next_batch, n_pushes)
        else:
            budget = [n_pushes]
            budget_lock = threading.Lock()
            threads = [
                threading.Thread(
                    target=self._worker_loop,
                    args=(server, w, next_batch, budget, budget_lock),
                    daemon=True,
                )
                for w in range(self.n_workers)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        server.metrics.wall_s += time.perf_counter() - t0
        m = server.metrics
        self._publish(server)
        return server.state, {
            "loss": np.asarray(m.losses, np.float32),
            "lag": np.asarray(m.lags, np.int32),
            "worker": np.asarray(m.workers, np.int32),
            **m.summary(),
        }

    def _publish(self, server: ParamServer) -> None:
        """Refresh the registry from this run's per-push records (delta
        counters, point-in-time gauges)."""
        m = server.metrics
        new_pushes = len(m.losses) - self._published
        if new_pushes > 0:
            self._published = len(m.losses)
            self._c_pushes.inc(new_pushes)
            for lag in m.lags[-new_pushes:]:
                self._h_lag.observe(float(lag))
        self._g_version.set(server.state.version)
        if m.losses:
            self._g_loss.set(m.losses[-1])
        s = m.summary()
        if s["pushes_per_sec"] == s["pushes_per_sec"]:  # not NaN
            self._g_pps.set(s["pushes_per_sec"])

    def _run_round_robin(self, server: ParamServer,
                         next_batch: Callable[[int], Any], n_pushes: int):
        """Deterministic schedule: rounds of (all workers pull the SAME
        snapshot) then (pushes apply in worker order). Worker w>0's
        gradient in each round applies onto params already advanced by
        workers <w — stale by construction, reproducibly."""
        tick = n_pushes
        pending: List = []
        while tick > 0 or pending:
            if not pending:
                k = min(self.n_workers, tick)
                snapshots = [server.pull() for _ in range(k)]
                for w in range(k):
                    tick -= 1
                    params, version = snapshots[w]
                    out = self._vg(params, next_batch(tick))
                    loss, grads = (out[0][0], out[1]) if self.has_aux else out
                    pending.append((grads, version, w, float(loss)))
            grads, version, w, loss = pending.pop(0)
            if server.push(grads, version, w, loss=loss) < 0:
                tick += 1  # SSP refresh: recompute on a fresh snapshot
