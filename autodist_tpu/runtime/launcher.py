"""Multi-host launcher: ``python -m autodist_tpu.runtime.launcher``.

The user-facing bring-up tool — the analog of the reference's implicit
"construct AutoDist on the chief and it SSH-launches everything" flow
(``/root/reference/autodist/autodist.py:120-128`` → ``cluster.start()`` →
``coordinator.launch_clients()``), packaged the way TPU users expect: one
command that runs the same training script on every host of the cluster with
the right role env, then watches the fleet.

Usage::

    python -m autodist_tpu.runtime.launcher --resource-spec spec.yml \
        -- python train.py --flags ...

On the chief this execs the script locally with chief role; for every other
node it re-execs the identical command over SSH (TPU-VM images) or as a local
subprocess (single-host multi-process testing with ``address: localhost``
specs is rejected by ResourceSpec validation, so local fan-out is driven by
``--num-local-processes`` instead, which emulates N hosts on one machine for
CPU-mesh testing).
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import threading
import time
from typing import Callable, List, Optional, Sequence, TYPE_CHECKING

from autodist_tpu import const
from autodist_tpu.const import ENV
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.runtime.cluster import Cluster, clean_stale_processes, write_pidfile
from autodist_tpu.runtime.coordinator import Coordinator
from autodist_tpu.utils import logging

if TYPE_CHECKING:
    from autodist_tpu.ft import FTConfig


def _scrub_role_vars(env: dict) -> dict:
    """Drop the framework's role/strategy vars from an environment.

    Any earlier chief-side ``build()`` in the calling process exports
    ``AUTODIST_STRATEGY_ID`` into ``os.environ`` (and a stale
    ``AUTODIST_WORKER`` can linger the same way); a freshly launched
    process inheriting them is misrouted onto the coordinator-shipped-
    strategy path, waiting for a file that was never shipped while the
    chief blocks in the runtime broadcast. Launchers must set role vars
    explicitly; behavior knobs (log level, testing flags) and user vars
    pass through.
    """
    role_vars = {
        ENV.AUTODIST_WORKER.name,
        ENV.AUTODIST_STRATEGY_ID.name,
        ENV.AUTODIST_COORDINATOR.name,
        ENV.AUTODIST_NUM_PROCESSES.name,
        ENV.AUTODIST_PROCESS_ID.name,
    }
    return {k: v for k, v in env.items() if k not in role_vars}


class _FleetWatch:
    """Launcher-side fleet observer: a non-publishing
    :class:`~autodist_tpu.ft.heartbeat.HealthMonitor` over the fleet's
    heartbeat directory, plus a watchdog thread that terminates the chief
    when the whole fleet goes silent (``fleet_hung``).

    This is the capability blind exit-code supervision cannot have: a hung
    fleet never *exits*, so ``--max-restarts`` alone would wait on it
    forever. The watchdog converts the HealthMonitor's verdict into a
    chief termination, which surfaces as a non-zero ``launch`` return the
    supervisor can act on.
    """

    def __init__(self, ft_config: "FTConfig"):
        from autodist_tpu.ft import FileTransport, HealthMonitor

        self.config = ft_config.resolved()
        # Sweep beats left by a previous incarnation: their stale stamps
        # would otherwise read as an immediately-hung fleet.
        hb_dir = self.config.heartbeat_dir
        os.makedirs(hb_dir, exist_ok=True)
        for name in os.listdir(hb_dir):
            if name.startswith("hb-"):
                try:
                    os.remove(os.path.join(hb_dir, name))
                except OSError:
                    pass
        self.monitor = HealthMonitor(
            FileTransport(hb_dir), publish=False, config=self.config)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.hang_detected = False

    def env(self) -> dict:
        """Role env every fleet process needs to heartbeat into the same
        base dir the watchdog sweeps. The pilot dir rides along so a
        controller (and the doctor stitching its decision journal) agree
        on one ``<base>/pilot`` across the fleet (docs/autopilot.md)."""
        return {
            ENV.AUTODIST_FT_DIR.name: self.config.base_dir,
            ENV.AUTODIST_PILOT_DIR.name: os.path.join(
                self.config.base_dir, "pilot"),
        }

    def write_bundle(self, reason: str = "fleet_hung") -> Optional[str]:
        """Persist a doctor bundle — last heartbeats (per-peer state +
        payload), fleet verdict, and this launcher's open spans — under
        ``<ft base>/doctor/`` BEFORE the kill, so a supervised termination
        is attributable: ``python -m autodist_tpu.obs doctor <ft base>``
        reads it as the primary wedge evidence (docs/observability.md).
        Best-effort, atomic, fsync'd; never blocks the kill on IO."""
        import json

        try:
            from autodist_tpu.obs.spans import get_tracer

            peers = {}
            for pid, p in self.monitor.peers().items():
                peers[str(pid)] = {
                    "state": p.state.value,
                    "last_seen": p.last_seen,
                    "misses": p.misses,
                    "last_payload": p.last_payload,
                }
            bundle = {
                "written_at": time.time(),
                "reason": reason,
                "verdict": self.monitor.verdict().value,
                "hang_after_misses": self.config.hang_after_misses,
                "heartbeat_interval_s": self.config.heartbeat_interval_s,
                "heartbeats": peers,
                "launcher_spans": [
                    {"name": s.name, "t_start_s": s.t_start_s,
                     "dur_s": s.dur_s, "attrs": s.attrs}
                    for s in get_tracer().spans()[-64:]
                ],
            }
            bundle_dir = os.path.join(self.config.base_dir, "doctor")
            os.makedirs(bundle_dir, exist_ok=True)
            path = os.path.join(
                bundle_dir, f"hang-bundle-{int(time.time())}.json")
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(bundle, f, indent=2, default=str)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            logging.info("wrote doctor bundle -> %s", path)
            return path
        except Exception:  # noqa: BLE001 - the kill must proceed regardless
            logging.warning("doctor bundle write failed", exc_info=True)
            return None

    def start(self, chief: subprocess.Popen) -> None:
        def watch():
            while not self._stop.is_set():
                try:
                    self.monitor.tick()
                    if self.monitor.fleet_hung():
                        self.hang_detected = True
                        logging.error(
                            "fleet heartbeats silent for %d intervals "
                            "(verdict %s); terminating chief for restart",
                            self.config.hang_after_misses,
                            self.monitor.verdict().value,
                        )
                        # Attribution before termination: the bundle is the
                        # context SIGTERM would otherwise discard.
                        self.write_bundle()
                        chief.terminate()
                        return
                except Exception:  # noqa: BLE001 - watchdog must not die
                    logging.warning("fleet watchdog tick failed", exc_info=True)
                self._stop.wait(self.config.heartbeat_interval_s)

        self._thread = threading.Thread(
            target=watch, name="ft-fleet-watch", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def verdict(self) -> str:
        return self.monitor.verdict().value

    def progress_step(self) -> Optional[int]:
        """Newest snapshot step the fleet persisted (the supervisor's
        progress signal)."""
        from autodist_tpu.ft.snapshot import latest_snapshot_step

        return latest_snapshot_step(self.config.snapshot_dir)


def launch(
    resource_spec: ResourceSpec,
    argv: Sequence[str],
    num_local_processes: int = 0,
    coordinator_port: Optional[int] = None,
    extra_env: Optional[dict] = None,
    supervised: bool = False,
    ft_config: "Optional[FTConfig]" = None,
) -> int:
    """Launch ``argv`` across the cluster; returns the chief's exit code.

    With ``num_local_processes > 1`` the cluster is emulated on this machine:
    N processes, process 0 (chief) runs in the foreground, the rest are
    subprocesses with worker role env — the moral equivalent of the
    reference's docker-on-one-box distributed CI (``Jenkinsfile:93-131``).

    ``extra_env`` is merged into every process's environment (chief and
    workers, local or SSH). ``supervised=True`` redirects the coordinator's
    worker-death fail-fast from ``os._exit(1)`` to terminating the chief
    subprocess, so this function *returns* non-zero instead of killing the
    calling process — required by :func:`launch_supervised`'s restart loop.
    ``ft_config`` additionally arms the fleet watchdog: every process gets
    ``AUTODIST_FT_DIR`` pointing at one shared base, and a launcher-side
    :class:`~autodist_tpu.ft.heartbeat.HealthMonitor` observer terminates a
    fleet whose heartbeats all go silent (a hang never exits on its own).
    """
    clean_stale_processes()
    argv = list(argv)
    extra_env = dict(extra_env or {})
    watch = None
    if ft_config is not None:
        watch = _FleetWatch(ft_config)
        extra_env = {**watch.env(), **extra_env}

    # Observability contract (docs/observability.md): ONE trace id for the
    # whole launch, exported to every process (chief, local workers, SSH
    # remotes) so their spans stitch into a single cross-process timeline.
    # current_trace_id() also pins it into this launcher's own env, so the
    # launcher's spans carry the same id.
    from autodist_tpu.obs.spans import current_trace_id

    # A caller-supplied extra_env id/dir wins over this launcher's env;
    # either way the launcher process pins the SAME values into its own
    # env so its spans (launcher.fleet) join the fleet's trace and the
    # stitch below sees the right dir.
    trace_id = extra_env.get(ENV.AUTODIST_TRACE_ID.name)
    if trace_id:
        os.environ[ENV.AUTODIST_TRACE_ID.name] = trace_id
    else:
        trace_id = extra_env[ENV.AUTODIST_TRACE_ID.name] = current_trace_id()
    trace_out = (extra_env.get(ENV.AUTODIST_TRACE_OUT.name)
                 or ENV.AUTODIST_TRACE_OUT.val)
    if trace_out:
        extra_env.setdefault(ENV.AUTODIST_TRACE_OUT.name, trace_out)
        os.environ[ENV.AUTODIST_TRACE_OUT.name] = trace_out
    t_launch = time.time()

    if num_local_processes > 1:
        base = {**_scrub_role_vars(dict(os.environ)), **extra_env}
        code = _launch_local_fleet(
            argv, num_local_processes, coordinator_port, base_env=base,
            watch=watch)
        _finish_trace(trace_out, trace_id, t_launch, num_local_processes,
                      code)
        return code

    cluster = Cluster(resource_spec, coordinator_port=coordinator_port)
    coordinator = Coordinator(cluster, argv=argv, extra_env=extra_env)
    if supervised:
        # Placeholder until the chief exists: a worker dying in this window
        # leaves the cluster torn down, the chief then fails its runtime
        # join and launch() returns non-zero — still restartable.
        coordinator.set_failure_action(lambda: None)
    coordinator.launch_clients()

    env = {
        **extra_env,
        ENV.AUTODIST_COORDINATOR.name: cluster.coordinator_address,
        ENV.AUTODIST_NUM_PROCESSES.name: str(cluster.num_processes),
        ENV.AUTODIST_PROCESS_ID.name: "0",
    }
    chief = subprocess.Popen(argv, env={**_scrub_role_vars(dict(os.environ)), **env})
    if supervised:
        coordinator.set_failure_action(chief.terminate)
    if watch is not None:
        watch.start(chief)
    code = chief.wait()
    if watch is not None:
        watch.stop()
        if watch.hang_detected and code == 0:
            # A SIGTERM'd chief that exits 0 (its preemption hook ran clean)
            # must still read as a failed attempt, or the supervisor would
            # declare a hung fleet done.
            code = 1
        if code != 0:
            logging.error("fleet attempt failed rc=%d; health verdict: %s",
                          code, watch.verdict())
    if code == 0:
        coordinator.join()
        if coordinator.any_failed:
            # A worker died after the chief already exited cleanly (e.g.
            # crash during teardown/final save): under supervision the
            # failure action (chief.terminate) was a no-op by then, so the
            # failure must surface in the return code — a clean-looking 0
            # here would make the supervisor (and CI) report success.
            logging.error("chief exited 0 but a worker failed; reporting failure")
            code = 1
    cluster.terminate()
    _finish_trace(trace_out, trace_id, t_launch, cluster.num_processes, code)
    return code


def _finish_trace(trace_out: str, trace_id: str, t_launch: float,
                  n_processes: int, code: int) -> None:
    """Close the launch's observability loop: record the launcher's own
    fleet span, flush it, and stitch every process's part-file into ONE
    chrome-trace JSON (``trace-<id>.json`` under the trace-out dir).
    Best-effort — tracing must never change a launch's outcome."""
    if not trace_out:
        return
    try:
        from autodist_tpu.obs.spans import get_tracer, stitch

        tracer = get_tracer()
        tracer.add_span("launcher.fleet", t_launch, time.time() - t_launch,
                        processes=n_processes, exit_code=code)
        tracer.flush_part(trace_out)
        merged = stitch(trace_out, trace_id=trace_id)
        if merged:
            logging.info("stitched fleet trace -> %s (load in Perfetto or "
                         "chrome://tracing)", merged)
    except Exception:  # noqa: BLE001 - observability is never fatal here
        logging.warning("trace stitch failed", exc_info=True)


def launch_supervised(
    resource_spec: ResourceSpec,
    argv: Sequence[str],
    max_restarts: int = 0,
    num_local_processes: int = 0,
    coordinator_port: Optional[int] = None,
    restart_backoff_s: float = 5.0,
    ft_config: "Optional[FTConfig]" = None,
    restart_backoff_max_s: float = 300.0,
    backoff_seed: Optional[int] = None,
    restart_sleep: Optional[Callable[[float], None]] = None,
) -> int:
    """:func:`launch` under a restart supervisor (checkpoint-resume loop).

    The reference's fault story ended at fail-fast (worker death kills the
    chief, ``coordinator.py:98-110``) + manual restart; this closes the
    loop: a fleet that exits non-zero is relaunched — same command, fresh
    role env, stale pidfiles swept by the inner :func:`launch` — up to
    ``max_restarts`` times. Worker death is survivable too: ``supervised``
    launches redirect the coordinator's fail-fast from ``os._exit(1)`` to
    terminating the chief, so it surfaces as a non-zero return here
    instead of killing this process. Training scripts resume by
    construction when they open their state with
    ``DistributedTrainStep.init_or_restore`` (fresh init when the
    checkpoint dir is empty, latest checkpoint otherwise), so the
    supervisor needs no protocol with the script. Each attempt carries
    ``AUTODIST_RESTART`` (0 on the first run) in every process's env —
    chief, local workers, and SSH-launched remote workers alike.

    With ``ft_config`` the supervisor stops being a blind exit-code
    counter and consumes the ft subsystem's verdicts instead:

    - each :func:`launch` runs under the fleet watchdog (a hung fleet is
      terminated and restarted rather than waited on forever);
    - the restart budget counts restarts *since the fleet last made
      progress*: when the newest snapshot step advanced across an attempt
      (``ft.snapshot.latest_snapshot_step``), the counter resets — a run
      that keeps progressing between preemptions is never "given up on"
      by an absolute cap sized for genuine crash loops.

    Restart pacing is **jittered exponential backoff** through the ONE
    retry layer (``utils/retry.py``): ``restart_backoff_s`` is the first
    delay's base, doubling per consecutive failed attempt up to
    ``restart_backoff_max_s``, each delay jittered down by up to 50% so a
    crashing multi-fleet deployment cannot restart-storm in lockstep. The
    backoff resets together with the restart budget whenever the snapshot
    ring advances — a preempted-but-progressing run restarts promptly
    forever; only a no-progress crash loop slows down. ``backoff_seed``
    pins the jitter (chaos replay determinism); ``restart_sleep``
    overrides the sleep (tests, harnesses).
    """
    import random as _random

    from autodist_tpu.utils import retry as _retry

    def _progress() -> Optional[int]:
        if ft_config is None:
            return None
        from autodist_tpu.ft.snapshot import latest_snapshot_step

        return latest_snapshot_step(ft_config.resolved().snapshot_dir)

    backoff = _retry.Backoff(
        _retry.RetryPolicy(
            initial_s=restart_backoff_s, max_s=restart_backoff_max_s,
            multiplier=2.0, jitter=0.5),
        rng=_random.Random(backoff_seed) if backoff_seed is not None else None,
    )
    attempt = 0
    last_progress = _progress()
    while True:
        code = launch(
            resource_spec, argv,
            num_local_processes=num_local_processes,
            coordinator_port=coordinator_port,
            extra_env={"AUTODIST_RESTART": str(attempt)},
            # max_restarts=0 keeps exact unsupervised fail-fast semantics
            # (immediate os._exit on worker death) — there is no restart
            # loop to protect, so the reference behavior wins. ft_config
            # passes through REGARDLESS: the hang watchdog and the
            # AUTODIST_FT_DIR export are useful with zero restarts too (a
            # hung fleet still becomes a reportable non-zero exit).
            supervised=max_restarts > 0,
            ft_config=ft_config,
        )
        if code != 0:
            step_now = _progress()
            if step_now is not None and (
                    last_progress is None or step_now > last_progress):
                if attempt:
                    logging.info(
                        "fleet progressed to snapshot step %d since the last "
                        "restart; resetting the restart budget and backoff",
                        step_now)
                attempt = 0
                backoff.reset()
                last_progress = step_now
        if code == 0 or attempt >= max_restarts:
            if code != 0:
                logging.error(
                    "fleet failed rc=%d after %d restart(s) without "
                    "progress; giving up", code, attempt,
                )
            return code
        attempt += 1
        delay = backoff.next_delay()
        logging.warning(
            "fleet exited rc=%d; restarting (%d/%d) in %.1fs",
            code, attempt, max_restarts, delay,
        )
        if delay > 0:
            (restart_sleep or time.sleep)(delay)


def _launch_local_fleet(
    argv: List[str], n: int, coordinator_port: Optional[int],
    base_env: Optional[dict] = None, watch: Optional[_FleetWatch] = None,
) -> int:
    """Emulate an n-host cluster on one machine (testing path).

    ``base_env`` replaces the inherited environment (tests use it to pin
    ``JAX_PLATFORMS=cpu`` regardless of the host's default backend) —
    except the framework role vars, which are scrubbed from either source
    and set explicitly below (see :func:`_scrub_role_vars`).
    """
    port = coordinator_port or const.DEFAULT_COORDINATOR_PORT
    coord = f"127.0.0.1:{port}"
    inherited = _scrub_role_vars(
        dict(os.environ) if base_env is None else dict(base_env)
    )
    procs: List[subprocess.Popen] = []
    for pid_idx in range(1, n):
        env = {
            **inherited,
            ENV.AUTODIST_WORKER.name: f"local-process-{pid_idx}",
            ENV.AUTODIST_COORDINATOR.name: coord,
            ENV.AUTODIST_NUM_PROCESSES.name: str(n),
            ENV.AUTODIST_PROCESS_ID.name: str(pid_idx),
        }
        procs.append(subprocess.Popen(argv, env=env, start_new_session=True))
    env = {
        **inherited,
        ENV.AUTODIST_COORDINATOR.name: coord,
        ENV.AUTODIST_NUM_PROCESSES.name: str(n),
        ENV.AUTODIST_PROCESS_ID.name: "0",
    }
    chief = subprocess.Popen(argv, env=env)
    if watch is not None:
        watch.start(chief)
    code = chief.wait()
    if watch is not None:
        watch.stop()
        if watch.hang_detected and code == 0:
            code = 1
    for p in procs:
        try:
            p.wait(timeout=60)
        except subprocess.TimeoutExpired:
            p.terminate()
            code = code or 1
    return code


def initialize_from_env() -> None:
    """Worker/chief-side runtime join, driven purely by the env contract.

    Call this at the top of a training script launched by :func:`launch`
    (or let ``AutoDist`` call it). Reads ``AUTODIST_COORDINATOR`` /
    ``AUTODIST_NUM_PROCESSES`` / ``AUTODIST_PROCESS_ID`` and calls
    ``jax.distributed.initialize`` when a multi-process launch is detected.
    """
    n = ENV.AUTODIST_NUM_PROCESSES.val
    coord = ENV.AUTODIST_COORDINATOR.val
    if n <= 1 or not coord:
        return
    import jax

    if jax.distributed.is_initialized():
        return  # idempotent: AutoDist.__init__ and user scripts may both call
    write_pidfile()
    logging.info(
        "initialize_from_env: coordinator=%s process=%d/%d",
        coord, ENV.AUTODIST_PROCESS_ID.val, n,
    )
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=n,
        process_id=ENV.AUTODIST_PROCESS_ID.val,
    )


def main(args: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="autodist_tpu.runtime.launcher",
        description="Launch a training script across an autodist_tpu cluster.",
    )
    parser.add_argument("--resource-spec", default="", help="path to resource_spec.yml")
    parser.add_argument(
        "--num-local-processes", type=int, default=0,
        help="emulate N hosts on this machine (testing)",
    )
    parser.add_argument("--coordinator-port", type=int, default=0)
    parser.add_argument(
        "--max-restarts", type=int, default=0,
        help="relaunch a non-zero-exiting fleet up to N times; scripts "
             "using init_or_restore resume from their latest checkpoint",
    )
    parser.add_argument("--restart-backoff", type=float, default=5.0)
    parser.add_argument(
        "--ft-dir", default="",
        help="enable fault-tolerance supervision rooted at this shared "
             "dir: fleet processes heartbeat under it, a hung fleet is "
             "terminated for restart, and the restart budget resets "
             "whenever the snapshot ring advances (docs/fault_tolerance.md)",
    )
    parser.add_argument(
        "--trace-out", default="",
        help="shared dir for cross-process span tracing: every fleet "
             "process flushes a chrome-trace part-file here and the "
             "launcher stitches them into one trace-<id>.json after the "
             "run (docs/observability.md)",
    )
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="-- python train.py ...")
    ns = parser.parse_args(args)
    command = [c for c in ns.command if c != "--"]
    if not command:
        parser.error("no command given; usage: launcher --resource-spec s.yml -- python train.py")
    spec = (
        ResourceSpec(ns.resource_spec) if ns.resource_spec else ResourceSpec.from_local_devices()
    )
    ft_config = None
    if ns.ft_dir:
        from autodist_tpu.ft import FTConfig

        ft_config = FTConfig(base_dir=ns.ft_dir)
    if ns.trace_out:
        # launch() reads the env contract; exporting here covers both the
        # launcher's own spans and every process it starts.
        os.environ[ENV.AUTODIST_TRACE_OUT.name] = ns.trace_out
    return launch_supervised(
        spec, command,
        max_restarts=ns.max_restarts,
        num_local_processes=ns.num_local_processes,
        coordinator_port=ns.coordinator_port or None,
        restart_backoff_s=ns.restart_backoff,
        ft_config=ft_config,
    )


if __name__ == "__main__":
    sys.exit(main())
