"""Cluster: the jax.distributed bootstrap + process table.

Replaces the reference's ``Cluster``/``SSHCluster``
(``/root/reference/autodist/cluster.py:54-268``). The reference built a TF
``ClusterSpec`` (``{'worker': ['ip:15000', ...]}``, sorted for cross-worker
determinism, ``cluster.py:70-82``) and started a grpc ``tf.train.Server`` per
node over SSH. On TPU the native equivalent is the JAX multi-controller
runtime: one Python process per host, all connecting to a coordinator service
on the chief (``jax.distributed.initialize``), with collectives riding
ICI/DCN instead of grpc.

Determinism parity: process ids come from the same chief-first,
address-sorted node ordering the ResourceSpec uses for device numbering, so
every process derives an identical cluster view from the spec alone — the
analog of the reference's sorted ip:port list.
"""
from __future__ import annotations

import os
import signal
import subprocess
from typing import Dict, List, Optional

from autodist_tpu import const
from autodist_tpu.const import ENV
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.utils import logging


def _deterministic_port(spec: ResourceSpec) -> int:
    """Pick a coordinator port in the reference's 15000-16000 range
    (``const.py:38``), keyed on the spec fingerprint so concurrent clusters
    on one machine do not collide but all members of one cluster agree."""
    rng = const.DEFAULT_PORT_RANGE
    return rng.start + int(spec.fingerprint(), 16) % len(rng)


class Cluster:
    """Process table + jax.distributed lifecycle for one ResourceSpec.

    One ``Cluster`` object exists per process; ``start()`` on the chief
    launches nothing itself (workers are launched by the
    :class:`~autodist_tpu.runtime.coordinator.Coordinator`) but initializes
    the distributed runtime. Single-node specs skip the coordinator service
    entirely, matching how the reference ran localhost specs without SSH.
    """

    def __init__(self, resource_spec: ResourceSpec, coordinator_port: Optional[int] = None):
        self.resource_spec = resource_spec
        self.coordinator_port = coordinator_port or _deterministic_port(resource_spec)
        # chief-first, address-sorted — must match ResourceSpec.tpu_devices.
        self._ordered_nodes = sorted(
            resource_spec.nodes, key=lambda n: (not n.chief, n.address)
        )
        self._initialized = False
        self._local_procs: List[subprocess.Popen] = []

    # ------------------------------------------------------------- identities
    @property
    def num_processes(self) -> int:
        return len(self._ordered_nodes)

    @property
    def coordinator_address(self) -> str:
        """``chief_ip:port`` — what every process dials into
        (reference analog: session target ``grpc://localhost:port``,
        ``cluster.py:149-157``)."""
        override = ENV.AUTODIST_COORDINATOR.val
        if override:
            return override
        return f"{self.resource_spec.chief_address}:{self.coordinator_port}"

    def process_id(self, address: Optional[str] = None) -> int:
        """Deterministic process index for a host address (default: self)."""
        if address is None:
            address = ENV.AUTODIST_WORKER.val or self.resource_spec.chief_address
        for i, node in enumerate(self._ordered_nodes):
            if node.address == address:
                return i
        raise ValueError(f"address {address!r} not in resource spec")

    @property
    def is_chief(self) -> bool:
        return const.is_chief_process()

    def env_for_worker(self, address: str, strategy_id: str = "") -> Dict[str, str]:
        """The env-var contract shipped to a worker process
        (reference: ``coordinator.py:66-76`` exported ``AUTODIST_WORKER``,
        ``AUTODIST_STRATEGY_ID`` etc. into the remote shell)."""
        env = {
            ENV.AUTODIST_WORKER.name: address,
            ENV.AUTODIST_COORDINATOR.name: self.coordinator_address,
            ENV.AUTODIST_NUM_PROCESSES.name: str(self.num_processes),
            ENV.AUTODIST_PROCESS_ID.name: str(self.process_id(address)),
            ENV.AUTODIST_MIN_LOG_LEVEL.name: str(ENV.AUTODIST_MIN_LOG_LEVEL.val),
        }
        if strategy_id:
            env[ENV.AUTODIST_STRATEGY_ID.name] = strategy_id
        return env

    # -------------------------------------------------------------- lifecycle
    def initialize(self) -> None:
        """Join the distributed runtime (idempotent).

        Multi-node: ``jax.distributed.initialize`` with the deterministic
        process table — the native replacement for starting per-node TF
        servers (``server_starter.py:49-77``). Single-node: no-op.
        """
        if self._initialized or self.num_processes == 1:
            self._initialized = True
            return
        import jax

        pid = self.process_id()
        logging.info(
            "joining cluster: coordinator=%s process=%d/%d",
            self.coordinator_address, pid, self.num_processes,
        )
        jax.distributed.initialize(
            coordinator_address=self.coordinator_address,
            num_processes=self.num_processes,
            process_id=pid,
        )
        self._initialized = True

    def start(self) -> None:
        """Chief-side cluster bring-up: clean stale state, then initialize.

        The reference's ``start()`` launched servers on every node
        (``cluster.py:160-210``); with multi-controller JAX the workers
        bring themselves up when the Coordinator re-execs the script, so
        chief-side start is local-only.
        """
        clean_stale_processes()
        self.initialize()

    def register_local_process(self, proc: subprocess.Popen) -> None:
        self._local_procs.append(proc)

    def terminate(self) -> None:
        """Kill any worker process groups this process launched
        (reference: killpg in ``cluster.py:212-216``)."""
        for proc in self._local_procs:
            if proc.poll() is None:
                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
        self._local_procs.clear()

    def shutdown(self) -> None:
        self.terminate()
        if self._initialized and self.num_processes > 1:
            import jax

            try:
                jax.distributed.shutdown()
            except Exception as e:  # noqa: BLE001 - best-effort teardown
                logging.warning("jax.distributed.shutdown failed: %s", e)
        self._initialized = False


# -------------------------------------------------------------- stale cleanup
def _pidfile_dir() -> str:
    d = os.path.join(const.DEFAULT_WORKING_DIR, "pids")
    os.makedirs(d, exist_ok=True)
    return d


def write_pidfile() -> str:
    """Record this process so a later launch can clean it up if it leaks
    (reference: ps/kill sweep on node start, ``server_starter.py:29-46``)."""
    path = os.path.join(_pidfile_dir(), f"{os.getpid()}.pid")
    with open(path, "w", encoding="utf-8") as f:
        f.write(str(os.getpid()))
    return path


def clean_stale_processes() -> int:
    """Kill processes recorded by previous runs that are still alive.

    Returns the number of stale processes signalled. Never signals self or
    ancestors.
    """
    killed = 0
    self_pid, parent_pid = os.getpid(), os.getppid()
    d = _pidfile_dir()
    for name in os.listdir(d):
        if not name.endswith(".pid"):
            continue
        path = os.path.join(d, name)
        try:
            with open(path, "r", encoding="utf-8") as f:
                pid = int(f.read().strip())
        except (ValueError, OSError):
            os.unlink(path)
            continue
        if pid in (self_pid, parent_pid):
            continue
        try:
            os.kill(pid, signal.SIGTERM)
            killed += 1
            logging.info("killed stale autodist process %d", pid)
        except ProcessLookupError:
            pass
        except PermissionError:  # someone else's pid now
            pass
        try:
            os.unlink(path)
        except OSError:
            pass
    return killed
