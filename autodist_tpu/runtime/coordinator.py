"""Coordinator: chief-side worker launch + monitoring.

Replaces the reference's ``Coordinator``
(``/root/reference/autodist/coordinator.py:41-110``): on the chief it shipped
the serialized strategy to every worker over SFTP, re-executed
``python <sys.argv>`` remotely with the ``AUTODIST_*`` role env vars, and ran
a monitor thread per worker that killed the chief (``os._exit(1)``) if any
worker died. The same contract holds here, minus paramiko: remote exec goes
through the system ``ssh``/``scp`` binaries (TPU-VM images ship them; GCE
metadata handles keys), local "remote" nodes are plain subprocesses, and the
strategy still travels as a file named by ``AUTODIST_STRATEGY_ID``.
"""
from __future__ import annotations

import os
import shlex
import subprocess
import sys
import threading
from typing import Dict, List, Optional, Sequence

from autodist_tpu import const
from autodist_tpu.const import ENV
from autodist_tpu.runtime.cluster import Cluster
from autodist_tpu.strategy import Strategy
from autodist_tpu.utils import logging

_LOCAL_ADDRESSES = ("localhost", "127.0.0.1", "0.0.0.0", "::1")


def _is_local(address: str) -> bool:
    if address in _LOCAL_ADDRESSES:
        return True
    try:
        import socket

        return address in (socket.gethostname(), socket.getfqdn())
    except OSError:  # pragma: no cover
        return False


class Coordinator:
    """Launch the user script on every worker host and watch it.

    ``launch_clients()`` re-execs ``python <sys.argv>`` per worker with the
    role env (reference ``coordinator.py:66-90``); monitor threads implement
    the chief fail-fast (``coordinator.py:98-110``).
    """

    def __init__(
        self,
        cluster: Cluster,
        strategy: Optional[Strategy] = None,
        argv: Optional[Sequence[str]] = None,
        extra_env: Optional[Dict[str, str]] = None,
    ):
        self.cluster = cluster
        self.strategy = strategy
        self.argv = list(argv) if argv is not None else [sys.executable] + sys.argv
        # Forwarded into every worker's env (local subprocess and SSH shell
        # alike) — the supervisor's AUTODIST_RESTART travels here so remote
        # workers see the same attempt counter as the chief.
        self.extra_env = dict(extra_env or {})
        self.procs: List[subprocess.Popen] = []
        self.threads: List[threading.Thread] = []
        self._failed = threading.Event()
        self._failure_action = None

    # ------------------------------------------------------------------ launch
    def launch_clients(self) -> None:
        strategy_id = self.strategy.id if self.strategy else ENV.AUTODIST_STRATEGY_ID.val
        workers = [
            n for n in self.cluster.resource_spec.nodes
            if n.address != self.cluster.resource_spec.chief_address
        ]
        for node in workers:
            env = {**self.extra_env,
                   **self.cluster.env_for_worker(node.address, strategy_id)}
            if _is_local(node.address):
                proc = self._launch_local(env)
            else:
                self._ship_strategy(node.address, strategy_id)
                proc = self._launch_remote(node.address, env)
            self.procs.append(proc)
            self.cluster.register_local_process(proc)
            t = threading.Thread(
                target=self._monitor, args=(node.address, proc), daemon=True
            )
            t.start()
            self.threads.append(t)
            logging.info("launched worker on %s (pid %d)", node.address, proc.pid)

    def _launch_local(self, env: Dict[str, str]) -> subprocess.Popen:
        full_env = {**os.environ, **env}
        # setsid: own process group so terminate() can killpg without taking
        # down the chief (reference cluster.py:191-201 used the same trick).
        return subprocess.Popen(
            self.argv, env=full_env, start_new_session=True,
            stdout=None, stderr=None,
        )

    def _ssh_parts(self, address: str):
        """(option args, target) honoring the spec's ssh config for this
        host (reference SSHConfig: username/port/key_file,
        resource_spec.py:291-331)."""
        cfg = self.cluster.resource_spec.ssh_config_for(address)
        opts = ["-o", "StrictHostKeyChecking=no"]
        target = address
        if cfg is not None:
            if cfg.port and cfg.port != 22:
                opts += ["-p", str(cfg.port)]
            if cfg.key_file:
                opts += ["-i", cfg.key_file]
            if cfg.user:
                target = f"{cfg.user}@{address}"
        return opts, target, cfg

    def _launch_remote(self, address: str, env: Dict[str, str]) -> subprocess.Popen:
        opts, target, cfg = self._ssh_parts(address)
        exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
        venv = (
            f". {shlex.quote(cfg.python_venv)}/bin/activate && "
            if cfg is not None and cfg.python_venv else ""
        )
        cmd = (
            f"{venv}cd {shlex.quote(os.getcwd())} && {exports} "
            f"{' '.join(shlex.quote(a) for a in self.argv)}"
        )
        if ENV.AUTODIST_DEBUG_REMOTE.val:
            # Parity with AUTODIST_DEBUG_REMOTE (reference cluster.py:340-341):
            # print instead of executing, for manual debugging. The printed
            # line is the exact replayable command, options included.
            logging.info("[debug-remote] ssh %s %s %s", " ".join(opts), target, cmd)
            return subprocess.Popen(["true"])
        return subprocess.Popen(
            ["ssh", *opts, target, cmd],
            start_new_session=True,
        )

    def _ship_strategy(self, address: str, strategy_id: str) -> None:
        """SFTP-analog: scp the serialized strategy file to the worker
        (reference coordinator.py:84-88)."""
        if not strategy_id:
            return
        path = os.path.join(const.DEFAULT_STRATEGY_DIR, strategy_id)
        if not os.path.exists(path) or ENV.AUTODIST_DEBUG_REMOTE.val:
            return
        opts, target, cfg = self._ssh_parts(address)
        # scp spells the port flag -P (capital), unlike ssh.
        scp_opts = ["-P" if o == "-p" else o for o in opts]
        subprocess.run(
            ["ssh", *opts, target,
             f"mkdir -p {shlex.quote(const.DEFAULT_STRATEGY_DIR)}"],
            check=True,
        )
        subprocess.run(
            ["scp", *scp_opts, path, f"{target}:{path}"],
            check=True,
        )

    # ----------------------------------------------------------------- monitor
    def set_failure_action(self, action) -> None:
        """Replace the fail-fast ``os._exit(1)`` with ``action()``.

        The default (reference parity, coordinator.py:98-110) kills the
        whole launcher process — correct for an unsupervised run, fatal
        for a restart supervisor living in the same process. A supervised
        launch installs ``chief.terminate`` instead: the chief subprocess
        dies, ``launch()`` returns its non-zero code, and the supervisor
        decides whether to relaunch.
        """
        self._failure_action = action

    def _monitor(self, address: str, proc: subprocess.Popen) -> None:
        code = proc.wait()
        if code != 0 and not self._failed.is_set():
            self._failed.set()
            logging.error(
                "worker %s exited with code %d — terminating chief "
                "(fail-fast, reference coordinator.py:98-110)", address, code,
            )
            self.cluster.terminate()
            if self._failure_action is not None:
                self._failure_action()
            else:
                os._exit(1)

    def join(self) -> None:
        """Block until every worker exits (clean launcher shutdown)."""
        for proc in self.procs:
            proc.wait()

    @property
    def any_failed(self) -> bool:
        return self._failed.is_set()
