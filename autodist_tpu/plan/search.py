"""Beam search over the per-variable strategy space (the offline planner).

The fixed ``Auto`` slate ranks ~10 whole-model policies; the actual decision
space is per-variable — every trainable variable independently chooses a
synchronizer mechanism (AllReduce / PS residency variants), a partition
axis, and a collective fusion group, and the mesh itself has shape choices.
Automap (arXiv 2112.02958) showed that *searching* this space beats fixed
heuristics and GSPMD (arXiv 2105.04663) that per-tensor decisions compose
into end-to-end wins; this module is the search half of that loop.

Search is entirely analytic — candidates are scored by
:class:`~autodist_tpu.strategy.cost_model.CostModel` (optionally through a
fitted :class:`~autodist_tpu.plan.calibrate.TopologyCalibration`) and NO
candidate is ever compiled, so visiting hundreds of plans costs
milliseconds. The emitted winner is an ordinary Strategy IR artifact: it
lowers through the same ``kernel/lowering.py`` path as any hand-picked
builder, and the plan cache (``plan/cache.py``) dry-runs that lowering
before trusting a cached winner.

Genome encoding (one :class:`VarGene` per trainable variable, model order):

- ``kind``: ``"ar"`` (AllReduce), ``"ps1"`` (PS, ZeRO-1 residency),
  ``"ps3"`` (PS, ZeRO-3);
- ``axis``: partition axis (``None`` = unpartitioned) — renders as the IR
  ``partitioner`` string, axis-shard count capped by the mesh degree and
  the axis length (same grammar the reference partitioner used);
- ``group``: collective fusion group id (AllReduce only, advisory on TPU);
- ``dest``: PS reduction-destination index into ``reduction_devices``.

Plus ONE genome-wide gene: ``bucket_bytes`` (``PlanGenome.bucket_bytes``,
choices in :data:`BUCKET_GENE_CHOICES`) — the backward-overlap gradient
bucketing target the lowering renders via ``kernel/bucketing.py``; the
cost model prices its hidden wire as ``overlap_s`` and the per-topology
calibration fits how much of it the hardware actually hides.

Seeds come from the live ``candidate_slate()`` builders, so search starts
from every policy ``Auto`` already knows and can only improve on the best
of them (the ``--selftest`` acceptance bound).
"""
from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from autodist_tpu.model_item import ModelItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.cost_model import CostModel, StrategyCost, candidate_slate
from autodist_tpu.strategy.base import reduction_devices
from autodist_tpu.strategy.ir import (
    AllReduceSynchronizer,
    NodeConfig,
    PSSynchronizer,
    Strategy,
)
from autodist_tpu.utils import logging

# "zero1" = AllReduce with weight-update sharding (shard_update capability:
# reduce-scatter grads, 1/N-sharded optimizer update, all-gather params —
# arXiv 2004.13336); same wire bytes as "ar", ~N× less optimizer HBM, one
# extra collective dispatch per fusion group. The cost model prices the
# trade per variable, so search mixes ar (tiny vars) and zero1 (big vars)
# freely within one plan.
KINDS = ("ar", "ps1", "ps3", "zero1")
CHUNK_SIZES = (1, 32, 128, 512)

# Backward-overlap bucket-size gene (GraphConfig.bucket_bytes): 0 keeps the
# monolithic post-backward sync; non-zero targets bucket the grad
# collectives inside the backward (kernel/bucketing.py). Genome-wide, not
# per-var — the assignment is a partition of the whole gradient set. The
# cost model prices the trade (overlap_s hides wire, per-bucket dispatch
# latency punishes confetti-sized buckets), and the per-topology
# calibration's overlap_s coefficient makes the gene's value measured, not
# assumed.
BUCKET_GENE_CHOICES = (0, 1 << 20, 4 << 20, 16 << 20, 64 << 20)


@dataclass(frozen=True)
class VarGene:
    """One variable's slot in the genome."""

    kind: str = "ar"
    axis: Optional[int] = None
    group: int = 0
    dest: int = 0


@dataclass(frozen=True, eq=False)
class PlanGenome:
    """A full candidate plan: per-variable genes + the genome-wide
    backward-overlap bucket-size gene. Hashable (beam/dedup key).

    Pre-bucket-gene code treated a genome as a bare tuple of VarGenes;
    iteration, length, equality and hashing preserve that view (an
    unbucketed PlanGenome equals — and hashes like — its genes tuple)."""

    genes: Tuple[VarGene, ...]
    bucket_bytes: int = 0

    def __len__(self) -> int:
        return len(self.genes)

    def __iter__(self):
        return iter(self.genes)

    def __eq__(self, other):
        if isinstance(other, PlanGenome):
            return (self.genes == other.genes
                    and self.bucket_bytes == other.bucket_bytes)
        if isinstance(other, tuple):
            return self.bucket_bytes == 0 and self.genes == other
        return NotImplemented

    def __hash__(self):
        if self.bucket_bytes == 0:
            return hash(self.genes)  # hash-consistent with the tuple view
        return hash((self.genes, self.bucket_bytes))


# One candidate in genome space. A bare tuple of VarGenes is accepted
# everywhere a Genome is (bucket_bytes = 0) for backward compatibility.
Genome = PlanGenome


def _as_genome(genome) -> PlanGenome:
    if isinstance(genome, PlanGenome):
        return genome
    return PlanGenome(genes=tuple(genome))


def _shard_count(dim: int, degree: int) -> int:
    """Axis-shard count the partitioner string carries: the largest divisor
    of ``dim`` that is ≤ the mesh shard ``degree`` (lowering pads when the
    user forces a non-divisor; the planner never needs to)."""
    for k in range(min(dim, degree), 1, -1):
        if dim % k == 0:
            return k
    return 1


def genome_to_strategy(
    genome: Genome, model_item: ModelItem, resource_spec: ResourceSpec,
) -> Strategy:
    """Render a genome as ordinary Strategy IR (node-level configs only —
    no per-shard ``part_config`` tables, which exist for reference-format
    parity and fold back to node-level settings at lowering anyway). The
    bucket-size gene lands on ``graph_config.bucket_bytes``."""
    from autodist_tpu.strategy.base import replica_devices

    genome = _as_genome(genome)
    variables = model_item.trainable_variables
    if len(genome.genes) != len(variables):
        raise ValueError(
            f"genome length {len(genome.genes)} != {len(variables)} "
            f"trainable vars")
    dests = reduction_devices(resource_spec)
    mesh_shape = resource_spec.mesh_shape(("data", "model"))
    n_model = max(int(mesh_shape.get("model", 1)), 1)
    n_data = max(int(mesh_shape.get("data", 1)), 1)
    degree = n_model if n_model > 1 else n_data

    strategy = Strategy(id=Strategy.new_id(resource_spec.fingerprint()))
    strategy.graph_config.replicas = replica_devices(resource_spec)
    strategy.graph_config.bucket_bytes = int(genome.bucket_bytes)
    for var, gene in zip(variables, genome.genes):
        partitioner = ""
        if (gene.axis is not None and gene.axis < len(var.shape)
                and gene.kind != "zero1"):
            # zero1 renders unpartitioned by definition (replicated param,
            # sharded update); a partitioned var already shards its update,
            # so an axis on a zero1 gene would only alias the "ar"+axis
            # rendering under a second genome spelling.
            k = _shard_count(int(var.shape[gene.axis]), degree)
            if k > 1:
                parts = [1] * len(var.shape)
                parts[gene.axis] = k
                partitioner = ",".join(map(str, parts))
        if gene.kind == "ar":
            sync = AllReduceSynchronizer(group=gene.group)
        elif gene.kind == "zero1":
            sync = AllReduceSynchronizer(group=gene.group, shard_update=True)
        else:
            sync = PSSynchronizer(
                reduction_destination=dests[gene.dest % len(dests)],
                local_replication=(gene.kind == "ps1"),
            )
        strategy.node_config.append(
            NodeConfig(var_name=var.name, synchronizer=sync,
                       partitioner=partitioner)
        )
    return strategy


def strategy_to_genome(strategy: Strategy, model_item: ModelItem,
                       resource_spec: ResourceSpec) -> Genome:
    """Project a built Strategy onto the genome space (seeding). Per-shard
    tables collapse to their node-level settings; unknown destinations map
    to index 0; the graph-wide bucket_bytes projects onto the bucket gene."""
    dests = {d: i for i, d in enumerate(reduction_devices(resource_spec))}
    genes: List[VarGene] = []
    for var in model_item.trainable_variables:
        node = strategy.node_config_for(var.name)
        if node is None:
            genes.append(VarGene())
            continue
        sync = node.synchronizer
        try:
            axis = node.active_partition_axis
        except ValueError:
            axis = None  # multi-active-axis tables have no genome rendering
        if isinstance(sync, AllReduceSynchronizer):
            kind = "zero1" if (sync.shard_update and axis is None) else "ar"
            genes.append(VarGene(kind=kind, axis=axis, group=sync.group))
        else:
            genes.append(VarGene(
                kind="ps1" if sync.local_replication else "ps3",
                axis=axis,
                dest=dests.get(sync.reduction_destination, 0),
            ))
    return PlanGenome(
        genes=tuple(genes),
        bucket_bytes=int(getattr(
            strategy.graph_config, "bucket_bytes", 0) or 0),
    )


def _objective(cost: StrategyCost, calibration=None) -> Tuple[bool, float]:
    """Feasible-first score, lower better — same shape as CostModel.rank:
    infeasible candidates compare on footprint so a model too big to
    replicate still yields the least-over-budget plan."""
    if not cost.feasible:
        return (True, cost.per_chip_bytes)
    if calibration is not None:
        return (False, calibration.predict_s(cost))
    return (False, cost.total_s)


@dataclass
class SearchConfig:
    """Search knobs. Defaults visit ~100 candidates in ~100 ms of pure
    cost-model arithmetic (nothing compiles during search)."""

    beam_width: int = 4
    generations: int = 4
    mutations_per_survivor: int = 8
    seed: int = 0
    include_sparse_seeds: bool = True
    # Also evaluate alternative (data, model) mesh factorizations of the
    # chip count (advisory: the winner strategy is mesh-agnostic IR; the
    # recommended shape rides the provenance for the user's `mesh:` block).
    search_mesh: bool = False
    max_mesh_candidates: int = 6


@dataclass
class SearchResult:
    strategy: Strategy
    cost: StrategyCost
    genome: Genome
    n_visited: int
    provenance: Dict = field(default_factory=dict)


class PlanSearch:
    """Beam search seeded by the Auto slate, scored by the cost model."""

    def __init__(
        self,
        model_item: ModelItem,
        resource_spec: ResourceSpec,
        config: Optional[SearchConfig] = None,
        calibration=None,
    ):
        self.model_item = model_item
        self.spec = resource_spec
        self.config = config or SearchConfig()
        self.calibration = calibration
        self.cost_model = CostModel(model_item, resource_spec)
        self._rng = random.Random(self.config.seed)
        self._axes_by_var = [
            # Candidate partition axes: every axis that could shard at
            # degree >= 2 on SOME mesh, plus "unpartitioned".
            [None] + [i for i, d in enumerate(v.shape) if int(d) >= 2]
            for v in self.model_item.trainable_variables
        ]
        self._n_dests = max(len(reduction_devices(resource_spec)), 1)
        # Seeds the static screen rejected before pricing: {name: [codes]}.
        self._screen_rejected: Dict[str, List[str]] = {}

    # ------------------------------------------------------------------ seeds
    def _seed_slate(self) -> Tuple[Dict[str, Strategy], Dict[str, Genome]]:
        """(lossless built slate strategies, their genome projections).

        The BUILT strategies compete directly in the candidate pool — a
        genome projection can lose builder details (per-shard group tables,
        reference shard counts), and the winner-never-worse-than-Auto bound
        must hold against what Auto would actually emit, not against a
        projection. Lossy compressed slate members (AllReduce+bf16/topk)
        are excluded from direct competition: compression changes numerics,
        so the planner must never auto-pick one silently — the same policy
        Auto and explain's "recommended:" line apply. Their genome
        projections (compressor dropped) still seed mutation.
        """
        from autodist_tpu.kernel.compressor import is_active_compressor
        from autodist_tpu.strategy.ir import iter_synchronizers

        def lossy(strategy: Strategy) -> bool:
            return any(
                is_active_compressor(getattr(s, "compressor", "") or "")
                for node in strategy.node_config
                for s in iter_synchronizers(node)
            )

        from autodist_tpu.analysis import screen_schedule, screen_strategy

        built: Dict[str, Strategy] = {}
        genomes: Dict[str, Genome] = {}
        slate = candidate_slate(
            include_sparse=self.config.include_sparse_seeds, full=True)
        for name, builder in slate:
            try:
                strategy = builder.build(self.model_item, self.spec)
            except Exception as e:  # noqa: BLE001 - skip unbuildable seeds
                logging.debug("plan search: seed %s failed to build (%s)",
                              name, e)
                continue
            # Static screen BEFORE pricing (docs/analysis.md SLS001): a
            # candidate that cannot lower (bad part tables, over-sharded
            # axes, async PS) must never enter the pool — pricing it would
            # let an unlowerable plan win the search and fail at build.
            # The schedule screen (SLO001 degenerate bucketing / SLM003
            # bucket-transient overcommit, sched.py) rejects for the same
            # reason: a candidate whose overlap is structurally impossible
            # or whose scheduled peak cannot fit must never be priced as
            # if its wire were hidden.
            findings = [f for f in (
                screen_strategy(strategy, self.model_item, self.spec)
                + screen_schedule(strategy, self.model_item, self.spec))
                if f.severity == "error"]
            if findings:
                self._screen_rejected[name] = [f.code for f in findings]
                logging.warning(
                    "plan search: seed %s rejected by the static screen "
                    "(%s)", name,
                    "; ".join(f.render() for f in findings))
                continue
            if not lossy(strategy):
                built[name] = strategy
            genomes[name] = strategy_to_genome(
                strategy, self.model_item, self.spec)
        if not genomes:
            # Degenerate fallback: all-AllReduce (always buildable).
            genomes["AllReduce"] = PlanGenome(genes=tuple(
                VarGene() for _ in self.model_item.trainable_variables))
        return built, genomes

    # -------------------------------------------------------------- mutation
    def _mutate(self, genome: Genome) -> Genome:
        genome = _as_genome(genome)
        genes = list(genome.genes)
        bucket = genome.bucket_bytes
        if not genes:  # model with no trainable variables: nothing to move
            return genome
        move = self._rng.random()
        if move < 0.12:
            # Genome-wide bucket-size gene: re-pick the backward-overlap
            # bucketing target (0 = monolithic post-backward sync).
            return PlanGenome(
                genes=tuple(genes),
                bucket_bytes=self._rng.choice(BUCKET_GENE_CHOICES))
        i = self._rng.randrange(len(genes))
        g = genes[i]
        move = self._rng.random()
        if move < 0.4:
            g = VarGene(kind=self._rng.choice(KINDS), axis=g.axis,
                        group=g.group, dest=g.dest)
        elif move < 0.7:
            g = VarGene(kind=g.kind,
                        axis=self._rng.choice(self._axes_by_var[i]),
                        group=g.group, dest=g.dest)
        elif move < 0.85 and g.kind != "ar":
            g = VarGene(kind=g.kind, axis=g.axis, group=g.group,
                        dest=self._rng.randrange(self._n_dests))
        else:
            # Re-chunk the whole genome's fusion groups (advisory on TPU,
            # but it keeps the group-id surface inside the search space).
            chunk = self._rng.choice(CHUNK_SIZES)
            genes = [
                VarGene(kind=x.kind, axis=x.axis, group=j // chunk,
                        dest=x.dest)
                for j, x in enumerate(genes)
            ]
            return PlanGenome(genes=tuple(genes), bucket_bytes=bucket)
        genes[i] = g
        return PlanGenome(genes=tuple(genes), bucket_bytes=bucket)

    # ----------------------------------------------------------------- score
    def _score(self, genome: Genome) -> Tuple[Tuple[bool, float], StrategyCost]:
        strategy = genome_to_strategy(genome, self.model_item, self.spec)
        cost = self.cost_model.strategy_cost(strategy)
        return _objective(cost, self.calibration), cost

    def _screen_genome(self, genome: Genome) -> List[str]:
        """Schedule-screen a mutated child pre-pricing (sched.py): a
        genome whose bucketing is structurally serialized (SLO001) or
        whose bucket transient overcommits (SLM003) never enters the
        pool. Genome-rendered strategies are well-formed by construction,
        so the SLS001 lowering screen is skipped here."""
        from autodist_tpu.analysis import screen_schedule

        strategy = genome_to_strategy(genome, self.model_item, self.spec)
        return sorted({
            f.code for f in screen_schedule(
                strategy, self.model_item, self.spec)
            if f.severity == "error"})

    # ------------------------------------------------------------------- run
    def run(self) -> SearchResult:
        cfg = self.config
        slate, seeds = self._seed_slate()
        scored: Dict[Genome, Tuple[Tuple[bool, float], StrategyCost]] = {}
        origin: Dict[Genome, str] = {}
        seed_rows = {}
        # Direct slate candidates: the exact strategies Auto's builders emit.
        slate_scored = {}
        for name, s in slate.items():
            cost = self.cost_model.strategy_cost(s)
            slate_scored[name] = (_objective(cost, self.calibration), cost)
        for name, (obj, cost) in slate_scored.items():
            seed_rows[name] = {
                "predicted_s": cost.total_s,
                "feasible": cost.feasible,
                "per_chip_gb": cost.per_chip_bytes / 1e9,
            }
        for name, genome in seeds.items():
            if genome not in scored:
                scored[genome] = self._score(genome)
                origin[genome] = f"seed:{name}"
            obj, cost = scored[genome]
            seed_rows.setdefault(name, {
                "predicted_s": cost.total_s,
                "feasible": cost.feasible,
                "per_chip_gb": cost.per_chip_bytes / 1e9,
            })
        # The bound the winner must meet: the best DIRECT slate strategy
        # (what Auto would emit); genome projections only fill in when the
        # whole slate failed to build.
        pool = slate_scored or {n: scored[g] for n, g in seeds.items()}
        best_seed = min(pool, key=lambda n: pool[n][0])
        best_seed_obj, best_seed_cost = pool[best_seed]

        beam = sorted(set(seeds.values()), key=lambda g: scored[g][0])
        beam = beam[: cfg.beam_width]
        trajectory = [{
            "generation": 0,
            "best_predicted_s": scored[beam[0]][1].total_s,
            "visited": len(scored) + len(slate_scored),
        }]
        screened_bad: set = set()
        for gen in range(1, cfg.generations + 1):
            for parent in list(beam):
                for _ in range(cfg.mutations_per_survivor):
                    child = self._mutate(parent)
                    if child in scored or child in screened_bad:
                        continue
                    codes = self._screen_genome(child)
                    if codes:
                        screened_bad.add(child)
                        merged = self._screen_rejected.setdefault(
                            "mutations", [])
                        self._screen_rejected["mutations"] = sorted(
                            set(merged) | set(codes))
                        continue
                    scored[child] = self._score(child)
                    origin.setdefault(
                        child, f"{origin.get(parent, '?')}+g{gen}")
            beam = sorted(scored, key=lambda g: scored[g][0])[: cfg.beam_width]
            trajectory.append({
                "generation": gen,
                "best_predicted_s": scored[beam[0]][1].total_s,
                "visited": len(scored) + len(slate_scored),
            })

        winner = beam[0]
        win_obj, win_cost = scored[winner]
        n_visited = len(scored) + len(slate_scored)
        if win_obj <= best_seed_obj or best_seed not in slate:
            strategy = genome_to_strategy(winner, self.model_item, self.spec)
            winner_origin = origin.get(winner, "?")
        else:
            # A genome projection can price above the exact slate strategy
            # it was projected from (per-shard tables, reference shard
            # counts); the planner must never emit worse than Auto would —
            # the best slate member wins outright. The reported genome is
            # then that strategy's PROJECTION (lossy; the emitted artifact
            # is the strategy itself).
            strategy = slate[best_seed]
            win_obj, win_cost = best_seed_obj, best_seed_cost
            winner_origin = f"slate:{best_seed}"
            winner = seeds.get(best_seed, winner)

        mesh_info = None
        if cfg.search_mesh:
            # Sweep the EMITTED strategy (mesh-agnostic IR), not a genome
            # re-render — the recommendation must describe the plan the
            # caller actually gets.
            mesh_info = self._mesh_sweep(strategy)

        improvement = 0.0
        best_seed_s = seed_rows[best_seed]["predicted_s"]
        if best_seed_s > 0:
            improvement = 1.0 - win_cost.total_s / best_seed_s
        why = (
            f"predicted {win_cost.total_s * 1e3:.3f} ms/step vs best seed "
            f"{best_seed} at {best_seed_s * 1e3:.3f} ms "
            f"({improvement * 100:+.1f}%), "
            f"{'fits' if win_cost.feasible else 'OVER'} "
            f"{win_cost.per_chip_bytes / 1e9:.2f} GB/chip"
        )
        provenance = {
            "n_visited": n_visited,
            "beam_width": cfg.beam_width,
            "generations": cfg.generations,
            "search_seed": cfg.seed,
            "seeds": seed_rows,
            "best_seed": best_seed,
            "winner": {
                "origin": winner_origin,
                "predicted_s": win_cost.total_s,
                "comm_s": win_cost.comm_s,
                "update_s": win_cost.update_s,
                "latency_s": win_cost.latency_s,
                "act_sync_s": win_cost.act_sync_s,
                "gather_s": win_cost.gather_s,
                "overlap_s": win_cost.overlap_s,
                "per_chip_gb": win_cost.per_chip_bytes / 1e9,
                "opt_gb_per_chip": win_cost.opt_bytes / 1e9,
                "n_shard_update": sum(
                    1 for g in _as_genome(winner).genes if g.kind == "zero1"),
                "bucket_bytes": _as_genome(winner).bucket_bytes,
                "feasible": win_cost.feasible,
            },
            "improvement_vs_best_seed": improvement,
            "trajectory": trajectory,
            # The bucket-size gene values the search actually visited —
            # the end-to-end evidence that the gene is searchable, pinned
            # by the plan selftest.
            "bucket_sizes_visited": sorted(
                {_as_genome(g).bucket_bytes for g in scored}),
            "screen_rejected": dict(self._screen_rejected),
            "why": why,
        }
        if self.calibration is not None:
            provenance["calibration"] = {
                "applied": True,
                "predicted_calibrated_s":
                    self.calibration.predict_s(win_cost),
                **self.calibration.describe(),
            }
        if mesh_info is not None:
            provenance["mesh"] = mesh_info
        logging.info("plan search: %s (visited %d candidates)",
                     why, n_visited)
        return SearchResult(
            strategy=strategy, cost=win_cost, genome=winner,
            n_visited=n_visited, provenance=provenance,
        )

    # ------------------------------------------------------------------ mesh
    def _mesh_factorizations(self) -> List[Dict[str, int]]:
        n = max(self.spec.num_chips, 1)
        shapes = []
        for model in range(1, n + 1):
            # data must stay non-trivial on a multi-chip cluster: the cost
            # model excludes (strategy-invariant) compute, so a data=1 mesh
            # looks free on paper while actually forfeiting all data
            # parallelism — pure model parallelism is an explicit user
            # choice, never a planner recommendation.
            if n % model == 0 and (n // model >= 2 or n == 1):
                shapes.append({"data": n // model, "model": model})
        # Prefer modest model degrees first (they're the realistic ones);
        # cap the sweep.
        shapes.sort(key=lambda s: s["model"])
        return shapes[: self.config.max_mesh_candidates]

    def _mesh_sweep(self, strategy: Strategy) -> Dict:
        """Score the winning strategy under alternative mesh factorizations.

        Advisory output: the Strategy IR itself is mesh-agnostic (lowering
        reads the live mesh), so the chosen shape is a recommendation for
        the resource spec's ``mesh:`` block, recorded in provenance."""
        rows = {}
        base = dict(self.spec.mesh_shape(("data", "model")))
        for shape in self._mesh_factorizations():
            try:
                variant = ResourceSpec(resource_dict={
                    **self.spec.to_dict(), "mesh": shape})
                cost = CostModel(
                    self.model_item, variant).strategy_cost(strategy)
            except Exception as e:  # noqa: BLE001 - skip invalid shapes
                logging.debug("plan search: mesh %s skipped (%s)", shape, e)
                continue
            rows[f"data={shape['data']},model={shape['model']}"] = {
                "predicted_s": cost.total_s,
                "feasible": cost.feasible,
                "per_chip_gb": cost.per_chip_bytes / 1e9,
            }
        if not rows:
            return {"searched": True, "candidates": {}}
        feasible = {k: v for k, v in rows.items() if v["feasible"]} or rows
        chosen = min(feasible, key=lambda k: feasible[k]["predicted_s"])
        return {
            "searched": True,
            "current": {k: int(v) for k, v in base.items()},
            "chosen": chosen,
            "candidates": rows,
        }


def search(
    model_item: ModelItem,
    resource_spec: ResourceSpec,
    config: Optional[SearchConfig] = None,
    calibration=None,
) -> SearchResult:
    """One-call façade over :class:`PlanSearch`."""
    return PlanSearch(model_item, resource_spec, config, calibration).run()
