"""plan: the search-based offline auto-planner (docs/planner.md).

Sits above the fixed ``strategy/`` builders: a beam search over the
per-variable strategy space (``search.py``) scored by the analytic cost
model through a per-topology measurement calibration (``calibrate.py``),
with a persistent plan cache keyed by (model fingerprint, resource digest,
package version) so a repeated question skips search entirely
(``cache.py``). ``Plan`` packages the three as an ordinary StrategyBuilder
— ``AutoDist(strategy_builder="plan")`` — and
``python -m autodist_tpu.plan --selftest`` is the zero-hardware proof.
"""
from autodist_tpu.plan.builder import Plan, PlanConfig
from autodist_tpu.plan.cache import (
    CacheEntry,
    PlanCache,
    default_cache_dir,
    dryrun_lowers,
    model_fingerprint,
    plan_key,
)
from autodist_tpu.plan.calibrate import (
    CalibrationRecord,
    TopologyCalibration,
    calibrate_from_records,
    prediction_error,
    record_from_profiler,
    topology_key,
)
from autodist_tpu.plan.search import (
    BUCKET_GENE_CHOICES,
    PlanGenome,
    PlanSearch,
    SearchConfig,
    SearchResult,
    VarGene,
    genome_to_strategy,
    search,
    strategy_to_genome,
)

__all__ = [
    "BUCKET_GENE_CHOICES",
    "CacheEntry",
    "CalibrationRecord",
    "Plan",
    "PlanCache",
    "PlanConfig",
    "PlanGenome",
    "PlanSearch",
    "SearchConfig",
    "SearchResult",
    "TopologyCalibration",
    "VarGene",
    "calibrate_from_records",
    "default_cache_dir",
    "dryrun_lowers",
    "genome_to_strategy",
    "model_fingerprint",
    "plan_key",
    "prediction_error",
    "record_from_profiler",
    "search",
    "strategy_to_genome",
    "topology_key",
]
