"""Measurement calibration: fit the cost model's constants to this topology.

The analytic :class:`~autodist_tpu.strategy.cost_model.CostModel` prices a
strategy as ``comm + update + latency + act_sync`` seconds from *nominal*
bandwidth/latency constants, and deliberately excludes the strategy-
invariant compute floor. PR 3's obs :class:`~autodist_tpu.obs.profiler.
StepProfiler` measures what actually happened (one-end-barrier step wall
time, dispatch gap, the compiled program's own FLOPs/bytes). This module
closes the loop: a set of ``(predicted components, measured seconds)``
records fits per-component efficiency coefficients

    measured_s ≈ base + a·comm_s + b·update_s + c·latency_s + d·act_sync_s
                 + e·gather_s + f·overlap_s

where ``base`` absorbs the compute floor (plus fixed dispatch overhead) and
``a..f`` the achieved fraction of each nominal peak (``gather_s`` is the
zero1 param re-gather wire; ``overlap_s`` the bucketed backward-overlap
wire, whose fitted coefficient is the measured exposed fraction — see
:data:`COMPONENTS`). The fit REPORTS its
own ranking error (mean |rel| error before vs after), and is persisted
per-topology — one file per (accelerator kind × chip count × mesh shape) —
so it shrinks with use and a calibration measured on one cluster never
silently prices another.

Relationship to ``strategy.cost_model.Calibration``: that is the older
scalar (base + scale·total) fit ``AutoDist.tune`` records and ``explain``
displays; this is its per-component superset for the planner. When fewer
than :data:`MIN_COMPONENT_POINTS` records exist (or the component matrix
is degenerate), the fit degrades to exactly the scalar form, so sparse
profiles never produce wild extrapolations.

Trace-fed precedence (docs/planner.md): records carrying
``measured_components`` — per-component device seconds attributed by
``obs/attrib.py``'s measured-wire join (:func:`record_from_attribution`)
— pin those components' coefficients DIRECTLY (Σmeasured/Σpredicted);
the regression only fits what the trace cannot see. One attributed record
already calibrates the wire components; the whole-step regression stays
the fallback when no trace exists.
"""
from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.cost_model import StrategyCost
from autodist_tpu.utils import logging

# gather_s (added with the zero1 shard_update capability) is the param
# re-gather wire of weight-update-sharded vars — fitted separately from
# comm_s because the all-gather overlaps differently with the update than
# the gradient reduction does with the backward pass. overlap_s (added
# with bucketed backward-overlap emission, GraphConfig.bucket_bytes) is
# the wire the latency-hiding scheduler is EXPECTED to hide under backward
# compute: its fitted coefficient is the measured exposed fraction — near
# 0 when overlap works, near 1 when it doesn't — replacing the analytic
# prior (cost_model.OVERLAP_EXPOSED_FRACTION).
COMPONENTS = ("comm_s", "update_s", "latency_s", "act_sync_s", "gather_s",
              "overlap_s")
# Below this many distinct records the per-component least squares is
# underdetermined; fall back to the scalar base+scale fit.
MIN_COMPONENT_POINTS = len(COMPONENTS) + 2


def _default_coefficients() -> Dict[str, float]:
    """Uncalibrated coefficients: nominal (1.0) for every component except
    overlap_s, which starts at the cost model's analytic exposure prior so
    an unfitted TopologyCalibration predicts exactly StrategyCost.total_s."""
    from autodist_tpu.strategy.cost_model import OVERLAP_EXPOSED_FRACTION

    coef = {c: 1.0 for c in COMPONENTS}
    coef["overlap_s"] = OVERLAP_EXPOSED_FRACTION
    return coef


def default_calibration_dir() -> str:
    from autodist_tpu import const

    return const.DEFAULT_PLAN_DIR


def topology_key(resource_spec: ResourceSpec, device_kind: str = "") -> str:
    """Filesystem-safe identity of the thing a calibration was measured on:
    accelerator kind (runtime ``device_kind`` wins over the spec's
    ``accelerator``), chip count, and logical mesh shape. NOT the full spec
    fingerprint — addresses/SSH blocks don't change achieved bandwidth."""
    kind = device_kind or resource_spec.tpu.accelerator or "unknown"
    mesh = resource_spec.mesh_shape(("data", "model"))
    shape = "x".join(f"{k}{v}" for k, v in sorted(mesh.items()) if v > 1) or "1"
    raw = f"{kind}-c{resource_spec.num_chips}-{shape}"
    return re.sub(r"[^A-Za-z0-9_.-]+", "_", raw).lower()


@dataclass
class CalibrationRecord:
    """One (predicted, measured) pair — a strategy that actually ran."""

    comm_s: float
    update_s: float
    latency_s: float
    act_sync_s: float
    measured_s: float
    name: str = ""
    gather_s: float = 0.0  # zero1 param re-gather wire (0 pre-zero1 records)
    # Bucketed backward-overlap wire (0 for pre-bucketing / unbucketed
    # records); see COMPONENTS.
    overlap_s: float = 0.0
    dispatch_gap_s: float = 0.0
    flops_per_step: float = 0.0
    bytes_per_step: float = 0.0
    # Trace-derived MEASURED seconds per component (obs/attrib.py
    # MeasuredWire.calibration_components): when present, the fit pins that
    # component's coefficient by direct attribution (Σmeasured/Σpredicted)
    # instead of asking the whole-step regression to disentangle it —
    # direct evidence beats a 6-coefficient least squares on few points.
    # Components a trace cannot attribute are simply absent.
    measured_components: Dict[str, float] = field(default_factory=dict)

    @property
    def predicted_s(self) -> float:
        """Mirrors StrategyCost.total_s (incl. the analytic overlap-exposure
        prior) so the uncalibrated error column grades the same number the
        search objective uses."""
        from autodist_tpu.strategy.cost_model import OVERLAP_EXPOSED_FRACTION

        return (self.comm_s + self.update_s + self.latency_s
                + self.act_sync_s + self.gather_s
                + OVERLAP_EXPOSED_FRACTION * self.overlap_s)

    @classmethod
    def from_cost(cls, cost: StrategyCost, measured_s: float,
                  name: str = "", **extra) -> "CalibrationRecord":
        return cls(
            comm_s=cost.comm_s, update_s=cost.update_s,
            latency_s=cost.latency_s, act_sync_s=cost.act_sync_s,
            gather_s=getattr(cost, "gather_s", 0.0),
            overlap_s=getattr(cost, "overlap_s", 0.0),
            measured_s=float(measured_s), name=name, **extra,
        )

    def to_json(self) -> dict:
        return {
            "comm_s": self.comm_s, "update_s": self.update_s,
            "latency_s": self.latency_s, "act_sync_s": self.act_sync_s,
            "measured_s": self.measured_s,
            **({"gather_s": self.gather_s} if self.gather_s else {}),
            **({"overlap_s": self.overlap_s} if self.overlap_s else {}),
            **({"name": self.name} if self.name else {}),
            **({"dispatch_gap_s": self.dispatch_gap_s}
               if self.dispatch_gap_s else {}),
            **({"flops_per_step": self.flops_per_step}
               if self.flops_per_step else {}),
            **({"bytes_per_step": self.bytes_per_step}
               if self.bytes_per_step else {}),
            **({"measured_components": dict(self.measured_components)}
               if self.measured_components else {}),
        }

    @classmethod
    def from_json(cls, d: dict) -> "CalibrationRecord":
        return cls(
            comm_s=float(d["comm_s"]), update_s=float(d["update_s"]),
            latency_s=float(d["latency_s"]),
            act_sync_s=float(d["act_sync_s"]),
            measured_s=float(d["measured_s"]), name=str(d.get("name", "")),
            gather_s=float(d.get("gather_s", 0.0)),
            overlap_s=float(d.get("overlap_s", 0.0)),
            dispatch_gap_s=float(d.get("dispatch_gap_s", 0.0)),
            flops_per_step=float(d.get("flops_per_step", 0.0)),
            bytes_per_step=float(d.get("bytes_per_step", 0.0)),
            measured_components={
                str(k): float(v)
                for k, v in (d.get("measured_components") or {}).items()
                if k in COMPONENTS},
        )


def record_from_profiler(report: Dict, cost: StrategyCost,
                         name: str = "") -> CalibrationRecord:
    """Pair an obs ``StepProfiler.report()`` with the analytic cost of the
    strategy it profiled. Measured time is the per-step WALL split (the
    one-end-barrier discipline makes it trustworthy on every platform);
    dispatch gap and the compiled program's FLOPs/bytes ride along for
    provenance."""
    steps = float(report.get("steps_per_window", 1.0)) or 1.0
    return CalibrationRecord.from_cost(
        cost,
        measured_s=float(report.get("step_wall_s", 0.0)),
        name=name,
        dispatch_gap_s=float(report.get("dispatch_gap_s", 0.0)) / steps,
        flops_per_step=float(report.get("flops_per_step", 0.0)),
        bytes_per_step=float(report.get("bytes_per_step", 0.0)),
    )


def record_from_attribution(report: Dict, cost: StrategyCost, measured_wire,
                            name: str = "") -> CalibrationRecord:
    """:func:`record_from_profiler` plus the trace-derived per-component
    seconds an ``obs.attrib.MeasuredWire`` attributes (wire components
    only — comm/gather/overlap; compute-side components stay with the
    regression). The fit pins the attributed components directly and
    spends the regression's degrees of freedom on the rest."""
    rec = record_from_profiler(report, cost, name=name)
    rec.measured_components = {
        k: float(v)
        for k, v in measured_wire.calibration_components().items()
        if k in COMPONENTS}
    return rec


@dataclass
class TopologyCalibration:
    """Fitted per-component correction for one topology."""

    coefficients: Dict[str, float] = field(
        default_factory=_default_coefficients)
    base_s: float = 0.0
    device: str = ""
    topology: str = ""
    n_points: int = 0
    # Mean |measured - predicted| / measured, uncalibrated vs calibrated —
    # the "is the simulator getting better with use" headline.
    error_before: float = float("nan")
    error_after: float = float("nan")

    # ----------------------------------------------------------------- apply
    def predict_s(self, cost: StrategyCost) -> float:
        """Calibrated seconds for anything exposing the component
        attributes — a :class:`~autodist_tpu.strategy.cost_model.
        StrategyCost` or a :class:`CalibrationRecord` (one formula, so the
        error grader and the search objective can never drift apart)."""
        c = self.coefficients
        return self.base_s + sum(
            c.get(comp, 1.0) * getattr(cost, comp, 0.0)
            for comp in COMPONENTS
        )

    def describe(self) -> dict:
        return {
            "coefficients": dict(self.coefficients),
            "base_ms": self.base_s * 1e3,
            "device": self.device,
            "topology": self.topology,
            "n_points": self.n_points,
            "mean_abs_rel_err_before": self.error_before,
            "mean_abs_rel_err_after": self.error_after,
        }

    # ------------------------------------------------------------------- fit
    @classmethod
    def fit(cls, records: Sequence[CalibrationRecord], device: str = "",
            topology: str = "") -> "TopologyCalibration":
        recs = [r for r in records
                if np.isfinite(r.measured_s) and r.measured_s > 0]
        out = cls(device=device, topology=topology, n_points=len(recs))
        if not recs:
            return out
        out.error_before = prediction_error(recs, None)

        # Direct attribution first: a component measured by trace
        # attribution (obs/attrib.py) gets its coefficient pinned as
        # Σmeasured / Σpredicted over the records carrying evidence —
        # per-op device time is stronger than anything a whole-step
        # regression can infer, and it frees the regression's degrees of
        # freedom for the components a trace cannot see. A 0.0 is
        # legitimate (fully-hidden overlap wire costs nothing).
        direct: Dict[str, float] = {}
        for comp in COMPONENTS:
            num = den = 0.0
            for r in recs:
                if comp in getattr(r, "measured_components", {}):
                    num += float(r.measured_components[comp])
                    den += float(getattr(r, comp))
            if den > 1e-12 and num >= 0:
                direct[comp] = num / den

        def residual(r) -> float:
            return r.measured_s - sum(
                direct[c] * getattr(r, c) for c in direct)

        fitted = False
        free = [c for c in COMPONENTS if c not in direct]
        if len(recs) >= MIN_COMPONENT_POINTS and free:
            A = np.array(
                [[getattr(r, c) for c in free] + [1.0]
                 for r in recs], np.float64)
            y = np.array([residual(r) for r in recs], np.float64)
            # Columns that never vary carry no signal; zero them so lstsq
            # can't spend them on noise (their coefficient stays 1.0).
            active = [i for i in range(len(free))
                      if float(np.ptp(A[:, i])) > 1e-12]
            if active:
                cols = active + [len(free)]
                coef, *_ = np.linalg.lstsq(A[:, cols], y, rcond=None)
                comp_coef = _default_coefficients()
                comp_coef.update(direct)
                free_coef = {}
                for i, col in enumerate(active):
                    free_coef[free[col]] = float(coef[i])
                base = float(coef[-1])
                # Negative efficiency coefficients mean the fit is chasing
                # noise (a "speedup" from sending more bytes); reject the
                # component fit rather than let it invert rankings.
                if base >= 0 and all(v > 0 for v in free_coef.values()):
                    comp_coef.update(free_coef)
                    out.coefficients = comp_coef
                    out.base_s = base
                    fitted = True
        if not fitted and direct:
            # Directly-attributed components pinned; the remainder keeps
            # its uncalibrated default and base_s absorbs the mean
            # residual (the compute floor) — no regression at all, so a
            # single trace-attributed record already calibrates.
            comp_coef = _default_coefficients()
            comp_coef.update(direct)
            rest = [residual(r) - sum(comp_coef[c] * getattr(r, c)
                                      for c in free) for r in recs]
            out.coefficients = comp_coef
            out.base_s = max(float(np.mean(rest)), 0.0)
            fitted = True
        if not fitted:
            # Scalar fallback: measured ≈ base + scale × predicted_total
            # (the tune()-era fit; see module docstring).
            pred = np.array([r.predicted_s for r in recs], np.float64)
            meas = np.array([r.measured_s for r in recs], np.float64)
            if len(recs) == 1 or float(np.ptp(pred)) < 1e-12:
                scale, base = 1.0, float(np.mean(meas - pred))
            else:
                scale, base = np.polyfit(pred, meas, 1)
                if scale <= 0:
                    scale, base = 1.0, float(np.mean(meas - pred))
            # Scalar form scales predicted_s, which already charges the
            # overlap-exposure prior — so the overlap coefficient carries
            # scale x prior to keep predict_s == base + scale·predicted_s.
            out.coefficients = {
                c: float(scale) * v for c, v in _default_coefficients().items()
            }
            out.base_s = max(float(base), 0.0)
        out.error_after = prediction_error(recs, out)
        return out

    # ---------------------------------------------------------- persistence
    def path_for(self, directory: Optional[str] = None) -> str:
        d = directory or default_calibration_dir()
        return os.path.join(d, f"calibration-{self.topology or 'default'}.json")

    def save(self, path: Optional[str] = None,
             records: Sequence[CalibrationRecord] = (),
             rejected_fits: Sequence[dict] = ()) -> str:
        path = path or self.path_for()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # Rejected refits (the keep-best guard in calibrate_from_records)
        # ride the file as provenance: the existing history is carried
        # forward on every save, newest-capped, so "why didn't the refit
        # land" is answerable from the artifact alone.
        prior_rejected: List[dict] = []
        try:
            with open(path, "r", encoding="utf-8") as f:
                prior_rejected = list(json.load(f).get("rejected_fits", []))
        except (OSError, ValueError, KeyError, TypeError):
            prior_rejected = []
        doc = {
            "coefficients": self.coefficients,
            "base_s": self.base_s,
            "device": self.device,
            "topology": self.topology,
            "n_points": self.n_points,
            "error_before": self.error_before,
            "error_after": self.error_after,
            "records": [r.to_json() for r in records],
            "rejected_fits": (prior_rejected + list(rejected_fits))[-32:],
        }
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2, sort_keys=True, default=float)
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, path: str) -> Optional["TopologyCalibration"]:
        if not os.path.exists(path):
            return None
        try:
            with open(path, "r", encoding="utf-8") as f:
                d = json.load(f)
            # Components absent from an older file (pre-overlap_s
            # calibrations) keep their uncalibrated default.
            defaults = _default_coefficients()
            coef = {c: float(d["coefficients"].get(c, defaults[c]))
                    for c in COMPONENTS}
            return cls(
                coefficients=coef,
                base_s=float(d.get("base_s", 0.0)),
                device=str(d.get("device", "")),
                topology=str(d.get("topology", "")),
                n_points=int(d.get("n_points", 0)),
                error_before=float(d.get("error_before", float("nan"))),
                error_after=float(d.get("error_after", float("nan"))),
            )
        except (OSError, ValueError, KeyError, TypeError) as e:
            # A torn/stale file degrades to "no calibration", loudly.
            logging.warning("plan calibration unreadable at %s (%s); "
                            "ignoring it", path, e)
            return None

    @classmethod
    def load_for(cls, resource_spec: ResourceSpec, device_kind: str = "",
                 directory: Optional[str] = None,
                 ) -> Optional["TopologyCalibration"]:
        key = topology_key(resource_spec, device_kind)
        d = directory or default_calibration_dir()
        return cls.load(os.path.join(d, f"calibration-{key}.json"))


def load_records(path: str) -> List[CalibrationRecord]:
    """Replay a persisted profile's records (the calibration file keeps
    them so refits can extend rather than restart)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            d = json.load(f)
        return [CalibrationRecord.from_json(r) for r in d.get("records", [])]
    except (OSError, ValueError, KeyError, TypeError):
        return []


def prediction_error(records: Sequence[CalibrationRecord],
                  calibration: Optional[TopologyCalibration]) -> float:
    """Mean |predicted - measured| / measured over the records; with
    ``calibration=None`` the raw analytic totals are graded (the "before"
    column). NaN when no record qualifies."""
    errs = []
    for r in records:
        if not (np.isfinite(r.measured_s) and r.measured_s > 0):
            continue
        pred = (r.predicted_s if calibration is None
                else calibration.predict_s(r))
        errs.append(abs(pred - r.measured_s) / r.measured_s)
    return float(np.mean(errs)) if errs else float("nan")


# Persisted-profile bound: newest records win. Keeps the calibration file
# O(1) across unbounded tune() invocations and stops one over-tuned
# configuration from drowning the fit (least squares weights every record
# equally).
MAX_PERSISTED_RECORDS = 512


def _merge_records(old: Sequence[CalibrationRecord],
                   new: Sequence[CalibrationRecord],
                   ) -> List[CalibrationRecord]:
    """old + new with exact duplicates collapsed (newest kept) and the
    total capped to the newest :data:`MAX_PERSISTED_RECORDS`."""
    merged: Dict[tuple, CalibrationRecord] = {}
    for r in list(old) + list(new):
        sig = (r.name, r.comm_s, r.update_s, r.latency_s, r.act_sync_s,
               r.gather_s, r.overlap_s, r.measured_s,
               tuple(sorted(r.measured_components.items())))
        merged.pop(sig, None)  # re-insert so the newest occurrence is last
        merged[sig] = r
    return list(merged.values())[-MAX_PERSISTED_RECORDS:]


def calibrate_from_records(
    records: Sequence[CalibrationRecord],
    resource_spec: ResourceSpec,
    device_kind: str = "",
    directory: Optional[str] = None,
    persist: bool = True,
) -> TopologyCalibration:
    """Fit + (optionally) persist the per-topology calibration, merging the
    new records with any the existing file already holds (exact duplicates
    collapsed, capped to the newest :data:`MAX_PERSISTED_RECORDS`).

    Refits are KEEP-BEST: when the fresh fit predicts the merged record
    set *worse* than the already-persisted coefficients do (a degenerate
    live window, an adversarial record the pilot gate let through, a
    regression to the scalar fallback), the persisted coefficients are
    kept and the rejected fit is recorded in the file's ``rejected_fits``
    provenance — live refits are monotone in fit error, so a production
    replan loop can only sharpen the simulator, never degrade it. The
    merged records still persist either way: evidence accumulates even
    when a fit loses."""
    key = topology_key(resource_spec, device_kind)
    d = directory or default_calibration_dir()
    path = os.path.join(d, f"calibration-{key}.json")
    merged = _merge_records(load_records(path), records)
    calib = TopologyCalibration.fit(merged, device=device_kind, topology=key)
    rejected_fits: List[dict] = []
    prior = TopologyCalibration.load(path)
    if prior is not None:
        prior_err = prediction_error(merged, prior)
        if (np.isfinite(prior_err) and np.isfinite(calib.error_after)
                and calib.error_after > prior_err + 1e-12):
            rejected_fits.append({
                "coefficients": dict(calib.coefficients),
                "base_s": calib.base_s,
                "n_points": calib.n_points,
                "error_after": calib.error_after,
                "error_best": prior_err,
            })
            logging.warning(
                "plan calibration (%s): refit rejected — error %.4f over "
                "the merged records vs %.4f for the persisted fit; "
                "keeping the previous coefficients (keep-best)",
                key, calib.error_after, prior_err)
            calib = TopologyCalibration(
                coefficients=dict(prior.coefficients), base_s=prior.base_s,
                device=device_kind or prior.device, topology=key,
                n_points=len(merged), error_before=calib.error_before,
                error_after=prior_err)
    if persist:
        calib.save(path, records=merged, rejected_fits=rejected_fits)
        logging.info(
            "plan calibration (%s): %d points, mean |rel err| %.1f%% -> "
            "%.1f%% -> %s", key, calib.n_points,
            calib.error_before * 100 if np.isfinite(calib.error_before)
            else float("nan"),
            calib.error_after * 100 if np.isfinite(calib.error_after)
            else float("nan"), path,
        )
    return calib
