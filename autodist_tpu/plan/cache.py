"""Persistent plan cache: search once, reuse the winning Strategy forever.

Every prior surface re-planned (and re-ranked, and re-compiled) from
scratch on each invocation. The cache keys the *question* — a model
fingerprint from :class:`~autodist_tpu.model_item.ModelItem` (variable
names/shapes/dtypes/flags + optimizer), the
:class:`~autodist_tpu.resource_spec.ResourceSpec` digest, and the package
version — and stores the *answer*: the winning serialized Strategy plus its
full search provenance. A re-run with the same question skips search
entirely and goes straight to lowering with byte-identical Strategy JSON.

Trust model: a cached plan is VALIDATED before it is believed —

- integrity: ``meta.json`` carries a sha256 over the strategy bytes; any
  mismatch (torn write, hand-edit, bitrot) is a loud warning + fresh
  search, never a crash;
- liveness + conformance: the plan is compiled against the current model
  (``StrategyCompiler``), dry-run lowered to a ShardingPlan over the live
  mesh, and then STATICALLY ANALYZED (``autodist_tpu.analysis``: shared
  degradation predicate, per-chip HBM budget — docs/analysis.md) when the
  runtime has the spec's device count — a plan that no longer lowers, or
  that lowers but trips the analyzer (shape drift the key missed, lowering
  rule changes inside one package version, HBM overcommit), is evicted
  with the finding attached to the warning.

Layout: ``<dir>/<key>/{strategy.json, provenance.json, meta.json}``, one
directory per key, writes staged in a temp dir and atomically renamed.
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
from dataclasses import dataclass, field
from typing import Dict, Optional

from autodist_tpu.model_item import ModelItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.ir import Strategy
from autodist_tpu.utils import logging, retry

CACHE_FORMAT = 1


def default_cache_dir() -> str:
    from autodist_tpu import const
    from autodist_tpu.const import ENV

    return ENV.AUTODIST_PLAN_CACHE.val or os.path.join(
        const.DEFAULT_PLAN_DIR, "cache")


def model_fingerprint(model_item: ModelItem) -> str:
    """Stable digest of everything the planner's answer depends on in the
    model: the full serialized ModelItem (variables with shapes/dtypes/
    sparse/expert/tp-role flags, optimizer spec, captured batch size)."""
    blob = json.dumps(model_item.to_json(), sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()


def plan_key(model_item: ModelItem, resource_spec: ResourceSpec,
             version: Optional[str] = None) -> str:
    """The cache key: (model fingerprint, resource digest, package version).

    The version is IN the key — a package upgrade may change lowering or
    cost-model semantics, so an old winner must re-search, not silently
    load (the dry-run validation is the second line of defense for drift
    within one version)."""
    if version is None:
        import autodist_tpu

        version = autodist_tpu.__version__
    blob = "\n".join([
        f"format={CACHE_FORMAT}",
        f"model={model_fingerprint(model_item)}",
        f"resources={resource_spec.fingerprint()}",
        f"version={version}",
    ]).encode()
    return hashlib.sha256(blob).hexdigest()[:32]


def dryrun_lowers(strategy: Strategy, model_item: ModelItem,
                  resource_spec: ResourceSpec) -> bool:
    """True when the strategy still lowers AND analyzes clean against the
    current model on a mesh of the spec's shape — the no-execution slice
    of the driver's ``dryrun_multichip`` contract: StrategyCompiler
    validation + a full ``GraphTransformer.transform()`` into a
    ShardingPlan, then the static analyzer (``autodist_tpu.analysis``)
    over the lowered plan — degradation drift vs the shared predicate and
    the per-chip HBM budget (docs/analysis.md). A cached winner that
    lowers but overcommits memory or whose flags disagree with the
    lowering rules is evicted WITH the finding attached, not trusted into
    an OOM at step 1.

    Skips (returns True with a debug log) when the live runtime doesn't
    have the spec's device count — validation needs a real mesh, and a
    chief planning offline for a bigger fleet is a legitimate caller."""
    import copy

    import jax

    from autodist_tpu.analysis import AnalysisError, analyze_plan
    from autodist_tpu.kernel import GraphTransformer, build_mesh
    from autodist_tpu.strategy.base import StrategyCompiler

    n = resource_spec.num_chips
    try:
        have = jax.device_count()
    except Exception:  # noqa: BLE001 - no backend: cannot validate
        have = -1
    if have != n:
        logging.debug(
            "plan cache: dryrun validation skipped (runtime has %s devices, "
            "spec wants %d)", have, n)
        return True
    # Deep-copy first: StrategyCompiler prunes node_config in place, and a
    # validation pass must not mutate the artifact it validates.
    candidate = copy.deepcopy(strategy)
    compiled = StrategyCompiler(model_item).compile(candidate)
    mesh = build_mesh(resource_spec)
    plan = GraphTransformer(compiled, model_item, mesh).transform()
    # model_item joins the call so the schedule screen (sched.py: a cached
    # winner whose bucketing is structurally serialized — SLO001 — or
    # whose bucket transient overcommits — SLM003) evicts too: a plan
    # with a schedule finding is never trusted.
    report = analyze_plan(
        plan, strategy=compiled, resource_spec=resource_spec,
        optimizer=model_item.optimizer_spec.name, program="plan-cache",
        model_item=model_item)
    if not report.ok:
        raise AnalysisError(report)
    return True


@dataclass
class CacheEntry:
    strategy: Strategy
    provenance: Dict
    path: str
    key: str
    strategy_bytes: bytes = b""


@dataclass
class PlanCache:
    """Filesystem plan cache with hit/miss accounting."""

    cache_dir: str = field(default_factory=default_cache_dir)
    validate: bool = True
    stats: Dict[str, int] = field(default_factory=lambda: {
        "hits": 0, "misses": 0, "invalidated": 0})

    def _entry_dir(self, key: str) -> str:
        return os.path.join(self.cache_dir, key)

    def _read_files(self, key: str) -> Optional[CacheEntry]:
        """One integrity-checked read of the entry's files (no lowering);
        raises on any defect, returns None when the entry doesn't exist."""
        d = self._entry_dir(key)
        spath = os.path.join(d, "strategy.json")
        if not os.path.exists(spath):
            return None
        with open(spath, "rb") as f:
            raw = f.read()
        with open(os.path.join(d, "meta.json"), "r", encoding="utf-8") as f:
            meta = json.load(f)
        if meta.get("strategy_sha256") != hashlib.sha256(raw).hexdigest():
            raise ValueError("strategy.json checksum mismatch")
        strategy = Strategy.from_json(json.loads(raw.decode("utf-8")))
        if not strategy.node_config:
            raise ValueError("cached strategy has no node configs")
        try:
            with open(os.path.join(d, "provenance.json"), "r",
                      encoding="utf-8") as f:
                provenance = json.load(f)
        except (OSError, ValueError):
            provenance = {}  # provenance is advisory; plan integrity isn't
        return CacheEntry(strategy=strategy, provenance=provenance,
                          path=d, key=key, strategy_bytes=raw)

    # ------------------------------------------------------------------- get
    def get(self, model_item: ModelItem, resource_spec: ResourceSpec,
            version: Optional[str] = None) -> Optional[CacheEntry]:
        """The cached winner for this (model, resources, version), fully
        validated — or None (counted as a miss; corrupt entries are evicted
        with a warning and also return None, never raise)."""
        key = plan_key(model_item, resource_spec, version)
        d = self._entry_dir(key)
        try:
            # A same-key writer replacing the entry mid-read produces a
            # mixed old/new view (strategy bytes from one generation, meta
            # checksum from the other). One short retry (through the ONE
            # backoff home, utils/retry.py) sees the settled files. Only
            # the cheap file-read phase retries — dry-run validation
            # failures below are deterministic and re-lowering would just
            # double the miss latency.
            try:
                entry = retry.retry_call(
                    lambda: self._read_files(key),
                    policy=retry.RetryPolicy(
                        initial_s=0.05, max_s=0.05, max_attempts=2),
                    describe=f"plan cache read {key}")
            except retry.RetryError as e:
                raise e.__cause__ or e
            if entry is not None and self.validate:
                dryrun_lowers(entry.strategy, model_item, resource_spec)
        except Exception as e:  # noqa: BLE001 - ANY defect => fresh search
            logging.warning(
                "plan cache: entry %s is invalid (%s); evicting and falling "
                "back to a fresh search", key, e)
            self.stats["invalidated"] += 1
            self.stats["misses"] += 1
            shutil.rmtree(d, ignore_errors=True)
            return None
        if entry is None:
            self.stats["misses"] += 1
            return None
        self.stats["hits"] += 1
        # NB: entry.strategy.path stays as serialized — mutating it would
        # break the byte-identical round-trip contract (selftest claim 3);
        # the entry's filesystem location rides CacheEntry.path instead.
        logging.info("plan cache HIT %s (%s)", key, entry.path)
        return entry

    # ------------------------------------------------------------------- put
    def put(self, model_item: ModelItem, resource_spec: ResourceSpec,
            strategy: Strategy, provenance: Optional[Dict] = None,
            version: Optional[str] = None) -> str:
        """Persist a winner; returns the entry directory.

        Crash-safe: files are staged in a temp dir and renamed into place,
        so a killed writer never leaves a half-written entry at the final
        path. Same-key concurrency is last-writer-wins: the brief
        remove-then-rename window can hand a racing reader a mixed view
        (``get`` retries once to ride it out) or a racing writer an
        ``ENOTEMPTY`` (retried once here; on a second loss the other
        writer's equally valid entry stands)."""
        key = plan_key(model_item, resource_spec, version)
        d = self._entry_dir(key)
        os.makedirs(self.cache_dir, exist_ok=True)
        tmp = os.path.join(self.cache_dir, f".tmp-{os.getpid()}-{key}")
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        if not strategy.id:
            strategy.id = Strategy.new_id(resource_spec.fingerprint())
        raw = json.dumps(strategy.to_json(), indent=2,
                         sort_keys=True).encode("utf-8")
        with open(os.path.join(tmp, "strategy.json"), "wb") as f:
            f.write(raw)
        with open(os.path.join(tmp, "provenance.json"), "w",
                  encoding="utf-8") as f:
            json.dump(provenance or {}, f, indent=2, sort_keys=True,
                      default=float)
        with open(os.path.join(tmp, "meta.json"), "w",
                  encoding="utf-8") as f:
            json.dump({
                "format": CACHE_FORMAT,
                "key": key,
                "strategy_id": strategy.id,
                "strategy_sha256": hashlib.sha256(raw).hexdigest(),
                "model_fingerprint": model_fingerprint(model_item),
                "resource_fingerprint": resource_spec.fingerprint(),
            }, f, indent=2, sort_keys=True)
        shutil.rmtree(d, ignore_errors=True)
        try:
            os.replace(tmp, d)
        except OSError:
            # A concurrent same-key writer recreated the target between our
            # rmtree and rename. Their entry answers the identical
            # question; retry once for last-writer-wins, then defer.
            shutil.rmtree(d, ignore_errors=True)
            try:
                os.replace(tmp, d)
            except OSError as e:
                shutil.rmtree(tmp, ignore_errors=True)
                logging.warning(
                    "plan cache: concurrent writer won entry %s (%s); "
                    "keeping theirs", key, e)
                return d
        logging.info("plan cache STORE %s -> %s", key, d)
        return d
