"""``Plan``: the search-based auto-planner as an ordinary StrategyBuilder.

Sits above the fixed builders the same way ``Auto`` does, but instead of
ranking a fixed slate it (1) consults the persistent plan cache — an
identical (model, resources, version) question returns the cached winner
byte-identically with zero search; (2) otherwise runs the beam search over
the per-variable strategy space (``plan/search.py``), scored through the
per-topology measurement calibration when one has been recorded
(``plan/calibrate.py``); (3) stores the winner + provenance back into the
cache. Decision flow vs ``Auto`` is documented in docs/planner.md.

Usage — the builder slots anywhere a builder goes, including by name::

    ad = AutoDist(strategy_builder="plan")          # default PlanConfig
    ad = AutoDist(strategy_builder=Plan(PlanConfig(
        cache_dir="/fast/plan-cache", generations=8)))

After ``build``, ``Plan.last_result`` holds the provenance (rendered by
``strategy/explain.py``'s ``explain_provenance``), and ``Plan.cache.stats``
the hit/miss counters bench.py's ``--plan-cache`` flag reports.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field, replace
from typing import Dict, Optional

from autodist_tpu.model_item import ModelItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.base import StrategyBuilder
from autodist_tpu.strategy.ir import Strategy
from autodist_tpu.utils import logging

from autodist_tpu.plan.cache import PlanCache, default_cache_dir
from autodist_tpu.plan.calibrate import TopologyCalibration
from autodist_tpu.plan.search import PlanSearch, SearchConfig


@dataclass
class PlanConfig:
    """Knobs for the planner (search + cache + calibration)."""

    # Cache: None disables persistence (always search).
    cache_dir: Optional[str] = field(default_factory=default_cache_dir)
    # Dry-run-lower cached plans before trusting them (cheap; see cache.py).
    validate_cache: bool = True
    # Search shape (see SearchConfig for semantics).
    beam_width: int = 4
    generations: int = 4
    mutations_per_survivor: int = 8
    seed: int = 0
    search_mesh: bool = False
    # Calibration: "auto" loads the per-topology file a prior profile
    # recorded (no-op when none exists); None disables; or pass a
    # TopologyCalibration directly.
    calibration: object = "auto"

    def search_config(self) -> SearchConfig:
        return SearchConfig(
            beam_width=self.beam_width,
            generations=self.generations,
            mutations_per_survivor=self.mutations_per_survivor,
            seed=self.seed,
            search_mesh=self.search_mesh,
        )


class Plan(StrategyBuilder):
    """Search-based planner with a persistent plan/compile cache."""

    def __init__(self, config: Optional[PlanConfig] = None, **overrides):
        cfg = config or PlanConfig()
        if overrides:
            cfg = replace(cfg, **overrides)
        self.config = cfg
        self.cache: Optional[PlanCache] = None
        if cfg.cache_dir is not None:
            self.cache = PlanCache(cache_dir=cfg.cache_dir,
                                   validate=cfg.validate_cache)
        # After build(): {"cache_hit", "key", "n_visited", "provenance"}.
        self.last_result: Optional[Dict] = None

    # ------------------------------------------------------------ calibration
    def _calibration(self, resource_spec: ResourceSpec):
        cal = self.config.calibration
        if cal is None:
            return None
        if isinstance(cal, TopologyCalibration):
            return cal
        if cal == "auto":
            kind = ""
            try:
                import jax

                kind = str(jax.devices()[0].device_kind)
            except Exception:  # noqa: BLE001 - planning may run backend-less
                pass
            return TopologyCalibration.load_for(resource_spec, kind)
        raise ValueError(
            f"PlanConfig.calibration must be None, 'auto', or a "
            f"TopologyCalibration; got {cal!r}")

    # ----------------------------------------------------------------- build
    def build(self, model_item: ModelItem,
              resource_spec: ResourceSpec) -> Strategy:
        if self.cache is not None:
            entry = self.cache.get(model_item, resource_spec)
            if entry is not None:
                self.last_result = {
                    "cache_hit": True,
                    "key": entry.key,
                    "n_visited": 0,
                    "provenance": entry.provenance,
                    "path": entry.path,
                }
                return entry.strategy
        calibration = self._calibration(resource_spec)
        result = PlanSearch(
            model_item, resource_spec, self.config.search_config(),
            calibration=calibration,
        ).run()
        self.last_result = {
            "cache_hit": False,
            "key": None,
            "n_visited": result.n_visited,
            "provenance": result.provenance,
        }
        if self.cache is not None:
            try:
                path = self.cache.put(
                    model_item, resource_spec, result.strategy,
                    provenance=result.provenance)
                self.last_result["path"] = path
                self.last_result["key"] = os.path.basename(path)
            except OSError as e:
                # A read-only cache dir must not fail planning.
                logging.warning("plan cache store failed (%s); continuing "
                                "uncached", e)
        return result.strategy
