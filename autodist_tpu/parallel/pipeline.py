"""Pipeline parallelism: GPipe-style microbatching over a mesh "pipe" axis.

TPU-native extension beyond the reference (pipeline parallelism explicitly
absent, SURVEY.md §2.2). Collective-ops formulation: every device holds one
stage's params (a leading-stacked ``[S, ...]`` pytree sharded over the pipe
axis), microbatches stream through the ring with ``lax.ppermute`` carrying
activations stage→stage. Stage s computes microbatch m at tick t = s + m, so
a full run is ``n_micro + S - 1`` ticks with the classic bubble fraction
``(S-1)/(n_micro+S-1)``. Gradients come from autodiff through the scan —
ppermute transposes to the reverse rotation, so backward is the reverse
pipeline, as it should be.

The stage function must be shape-preserving (``[mb, ...] -> [mb, ...]``),
which transformer block stacks are. Embedding/head layers stay outside the
pipelined region (replicated), matching common practice for small stage
counts.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from autodist_tpu import const
from autodist_tpu.utils.compat import shard_map


def pipeline_apply_local(
    stage_params,
    x,
    stage_fn: Callable,
    n_microbatches: int,
    n_stages: int,
    axis_name: str = const.MESH_AXIS_PIPE,
):
    """Run the pipeline on per-device values — call inside ``shard_map``.

    ``stage_params``: this device's stage slice (no leading stage dim);
    ``x``: the full batch, identical on every pipe device; ``n_stages`` must
    be passed statically (the tick count is a trace-time constant).
    """
    return _pipeline_local(
        stage_params, x, stage_fn=stage_fn, n_micro=n_microbatches,
        n_stages=n_stages, axis_name=axis_name,
    )


def _pipeline_local(stage_params, x, *, stage_fn, n_micro, n_stages, axis_name):
    s_idx = lax.axis_index(axis_name)
    b = x.shape[0]
    if b % n_micro != 0:
        raise ValueError(
            f"batch {b} not divisible by n_microbatches {n_micro}"
        )
    mb = b // n_micro
    micro = x.reshape((n_micro, mb) + x.shape[1:])
    perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    state = jnp.zeros_like(micro[0])
    outputs = jnp.zeros_like(micro)

    def tick(carry, t):
        state, outputs = carry
        # Stage 0 injects microbatch t (clamped; masked out when t >= n_micro).
        inj = lax.dynamic_index_in_dim(
            micro, jnp.clip(t, 0, n_micro - 1), axis=0, keepdims=False
        )
        inp = jnp.where(s_idx == 0, inj, state)
        out = stage_fn(stage_params, inp)
        # Last stage owns microbatch t - (S-1) when in range.
        out_idx = t - (n_stages - 1)
        write = (s_idx == n_stages - 1) & (out_idx >= 0)
        outputs = lax.cond(
            write,
            lambda o: lax.dynamic_update_index_in_dim(
                o, out, jnp.clip(out_idx, 0, n_micro - 1), axis=0
            ),
            lambda o: o,
            outputs,
        )
        state = lax.ppermute(out, axis_name, perm_fwd)
        return (state, outputs), None

    (state, outputs), _ = lax.scan(
        tick, (state, outputs), jnp.arange(n_micro + n_stages - 1)
    )
    # Broadcast the last stage's outputs to every pipe device (keeps the
    # wrapper's out_spec replicated over the pipe axis).
    outputs = lax.psum(
        jnp.where(s_idx == n_stages - 1, outputs, jnp.zeros_like(outputs)),
        axis_name,
    )
    return outputs.reshape((b,) + x.shape[1:])


def _pipeline_1f1b_local(stage_fn, n_micro, n_stages, axis_name):
    """Local (per-device) pipeline with a 1F1B-style hand-written backward.

    ``jax.grad`` through the GPipe scan saves every tick's residuals —
    ppermute states plus stage interiors, O(n_micro + S) ticks live at
    once. This variant wraps the forward in ``jax.custom_vjp``: the forward
    additionally records ONLY each microbatch's stage-boundary input
    ([n_micro, mb, ...] per device), and the backward replays the pipeline
    in reverse — cotangents enter at the last stage and ``ppermute``
    stage-to-stage in the reverse rotation while each stage recomputes its
    vjp from the saved boundary input (remat). Tick residuals never
    materialize together, which is the memory shape 1F1B schedules buy;
    values and gradients are identical to the autodiff path.
    """
    S = n_stages
    perm_fwd = [(i, (i + 1) % S) for i in range(S)]
    perm_rev = [(i, (i - 1) % S) for i in range(S)]

    def fwd_impl(params, x):
        s_idx = lax.axis_index(axis_name)
        b = x.shape[0]
        if b % n_micro != 0:
            raise ValueError(
                f"batch {b} not divisible by n_microbatches {n_micro}")
        mb = b // n_micro
        micro = x.reshape((n_micro, mb) + x.shape[1:])
        state = jnp.zeros_like(micro[0])
        outputs = jnp.zeros_like(micro)
        saved = jnp.zeros_like(micro)  # this stage's input per microbatch

        def tick(carry, t):
            state, outputs, saved = carry
            m_f = t - s_idx
            inj = lax.dynamic_index_in_dim(
                micro, jnp.clip(t, 0, n_micro - 1), axis=0, keepdims=False)
            inp = jnp.where(s_idx == 0, inj, state)
            valid_f = (m_f >= 0) & (m_f < n_micro)
            saved = lax.cond(
                valid_f,
                lambda s: lax.dynamic_update_index_in_dim(
                    s, inp, jnp.clip(m_f, 0, n_micro - 1), axis=0),
                lambda s: s,
                saved,
            )
            out = stage_fn(params, inp)
            out_idx = t - (S - 1)
            write = (s_idx == S - 1) & (out_idx >= 0)
            outputs = lax.cond(
                write,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, out, jnp.clip(out_idx, 0, n_micro - 1), axis=0),
                lambda o: o,
                outputs,
            )
            state = lax.ppermute(out, axis_name, perm_fwd)
            return (state, outputs, saved), None

        (state, outputs, saved), _ = lax.scan(
            tick, (state, outputs, saved), jnp.arange(n_micro + S - 1))
        outputs = lax.psum(
            jnp.where(s_idx == S - 1, outputs, jnp.zeros_like(outputs)),
            axis_name,
        )
        return outputs.reshape((b,) + x.shape[1:]), saved

    @jax.custom_vjp
    def f(params, x):
        out, _ = fwd_impl(params, x)
        return out

    def f_fwd(params, x):
        out, saved = fwd_impl(params, x)
        return out, (params, saved)

    def f_bwd(res, g):
        params, saved = res
        s_idx = lax.axis_index(axis_name)
        # shard_map's unchecked-replication (check_vma=False) transpose
        # convention, pinned by tests/test_moe_pipeline.py: a replicated
        # (P()) OUTPUT's cotangent arrives divided by the axis size, and a
        # replicated INPUT's cotangent is psummed across devices. Undo the
        # division here; gx below relies on the psum.
        g = g * n_stages
        # The stage stack is shape-preserving, so g's shape IS x's shape.
        x_shape = g.shape
        mb = x_shape[0] // n_micro
        g_micro = g.reshape((n_micro, mb) + x_shape[1:])
        g_state = jnp.zeros_like(g_micro[0])
        gx_micro = jnp.zeros_like(g_micro)
        grad_acc = jax.tree_util.tree_map(jnp.zeros_like, params)

        def tick(carry, r):
            g_state, gx_micro, grad_acc = carry
            # Reverse pipeline: the LAST stage is reverse-position 0 and
            # injects cotangent microbatch r; stage s handles microbatch
            # m_b = r - (S-1-s), one ppermute hop behind its successor.
            m_b = r - (S - 1 - s_idx)
            inj = lax.dynamic_index_in_dim(
                g_micro, jnp.clip(r, 0, n_micro - 1), axis=0, keepdims=False)
            g_out = jnp.where(s_idx == S - 1, inj, g_state)
            valid_b = (m_b >= 0) & (m_b < n_micro)
            saved_inp = lax.dynamic_index_in_dim(
                saved, jnp.clip(m_b, 0, n_micro - 1), axis=0, keepdims=False)
            _, svjp = jax.vjp(stage_fn, params, saved_inp)
            g_p, g_inp = svjp(g_out)
            grad_acc = jax.tree_util.tree_map(
                lambda a, gg: a + jnp.where(valid_b, gg, 0), grad_acc, g_p)
            gx_micro = lax.cond(
                valid_b & (s_idx == 0),
                lambda o: lax.dynamic_update_index_in_dim(
                    o, g_inp, jnp.clip(m_b, 0, n_micro - 1), axis=0),
                lambda o: o,
                gx_micro,
            )
            g_state = lax.ppermute(g_inp, axis_name, perm_rev)
            return (g_state, gx_micro, grad_acc), None

        (g_state, gx_micro, grad_acc), _ = lax.scan(
            tick, (g_state, gx_micro, grad_acc), jnp.arange(n_micro + S - 1))
        # x is replicated (P()): per-device cotangent returns are psummed by
        # the transpose, so return only this device's true contribution —
        # stage 0 holds it all, everyone else contributes zero.
        gx = jnp.where(
            s_idx == 0, gx_micro, jnp.zeros_like(gx_micro)
        ).reshape(x_shape)
        return grad_acc, gx

    f.defvjp(f_fwd, f_bwd)
    return f


def _resolve_mesh(mesh: Optional[Mesh]) -> Optional[Mesh]:
    if mesh is not None:
        return mesh
    from autodist_tpu.api import get_default_autodist

    ad = get_default_autodist()
    return ad.mesh if ad is not None else None


def _pipe_axis_size(mesh: Optional[Mesh], axis_name: str) -> int:
    if mesh is None:
        return 1
    return dict(zip(mesh.axis_names, mesh.devices.shape)).get(axis_name, 1)


def pipeline_apply(
    stage_fn: Callable,
    stacked_params,
    x,
    n_microbatches: int,
    mesh: Optional[Mesh] = None,
    axis_name: str = const.MESH_AXIS_PIPE,
    remat_stages: bool = False,
    schedule: str = "gpipe",
):
    """Apply a pipelined stage stack to global ``x``.

    ``stacked_params``: pytree whose leaves carry a leading ``[S]`` stage
    dim (stage s's slice feeds ``stage_fn`` at ring position s).
    Falls back to a sequential ``lax.scan`` over stages when the mesh has no
    non-trivial pipe axis — same math, no communication.

    ``remat_stages=True`` wraps each stage in ``jax.checkpoint``: GPipe's
    backward holds every microbatch's stage activations live (the classic
    memory cost vs 1F1B schedules); rematerializing the stage interior
    drops that to boundary activations only, at ~1/3 extra stage FLOPs —
    usually the right trade at large microbatch counts.

    ``schedule``: ``"gpipe"`` (default) differentiates through the forward
    scan; ``"1f1b"`` installs a hand-written reverse-pipeline backward that
    saves only stage-boundary inputs and recomputes stage vjps tick by
    tick (see :func:`_pipeline_1f1b_local`) — same values and gradients,
    smaller peak memory. For the fully interleaved 1F1B loop whose live
    activations stay O(S) independent of the microbatch count (possible
    only when the loss is computed inside the pipelined region), use
    :func:`pipeline_value_and_grad`.
    """
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    if remat_stages:
        # prevent_cse=False: the checkpointed stage only ever runs inside
        # lax.scan bodies (the tick loop / the sequential fallback), where
        # the CSE-prevention barrier is unnecessary overhead.
        stage_fn = jax.checkpoint(stage_fn, prevent_cse=False)
    mesh = _resolve_mesh(mesh)
    n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    axis_size = _pipe_axis_size(mesh, axis_name)
    if axis_size <= 1:
        def body(h, sp):
            return stage_fn(sp, h), None

        out, _ = lax.scan(body, x, stacked_params)
        return out
    if axis_size != n_stages:
        raise ValueError(
            f"stage dim ({n_stages}) must equal mesh axis {axis_name!r} "
            f"size ({axis_size})"
        )

    spec_params = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)
    local_stage = lambda sp, h: stage_fn(  # noqa: E731 - tiny adapter
        jax.tree_util.tree_map(lambda a: a[0], sp), h)
    if schedule == "1f1b":
        local = _pipeline_1f1b_local(
            local_stage, n_microbatches, n_stages, axis_name)
    else:
        local = functools.partial(
            _pipeline_local,
            stage_fn=local_stage,
            n_micro=n_microbatches,
            n_stages=n_stages,
            axis_name=axis_name,
        )
    sm = shard_map(
        local,
        mesh=mesh,
        in_specs=(spec_params, P()),
        out_specs=P(),
        axis_names={axis_name},
        check_vma=False,
    )
    return sm(stacked_params, x)


def _1f1b_interleaved_local(stage_fn, loss_head, n_micro, n_stages, axis_name):
    """The fully interleaved 1F1B loop (per device, inside ``shard_map``).

    One scan whose every tick does a (masked) forward AND a (masked)
    backward: stage ``s`` forwards microbatch ``m`` at tick ``t = s + m``
    and backwards it at ``t = 2(S-1) + m - s`` — the last stage turns a
    microbatch around immediately (its loss cotangent is computed the same
    tick its forward completes), cotangents then ride the reverse rotation.
    A microbatch's boundary input therefore lives ``2(S-1-s)`` ticks, so a
    ring buffer of ``R = 2S-1`` slots bounds live activations at O(S)
    regardless of ``n_micro`` — the property GPipe-style split forward/
    backward cannot have, and the reason the loss must be computed inside
    the pipelined region. Stage interiors are rematerialized in the
    backward (``jax.vjp`` re-runs the stage), the same trade
    ``remat_stages`` makes.

    Returns ``(loss, grads, gx)``: mean-over-microbatches loss, this
    stage's parameter gradients, and the input-cotangent contribution
    (nonzero only on stage 0; shard_map's transpose-style psum assembly is
    done by the caller's ``out_specs``).
    """
    S = n_stages
    R = 2 * S - 1
    perm_fwd = [(i, (i + 1) % S) for i in range(S)]
    perm_rev = [(i, (i - 1) % S) for i in range(S)]

    def run(params, x, tgt):
        s_idx = lax.axis_index(axis_name)
        b = x.shape[0]
        if b % n_micro != 0:
            raise ValueError(
                f"batch {b} not divisible by n_microbatches {n_micro}")
        mb = b // n_micro
        micro = x.reshape((n_micro, mb) + x.shape[1:])
        tgt_micro = (
            None if tgt is None else jax.tree_util.tree_map(
                lambda a: a.reshape((n_micro, mb) + a.shape[1:]), tgt)
        )
        fwd_state = jnp.zeros_like(micro[0])
        bwd_state = jnp.zeros_like(micro[0])
        ring = jnp.zeros((R,) + micro[0].shape, micro.dtype)
        gx_micro = jnp.zeros_like(micro)
        grad_acc = jax.tree_util.tree_map(jnp.zeros_like, params)

        def tick(carry, t):
            fwd_state, bwd_state, ring, gx_micro, grad_acc, loss_acc = carry
            # ---- forward half-tick
            m_f = t - s_idx
            valid_f = (m_f >= 0) & (m_f < n_micro)
            inj = lax.dynamic_index_in_dim(
                micro, jnp.clip(m_f, 0, n_micro - 1), axis=0, keepdims=False)
            inp = jnp.where(s_idx == 0, inj, fwd_state)
            out = stage_fn(params, inp)
            # Always-write is safe: slot t%R was last written R ticks ago
            # and every saved input's lifetime is <= R-1 ticks.
            ring = lax.dynamic_update_index_in_dim(
                ring, inp, jnp.mod(t, R), axis=0)
            # ---- last stage turns the microbatch around: loss + cotangent
            last = s_idx == S - 1
            if tgt_micro is None:
                loss_m, lvjp = jax.vjp(loss_head, out)
            else:
                tgt_mb = jax.tree_util.tree_map(
                    lambda a: lax.dynamic_index_in_dim(
                        a, jnp.clip(m_f, 0, n_micro - 1), axis=0,
                        keepdims=False),
                    tgt_micro,
                )
                loss_m, lvjp = jax.vjp(lambda o: loss_head(o, tgt_mb), out)
            (g_out_self,) = lvjp(jnp.ones_like(loss_m))
            loss_acc = loss_acc + jnp.where(last & valid_f, loss_m, 0.0)
            # ---- backward half-tick
            m_b = t - 2 * (S - 1) + s_idx
            valid_b = (m_b >= 0) & (m_b < n_micro)
            g_out = jnp.where(last, g_out_self, bwd_state)
            slot_b = jnp.mod(t - 2 * (S - 1) + 2 * s_idx, R)
            saved_inp = lax.dynamic_index_in_dim(
                ring, slot_b, axis=0, keepdims=False)
            _, svjp = jax.vjp(stage_fn, params, saved_inp)
            g_p, g_inp = svjp(g_out)
            grad_acc = jax.tree_util.tree_map(
                lambda a, gg: a + jnp.where(valid_b, gg, 0), grad_acc, g_p)
            gx_micro = lax.cond(
                valid_b & (s_idx == 0),
                lambda o: lax.dynamic_update_index_in_dim(
                    o, g_inp, jnp.clip(m_b, 0, n_micro - 1), axis=0),
                lambda o: o,
                gx_micro,
            )
            fwd_state = lax.ppermute(out, axis_name, perm_fwd)
            bwd_state = lax.ppermute(g_inp, axis_name, perm_rev)
            return (fwd_state, bwd_state, ring, gx_micro, grad_acc,
                    loss_acc), None

        carry = (fwd_state, bwd_state, ring, gx_micro, grad_acc,
                 jnp.zeros((), x.dtype))
        carry, _ = lax.scan(
            tick, carry, jnp.arange(n_micro + 2 * (S - 1)))
        _, _, _, gx_micro, grad_acc, loss_acc = carry
        s_idx = lax.axis_index(axis_name)
        loss = lax.psum(
            jnp.where(s_idx == S - 1, loss_acc, 0.0), axis_name) / n_micro
        grads = jax.tree_util.tree_map(lambda g: g / n_micro, grad_acc)
        gx = lax.psum(
            jnp.where(s_idx == 0, gx_micro, jnp.zeros_like(gx_micro)),
            axis_name,
        ).reshape(x.shape) / n_micro
        return loss, grads, gx

    return run


def pipeline_value_and_grad(
    stage_fn: Callable,
    stacked_params,
    x,
    loss_head: Callable,
    n_microbatches: int,
    targets=None,
    mesh: Optional[Mesh] = None,
    axis_name: str = const.MESH_AXIS_PIPE,
):
    """Loss + gradients of a pipelined stage stack in ONE interleaved 1F1B
    loop — live activations O(S) per device, independent of ``n_micro``.

    ``loss_head(out_microbatch[, target_microbatch]) -> scalar`` is the
    per-microbatch MEAN loss computed at the last stage (targets, when
    given, are batched like ``x`` on dim 0 and microbatched alongside it);
    the returned loss/gradients are the mean over microbatches, identical
    to ``loss_head`` over ``pipeline_apply``'s output when microbatches are
    equal-sized. Returns ``(loss, stacked_grads, gx)`` with
    ``stacked_grads`` shaped like ``stacked_params`` and ``gx`` the
    cotangent of ``x`` (for layers below the pipelined region).

    The loss must live inside the pipelined region for true 1F1B: with a
    split forward/backward (``jax.grad`` over :func:`pipeline_apply`), all
    microbatches' residuals necessarily coexist between the phases — see
    ``schedule="1f1b"`` there for that (weaker) memory shape.
    """
    mesh = _resolve_mesh(mesh)
    n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    axis_size = _pipe_axis_size(mesh, axis_name)
    if axis_size <= 1:
        # Sequential fallback: same math via plain autodiff.
        def total_loss(p, xx):
            def body(h, sp):
                return stage_fn(sp, h), None

            out, _ = lax.scan(body, xx, p)
            mb = out.shape[0] // n_microbatches
            outs = out.reshape((n_microbatches, mb) + out.shape[1:])
            if targets is None:
                losses = jax.vmap(loss_head)(outs)
            else:
                tgts = jax.tree_util.tree_map(
                    lambda a: a.reshape((n_microbatches, mb) + a.shape[1:]),
                    targets,
                )
                losses = jax.vmap(loss_head)(outs, tgts)
            return jnp.mean(losses)

        (loss, (grads, gx)) = (
            jax.value_and_grad(total_loss, argnums=(0, 1))(stacked_params, x)
        )
        return loss, grads, gx
    if axis_size != n_stages:
        raise ValueError(
            f"stage dim ({n_stages}) must equal mesh axis {axis_name!r} "
            f"size ({axis_size})"
        )

    spec_params = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)
    local_stage = lambda sp, h: stage_fn(  # noqa: E731 - tiny adapter
        jax.tree_util.tree_map(lambda a: a[0], sp), h)
    local = _1f1b_interleaved_local(
        local_stage, loss_head, n_microbatches, n_stages, axis_name)
    tgt_spec = (
        None if targets is None
        else jax.tree_util.tree_map(lambda _: P(), targets)
    )
    sm = shard_map(
        local,
        mesh=mesh,
        in_specs=(spec_params, P(), tgt_spec),
        out_specs=(P(), spec_params, P()),
        axis_names={axis_name},
        check_vma=False,
    )
    return sm(stacked_params, x, targets)


# ----------------------------------------------------------------- train step
class PipelineTrainStep:
    """``DistributedTrainStep``-shaped surface over a pipelined stage stack.

    Makes pipeline parallelism first-class in the user API
    (``AutoDist.build_pipeline``) instead of a raw library call: the same
    ``init / __call__ / run / evaluate`` contract the strategy-compiled
    step exposes, backed by :func:`pipeline_value_and_grad` (interleaved
    1F1B, O(S) live activations) with the stage stack sharded over the
    ``pipe`` axis and the batch over the data axis (GSPMD composes the two
    — the pipelined region is partial-manual over ``pipe`` only).

    ``batch`` is ``(x, targets)`` (``targets=None`` for self-supervised
    ``loss_head``\\s). Embedding/head layers stay outside the pipelined
    region by design (module docstring); fold them into ``stage_fn`` s=0 /
    s=S-1 branches or keep them replicated in ``stacked_params``-adjacent
    state of your own.
    """

    def __init__(
        self,
        stage_fn: Callable,
        loss_head: Callable,
        tx,
        n_microbatches: int,
        mesh: Optional[Mesh] = None,
        axis_name: str = const.MESH_AXIS_PIPE,
        donate_state: bool = True,
    ):
        self.stage_fn = stage_fn
        self.loss_head = loss_head
        self.tx = tx
        self.n_microbatches = n_microbatches
        self.mesh = _resolve_mesh(mesh)
        self.axis_name = axis_name
        self._donate = donate_state
        self._compiled = {}

    # ----------------------------------------------------------- shardings
    def _stage_spec(self, leaf) -> P:
        rank = getattr(leaf, "ndim", 0)
        if rank == 0:
            return P()
        return P(self.axis_name, *([None] * (rank - 1)))

    def _state_shardings(self, state):
        from jax.sharding import NamedSharding

        # Params are stacked [S, ...] and always stage-sharded. Optimizer
        # slots are stage-sharded when their FULL shape mirrors some param
        # leaf's (Adam m/v etc.) or a single-axis reduction of one
        # (factored second-moment row/col stats, adafactor-style: param
        # [S, d1, d2] -> stats [S, d1] / [S, d2]); anything else — scalar
        # counters, schedule states, custom hyperparameter vectors even of
        # coincidental length S — stays replicated. (Matching on shape[0]
        # alone would silently pipe-shard such a vector; reduced matches
        # stay rank>=2 so a [S] vector never matches a factored stat.)
        param_shapes = {
            tuple(leaf.shape) for leaf in jax.tree_util.tree_leaves(state.params)
        }
        slot_shapes = set(param_shapes)
        for shape in param_shapes:
            dims = shape[1:]
            for i in range(len(dims)):
                reduced = shape[:1] + dims[:i] + dims[i + 1:]
                if len(reduced) >= 2:
                    slot_shapes.add(reduced)

        def param_spec(leaf):
            return NamedSharding(self.mesh, self._stage_spec(leaf))

        def slot_spec(leaf):
            if getattr(leaf, "ndim", 0) >= 1 and tuple(leaf.shape) in slot_shapes:
                return NamedSharding(self.mesh, self._stage_spec(leaf))
            return NamedSharding(self.mesh, P())

        def replicated(leaf):
            return NamedSharding(self.mesh, P())

        # Every OTHER TrainState field (step, comp_state, stale_state, and
        # anything future) maps to replicated — leaving a field holding raw
        # values inside the shardings pytree would crash device_put the
        # moment that field carries leaves.
        import dataclasses

        others = {
            f.name: jax.tree.map(replicated, getattr(state, f.name))
            for f in dataclasses.fields(state)
            if f.name not in ("params", "opt_state")
        }
        return state.replace(
            params=jax.tree.map(param_spec, state.params),
            opt_state=jax.tree.map(slot_spec, state.opt_state),
            **others,
        )

    # ----------------------------------------------------------------- api
    def init(self, stacked_params):
        from autodist_tpu.kernel.lowering import TrainState

        state = TrainState(
            step=jnp.zeros((), jnp.int32),
            params=stacked_params,
            opt_state=self.tx.init(stacked_params),
        )
        return jax.device_put(state, self._state_shardings(state))

    def _update(self, state, batch):
        x, targets = batch
        loss, grads, _ = pipeline_value_and_grad(
            self.stage_fn, state.params, x, self.loss_head,
            n_microbatches=self.n_microbatches, targets=targets,
            mesh=self.mesh, axis_name=self.axis_name,
        )
        updates, opt_state = self.tx.update(grads, state.opt_state, state.params)
        import optax

        params = optax.apply_updates(state.params, updates)
        return state.replace(
            step=state.step + 1, params=params, opt_state=opt_state
        ), {"loss": loss}

    def _get(self, key, build):
        fn = self._compiled.get(key)
        if fn is None:
            fn = self._compiled[key] = build()
        return fn

    def __call__(self, state, batch):
        fn = self._get(("step",), lambda: jax.jit(
            self._update,
            donate_argnums=(0,) if self._donate else (),
        ))
        return fn(state, batch)

    def run(self, state, batch, n_steps: int):
        """``n_steps`` on ONE batch in a single device program (scan window
        — same hot-loop contract as ``DistributedTrainStep.run``)."""

        def build():
            def multi(st, b):
                def body(c, _):
                    c, m = self._update(c, b)
                    return c, m

                return lax.scan(body, st, None, length=n_steps)

            return jax.jit(
                multi, donate_argnums=(0,) if self._donate else ())

        return self._get(("run", int(n_steps)), build)(state, batch)

    def evaluate(self, state, batch):
        """Mean microbatched loss, no gradients or state mutation."""

        def build():
            def ev(params, b):
                x, targets = b
                out = pipeline_apply(
                    self.stage_fn, params, x, self.n_microbatches,
                    mesh=self.mesh, axis_name=self.axis_name,
                )
                mb = out.shape[0] // self.n_microbatches
                outs = out.reshape((self.n_microbatches, mb) + out.shape[1:])
                if targets is None:
                    losses = jax.vmap(self.loss_head)(outs)
                else:
                    tgts = jax.tree.map(
                        lambda t: t.reshape((self.n_microbatches, mb) + t.shape[1:]),
                        targets)
                    losses = jax.vmap(self.loss_head)(outs, tgts)
                return {"loss": jnp.mean(losses)}

            return jax.jit(ev)

        return self._get(("eval",), build)(state.params, batch)
