"""Pipeline parallelism: GPipe-style microbatching over a mesh "pipe" axis.

TPU-native extension beyond the reference (pipeline parallelism explicitly
absent, SURVEY.md §2.2). Collective-ops formulation: every device holds one
stage's params (a leading-stacked ``[S, ...]`` pytree sharded over the pipe
axis), microbatches stream through the ring with ``lax.ppermute`` carrying
activations stage→stage. Stage s computes microbatch m at tick t = s + m, so
a full run is ``n_micro + S - 1`` ticks with the classic bubble fraction
``(S-1)/(n_micro+S-1)``. Gradients come from autodiff through the scan —
ppermute transposes to the reverse rotation, so backward is the reverse
pipeline, as it should be.

The stage function must be shape-preserving (``[mb, ...] -> [mb, ...]``),
which transformer block stacks are. Embedding/head layers stay outside the
pipelined region (replicated), matching common practice for small stage
counts.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from autodist_tpu import const


def pipeline_apply_local(
    stage_params,
    x,
    stage_fn: Callable,
    n_microbatches: int,
    n_stages: int,
    axis_name: str = const.MESH_AXIS_PIPE,
):
    """Run the pipeline on per-device values — call inside ``shard_map``.

    ``stage_params``: this device's stage slice (no leading stage dim);
    ``x``: the full batch, identical on every pipe device; ``n_stages`` must
    be passed statically (the tick count is a trace-time constant).
    """
    return _pipeline_local(
        stage_params, x, stage_fn=stage_fn, n_micro=n_microbatches,
        n_stages=n_stages, axis_name=axis_name,
    )


def _pipeline_local(stage_params, x, *, stage_fn, n_micro, n_stages, axis_name):
    s_idx = lax.axis_index(axis_name)
    b = x.shape[0]
    if b % n_micro != 0:
        raise ValueError(
            f"batch {b} not divisible by n_microbatches {n_micro}"
        )
    mb = b // n_micro
    micro = x.reshape((n_micro, mb) + x.shape[1:])
    perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    state = jnp.zeros_like(micro[0])
    outputs = jnp.zeros_like(micro)

    def tick(carry, t):
        state, outputs = carry
        # Stage 0 injects microbatch t (clamped; masked out when t >= n_micro).
        inj = lax.dynamic_index_in_dim(
            micro, jnp.clip(t, 0, n_micro - 1), axis=0, keepdims=False
        )
        inp = jnp.where(s_idx == 0, inj, state)
        out = stage_fn(stage_params, inp)
        # Last stage owns microbatch t - (S-1) when in range.
        out_idx = t - (n_stages - 1)
        write = (s_idx == n_stages - 1) & (out_idx >= 0)
        outputs = lax.cond(
            write,
            lambda o: lax.dynamic_update_index_in_dim(
                o, out, jnp.clip(out_idx, 0, n_micro - 1), axis=0
            ),
            lambda o: o,
            outputs,
        )
        state = lax.ppermute(out, axis_name, perm_fwd)
        return (state, outputs), None

    (state, outputs), _ = lax.scan(
        tick, (state, outputs), jnp.arange(n_micro + n_stages - 1)
    )
    # Broadcast the last stage's outputs to every pipe device (keeps the
    # wrapper's out_spec replicated over the pipe axis).
    outputs = lax.psum(
        jnp.where(s_idx == n_stages - 1, outputs, jnp.zeros_like(outputs)),
        axis_name,
    )
    return outputs.reshape((b,) + x.shape[1:])


def pipeline_apply(
    stage_fn: Callable,
    stacked_params,
    x,
    n_microbatches: int,
    mesh: Optional[Mesh] = None,
    axis_name: str = const.MESH_AXIS_PIPE,
    remat_stages: bool = False,
):
    """Apply a pipelined stage stack to global ``x``.

    ``stacked_params``: pytree whose leaves carry a leading ``[S]`` stage
    dim (stage s's slice feeds ``stage_fn`` at ring position s).
    Falls back to a sequential ``lax.scan`` over stages when the mesh has no
    non-trivial pipe axis — same math, no communication.

    ``remat_stages=True`` wraps each stage in ``jax.checkpoint``: GPipe's
    backward holds every microbatch's stage activations live (the classic
    memory cost vs 1F1B schedules); rematerializing the stage interior
    drops that to boundary activations only, at ~1/3 extra stage FLOPs —
    usually the right trade at large microbatch counts.
    """
    if remat_stages:
        # prevent_cse=False: the checkpointed stage only ever runs inside
        # lax.scan bodies (the tick loop / the sequential fallback), where
        # the CSE-prevention barrier is unnecessary overhead.
        stage_fn = jax.checkpoint(stage_fn, prevent_cse=False)
    if mesh is None:
        from autodist_tpu.api import get_default_autodist

        ad = get_default_autodist()
        mesh = ad.mesh if ad is not None else None
    n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    axis_size = (
        dict(zip(mesh.axis_names, mesh.devices.shape)).get(axis_name, 1)
        if mesh is not None else 1
    )
    if axis_size <= 1:
        def body(h, sp):
            return stage_fn(sp, h), None

        out, _ = lax.scan(body, x, stacked_params)
        return out
    if axis_size != n_stages:
        raise ValueError(
            f"stage dim ({n_stages}) must equal mesh axis {axis_name!r} "
            f"size ({axis_size})"
        )

    spec_params = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)
    local = functools.partial(
        _pipeline_local,
        stage_fn=lambda sp, h: stage_fn(
            jax.tree_util.tree_map(lambda a: a[0], sp), h
        ),
        n_micro=n_microbatches,
        n_stages=n_stages,
        axis_name=axis_name,
    )
    sm = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(spec_params, P()),
        out_specs=P(),
        axis_names={axis_name},
        check_vma=False,
    )
    return sm(stacked_params, x)
