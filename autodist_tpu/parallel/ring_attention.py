"""Ring attention + Ulysses all-to-all sequence parallelism.

Long-context attention over a mesh "seq" axis — capability the reference
lacks entirely (SURVEY.md §5: no sequence-dim logic anywhere in
``/root/reference/autodist/``), built TPU-native:

- **Ring attention** (Liu et al., arXiv 2310.01889): Q stays put, K/V chunks
  rotate around the ICI ring via ``lax.ppermute``; each step merges a chunk's
  attention into fp32 online-softmax accumulators, so no device ever holds
  more than ``seq/n`` of K/V and the logits matrix never materializes beyond
  ``[chunk, chunk]``. Gradients come from autodiff through the
  (rematerialized) scan — ``ppermute``'s transpose is the reverse rotation,
  so the backward pass is itself a ring.
- **Ulysses** (DeepSpeed-Ulysses, arXiv 2309.14509): two ``lax.all_to_all``
  collectives re-shard [B, seq/n, H, D] → [B, seq, H/n, D] so each device
  runs ordinary full-sequence flash attention on a head subset. Cheaper
  collectives than the ring on all-to-all-friendly topologies; requires
  ``heads % n == 0``.

Both come in two forms: ``*_local`` for use inside an existing
``shard_map`` (axis already manual), and a global-array wrapper that opens a
partial-manual ``shard_map`` over just the seq axis (other mesh axes stay
under GSPMD auto, so data/model sharding composes).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from autodist_tpu import const

_NEG_INF = -1e30


# ----------------------------------------------------------------- ring core
def _chunk_merge(q, k, v, q_off, k_off, causal, scale, m, l, acc):
    """Merge one K/V chunk into online-softmax stats.

    q: [b, cq, h, d]; k, v: [b, ck, h, d]; m, l: [b, h, cq, 1];
    acc: [b, h, cq, d] (fp32). Offsets are global sequence positions of the
    chunks (traced values — the k offset depends on ring step).
    """
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        cq, ck = q.shape[1], k.shape[1]
        rows = q_off + lax.broadcasted_iota(jnp.int32, (cq, ck), 0)
        cols = k_off + lax.broadcasted_iota(jnp.int32, (cq, ck), 1)
        s = jnp.where((rows >= cols)[None, None], s, _NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l_new = alpha * l + p.sum(axis=-1, keepdims=True)
    acc_new = acc * alpha + jnp.einsum(
        "bhqk,bkhd->bhqd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, acc_new


def ring_attention_local(
    q, k, v, causal: bool = False, axis_name: str = const.MESH_AXIS_SEQ
):
    """Ring attention on per-device chunks — call inside ``shard_map``.

    q, k, v: [batch, seq_local, heads, head_dim], the ``axis_name`` shard of
    the global sequence. Returns the local output chunk, same shape as q.
    """
    n = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    b, c, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    perm = [(i, (i + 1) % n) for i in range(n)]

    m0 = jnp.full((b, h, c, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, c, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, c, d), jnp.float32)
    q_off = r * c

    def attend_step(t, k_t, v_t, m, l, acc):
        kv_idx = (r - t) % n
        k_off = kv_idx * c

        def attend(args):
            m, l, acc = args
            return _chunk_merge(q, k_t, v_t, q_off, k_off, causal, scale, m, l, acc)

        if causal:
            # Chunks strictly above the causal diagonal contribute nothing;
            # skip their matmuls at runtime (the ring still rotates).
            return lax.cond(kv_idx <= r, attend, lambda args: args, (m, l, acc))
        return attend((m, l, acc))

    # prevent_cse=False: this body runs only inside lax.scan, where the
    # CSE barrier is unnecessary overhead (same note in pipeline.py).
    @functools.partial(jax.checkpoint, prevent_cse=False)
    def step(carry, t):
        k_t, v_t, m, l, acc = carry
        m, l, acc = attend_step(t, k_t, v_t, m, l, acc)
        # Rotate K/V to the next device; after the loop every chunk has
        # visited every device.
        k_t, v_t = jax.tree.map(
            lambda x: lax.ppermute(x, axis_name, perm), (k_t, v_t)
        )
        return (k_t, v_t, m, l, acc), None

    # Scan the first n-1 steps (each ends in a rotation), then merge the
    # final chunk without rotating — the last ppermute would only restore
    # the initial layout, a pure waste of ICI bandwidth fwd and bwd.
    (k_t, v_t, m, l, acc), _ = lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n - 1)
    )
    m, l, acc = attend_step(n - 1, k_t, v_t, m, l, acc)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = (acc / l_safe).astype(q.dtype)          # [b, h, c, d]
    return jnp.transpose(out, (0, 2, 1, 3))       # [b, c, h, d]


# -------------------------------------------------------------- ulysses core
def ulysses_attention_local(
    q, k, v, causal: bool = False, axis_name: str = const.MESH_AXIS_SEQ
):
    """All-to-all sequence parallelism — call inside ``shard_map``.

    Re-shards [b, seq/n, h, d] → [b, seq, h/n, d], runs full-sequence flash
    attention on the head subset, re-shards back.
    """
    from autodist_tpu.ops.flash_attention import flash_attention

    n = lax.psum(1, axis_name)
    h = q.shape[2]
    if h % n != 0:
        raise ValueError(
            f"ulysses attention needs heads ({h}) divisible by the seq-axis "
            f"size ({n}); use ring attention for this shape"
        )

    def seq_to_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qf, kf, vf = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    o = flash_attention(qf, kf, vf, causal=causal)
    return heads_to_seq(o)


# ------------------------------------------------------------------ wrappers
def _seq_sharded(fn_local, q, k, v, causal, mesh, axis_name):
    if mesh is None:
        mesh = _default_mesh()
    axis_size = dict(zip(mesh.axis_names, mesh.devices.shape)).get(axis_name, 1)
    if axis_size <= 1:
        # No seq axis on this mesh — plain flash attention.
        from autodist_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal)
    if q.shape[1] % axis_size != 0:
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by mesh axis "
            f"{axis_name!r}={axis_size}"
        )
    spec = P(None, axis_name, None, None)
    from autodist_tpu.utils.compat import shard_map

    sm = shard_map(
        functools.partial(fn_local, causal=causal, axis_name=axis_name),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names={axis_name},   # partial-manual: data/model stay GSPMD-auto
        check_vma=False,
    )
    return sm(q, k, v)


def _default_mesh() -> Mesh:
    from autodist_tpu.api import get_default_autodist

    ad = get_default_autodist()
    if ad is None:
        raise ValueError(
            "ring/ulysses attention needs a mesh: pass mesh= or construct "
            "an AutoDist first"
        )
    return ad.mesh


def ring_attention(
    q, k, v, causal: bool = False,
    mesh: Optional[Mesh] = None,
    axis_name: str = const.MESH_AXIS_SEQ,
):
    """Ring attention on global [B, S, H, D] arrays.

    Opens a partial-manual ``shard_map`` over the mesh's seq axis; falls back
    to plain flash attention when that axis is trivial, so models can enable
    it unconditionally.
    """
    return _seq_sharded(ring_attention_local, q, k, v, causal, mesh, axis_name)


def ulysses_attention(
    q, k, v, causal: bool = False,
    mesh: Optional[Mesh] = None,
    axis_name: str = const.MESH_AXIS_SEQ,
):
    """Ulysses (all-to-all) sequence-parallel attention on global arrays."""
    return _seq_sharded(ulysses_attention_local, q, k, v, causal, mesh, axis_name)
