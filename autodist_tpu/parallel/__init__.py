"""Sequence/context parallelism (TPU-native extension).

The reference is data-parallel only (``/root/reference/docs/design/
architecture.rst:49-51``) — long-context support is new capability, designed
TPU-first: ring attention rotates K/V chunks around the ICI ring with
``lax.ppermute`` (communication overlaps the per-chunk attention compute),
and Ulysses-style all-to-all re-shards activations seq→heads so full-sequence
flash attention runs locally (one ``lax.all_to_all`` each way).
"""
from autodist_tpu.parallel.pipeline import (
    PipelineTrainStep,
    pipeline_apply,
    pipeline_apply_local,
    pipeline_value_and_grad,
)
from autodist_tpu.parallel.ring_attention import (
    ring_attention,
    ring_attention_local,
    ulysses_attention,
    ulysses_attention_local,
)

__all__ = [
    "PipelineTrainStep",
    "pipeline_apply",
    "pipeline_apply_local",
    "pipeline_value_and_grad",
    "ring_attention",
    "ring_attention_local",
    "ulysses_attention",
    "ulysses_attention_local",
]
