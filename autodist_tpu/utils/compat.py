"""JAX API-drift bridges.

One place for every "this API moved between the jax versions this package
spans" adapter, so call sites stay written against the CURRENT jax surface
and older toolchains are bridged here instead of each site growing its own
try/except (docs/parity.md § shard_map drift triage).
"""
from __future__ import annotations

from typing import Callable, Optional, Set


def shard_map(
    f: Callable,
    *,
    mesh,
    in_specs,
    out_specs,
    axis_names: Optional[Set[str]] = None,
    check_vma: bool = False,
):
    """``jax.shard_map`` across the experimental→top-level API drift.

    Newer jax exposes ``jax.shard_map(f, mesh, in_specs, out_specs,
    axis_names=..., check_vma=...)`` where ``axis_names`` lists the MANUAL
    axes (partial-manual mode: the rest stay GSPMD-auto). jax 0.4.x ships
    the same machinery as ``jax.experimental.shard_map.shard_map`` with the
    complementary spelling — ``auto=`` lists the AUTO axes and the varying-
    manual-axes check is called ``check_rep``. Call sites here are written
    against the new surface; this shim maps it onto whichever one the
    installed jax provides.
    """
    import jax

    if hasattr(jax, "shard_map"):
        kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=check_vma)
        if axis_names is not None:
            kwargs["axis_names"] = set(axis_names)
        return jax.shard_map(f, **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map

    auto = frozenset()
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=bool(check_vma), auto=auto,
    )
