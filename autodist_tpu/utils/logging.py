"""Logging for autodist_tpu.

Mirrors the reference logger's behavior (``/root/reference/autodist/utils/
logging.py:33-107``): a module-level logger that writes PID-tagged records to
stderr and to a timestamped file under the working dir, with verbosity taken
from ``AUTODIST_MIN_LOG_LEVEL``.
"""
import logging as _logging
import os
import sys
import time

from autodist_tpu.const import DEFAULT_LOG_DIR, ENV

_LOGGER_NAME = "autodist_tpu"
_FMT = "%(asctime)s [pid %(process)d] %(levelname)s %(name)s: %(message)s"


def _build_logger() -> _logging.Logger:
    logger = _logging.getLogger(_LOGGER_NAME)
    if logger.handlers:
        return logger
    level = getattr(_logging, str(ENV.AUTODIST_MIN_LOG_LEVEL.val).upper(), _logging.INFO)
    logger.setLevel(level)
    formatter = _logging.Formatter(_FMT)

    stream = _logging.StreamHandler(sys.stderr)
    stream.setFormatter(formatter)
    logger.addHandler(stream)

    try:
        os.makedirs(DEFAULT_LOG_DIR, exist_ok=True)
        fname = os.path.join(DEFAULT_LOG_DIR, f"log.{time.strftime('%Y%m%d-%H%M%S')}.{os.getpid()}")
        fileh = _logging.FileHandler(fname)
        fileh.setFormatter(formatter)
        logger.addHandler(fileh)
    except OSError:  # read-only fs etc. — stderr logging still works
        pass
    logger.propagate = False
    return logger


_logger = _build_logger()

debug = _logger.debug
info = _logger.info
warning = _logger.warning
error = _logger.error
critical = _logger.critical


def set_verbosity(level: str) -> None:
    """Set the log level by name (DEBUG/INFO/WARNING/ERROR)."""
    _logger.setLevel(getattr(_logging, level.upper()))


def get_logger() -> _logging.Logger:
    return _logger
