"""Pidfile liveness shared by the TPU queue driver and bench.

The axon tunnel is single-occupancy: the queue driver
(examples/benchmark/run_tpu_queue.py) publishes its pid in a lock file,
and bench.py waits on it before touching the tunnel. Both sides MUST
judge liveness identically — drift between two hand-rolled copies either
races the tunnel (false-dead) or stalls for nothing (false-alive) — so
the one rule lives here.
"""
from __future__ import annotations

import os
import time
from typing import Optional


def holder_alive(lock_path: str, cmdline_token: bytes = b"run_tpu_queue",
                 fresh_grace_s: float = 60.0) -> Optional[int]:
    """Who (if anyone) holds the pidfile lock.

    Returns the holder's pid when the file names a live process whose
    cmdline contains ``cmdline_token`` (recycled-pid protection); ``-1``
    when the content is unparseable but the file is younger than
    ``fresh_grace_s`` (a foreign-but-fresh file is treated as live to
    stay safe — the driver's atomic link publish never leaves partial
    content, so this only triggers on third-party files); ``None`` when
    the lock is absent, stale, or held by a dead/unrelated process.

    EPERM from ``kill(pid, 0)`` means the process EXISTS under another
    uid — that counts as alive, not dead.
    """
    try:
        raw = open(lock_path).read().strip()
    except OSError:
        return None
    try:
        pid = int(raw)
    except ValueError:
        try:
            age = time.time() - os.stat(lock_path).st_mtime
        except OSError:
            return None
        return -1 if age < fresh_grace_s else None
    try:
        os.kill(pid, 0)
    except PermissionError:
        pass  # exists, different owner: alive
    except OSError:
        return None
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            if cmdline_token not in f.read():
                return None  # pid recycled by an unrelated process
    except OSError:
        pass  # no /proc: trust the existence signal
    return pid
