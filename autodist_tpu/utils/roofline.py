"""Roofline bounds from the program itself: jaxpr-derived HBM traffic.

docs/performance.md's conv-net ceiling discussion needs a *bound*, not a
vibe: is the measured step time explained by the hardware (MXU FLOPs or
HBM bytes at the measured platform bandwidth), or is there unexplained
overhead? This module derives the two traffic envelopes mechanically
from the training step's jaxpr:

- **Lower bound** (perfect fusion): bytes that MUST move regardless of
  scheduling — operands read once from HBM (params, batch), final
  outputs written once, and every MXU op's (dot/conv) output written +
  read once: XLA fuses elementwise epilogues into the matmul, but the
  matmul result itself still materializes. Everything else (pure
  elementwise/reshape chains) is assumed fused away.
- **Upper bound** (zero fusion): every equation reads its inputs and
  writes its outputs through HBM. No real compiler is this bad; the
  truth lives between the bounds.

With a measured platform bandwidth (examples/benchmark/membw.py) and the
chip's peak FLOPs, the bounds become times:

    t_roofline = max(flops / peak_flops, lower_bytes / measured_bw)

A measured step near t_roofline is AT the hardware ceiling; a large gap
is unexplained overhead worth hunting. ``examples/benchmark/
roofline_report.py`` packages this against the committed artifacts.

Beyond the reference: AutoDist shipped no perf-bound tooling at all.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Set

import jax
import numpy as np

# Equations whose outputs materialize even under aggressive fusion: the
# MXU writes its result to HBM (epilogues fuse in, but the buffer exists),
# and data-movement ops with layout changes generally copy.
_MATERIALIZE_PRIMS = {
    "dot_general",
    "conv_general_dilated",
    "scatter", "scatter-add", "scatter_add",
    "gather",
    "sort",
    "cumsum", "cumlogsumexp", "cummax", "cummin", "cumprod",
    "all_gather", "psum", "all_to_all", "ppermute", "reduce_scatter",
}

# Flop-carrying primitives for the arithmetic side of the roofline.
_FLOP_PRIMS = {"dot_general", "conv_general_dilated"}


def _aval_bytes(aval) -> int:
    try:
        return int(np.prod(aval.shape)) * aval.dtype.itemsize
    except Exception:  # noqa: BLE001 - abstract tokens etc.
        return 0


def _eqn_flops(eqn) -> float:
    """2·macs for dots/convs, from the equation's shapes alone."""
    if eqn.primitive.name == "dot_general":
        lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
        dims = eqn.params["dimension_numbers"]
        (lc, rc), (lb, rb) = dims
        contract = int(np.prod([lhs.shape[i] for i in lc])) or 1
        batch = int(np.prod([lhs.shape[i] for i in lb])) or 1
        m = int(np.prod([s for i, s in enumerate(lhs.shape)
                         if i not in set(lc) | set(lb)])) or 1
        n = int(np.prod([s for i, s in enumerate(rhs.shape)
                         if i not in set(rc) | set(rb)])) or 1
        return 2.0 * batch * m * n * contract
    if eqn.primitive.name == "conv_general_dilated":
        # 2 x out_elems x (kernel spatial x in-channels) macs. EXACT for
        # forward-shaped convs; gradient convs (wgrad expressed as a conv
        # whose "kernel" operand is an activation tensor) over-count with
        # this shape mapping, so whole-model FLOP totals from a jaxpr walk
        # run high on conv nets — prefer a vetted per-example FLOP count
        # (model.flops_per_example) for the arithmetic roofline and treat
        # this as the fallback. The HBM envelopes are unaffected.
        out = eqn.outvars[0].aval
        rhs = eqn.invars[1].aval
        out_elems = int(np.prod(out.shape))
        rhs_elems = int(np.prod(rhs.shape))
        dn = eqn.params["dimension_numbers"]
        out_c = int(rhs.shape[dn.rhs_spec[0]]) if hasattr(dn, "rhs_spec") \
            else int(rhs.shape[-1])
        return 2.0 * out_elems * (rhs_elems / max(out_c, 1))
    return 0.0


def _walk(jaxpr, seen_sub: Set[int], acc: Dict[str, float],
          program_outs: Set[int]) -> None:
    for eqn in jaxpr.eqns:
        in_bytes = sum(_aval_bytes(v.aval) for v in eqn.invars
                       if hasattr(v, "aval"))
        out_bytes = sum(_aval_bytes(v.aval) for v in eqn.outvars)
        acc["unfused_bytes"] += in_bytes + out_bytes
        acc["flops"] += _eqn_flops(eqn)
        if eqn.primitive.name in _MATERIALIZE_PRIMS:
            # An INTERMEDIATE materialization is written by the producer
            # and read by a consumer (2x). A program output is already
            # priced once in out_bytes — don't double count it.
            inter = sum(_aval_bytes(v.aval) for v in eqn.outvars
                        if id(v) not in program_outs)
            acc["materialized_bytes"] += 2.0 * inter
        for sub in _sub_jaxprs(eqn):
            if id(sub) not in seen_sub:
                seen_sub.add(id(sub))
                _walk(sub, seen_sub, acc, program_outs)


def _sub_jaxprs(eqn):
    out = []
    for v in eqn.params.values():
        if hasattr(v, "jaxpr"):  # ClosedJaxpr
            out.append(v.jaxpr)
        elif hasattr(v, "eqns"):  # raw Jaxpr
            out.append(v)
        elif isinstance(v, (tuple, list)):
            for x in v:
                if hasattr(x, "jaxpr"):
                    out.append(x.jaxpr)
                elif hasattr(x, "eqns"):
                    out.append(x)
    return out


def traffic_bounds(fn: Callable, *example_args: Any) -> Dict[str, float]:
    """HBM traffic + FLOP envelopes for one call of ``fn``.

    Returns bytes/flops for ONE invocation (e.g. pass a full train-step
    function for per-step numbers). Scan bodies are counted once — for a
    windowed ``run`` pass the single-step function instead.
    """
    closed = jax.make_jaxpr(fn)(*example_args)
    acc = {"unfused_bytes": 0.0, "materialized_bytes": 0.0, "flops": 0.0}
    program_outs = {id(v) for v in closed.jaxpr.outvars}
    _walk(closed.jaxpr, set(), acc, program_outs)
    arg_bytes = sum(
        _aval_bytes(v.aval) for v in closed.jaxpr.invars if hasattr(v, "aval"))
    out_bytes = sum(
        _aval_bytes(v.aval) for v in closed.jaxpr.outvars if hasattr(v, "aval"))
    # Lower bound: inputs read once + outputs written once + MXU/data-op
    # materialization points.
    lower = arg_bytes + out_bytes + acc["materialized_bytes"]
    return {
        "flops": acc["flops"],
        "lower_bytes": float(lower),
        "upper_bytes": float(acc["unfused_bytes"]),
        "arg_bytes": float(arg_bytes),
        "out_bytes": float(out_bytes),
    }


def roofline_times(bounds: Dict[str, float], peak_flops: float,
                   bw_bytes_per_s: float) -> Dict[str, float]:
    """Convert envelopes to per-invocation time bounds."""
    t_mxu = bounds["flops"] / peak_flops if peak_flops else float("nan")
    t_hbm_lower = bounds["lower_bytes"] / bw_bytes_per_s
    t_hbm_upper = bounds["upper_bytes"] / bw_bytes_per_s
    return {
        "t_mxu_s": t_mxu,
        "t_hbm_lower_s": t_hbm_lower,
        "t_hbm_upper_s": t_hbm_upper,
        "t_roofline_s": max(t_mxu, t_hbm_lower),
    }
