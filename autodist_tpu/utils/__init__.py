"""Shared small utilities."""
from __future__ import annotations

from typing import Sequence


def is_broadcast_leaf(shape: Sequence[int]) -> bool:
    """The framework-wide broadcast convention for batch leaves, in ONE place.

    A batch leaf whose (global) shape is rank-0 or has leading dim <= 1 is a
    deliberate broadcast leaf — an attention mask, a per-feature constant —
    and is replicated rather than sharded/split/sliced along the batch axis.
    Every site that splits, shards, validates, or assembles a batch
    (``batch_shardings``, ``global_batch_from_local``, microbatch splitting,
    the fleet-tune feed contract) must use this predicate so the convention
    cannot drift between call sites.

    Note the contract is about GLOBAL shapes. A per-process *local* slice of
    a genuinely batched leaf can also have leading dim 1 (global batch ==
    process count); callers holding only local shapes must disambiguate
    explicitly (see ``global_batch_from_local``'s ``broadcast`` parameter).
    """
    shape = tuple(shape)
    return len(shape) == 0 or shape[0] <= 1
