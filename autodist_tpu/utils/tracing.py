"""jax.profiler wrappers + compile-artifact dumps (partly a compat shim).

.. note:: The unified observability subsystem lives in
   :mod:`autodist_tpu.obs` now (docs/observability.md carries the span
   model, the reference citations that used to live here, and the export
   formats). This module keeps two things:

   - the ``jax.profiler`` device-timeline wrappers (:func:`trace`,
     :func:`annotate`) and the per-compile HLO dumps (:func:`dump_hlo`,
     :func:`dump_compiled`) — xplane/TensorBoard tooling, distinct from
     the host-side span tracer in ``obs.spans``;
   - a **compat shim** for :class:`StepTimer`, which moved to
     :mod:`autodist_tpu.obs.profiler` — import it from there in new code.
"""
from __future__ import annotations

import contextlib
import os
import time
from typing import List, Optional

from autodist_tpu import const
from autodist_tpu.const import ENV
# Compat shim: StepTimer's home is the obs subsystem now; this re-export
# keeps the historical `utils.tracing.StepTimer` path working.
from autodist_tpu.obs.profiler import StepTimer  # noqa: F401
from autodist_tpu.utils import logging


# ------------------------------------------------------------------- tracing
@contextlib.contextmanager
def trace(name: str = "trace", trace_dir: Optional[str] = None):
    """Profile everything inside the block; writes a TensorBoard trace.

    Creates ``trace_dir`` (including parents) when missing and yields the
    resolved path, so callers — ``train.py --profile-dir``, the
    measured-wire capture (``obs/attrib.py``) — get the directory the
    device profile actually landed in regardless of whether they named
    one.

    Usage::

        with tracing.trace("step-100") as td:
            state, metrics = step(state, batch)
            jax.block_until_ready(state.params)
        # td -> parse with obs attrib / profile_ops.py --parse
    """
    import jax

    trace_dir = trace_dir or os.path.join(
        const.DEFAULT_TRACE_DIR, f"{name}-{int(time.time())}"
    )
    os.makedirs(trace_dir, exist_ok=True)
    logging.info("profiler trace -> %s", trace_dir)
    with jax.profiler.trace(trace_dir):
        yield trace_dir


def annotate(name: str):
    """Named region inside a trace (`jax.profiler.TraceAnnotation`)."""
    import jax

    return jax.profiler.TraceAnnotation(name)


# ------------------------------------------------------------------ HLO dump
def dump_hlo(tag: str, stage: str, text: str, hlo_dir: Optional[str] = None) -> str:
    """Write one compile-stage artifact (visualization_util.log_graph analog).

    Stages mirror the reference's numbered snapshots ("0-original",
    "1-after-partition", ...): we use "0-stablehlo" (lowered, pre-XLA) and
    "1-optimized" (post-XLA-passes, what actually runs).
    """
    d = hlo_dir or ENV.SYS_DATA_PATH.val or const.DEFAULT_HLO_DIR
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"{tag}-{stage}.txt")
    with open(path, "w", encoding="utf-8") as f:
        f.write(text)
    logging.debug("dumped HLO %s/%s (%d bytes)", tag, stage, len(text))
    return path


def dump_compiled(tag: str, lowered, compiled=None, hlo_dir: Optional[str] = None) -> List[str]:
    """Dump a jax ``Lowered`` (and optionally ``Compiled``) pair."""
    paths = [dump_hlo(tag, "0-stablehlo", lowered.as_text(), hlo_dir)]
    if compiled is not None:
        try:
            paths.append(dump_hlo(tag, "1-optimized", compiled.as_text(), hlo_dir))
        except Exception as e:  # noqa: BLE001 - optimized text is best-effort
            logging.debug("optimized HLO unavailable: %s", e)
    return paths


