"""Tracing / profiling / compile-artifact dumps.

TPU-native analog of the reference's observability hooks:

- chrome-trace timeline per traced ``session.run``
  (``/root/reference/autodist/runner.py:64-75,123-131``) → ``trace()``
  context manager around ``jax.profiler`` writing TensorBoard-loadable
  traces (the TPU profile includes the real xplane timeline: device compute,
  ICI collectives, host transfers).
- per-stage graph snapshots to TensorBoard
  (``utils/visualization_util.py:24-36``, called at each transform stage
  ``graph_transformer.py:62-90``) → ``dump_hlo()`` snapshots of the lowered
  StableHLO / optimized HLO per compile, named by stage.
- step timing: ``StepTimer`` collects wall-times and derives throughput
  percentiles — the role the vendored benchmark loggers played
  (``examples/benchmark/utils/logs/logger.py``).
"""
from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Any, Dict, List, Optional

from autodist_tpu import const
from autodist_tpu.const import ENV
from autodist_tpu.utils import logging


# ------------------------------------------------------------------- tracing
@contextlib.contextmanager
def trace(name: str = "trace", trace_dir: Optional[str] = None):
    """Profile everything inside the block; writes a TensorBoard trace.

    Usage::

        with tracing.trace("step-100"):
            state, metrics = step(state, batch)
            jax.block_until_ready(state.params)
    """
    import jax

    trace_dir = trace_dir or os.path.join(
        const.DEFAULT_TRACE_DIR, f"{name}-{int(time.time())}"
    )
    os.makedirs(trace_dir, exist_ok=True)
    logging.info("profiler trace -> %s", trace_dir)
    with jax.profiler.trace(trace_dir):
        yield trace_dir


def annotate(name: str):
    """Named region inside a trace (`jax.profiler.TraceAnnotation`)."""
    import jax

    return jax.profiler.TraceAnnotation(name)


# ------------------------------------------------------------------ HLO dump
def dump_hlo(tag: str, stage: str, text: str, hlo_dir: Optional[str] = None) -> str:
    """Write one compile-stage artifact (visualization_util.log_graph analog).

    Stages mirror the reference's numbered snapshots ("0-original",
    "1-after-partition", ...): we use "0-stablehlo" (lowered, pre-XLA) and
    "1-optimized" (post-XLA-passes, what actually runs).
    """
    d = hlo_dir or ENV.SYS_DATA_PATH.val or const.DEFAULT_HLO_DIR
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"{tag}-{stage}.txt")
    with open(path, "w", encoding="utf-8") as f:
        f.write(text)
    logging.debug("dumped HLO %s/%s (%d bytes)", tag, stage, len(text))
    return path


def dump_compiled(tag: str, lowered, compiled=None, hlo_dir: Optional[str] = None) -> List[str]:
    """Dump a jax ``Lowered`` (and optionally ``Compiled``) pair."""
    paths = [dump_hlo(tag, "0-stablehlo", lowered.as_text(), hlo_dir)]
    if compiled is not None:
        try:
            paths.append(dump_hlo(tag, "1-optimized", compiled.as_text(), hlo_dir))
        except Exception as e:  # noqa: BLE001 - optimized text is best-effort
            logging.debug("optimized HLO unavailable: %s", e)
    return paths


# ----------------------------------------------------------------- StepTimer
class StepTimer:
    """Wall-clock step timing + throughput summary.

    ``items_per_step`` (e.g. global batch size, or tokens/step) turns times
    into throughput. First ``warmup`` steps are excluded (compile + cache
    effects). Use as a callable context around each step.
    """

    def __init__(self, items_per_step: float = 0.0, warmup: int = 2):
        self.items_per_step = items_per_step
        self.warmup = warmup
        self.times: List[float] = []
        self._t0: Optional[float] = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        assert self._t0 is not None
        self.times.append(time.perf_counter() - self._t0)
        self._t0 = None
        return False

    @property
    def measured(self) -> List[float]:
        return self.times[self.warmup:] if len(self.times) > self.warmup else []

    def summary(self) -> Dict[str, Any]:
        xs = sorted(self.measured)
        if not xs:
            return {"steps": len(self.times), "measured": 0}
        n = len(xs)
        mean = sum(xs) / n
        out = {
            "steps": len(self.times),
            "measured": n,
            "mean_s": mean,
            "p50_s": xs[n // 2],
            "p90_s": xs[min(n - 1, int(n * 0.9))],
            "min_s": xs[0],
        }
        if self.items_per_step:
            out["items_per_sec"] = self.items_per_step / mean
        return out

    def log_summary(self, prefix: str = "steps") -> Dict[str, Any]:
        s = self.summary()
        logging.info("%s: %s", prefix, json.dumps(s, sort_keys=True))
        return s
