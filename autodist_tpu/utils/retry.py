"""Jittered exponential backoff with deadline — the ONE retry/poll home.

Before this module, every seam that needed "try again in a bit" grew its
own loop: the launcher slept a fixed 5s between fleet restarts, the plan
cache hand-rolled a one-shot read retry, the serve batcher and the
strategy-wait path spun on ``time.sleep`` polls. Each re-implementation
picked its own (usually missing) jitter, cap and deadline — exactly the
class of drift the chaos soak harness (:mod:`autodist_tpu.chaos`) exists
to flush out: an unjittered fleet restart-storms in lockstep, an uncapped
poll hangs forever.

Three primitives, adopted across the stack (``tools/check_patterns.py``
rule 6 bans ``time.sleep`` retry/poll loops anywhere else in the package):

- :class:`Backoff` — a stateful jittered-exponential delay generator with
  ``reset()`` (the launcher resets it when the snapshot ring advances, the
  same signal that resets its restart budget).
- :func:`retry_call` — call a function until it succeeds, the attempt
  budget runs out, or the deadline passes. Never retries after success;
  always re-raises the last error when it gives up.
- :func:`wait_until` — bounded condition polling (the one sleep-poll
  loop), returning whether the predicate turned true in time.

Determinism: every random draw comes from the ``rng`` the caller passes
(``random.Random(seed)``); the default is a module-private instance so
production jitter stays uncorrelated across processes while chaos
schedules replay byte-for-byte.
"""
from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type

__all__ = ["Backoff", "RetryError", "RetryPolicy", "retry_call", "wait_until"]

_DEFAULT_RNG = random.Random()


@dataclass(frozen=True)
class RetryPolicy:
    """One retry/backoff shape.

    ``initial_s`` is the first delay's base; each subsequent base is
    multiplied by ``multiplier`` and capped at ``max_s``. Every emitted
    delay is drawn uniformly from ``[base * (1 - jitter), base]`` — jitter
    pulls *early*, never past the cap, so the worst case stays bounded.
    ``max_attempts`` bounds total calls (0 = unbounded by count);
    ``deadline_s`` bounds total elapsed time from the first attempt
    (None = unbounded). Whichever budget runs out first wins.
    """

    initial_s: float = 0.1
    max_s: float = 30.0
    multiplier: float = 2.0
    jitter: float = 0.5
    max_attempts: int = 0
    deadline_s: Optional[float] = None


class RetryError(RuntimeError):
    """Raised by :func:`retry_call` when every attempt failed; the last
    underlying error rides as ``__cause__``."""


class Backoff:
    """Stateful delay generator over a :class:`RetryPolicy`.

    ``next_delay()`` returns the next jittered delay and advances the
    exponential base; ``sleep()`` additionally sleeps it; ``reset()``
    rewinds to the initial base (progress signal — e.g. the launcher's
    snapshot-ring advance). Deterministic given a seeded ``rng``.
    """

    def __init__(
        self,
        policy: RetryPolicy,
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.policy = policy
        self.rng = rng or _DEFAULT_RNG
        self._sleep = sleep
        self._clock = clock
        self.attempts = 0
        self._base = max(0.0, float(policy.initial_s))
        self._started: Optional[float] = None

    def reset(self) -> None:
        """Rewind to the initial base (attempt count and deadline too):
        the caller observed progress, so the next failure is a NEW episode,
        not a continuation of the old one."""
        self.attempts = 0
        self._base = max(0.0, float(self.policy.initial_s))
        self._started = None

    def next_delay(self) -> float:
        """The next jittered delay; advances the exponential base."""
        if self._started is None:
            self._started = self._clock()
        base = min(self._base, float(self.policy.max_s))
        j = min(max(float(self.policy.jitter), 0.0), 1.0)
        delay = base * (1.0 - j * self.rng.random()) if base > 0 else 0.0
        self._base = min(max(self._base, 1e-9) * float(self.policy.multiplier),
                         float(self.policy.max_s))
        self.attempts += 1
        return delay

    def sleep(self) -> float:
        d = self.next_delay()
        if d > 0:
            self._sleep(d)
        return d

    def expired(self) -> bool:
        """True when another attempt would bust a budget (attempts or
        deadline)."""
        p = self.policy
        if p.max_attempts and self.attempts >= p.max_attempts:
            return True
        if p.deadline_s is not None and self._started is not None:
            return self._clock() - self._started >= p.deadline_s
        return False


def retry_call(
    fn: Callable,
    *,
    policy: Optional[RetryPolicy] = None,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    describe: str = "",
    on_retry: Optional[Callable[[BaseException, float, int], None]] = None,
    rng: Optional[random.Random] = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
):
    """Call ``fn()`` until it returns (never retried after success).

    A raised ``retry_on`` error consumes one attempt; when the policy's
    attempt or deadline budget is spent, the final error is re-raised
    wrapped in :class:`RetryError` (cause preserved) so callers can tell
    "gave up after retries" from a first-try failure type. ``on_retry``
    observes each retry as ``(error, upcoming_delay_s, attempt_number)``
    — the place callers hang logging/metrics.
    """
    policy = policy or RetryPolicy()
    backoff = Backoff(policy, rng=rng, sleep=sleep, clock=clock)
    what = describe or getattr(fn, "__name__", "call")
    started = clock()
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 - the retry loop IS the point
            if policy.max_attempts and attempt >= policy.max_attempts:
                raise RetryError(
                    f"{what} failed after {attempt} attempt(s): "
                    f"{type(e).__name__}: {e}") from e
            delay = backoff.next_delay()
            if (policy.deadline_s is not None
                    and clock() + delay - started > policy.deadline_s):
                # Honor the deadline strictly: never start a sleep that
                # would end past it.
                raise RetryError(
                    f"{what} deadline ({policy.deadline_s:.3f}s) reached "
                    f"after {attempt} attempt(s): "
                    f"{type(e).__name__}: {e}") from e
            if on_retry is not None:
                on_retry(e, delay, attempt)
            if delay > 0:
                sleep(delay)


def wait_until(
    predicate: Callable[[], bool],
    timeout_s: float,
    interval_s: float = 0.01,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
) -> bool:
    """Poll ``predicate`` every ``interval_s`` until it returns true or
    ``timeout_s`` elapses; returns the predicate's final verdict. The ONE
    sleep-poll loop (drain/stop waits, strategy-file waits)."""
    deadline = clock() + max(0.0, float(timeout_s))
    while True:
        if predicate():
            return True
        now = clock()
        if now >= deadline:
            return bool(predicate())
        sleep(min(max(interval_s, 0.0), deadline - now))
