"""Observability configuration + the per-process runtime bundle.

``AutoDist(observability=ObsConfig(...))`` constructs an :class:`ObsRuntime`
on ``autodist.obs`` — the same knob-object pattern the ft subsystem uses
(``fault_tolerance=FTConfig(...)`` → ``autodist.ft``). Everything is off by
default and each piece is independent: spans alone, a metrics file alone,
or the full bundle (spans + file exporter + cross-host aggregation).
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

from autodist_tpu import const, metrics as M
from autodist_tpu.const import ENV
from autodist_tpu.obs import recorder as _flight
from autodist_tpu.obs import spans as _spans
from autodist_tpu.obs.aggregate import HostAggregator
from autodist_tpu.obs.exporter import FileExporter
from autodist_tpu.obs.profiler import StepProfiler
from autodist_tpu.obs.sentry import Sentry, SentryConfig

__all__ = ["ObsConfig", "ObsRuntime"]


@dataclass
class ObsConfig:
    """Knobs for spans, metrics export and cross-host aggregation.

    - ``trace_out``: shared directory for chrome-trace span part-files
      (every process flushes at exit; ``obs.spans.stitch`` merges).
      Falls back to ``AUTODIST_TRACE_OUT`` when empty.
    - ``span_capacity``: default tracer ring size (spans, not bytes).
    - ``metrics_path`` / ``metrics_interval_s``: periodic OpenMetrics file
      exporter for headless training ("" disables).
    - ``aggregate``: publish per-host step-time quantiles and sweep the
      fleet's over a file transport rooted at ``aggregate_dir`` (default:
      ``<ft base>/obs`` so one shared dir serves both subsystems).
    - ``straggler_threshold`` / ``escalate_after``: a host whose step-time
      p50 exceeds ``threshold ×`` the fleet median for ``escalate_after``
      consecutive aggregation ticks is escalated to the HealthMonitor's
      SUSPECT state (no-op when no monitor is attached).
    - ``flight`` / ``flight_dir``: the always-on black-box flight recorder
      (docs/observability.md): one compact JSONL record per profiled step
      window plus sparse events, in a crash-safe fsync'd ring under
      ``flight_dir`` (default ``<ft base>/flight``). The recorder is
      installed as the **process default**, so every built-in
      instrumentation point (train step compiles/errors, serve admits,
      snapshots, heartbeat transitions) writes to the same box.
    - ``sentry`` / ``sentry_config``: the online anomaly sentry over that
      stream (``obs/sentry.py``): NaN/Inf loss or grads, loss spikes,
      step-time regressions, HBM creep, stragglers — each a stable
      ``SNT###`` verdict, escalated into the ft HealthMonitor when one is
      attached.
    """

    trace_out: str = ""
    span_capacity: int = 4096
    metrics_path: str = ""
    metrics_interval_s: float = 10.0
    aggregate: bool = False
    aggregate_dir: str = ""
    aggregate_interval_s: float = 5.0
    straggler_threshold: float = 1.5
    escalate_after: int = 3
    flight: bool = True
    flight_dir: str = ""
    sentry: bool = True
    sentry_config: Optional[SentryConfig] = None

    def resolved(self) -> "ObsConfig":
        """Fill env/derived defaults (same pattern as ``FTConfig.resolved``)."""
        out = ObsConfig(**self.__dict__)
        if not out.trace_out:
            out.trace_out = ENV.AUTODIST_TRACE_OUT.val
        base = ENV.AUTODIST_FT_DIR.val or const.DEFAULT_FT_DIR
        if out.aggregate and not out.aggregate_dir:
            out.aggregate_dir = os.path.join(base, "obs")
        if os.environ.get("AUTODIST_NO_FLIGHT") == "1":
            # The operator's opt-out (slow/read-only filesystem) beats the
            # default-on contract AND an explicit ObsConfig — one switch
            # that stops every flight write in the process.
            out.flight = False
        if out.flight and not out.flight_dir:
            out.flight_dir = (ENV.AUTODIST_FLIGHT_DIR.val
                              or _flight.flight_dir(base))
        return out


class ObsRuntime:
    """Started observability components for one process.

    ``tracer`` is always the process-default :class:`~autodist_tpu.obs.spans
    .SpanTracer` (so library instrumentation and user spans land in one
    timeline); ``exporter``/``aggregator`` exist only when configured.
    :meth:`profiler` wraps a built step; :meth:`observe_step` feeds the
    aggregator (no-op without one); :meth:`close` flushes and stops.
    """

    def __init__(self, config: Optional[ObsConfig] = None,
                 registry: Optional[M.MetricsRegistry] = None,
                 monitor=None):
        self.config = (config or ObsConfig()).resolved()
        self.registry = registry or M.registry
        if self.config.trace_out:
            _spans.enable_trace_out(self.config.trace_out)
        self.tracer = _spans.get_tracer()
        if self.config.span_capacity != self.tracer._spans.maxlen:
            self.tracer.set_capacity(self.config.span_capacity)
        self.exporter: Optional[FileExporter] = None
        if self.config.metrics_path:
            self.exporter = FileExporter(
                self.config.metrics_path, registry=self.registry,
                interval_s=self.config.metrics_interval_s).start()
        # Flight recorder + sentry (the black-box pair): the recorder is
        # installed as the process default so library instrumentation
        # points (train-step compiles/errors, serve admits, ft snapshot
        # and heartbeat events) write into the same ring this runtime
        # owns; the sentry watches the per-step stream online.
        self.recorder = None
        if self.config.flight and self.config.flight_dir:
            self.recorder = _flight.enable(self.config.flight_dir)
        self.sentry: Optional[Sentry] = None
        if self.config.sentry:
            self.sentry = Sentry(
                config=self.config.sentry_config, registry=self.registry,
                monitor=monitor, recorder=self.recorder)
        self.aggregator: Optional[HostAggregator] = None
        if self.config.aggregate:
            from autodist_tpu.ft.heartbeat import FileTransport

            self.aggregator = HostAggregator(
                FileTransport(self.config.aggregate_dir),
                process_id=ENV.AUTODIST_PROCESS_ID.val,
                registry=self.registry,
                interval_s=self.config.aggregate_interval_s,
                monitor=monitor,
                straggler_threshold=self.config.straggler_threshold,
                escalate_after=self.config.escalate_after,
            ).start()

    def profiler(self, step, **kwargs) -> StepProfiler:
        """A :class:`StepProfiler` over ``step`` wired into this runtime's
        registry, tracer, flight recorder, and sentry."""
        kwargs.setdefault("registry", self.registry)
        kwargs.setdefault("tracer", self.tracer)
        kwargs.setdefault("recorder", self.recorder)
        kwargs.setdefault("sentry", self.sentry)
        return StepProfiler(step, **kwargs)

    def observe_step(self, seconds: float) -> None:
        if self.aggregator is not None:
            self.aggregator.observe_step(seconds)

    def attach_monitor(self, monitor) -> None:
        """Late-bind a HealthMonitor (ft starts after obs in AutoDist)."""
        if self.aggregator is not None:
            self.aggregator.monitor = monitor
        if self.sentry is not None:
            self.sentry.monitor = monitor

    def close(self) -> None:
        if self.aggregator is not None:
            self.aggregator.stop()
        if self.exporter is not None:
            self.exporter.stop()
        if self.recorder is not None:
            self.recorder.close()  # writes the clean run_end marker
        if self.config.trace_out and self.tracer.spans():
            try:
                self.tracer.flush_part(self.config.trace_out)
            except OSError:
                pass
