"""Lightweight cross-process span tracing → chrome-trace / Perfetto JSON.

The reference wrote a chrome-trace timeline per traced ``session.run``
(``/root/reference/autodist/runner.py:64-75``); this module generalizes
that into a process-wide span tracer any layer can write into — serve
request phases, snapshot writes, tune candidates, profiled step windows —
with one property the per-run timeline lacked: spans from *different
processes of one launch* stitch into a single timeline.

Mechanics:

- :class:`SpanTracer` holds a thread-safe ring buffer of completed spans
  (bounded memory; a long-running server can trace forever). Spans are
  opened with the :meth:`SpanTracer.span` context manager or the
  :func:`traced` decorator, or recorded retroactively with
  :meth:`SpanTracer.add_span` (e.g. queue-wait time measured by the
  batcher after the fact). Timestamps are wall-clock (``time.time`` —
  the only clock comparable across processes on one host fleet to span
  precision); durations come from ``time.perf_counter`` deltas.
- The **trace id** rides the ``AUTODIST_TRACE_ID`` env var: the launcher
  generates one and exports it to every process it starts
  (``runtime/launcher.py``), so launcher → coordinator → worker spans all
  carry the same id. :func:`current_trace_id` generates-and-pins one when
  unset, so single-process runs trace too.
- Export is the chrome-trace JSON object format (``traceEvents`` with
  ``ph: "X"`` complete events, microsecond ``ts``/``dur``) that both
  ``chrome://tracing`` and Perfetto load directly. With
  ``AUTODIST_TRACE_OUT=<dir>`` set, every process flushes its part-file
  into the shared dir at exit; :func:`stitch` merges the parts into ONE
  ``trace-<id>.json`` (the launcher calls it after the fleet exits).

The tracer is dependency-free (no jax import): the launcher — which never
initializes a backend — traces through the same module.
"""
from __future__ import annotations

import atexit
import contextlib
import functools
import json
import os
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from autodist_tpu.const import ENV

__all__ = [
    "Span",
    "SpanTracer",
    "add_span",
    "current_trace_id",
    "enable_trace_out",
    "events_for_request",
    "export",
    "get_tracer",
    "span",
    "stitch",
    "traced",
]

_PART_PREFIX = "obs-part-"


def current_trace_id() -> str:
    """The trace id every span in this process carries.

    Inherited from ``AUTODIST_TRACE_ID`` when the launcher exported one;
    otherwise generated once and pinned into ``os.environ`` so any child
    processes started from here join the same trace.
    """
    tid = ENV.AUTODIST_TRACE_ID.val
    if not tid:
        tid = uuid.uuid4().hex[:16]
        os.environ[ENV.AUTODIST_TRACE_ID.name] = tid
    return tid


@dataclass
class Span:
    """One completed span: wall-clock start, measured duration, identity."""

    name: str
    t_start_s: float                 # wall clock (time.time) at open
    dur_s: float                     # perf_counter-measured duration
    trace_id: str
    process: int                     # AUTODIST_PROCESS_ID (mesh role)
    os_pid: int
    tid: int
    attrs: Dict[str, Any] = field(default_factory=dict)

    def to_event(self) -> Dict[str, Any]:
        """Chrome-trace "X" (complete) event, microsecond units.

        The chrome ``pid`` is the OS pid, not the mesh role: the launcher
        and the chief are both role 0 but must render as separate tracks
        (the role rides in ``args`` and the process_name metadata)."""
        return {
            "name": self.name,
            "ph": "X",
            "ts": self.t_start_s * 1e6,
            "dur": max(self.dur_s, 0.0) * 1e6,
            "pid": self.os_pid,
            "tid": self.tid,
            "args": {**self.attrs, "trace_id": self.trace_id,
                     "process": self.process},
        }


class SpanTracer:
    """Thread-safe bounded span buffer with chrome-trace export."""

    def __init__(self, capacity: int = 4096, trace_id: Optional[str] = None,
                 process: Optional[int] = None):
        self._spans: deque = deque(maxlen=max(1, int(capacity)))
        self._lock = threading.Lock()
        self._trace_id = trace_id
        self._process = process
        self._dropped = 0

    @property
    def trace_id(self) -> str:
        # Resolved lazily: the launcher may export AUTODIST_TRACE_ID after
        # this module (and the default tracer) was imported.
        if self._trace_id is None:
            self._trace_id = current_trace_id()
        return self._trace_id

    @property
    def process(self) -> int:
        if self._process is None:
            self._process = ENV.AUTODIST_PROCESS_ID.val
        return self._process

    # ------------------------------------------------------------- recording
    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """``with tracer.span("phase", key=val): ...`` — monotonic-clocked,
        recorded on exit (exceptions mark the span ``error: true``)."""
        t_wall = time.time()
        t0 = time.perf_counter()
        try:
            yield self
        except BaseException:
            attrs = {**attrs, "error": True}
            raise
        finally:
            self.add_span(name, t_wall, time.perf_counter() - t0, **attrs)

    def traced(self, name: Optional[str] = None):
        """Decorator form of :meth:`span` (span named after the function)."""

        def deco(fn):
            label = name or getattr(fn, "__qualname__", fn.__name__)

            @functools.wraps(fn)
            def wrapper(*args, **kwargs):
                with self.span(label):
                    return fn(*args, **kwargs)

            return wrapper

        return deco

    def add_span(self, name: str, t_start_s: float, dur_s: float,
                 **attrs) -> Span:
        """Record a span measured elsewhere (retroactive — e.g. queue wait
        computed at admission time). ``t_start_s`` is wall-clock seconds."""
        sp = Span(
            name=name, t_start_s=float(t_start_s), dur_s=float(dur_s),
            trace_id=self.trace_id, process=self.process,
            os_pid=os.getpid(), tid=threading.get_ident() % 1_000_000,
            attrs=attrs,
        )
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self._dropped += 1
            self._spans.append(sp)
        return sp

    # --------------------------------------------------------------- reading
    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    @property
    def dropped(self) -> int:
        """Spans evicted by the ring since construction (capacity pressure)."""
        return self._dropped

    def set_capacity(self, capacity: int) -> None:
        """Resize the ring, keeping the newest spans (``ObsConfig
        .span_capacity`` applies through here)."""
        with self._lock:
            self._spans = deque(self._spans, maxlen=max(1, int(capacity)))

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    # ---------------------------------------------------------------- export
    def to_chrome_trace(self) -> Dict[str, Any]:
        """Chrome-trace JSON object (self-contained, loadable as-is)."""
        events: List[Dict[str, Any]] = [
            {
                "name": "process_name", "ph": "M", "pid": os.getpid(),
                "args": {"name": f"autodist role {self.process} "
                                 f"(os pid {os.getpid()})"},
            }
        ]
        events.extend(sp.to_event() for sp in self.spans())
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"trace_id": self.trace_id},
        }

    def export(self, path: str) -> str:
        """Write the chrome trace to ``path`` (atomic tmp + replace)."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.to_chrome_trace(), f)
        os.replace(tmp, path)
        return path

    def flush_part(self, directory: str) -> str:
        """Write this process's part-file into a shared trace-out dir, named
        so :func:`stitch` can find every part of one trace."""
        name = (f"{_PART_PREFIX}{self.trace_id}"
                f"-r{self.process}-{os.getpid()}.json")
        return self.export(os.path.join(directory, name))


# ----------------------------------------------------------- default tracer
_default_tracer: Optional[SpanTracer] = None
_default_lock = threading.Lock()
_autoflush_installed = False


def get_tracer() -> SpanTracer:
    """The process-default tracer (every built-in instrumentation point
    writes here). First use arms the ``AUTODIST_TRACE_OUT`` at-exit flush
    when that env var names a directory."""
    global _default_tracer, _autoflush_installed
    with _default_lock:
        if _default_tracer is None:
            _default_tracer = SpanTracer()
        if not _autoflush_installed and ENV.AUTODIST_TRACE_OUT.val:
            _autoflush_installed = True
            atexit.register(_flush_at_exit)
    return _default_tracer


def _flush_at_exit() -> None:
    out = ENV.AUTODIST_TRACE_OUT.val
    tracer = _default_tracer
    if not out or tracer is None or not tracer.spans():
        return
    try:
        tracer.flush_part(out)
    except OSError:
        pass  # exit-path best effort: a full disk must not mask the exit code


def enable_trace_out(directory: str) -> None:
    """Programmatic equivalent of ``AUTODIST_TRACE_OUT=<dir>``: this process
    (and children inheriting the env) flush span part-files into ``dir``."""
    os.environ[ENV.AUTODIST_TRACE_OUT.name] = directory
    get_tracer()  # arms the at-exit flush


def span(name: str, **attrs):
    """Module-level convenience over the default tracer."""
    return get_tracer().span(name, **attrs)


def traced(name: Optional[str] = None):
    return get_tracer().traced(name)


def add_span(name: str, t_start_s: float, dur_s: float, **attrs) -> Span:
    return get_tracer().add_span(name, t_start_s, dur_s, **attrs)


def export(path: str) -> str:
    return get_tracer().export(path)


# --------------------------------------------------------- request tracing
def events_for_request(trace: Dict[str, Any], request_id: str,
                       ) -> List[Dict[str, Any]]:
    """Filter a chrome-trace document (``to_chrome_trace()`` output or a
    stitched file's JSON) down to one request's span chain, time-ordered.

    The serving layers tag every request-scoped span with the stable
    string ``request_id`` (router admit/route/failover/delivery, batcher
    queue wait, engine prefill chunks) or, for batched device steps that
    serve many requests at once (the decode step), a ``request_ids``
    list — both match here, so the returned chain is the request's full
    life including a mid-decode failover across replicas."""
    out = []
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        args = ev.get("args", {}) or {}
        if args.get("request_id") == request_id or (
                isinstance(args.get("request_ids"), (list, tuple))
                and request_id in args["request_ids"]):
            out.append(ev)
    out.sort(key=lambda e: float(e.get("ts", 0.0)))
    return out


# ------------------------------------------------------------------- stitch
def stitch(directory: str, trace_id: Optional[str] = None,
           out: Optional[str] = None) -> Optional[str]:
    """Merge every process's part-file for one trace into a single
    chrome-trace JSON; returns the merged path (None when no parts exist).

    ``trace_id=None`` merges the id the most parts carry (a trace-out dir
    normally holds exactly one launch). Part files are left in place —
    they remain individually loadable and a re-stitch stays possible.
    """
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return None
    parts: Dict[str, List[dict]] = {}
    for name in names:
        if not (name.startswith(_PART_PREFIX) and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(directory, name), encoding="utf-8") as f:
                doc = json.load(f)
            tid = doc.get("otherData", {}).get("trace_id", "")
            parts.setdefault(tid, []).append(doc)
        except (OSError, ValueError):
            continue  # torn/foreign file: skip, never fail the stitch
    if trace_id is None and parts:
        trace_id = max(parts, key=lambda t: len(parts[t]))
    docs = parts.get(trace_id or "", [])
    if not docs:
        return None
    events: List[dict] = []
    for doc in docs:
        events.extend(doc.get("traceEvents", []))
    merged = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"trace_id": trace_id, "n_parts": len(docs)},
    }
    out = out or os.path.join(directory, f"trace-{trace_id}.json")
    tmp = f"{out}.tmp-{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(merged, f)
    os.replace(tmp, out)
    return out
