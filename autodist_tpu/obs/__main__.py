"""CLI: ``python -m autodist_tpu.obs --selftest``.

The zero-hardware observability proof, mirroring ``serve --selftest`` so it
can ride the same smoke-check harness: on a CPU mesh it exercises the whole
subsystem — spans (context manager, decorator, retroactive), the
:class:`~autodist_tpu.obs.profiler.StepProfiler` over a real
``AutoDist.build`` step, chrome-trace export, and the OpenMetrics renderer
through BOTH surfaces (string render + file exporter) — and **exits
nonzero on any malformed output**: an unparseable exposition, a chrome
trace Perfetto would reject, or per-step FLOPs that disagree with the
compiled program's own cost analysis.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time


def _provision_cpu_mesh(n_devices: int = 8) -> None:
    """Force an ``n_devices`` CPU host mesh when no backend exists yet
    (the __graft_entry__ recipe); a live backend is used as-is."""
    try:
        from jax._src import xla_bridge

        if xla_bridge._backends:
            return
    except Exception:  # noqa: BLE001 - internal moved: assume initialized
        return
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def selftest(window: int = 4, n_windows: int = 3) -> int:
    """Returns a process exit code; prints ONE JSON line."""
    _provision_cpu_mesh()
    import jax

    from autodist_tpu import metrics as M
    import autodist_tpu.strategy as S
    from autodist_tpu.api import AutoDist
    from autodist_tpu.models import get_model
    from autodist_tpu.obs.exporter import (
        FileExporter, parse_openmetrics, render_openmetrics)
    from autodist_tpu.obs.profiler import StepProfiler
    from autodist_tpu.obs.spans import SpanTracer

    failures = []
    registry = M.MetricsRegistry()
    tracer = SpanTracer(capacity=512)

    # ------------------------------------------------------------- spans
    with tracer.span("selftest.setup", phase="build"):
        model = get_model("mlp", in_dim=16, hidden=(32,), num_classes=4)
        params = model.init(jax.random.PRNGKey(0))
        batch = model.example_batch(8)
        AutoDist.reset_default()
        ad = AutoDist(strategy_builder=S.AllReduce())
        step = ad.build(model.loss_fn, params, batch)
        AutoDist.reset_default()

    @tracer.traced("selftest.decorated")
    def _decorated():
        return 41 + 1

    if _decorated() != 42:
        failures.append("decorator changed the return value")
    tracer.add_span("selftest.retroactive", time.time(), 0.001)

    # ---------------------------------------------------------- profiler
    prof = StepProfiler(step, registry=registry, tracer=tracer)
    state = step.init(params)
    for _ in range(n_windows):
        state, _metrics = prof.run(state, batch, window)
    rep = prof.report()
    if rep["windows"] != n_windows:
        failures.append(f"profiler recorded {rep['windows']} != {n_windows}")
    # Per-step FLOPs must agree with the compiled program's own numbers
    # (the single-step program's cost analysis — see window_cost).
    want = step.window_cost(state, batch, 1)["flops"]
    got = rep.get("flops_per_step", 0.0)
    if want > 0 and abs(got - want) > 1e-6 * want:
        failures.append(f"flops mismatch: profiler {got} vs compiled {want}")
    if want <= 0:
        failures.append("compiled cost analysis returned no flops")

    # -------------------------------------------------------- chrome trace
    tmpdir = tempfile.mkdtemp(prefix="obs-selftest-")
    trace_path = tracer.export(os.path.join(tmpdir, "trace.json"))
    try:
        with open(trace_path, encoding="utf-8") as f:
            doc = json.load(f)
        events = doc["traceEvents"]
        xs = [e for e in events if e.get("ph") == "X"]
        if not xs:
            failures.append("chrome trace has no complete (X) events")
        for e in xs:
            for key in ("name", "ts", "dur", "pid", "tid"):
                if key not in e:
                    failures.append(f"event missing {key!r}: {e}")
                    break
        ids = {e["args"].get("trace_id") for e in xs}
        if len(ids) != 1:
            failures.append(f"events carry {len(ids)} trace ids: {ids}")
        names = {e["name"] for e in xs}
        if "profiler.window" not in names:
            failures.append(f"no profiler.window span in {sorted(names)}")
    except (OSError, ValueError, KeyError) as e:
        failures.append(f"chrome trace unloadable: {e}")

    # --------------------------------------------------------- openmetrics
    snap = registry.snapshot()
    text_render = render_openmetrics(registry, snapshot=snap)
    exporter = FileExporter(os.path.join(tmpdir, "metrics.prom"),
                            registry=registry)
    text_file = exporter.write_once(snapshot=snap)
    if text_render.encode() != text_file.encode():
        failures.append("render and file exporter disagree byte-for-byte")
    try:
        with open(exporter.path, encoding="utf-8") as f:
            on_disk = f.read()
        samples = parse_openmetrics(on_disk)
        if ("obs_profiled_windows_total", "") not in samples:
            failures.append("exposition missing obs_profiled_windows_total")
        if ("obs_step_wall_s_count", "") not in samples:
            failures.append("exposition missing obs_step_wall_s summary")
    except (OSError, ValueError) as e:
        failures.append(f"openmetrics exposition malformed: {e}")

    ok = not failures
    line = {
        "selftest": "autodist_tpu.obs",
        "ok": ok,
        "windows": n_windows,
        "steps_per_window": window,
        "flops_per_step": rep.get("flops_per_step"),
        "dispatch_gap_ms": round(rep.get("dispatch_gap_s", 0.0) * 1e3, 3),
        "step_wall_ms": round(rep.get("step_wall_s", 0.0) * 1e3, 3),
        "compiles": rep.get("compiles", {}).get("count"),
        "trace_events": len(tracer.spans()),
        "openmetrics_bytes": len(text_file),
        "device": jax.devices()[0].platform,
        "n_devices": jax.device_count(),
    }
    if failures:
        line["failures"] = failures
    print(json.dumps(line))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m autodist_tpu.obs",
                                 description=__doc__)
    ap.add_argument("--selftest", action="store_true",
                    help="run the CPU observability proof and exit")
    ap.add_argument("--window", type=int, default=4,
                    help="selftest: steps per profiled window")
    ap.add_argument("--windows", type=int, default=3,
                    help="selftest: profiled windows")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest(window=args.window, n_windows=args.windows)
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
