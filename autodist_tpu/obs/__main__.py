"""CLI: ``python -m autodist_tpu.obs [--selftest | doctor <dir> | attrib]``.

Three entry points:

- ``doctor <ft-base-dir> [--json] [--trace-out DIR]`` — the postmortem:
  stitch a dead run's flight records, heartbeats, snapshot MANIFESTs,
  hang bundles and span part-files into one timeline and classify the
  death (``DOC###`` verdicts, :mod:`autodist_tpu.obs.doctor`). Exit 0 for
  clean, 1 for a classified failure, 3 for unknown. ``bench.py`` invokes
  this on every abnormal exit so a round can never again end
  ``parsed: null`` with no classification.

- ``attrib [--selftest | --parse DIR]`` — measured-wire attribution
  (:mod:`autodist_tpu.obs.attrib`, docs/observability.md § attribution).
  ``--parse`` prints the per-category device-op table of an existing
  trace; ``--selftest`` is the zero-hardware join proof: on a CPU mesh it
  captures a real ``jax.profiler`` trace of the bucketed-zero1 dryrun
  family (family #12's build), joins every measured op back to the plan —
  every promised collective matched, every ``gradsync.bucket_{i}`` scope
  resolved to exactly one bucket with measured time, zero
  unattributed-large rows — verifies seeded mismatches trip
  SLT001/SLT002/SLT003, and proves the trace-fed calibration fits the
  replayed profile tighter than the regression-only fit.

- ``--selftest`` — the zero-hardware observability proof, mirroring
  ``serve --selftest``: on a CPU mesh it exercises the whole subsystem —
  spans (context manager, decorator, retroactive), the
  :class:`~autodist_tpu.obs.profiler.StepProfiler` over a real
  ``AutoDist.build`` step, chrome-trace export, the OpenMetrics renderer
  through BOTH surfaces, PLUS the black-box layer: the flight
  recorder/sentry on a clean profiled loop (zero findings, recorder
  overhead measured <1% per step), every seeded anomaly class tripping
  exactly its ``SNT###`` code, and the doctor classifying seeded
  wedge/NaN/OOM/preemption/straggler bundles correctly — and **exits
  nonzero on any malformed output or misclassification**.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import math
import os
import sys
import tempfile
import time


def _provision_cpu_mesh(n_devices: int = 8) -> None:
    """Force an ``n_devices`` CPU host mesh when no backend exists yet
    (the __graft_entry__ recipe); a live backend is used as-is."""
    try:
        from jax._src import xla_bridge

        if xla_bridge._backends:
            return
    except Exception:  # noqa: BLE001 - internal moved: assume initialized
        return
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def _seeded_sentry_checks(failures: list) -> dict:
    """Every anomaly class trips exactly its intended code; a clean
    synthetic stream trips none. Pure host-side — no device involved."""
    from autodist_tpu import metrics as M
    from autodist_tpu.obs.sentry import CODES, Sentry, SentryConfig

    def fresh():
        return Sentry(config=SentryConfig(min_history=8, hbm_min_history=8),
                      registry=M.MetricsRegistry())

    tripped = {}

    def run_case(name, feed, want_code):
        s = fresh()
        feed(s)
        codes = s.codes()
        tripped[name] = codes
        if codes != [want_code]:
            failures.append(
                f"seeded {name}: expected exactly [{want_code}], got {codes}")

    def clean_feed(s):
        for i in range(64):
            s.observe_step(step=i, loss=2.0 - 0.01 * i, step_time_s=0.1,
                           hbm_bytes=8e9, grad_norm=1.0, update_norm=0.01)

    s = fresh()
    clean_feed(s)
    if s.findings:
        failures.append(f"clean stream tripped {s.codes()} (expected none)")

    run_case("nan_loss", lambda s: [
        s.observe_step(step=i, loss=(float("nan") if i >= 20 else 2.0),
                       step_time_s=0.1) for i in range(24)], "SNT001")
    run_case("nan_grad", lambda s: [
        s.observe_step(step=i, loss=2.0, step_time_s=0.1,
                       grad_norm=(float("inf") if i == 20 else 1.0))
        for i in range(24)], "SNT002")
    run_case("loss_spike", lambda s: [
        s.observe_step(step=i, loss=(50.0 if i == 20 else
                                     2.0 + 0.01 * (i % 3)), step_time_s=0.1)
        for i in range(24)], "SNT003")
    run_case("step_time_regression", lambda s: [
        s.observe_step(step=i, loss=2.0,
                       step_time_s=(0.5 if i >= 16 else 0.1))
        for i in range(24)], "SNT004")
    run_case("hbm_creep", lambda s: [
        s.observe_step(step=i, loss=2.0, step_time_s=0.1,
                       hbm_bytes=8e9 * (1.0 + max(0, i - 8) * 0.02))
        for i in range(24)], "SNT005")
    run_case("straggler", lambda s: [
        s.observe_scores({0: 1.0, 1: 1.02, 2: 2.4}, step=i)
        for i in range(4)], "SNT006")

    unknown = {c for cs in tripped.values() for c in cs} - set(CODES)
    if unknown:
        failures.append(f"sentry emitted undocumented codes: {unknown}")
    return tripped


def _seeded_doctor_checks(failures: list, tmpdir: str) -> dict:
    """Build one synthetic ft base per failure class through the ONE
    writer (the recorder API) and assert the doctor names each correctly."""
    from autodist_tpu import metrics as M
    from autodist_tpu.ft.heartbeat import FileTransport
    from autodist_tpu.obs.doctor import diagnose
    from autodist_tpu.obs.recorder import FlightRecorder, flight_dir
    from autodist_tpu.obs.sentry import Sentry, SentryConfig

    verdicts = {}

    def base(name):
        d = os.path.join(tmpdir, f"doctor-{name}")
        os.makedirs(d, exist_ok=True)
        return d, FlightRecorder(flight_dir(d))

    def steps(rec, n=12, loss0=2.0):
        for i in range(n):
            rec.record_step(steps=1, loss=loss0 - 0.01 * i,
                            step_wall_s=0.1, dispatch_gap_s=0.01)

    # clean: steady records + a run_end marker.
    d, rec = base("clean")
    steps(rec)
    rec.close(ok=True)
    verdicts["clean"] = diagnose(d).verdict

    # nan: the sentry trips SNT001 mid-run; no clean end.
    d, rec = base("nan")
    steps(rec)
    Sentry(config=SentryConfig(), registry=M.MetricsRegistry(),
           recorder=rec).observe_step(step=12, loss=float("nan"))
    verdicts["nan"] = diagnose(d).verdict

    # oom: an error event carrying the allocator's signature.
    d, rec = base("oom")
    steps(rec)
    rec.record_event("error", error="RESOURCE_EXHAUSTED: Out of memory "
                     "allocating 17179869184 bytes in HBM")
    verdicts["oom"] = diagnose(d).verdict

    # preemption: the SIGTERM snapshot hook's event.
    d, rec = base("preemption")
    steps(rec)
    rec.record_event("preempt", step=11, signal="SIGTERM")
    verdicts["preemption"] = diagnose(d).verdict

    # wedge: records + heartbeats just stop — no terminal event at all.
    d, rec = base("wedge")
    steps(rec)
    hb = FileTransport(os.path.join(d, "heartbeats"))
    for pid in range(2):
        hb.publish(pid, {"time": time.time() - 120.0, "step": 11})
    verdicts["wedge"] = diagnose(d).verdict

    # straggler: abnormal end with SNT006 findings on record.
    d, rec = base("straggler")
    steps(rec)
    Sentry(config=SentryConfig(), registry=M.MetricsRegistry(),
           recorder=rec).observe_scores({0: 1.0, 1: 2.6})
    verdicts["straggler"] = diagnose(d).verdict

    for want, got in verdicts.items():
        if got != want:
            failures.append(
                f"doctor misclassified seeded {want} bundle as {got!r}")
    return verdicts


def selftest(window: int = 4, n_windows: int = 3) -> int:
    """Returns a process exit code; prints ONE JSON line."""
    _provision_cpu_mesh()
    import jax

    from autodist_tpu import metrics as M
    import autodist_tpu.strategy as S
    from autodist_tpu.api import AutoDist
    from autodist_tpu.models import get_model
    from autodist_tpu.obs.doctor import diagnose
    from autodist_tpu.obs.exporter import (
        FileExporter, parse_openmetrics, render_openmetrics)
    from autodist_tpu.obs.profiler import StepProfiler
    from autodist_tpu.obs.recorder import FlightRecorder, flight_dir
    from autodist_tpu.obs.sentry import Sentry
    from autodist_tpu.obs.spans import SpanTracer

    failures = []
    registry = M.MetricsRegistry()
    tracer = SpanTracer(capacity=512)

    # ------------------------------------------------------------- spans
    with tracer.span("selftest.setup", phase="build"):
        model = get_model("mlp", in_dim=16, hidden=(32,), num_classes=4)
        params = model.init(jax.random.PRNGKey(0))
        batch = model.example_batch(8)
        AutoDist.reset_default()
        ad = AutoDist(strategy_builder=S.AllReduce())
        step = ad.build(model.loss_fn, params, batch)
        AutoDist.reset_default()

    @tracer.traced("selftest.decorated")
    def _decorated():
        return 41 + 1

    if _decorated() != 42:
        failures.append("decorator changed the return value")
    tracer.add_span("selftest.retroactive", time.time(), 0.001)

    # ------------------------------- profiler + flight recorder + sentry
    # The live clean-run proof: the profiled loop feeds the black box and
    # the sentry, and a healthy run must produce ZERO findings.
    tmpdir = tempfile.mkdtemp(prefix="obs-selftest-")
    ft_base = os.path.join(tmpdir, "ft")
    recorder = FlightRecorder(flight_dir(ft_base))
    sentry = Sentry(registry=registry, recorder=recorder)
    prof = StepProfiler(step, registry=registry, tracer=tracer,
                        recorder=recorder, sentry=sentry)
    state = step.init(params)
    for _ in range(n_windows):
        state, _metrics = prof.run(state, batch, window)
    rep = prof.report()
    if rep["windows"] != n_windows:
        failures.append(f"profiler recorded {rep['windows']} != {n_windows}")
    # Per-step FLOPs must agree with the compiled program's own numbers
    # (the single-step program's cost analysis — see window_cost).
    want = step.window_cost(state, batch, 1)["flops"]
    got = rep.get("flops_per_step", 0.0)
    if want > 0 and abs(got - want) > 1e-6 * want:
        failures.append(f"flops mismatch: profiler {got} vs compiled {want}")
    if want <= 0:
        failures.append("compiled cost analysis returned no flops")
    if sentry.findings:
        failures.append(
            f"clean profiled run tripped sentry codes {sentry.codes()}")

    # Recorder overhead on the dryrun train loop: <1% per step, measured
    # by the recorder's own cost accounting (append_s covers serialize +
    # write + flush + its amortized fsync share) over post-compile
    # windows. One warmup window first: it compiles the wide program AND
    # absorbs the recorder's pending interval-fsync, so the measured loop
    # sees the steady-state discipline.
    over_prof = StepProfiler(step, registry=M.MetricsRegistry(),
                             tracer=SpanTracer(capacity=64),
                             recorder=recorder, sentry=None)
    state, _ = over_prof.run(state, batch, 256)
    s0 = recorder.stats()
    t0 = time.perf_counter()
    for _ in range(3):
        state, _ = over_prof.run(state, batch, 256)
    loop_wall = time.perf_counter() - t0
    s1 = recorder.stats()
    overhead = (s1["append_s"] - s0["append_s"]) / max(loop_wall, 1e-9)
    if not math.isfinite(overhead) or overhead >= 0.01:
        failures.append(
            f"recorder overhead {overhead * 100:.3f}% >= 1% of the dryrun "
            f"train loop")
    recorder.close(ok=True)
    clean_diag = diagnose(ft_base)
    if clean_diag.verdict != "clean":
        failures.append(
            f"doctor called the live clean run {clean_diag.verdict!r}")

    # ------------------------------------------- seeded anomalies + doctor
    sentry_cases = _seeded_sentry_checks(failures)
    doctor_cases = _seeded_doctor_checks(failures, tmpdir)

    # -------------------------------------------------------- chrome trace
    trace_path = tracer.export(os.path.join(tmpdir, "trace.json"))
    try:
        with open(trace_path, encoding="utf-8") as f:
            doc = json.load(f)
        events = doc["traceEvents"]
        xs = [e for e in events if e.get("ph") == "X"]
        if not xs:
            failures.append("chrome trace has no complete (X) events")
        for e in xs:
            for key in ("name", "ts", "dur", "pid", "tid"):
                if key not in e:
                    failures.append(f"event missing {key!r}: {e}")
                    break
        ids = {e["args"].get("trace_id") for e in xs}
        if len(ids) != 1:
            failures.append(f"events carry {len(ids)} trace ids: {ids}")
        names = {e["name"] for e in xs}
        if "profiler.window" not in names:
            failures.append(f"no profiler.window span in {sorted(names)}")
    except (OSError, ValueError, KeyError) as e:
        failures.append(f"chrome trace unloadable: {e}")

    # --------------------------------------------------------- openmetrics
    snap = registry.snapshot()
    text_render = render_openmetrics(registry, snapshot=snap)
    exporter = FileExporter(os.path.join(tmpdir, "metrics.prom"),
                            registry=registry)
    text_file = exporter.write_once(snapshot=snap)
    if text_render.encode() != text_file.encode():
        failures.append("render and file exporter disagree byte-for-byte")
    try:
        with open(exporter.path, encoding="utf-8") as f:
            on_disk = f.read()
        samples = parse_openmetrics(on_disk)
        if ("obs_profiled_windows_total", "") not in samples:
            failures.append("exposition missing obs_profiled_windows_total")
        if ("obs_step_wall_s_count", "") not in samples:
            failures.append("exposition missing obs_step_wall_s summary")
        if ("obs_sentry_findings_total", "") not in samples:
            failures.append("exposition missing obs_sentry_findings_total")
    except (OSError, ValueError) as e:
        failures.append(f"openmetrics exposition malformed: {e}")

    ok = not failures
    line = {
        "selftest": "autodist_tpu.obs",
        "ok": ok,
        "windows": n_windows,
        "steps_per_window": window,
        "flops_per_step": rep.get("flops_per_step"),
        "dispatch_gap_ms": round(rep.get("dispatch_gap_s", 0.0) * 1e3, 3),
        "step_wall_ms": round(rep.get("step_wall_s", 0.0) * 1e3, 3),
        "compiles": rep.get("compiles", {}).get("count"),
        "trace_events": len(tracer.spans()),
        "openmetrics_bytes": len(text_file),
        "flight_records": recorder.stats()["records"],
        "recorder_overhead_pct": round(overhead * 100, 4),
        "sentry_cases": {k: v for k, v in sorted(sentry_cases.items())},
        "doctor_cases": {k: v for k, v in sorted(doctor_cases.items())},
        "device": jax.devices()[0].platform,
        "n_devices": jax.device_count(),
    }
    if failures:
        line["failures"] = failures
    print(json.dumps(line))
    return 0 if ok else 1


def family12_recipe(n_devices: int) -> dict:
    """Build constants of dryrun family #12 (``bucketed_overlap``) — the
    ONE definition ``__graft_entry__._dryrun_bucketed_overlap`` and the
    attrib selftest/tests share, so "the family the join is proven on"
    and the driver-gate family can never silently diverge. One hidden
    kernel's bytes close a bucket, so the three mlp kernels (+ riding
    biases) split into >= 2 buckets."""
    return {
        "model": "mlp",
        "model_kwargs": {"in_dim": 8 * n_devices,
                         "hidden": (8 * n_devices, 8 * n_devices),
                         "num_classes": 4},
        "batch_size": 2 * n_devices,
        "bucket_bytes": (8 * n_devices) ** 2 * 4,
    }


def _build_bucketed_zero1(n_devices: int = 8):
    """The dryrun family #12 build (bucketed zero1 over an n-device CPU
    mesh) — the join proof's subject: >= 2 backward-overlap buckets, rs +
    ag promised for every shard_update var, a loss psum riding along."""
    import jax
    import optax

    import autodist_tpu.strategy as S
    from autodist_tpu.api import AutoDist
    from autodist_tpu.model_item import ModelItem
    from autodist_tpu.models import get_model
    from autodist_tpu.resource_spec import ResourceSpec

    recipe = family12_recipe(n_devices)
    rs = ResourceSpec(resource_dict={"nodes": [
        {"address": "localhost", "chips": n_devices, "chief": True}]})
    builder = S.Zero1(bucket_bytes=recipe["bucket_bytes"])
    model = get_model(recipe["model"], **recipe["model_kwargs"])
    params = model.init(jax.random.PRNGKey(0))
    batch = model.example_batch(recipe["batch_size"])
    AutoDist.reset_default()
    ad = AutoDist(resource_spec=rs, strategy_builder=builder)
    step = ad.build(model.loss_fn, params, batch,
                    optimizer=optax.adam(1e-3))
    AutoDist.reset_default()
    item = ModelItem.from_params(params, loss_fn=model.loss_fn,
                                 example_batch=batch)
    strategy = builder.build(item, rs)
    return step, params, batch, item, strategy, rs


def attrib_selftest(window: int = 4) -> int:
    """The measured-wire join proof; prints ONE JSON line."""
    _provision_cpu_mesh()

    from autodist_tpu import metrics as M
    from autodist_tpu.analysis.passes import measured_wire_check
    from autodist_tpu.obs.attrib import (
        BucketWire,
        MeasuredOp,
        MeasuredWire,
    )
    from autodist_tpu.obs.profiler import StepProfiler
    from autodist_tpu.obs.spans import SpanTracer
    from autodist_tpu.plan.calibrate import (
        TopologyCalibration,
        record_from_attribution,
    )
    from autodist_tpu.strategy.cost_model import CostModel

    failures = []
    step, params, batch, item, strategy, rs = _build_bucketed_zero1()
    prof = StepProfiler(step, registry=M.MetricsRegistry(),
                        tracer=SpanTracer(capacity=64), recorder=None,
                        sentry=None)
    state = step.init(params)
    state, _ = prof.run(state, batch, window)
    wire, state = prof.attribute(state, batch, num_steps=window)

    # ---------------------------------------------------------- join proof
    plan = step.plan
    assignment = plan.bucket_assignment()
    if len(assignment) < 2:
        failures.append(f"expected >= 2 buckets, got {assignment}")
    measured_buckets = {b.bucket: b for b in wire.buckets}
    for bi in range(len(assignment)):
        b = measured_buckets.get(bi)
        if b is None:
            failures.append(f"bucket {bi} has no measured collective")
        elif b.measured_s_per_step <= 0:
            failures.append(f"bucket {bi} measured 0 seconds")
        elif not (0.0 <= b.overlap_fraction <= 1.0):
            failures.append(
                f"bucket {bi} overlap {b.overlap_fraction} outside [0,1]")
    if set(measured_buckets) - set(range(len(assignment))):
        failures.append(
            f"measured buckets {sorted(measured_buckets)} outside the "
            f"plan's assignment ({len(assignment)} buckets)")
    if wire.unobserved:
        failures.append(
            f"promised collectives never observed: {wire.unobserved}")
    large = wire.unattributed_large
    if large:
        failures.append(
            "unattributed-large rows: "
            + ", ".join(f"{o.name} ({o.seconds_per_step * 1e3:.3f} ms)"
                        for o in large))
    if wire.device_total_s_per_step <= 0 or not wire.collectives:
        failures.append("parse produced no device time / no collectives")
    got = wire.exposed_comm_fraction
    agg = wire.bucket_summed_exposed_fraction()
    if got is None or agg is None or abs(got - agg) > 1e-6:
        failures.append(
            f"bucket-summed exposed fraction {agg} disagrees with the "
            f"report's {got}")
    if prof.exposed_comm_fraction != got:
        failures.append("StepProfiler.exposed_comm_fraction did not adopt "
                        "the trace-measured value")

    clean = measured_wire_check(plan, wire)
    bad = [f for f in clean if f.code in ("SLT001", "SLT002")]
    if bad:
        failures.append(
            f"clean join tripped {[f.code for f in bad]}: "
            f"{[f.message for f in bad]}")

    # ------------------------------------------------- seeded mismatches
    seeded = MeasuredWire.from_json(wire.to_json())
    seeded.ops.append(MeasuredOp(
        name="all-to-all.999", kind="all-to-all",
        seconds_per_step=1e-3, count=1, payload_elements=1 << 20,
        payload_bytes=4 << 20, matched=False))
    codes = [f.code for f in measured_wire_check(plan, seeded)]
    if codes.count("SLT001") != 1:
        failures.append(f"seeded unplanned collective: expected exactly "
                        f"one SLT001, got {codes}")
    seeded2 = MeasuredWire.from_json(wire.to_json())
    seeded2.unobserved.append(("dense1/kernel", "zero1", "reduce-scatter"))
    codes2 = [f.code for f in measured_wire_check(plan, seeded2)]
    if codes2.count("SLT002") != 1:
        failures.append(f"seeded missing collective: expected exactly one "
                        f"SLT002, got {codes2}")
    seeded3 = MeasuredWire(
        overlap_measurable=True, device_total_s_per_step=1.0,
        buckets=[BucketWire(bucket=0, measured_s_per_step=0.1,
                            overlap_fraction=0.05,
                            exposed_s_per_step=0.095)])
    codes3 = [f.code for f in measured_wire_check(plan, seeded3)]
    if codes3 != ["SLT003"]:
        failures.append(f"seeded under-overlap: expected [SLT003], "
                        f"got {codes3}")

    # ------------------------------------ trace-fed calibration precedence
    cost = CostModel(item, rs).strategy_cost(strategy)
    rec = record_from_attribution(prof.report(), cost, wire,
                                  name="zero1_bucketed")
    if not rec.measured_components:
        failures.append("attribution yielded no calibration components")
    # Replayed profile: the trace-anchored record plus variants with
    # different wire mixes, generated by the truth model the trace
    # implies (coefficient = measured/predicted per attributed component,
    # constant compute floor). Few points + heterogeneous mixes is
    # exactly where the whole-step regression has too few degrees of
    # freedom and the direct attribution should win.
    truth = {c: rec.measured_components[c] / getattr(rec, c)
             for c in rec.measured_components if getattr(rec, c) > 0}
    base = max(rec.measured_s - sum(
        truth[c] * getattr(rec, c) for c in truth), 1e-4)

    def replay(scales):
        r = record_from_attribution(prof.report(), cost, wire,
                                    name=f"replay{scales}")
        for comp, s in scales.items():
            setattr(r, comp, getattr(r, comp) * s)
            if comp in r.measured_components:
                r.measured_components[comp] *= s
        r.measured_s = base + sum(
            truth[c] * getattr(r, c) for c in truth)
        return r

    replayed = [replay(s) for s in (
        {}, {"overlap_s": 4.0}, {"gather_s": 6.0},
        {"overlap_s": 2.0, "gather_s": 0.25})]
    fit_direct = TopologyCalibration.fit(replayed, topology="selftest")
    stripped = [dataclasses.replace(r, measured_components={})
                for r in replayed]
    fit_reg = TopologyCalibration.fit(stripped, topology="selftest")
    if not (math.isfinite(fit_direct.error_after)
            and math.isfinite(fit_reg.error_after)):
        failures.append(
            f"calibration errors not finite: direct "
            f"{fit_direct.error_after}, regression {fit_reg.error_after}")
    elif fit_direct.error_after >= fit_reg.error_after:
        failures.append(
            f"trace-fed fit ({fit_direct.error_after:.4f}) did not beat "
            f"the regression-only fit ({fit_reg.error_after:.4f}) on the "
            f"replayed profile")

    ok = not failures
    line = {
        "selftest": "autodist_tpu.obs.attrib",
        "ok": ok,
        "window": window,
        "n_devices": wire.n_devices,
        "n_collectives": len(wire.collectives),
        "n_matched": sum(1 for o in wire.collectives if o.matched),
        "buckets": {str(b.bucket): {
            "ms_per_step": round(b.measured_s_per_step * 1e3, 4),
            "overlap": round(b.overlap_fraction, 4),
            "vars": len(b.vars)} for b in wire.buckets},
        "exposed_comm_fraction": wire.exposed_comm_fraction,
        "overlap_measurable": wire.overlap_measurable,
        "unattributed_large": len(large),
        "seeded_codes": {"SLT001": codes.count("SLT001"),
                         "SLT002": codes2.count("SLT002"),
                         "SLT003": codes3.count("SLT003")},
        "calibration": {
            "components_measured": sorted(rec.measured_components),
            "error_after_direct": fit_direct.error_after,
            "error_after_regression": fit_reg.error_after,
        },
    }
    if failures:
        line["failures"] = failures
    print(json.dumps(line, default=float))
    return 0 if ok else 1


def _attrib_parse(trace_dir: str, window: int = 0, top: int = 0,
                  out: str = "") -> int:
    """``attrib --parse``: the per-category device-op table of an existing
    trace (the profile_ops.py output shape, via the ONE parser)."""
    from autodist_tpu.obs.attrib import (
        category_table,
        parse_trace,
        read_capture_meta,
    )

    parsed = parse_trace(trace_dir)
    window = window or int(read_capture_meta(trace_dir).get("window", 1))
    table = category_table(parsed, window, top=top)
    print(f"device-op total {table['total_ms_per_step']:.2f} ms/step "
          f"(window {window}, {table['n_timelines']} device timeline(s))")
    for row in table["rows"]:
        print(f"  {row['ms_per_step']:7.2f} ms/step {row['pct']:5.1f}% "
              f" n={row['kernels']:6d}  {row['category']}")
    for op in table.get("top_ops", []):
        print(f"  {op['ms_per_step']:7.3f} ms/step  {op['name']}")
    if out:
        with open(out, "w", encoding="utf-8") as fh:
            json.dump(table, fh, indent=2)
        print(f"wrote {out}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m autodist_tpu.obs",
                                 description=__doc__)
    ap.add_argument("--selftest", action="store_true",
                    help="run the CPU observability proof and exit")
    ap.add_argument("--window", type=int, default=4,
                    help="selftest: steps per profiled window")
    ap.add_argument("--windows", type=int, default=3,
                    help="selftest: profiled windows")
    sub = ap.add_subparsers(dest="cmd")
    doc = sub.add_parser(
        "doctor",
        help="postmortem: classify the death recorded under an ft base dir")
    doc.add_argument("dir", help="ft base dir (what AUTODIST_FT_DIR "
                                 "pointed at)")
    doc.add_argument("--json", action="store_true",
                     help="emit ONE machine-readable JSON line")
    doc.add_argument("--trace-out", default="",
                     help="span part-file dir (default: <dir>/traces)")
    att = sub.add_parser(
        "attrib",
        help="measured-wire attribution: join a device profile back to "
             "the plan (docs/observability.md § attribution)")
    att.add_argument("--selftest", action="store_true",
                     help="run the CPU join proof and exit")
    att.add_argument("--parse", default="",
                     help="print the per-category device-op table of an "
                          "existing jax.profiler trace dir")
    att.add_argument("--window", type=int, default=0,
                     help="steps per window (selftest default 4; parse "
                          "default: the trace's capture_meta.json)")
    att.add_argument("--top", type=int, default=0,
                     help="--parse: also print the N largest kernels")
    att.add_argument("--out", default="",
                     help="--parse: write the table as JSON here")
    args = ap.parse_args(argv)
    if args.cmd == "doctor":
        from autodist_tpu.obs.doctor import run_cli

        return run_cli(args.dir, as_json=args.json,
                       trace_out=args.trace_out)
    if args.cmd == "attrib":
        if args.parse:
            return _attrib_parse(args.parse, window=args.window,
                                 top=args.top, out=args.out)
        if args.selftest:
            return attrib_selftest(window=args.window or 4)
        att.print_help()
        return 2
    if args.selftest:
        return selftest(window=args.window, n_windows=args.windows)
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
