"""One OpenMetrics text renderer for every export surface.

Before this module, metric text lived in two ad-hoc places: serve's
``GET /metrics`` route rendered ``MetricsRegistry.render_text`` and
headless training had nothing. Now a single :func:`render_openmetrics`
produces the canonical exposition — serve's route and the
:class:`FileExporter` both call it, so the two surfaces are *byte-identical*
on the same registry snapshot (the acceptance bar pins this).

Format (OpenMetrics-flavored prometheus text):

- ``# TYPE`` comment per family — ``counter`` for ``*_total`` names (the
  family is the name minus the suffix, per the OpenMetrics convention),
  ``summary`` for histograms, ``gauge`` otherwise;
- histogram quantiles as ``name{quantile="0.5"}`` plus ``_count``/``_sum``
  (quantile lines are omitted while the histogram is empty — ``nan`` is
  not a valid exposition token);
- snapshot keys may carry a label set inline (``name{replica="0"}`` —
  the router's fleet aggregation labels per-replica samples this way);
  the ``# TYPE`` comment is emitted once per *family*, so labeled
  samples of one family share it (unlabeled snapshots render
  byte-identically to before);
- deterministic ordering (sorted by name) and a trailing ``# EOF``.

:func:`parse_openmetrics` is the matching reader — the selftest and the
shared serve/file-exporter test validate every surface through it, so a
renderer regression cannot ship malformed text silently.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional, Tuple

from autodist_tpu import metrics as M
from autodist_tpu.utils import logging

__all__ = ["FileExporter", "parse_openmetrics", "render_openmetrics"]

_QUANTILES = (("p50", "0.5"), ("p90", "0.9"), ("p99", "0.99"))


def _fmt(v: float) -> str:
    return f"{float(v):.6g}"


def render_openmetrics(registry: Optional[M.MetricsRegistry] = None,
                       snapshot: Optional[Dict[str, Any]] = None) -> str:
    """The canonical exposition of a registry (or a frozen ``snapshot``
    from :meth:`~autodist_tpu.metrics.MetricsRegistry.snapshot` — pass one
    when several surfaces must render the exact same instant)."""
    if snapshot is None:
        snapshot = (registry or M.registry).snapshot()
    lines = []
    last_family = None

    def sort_key(name: str):
        # Group by FAMILY first (labeled siblings adjacent, counters next
        # to nothing that could reopen their family), then by full name.
        # Plain name-sort almost gives this, but a family that is a
        # string-prefix of another (`foo` vs `foo_bar` vs `foo{a="1"}`)
        # would interleave — a reopened # TYPE family, which strict
        # OpenMetrics scrapers reject.
        base = name.partition("{")[0]
        fam = (base[:-len("_total")]
               if not isinstance(snapshot[name], dict)
               and base.endswith("_total") else base)
        return (fam, name)

    for name in sorted(snapshot, key=sort_key):
        val = snapshot[name]
        # A snapshot key may carry an inline label set: base name decides
        # the family/type, the labels ride on every sample line.
        base, _, labels = name.partition("{")
        labels = f"{{{labels}" if labels else ""
        if isinstance(val, dict):  # histogram summary
            if (base, "summary") != last_family:
                lines.append(f"# TYPE {base} summary")
                last_family = (base, "summary")
            if val.get("count"):
                for key, label in _QUANTILES:
                    qlabels = (f'{labels[:-1]},quantile="{label}"}}' if labels
                               else f'{{quantile="{label}"}}')
                    lines.append(f"{base}{qlabels} {_fmt(val[key])}")
            lines.append(f"{base}_count{labels} {_fmt(val.get('count', 0))}")
            lines.append(f"{base}_sum{labels} {_fmt(val.get('sum', 0.0))}")
        elif base.endswith("_total"):
            family = base[:-len("_total")]
            if (family, "counter") != last_family:
                lines.append(f"# TYPE {family} counter")
                last_family = (family, "counter")
            lines.append(f"{base}{labels} {_fmt(val)}")
        else:
            if (base, "gauge") != last_family:
                lines.append(f"# TYPE {base} gauge")
                last_family = (base, "gauge")
            lines.append(f"{base}{labels} {_fmt(val)}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def parse_openmetrics(text: str) -> Dict[Tuple[str, str], float]:
    """Parse an exposition back into ``{(name, labels): value}``.

    Validates structure the way a scraper would: every sample line is
    ``name[{labels}] value`` with a finite-or-inf float value, and the
    document ends with ``# EOF``. Raises ``ValueError`` on malformed input
    (the selftest's exit-nonzero contract rides on this).
    """
    import math

    lines = text.splitlines()
    if not lines or lines[-1].strip() != "# EOF":
        raise ValueError("exposition missing trailing # EOF")
    out: Dict[Tuple[str, str], float] = {}
    for ln in lines[:-1]:
        ln = ln.strip()
        if not ln or ln.startswith("#"):
            if ln.startswith("#") and not ln.startswith(("# TYPE", "# HELP",
                                                         "# UNIT", "# EOF")):
                raise ValueError(f"unknown comment line: {ln!r}")
            continue
        name, _, rest = ln.partition(" ")
        if not rest:
            raise ValueError(f"sample line without value: {ln!r}")
        labels = ""
        if "{" in name:
            if not name.endswith("}"):
                raise ValueError(f"unterminated label set: {ln!r}")
            name, _, labels = name.partition("{")
            labels = labels[:-1]
        v = float(rest.split()[0])  # raises on non-numeric
        if math.isnan(v):
            raise ValueError(f"NaN sample value: {ln!r}")
        out[(name, labels)] = v
    return out


class FileExporter:
    """Periodic OpenMetrics file writer for headless training.

    A training job with no HTTP front end still needs scrapeable metrics;
    this writes :func:`render_openmetrics` to ``path`` atomically (tmp +
    replace — a scraper never reads a torn file) every ``interval_s``
    from a daemon thread, plus on :meth:`stop`. ``write_once`` is the
    synchronous form (tests, end-of-run flush).
    """

    def __init__(self, path: str, registry: Optional[M.MetricsRegistry] = None,
                 interval_s: float = 10.0):
        self.path = path
        self.registry = registry or M.registry
        self.interval_s = float(interval_s)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def write_once(self, snapshot: Optional[Dict[str, Any]] = None) -> str:
        text = render_openmetrics(self.registry, snapshot=snapshot)
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{self.path}.tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(text)
        os.replace(tmp, self.path)
        return text

    def start(self) -> "FileExporter":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.write_once()
                except OSError as e:
                    logging.warning("metrics file export failed: %s", e)
                self._stop.wait(self.interval_s)

        self._thread = threading.Thread(
            target=loop, name="obs-file-exporter", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(5.0, self.interval_s))
            self._thread = None
        try:
            self.write_once()  # final flush: the file reflects run end
        except OSError:
            pass

    def __enter__(self) -> "FileExporter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
