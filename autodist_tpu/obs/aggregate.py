"""Cross-host observability aggregation over the ft coordination transports.

GSPMD splits one program across the mesh, so a slow *host* shows up only as
fleet-wide step time — per-op attribution can't name it. The classic
diagnostic is per-host step-time distributions compared across the fleet: a
host whose p50 sits above the fleet median is a straggler (thermal
throttling, a noisy neighbor, a dying HBM) long before it misses a
heartbeat. :class:`HostAggregator` publishes each host's recent step-time
quantiles over the same pluggable transports the ft heartbeat subsystem
already ships (:class:`~autodist_tpu.ft.heartbeat.FileTransport` /
``CoordinatorTransport`` / ``MemoryTransport``), sweeps every host's
summary, and derives **straggler scores** — ``host_p50 / fleet_median_p50``
— that feed :meth:`~autodist_tpu.ft.heartbeat.HealthMonitor.escalate`:
a persistent straggler is promoted to SUSPECT scrutiny *while still
beating its heart*, closing the gap between "alive" and "healthy".

The transport payloads are versioned dicts next to (not inside) the
heartbeat files — an aggregator dir under the ft base, or any directory
the caller picks — so observability traffic never races the liveness
signal.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

import numpy as np

from autodist_tpu import metrics as M
from autodist_tpu.chaos import hooks as chaos_hooks
from autodist_tpu.utils import logging

__all__ = ["HostAggregator"]


class HostAggregator:
    """Per-host step-time quantiles + fleet straggler scores.

    ``observe_step(seconds)`` records local step times (bounded window);
    :meth:`tick` publishes this host's summary and sweeps the fleet's.
    ``monitor``/``straggler_threshold`` arm the HealthMonitor escalation:
    a peer whose score exceeds the threshold for ``escalate_after``
    consecutive ticks is escalated to SUSPECT with a straggler reason.
    Drive :meth:`tick` from your loop, or :meth:`start` a daemon thread.
    """

    def __init__(
        self,
        transport,
        process_id: int = 0,
        registry: Optional[M.MetricsRegistry] = None,
        window: int = 256,
        interval_s: float = 5.0,
        monitor=None,
        straggler_threshold: float = 1.5,
        escalate_after: int = 3,
        clock: Callable[[], float] = time.time,
    ):
        self.transport = transport
        self.process_id = int(process_id)
        self.interval_s = float(interval_s)
        self.monitor = monitor
        self.straggler_threshold = float(straggler_threshold)
        self.escalate_after = max(1, int(escalate_after))
        self.clock = clock
        self._times: deque = deque(maxlen=max(8, int(window)))
        self._lock = threading.Lock()
        self._fleet: Dict[int, dict] = {}
        self._over: Dict[int, int] = {}  # pid -> consecutive over-threshold
        self._escalated: set = set()     # escalated once per straggle episode
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

        reg = registry or M.registry
        self._g_hosts = reg.gauge("obs_fleet_hosts")
        self._g_fleet_p50 = reg.gauge("obs_fleet_step_p50_s")
        self._g_local_p50 = reg.gauge("obs_host_step_p50_s")
        self._g_score = reg.gauge("obs_straggler_score")
        self._g_score_max = reg.gauge("obs_straggler_score_max")
        self._c_escalations = reg.counter("obs_straggler_escalations_total")

    # ------------------------------------------------------------ recording
    def observe_step(self, seconds: float) -> None:
        with self._lock:
            self._times.append(float(seconds))

    def quantiles(self) -> Dict[str, float]:
        """Local step-time summary (empty dict before any observation)."""
        with self._lock:
            xs = np.asarray(self._times, np.float64)
        if not xs.size:
            return {}
        return {
            "n": int(xs.size),
            "p50": float(np.percentile(xs, 50)),
            "p90": float(np.percentile(xs, 90)),
            "p99": float(np.percentile(xs, 99)),
            "mean": float(xs.mean()),
        }

    # ----------------------------------------------------------------- tick
    def tick(self, now: Optional[float] = None) -> Dict[int, dict]:
        """Publish local quantiles, sweep the fleet's, update scores.

        Returns the swept ``{pid: summary}`` view (own host included)."""
        now = self.clock() if now is None else now
        local = self.quantiles()
        if local:
            try:
                self.transport.publish(self.process_id,
                                       {"time": now, **local})
            except Exception as e:  # noqa: BLE001 - observability never fatal
                logging.warning("obs aggregate publish failed (%s)", e)
        try:
            fleet = self.transport.sweep()
        except Exception:  # noqa: BLE001
            fleet = {}
        # Chaos seam (docs/chaos.md): an installed plant may slow a host's
        # swept quantiles (straggler injection feeding SNT006).
        fleet = chaos_hooks.apply(chaos_hooks.SEAM_AGG_SWEEP, fleet)
        with self._lock:
            self._fleet = fleet
        self._update_scores(fleet)
        return fleet

    def _update_scores(self, fleet: Dict[int, dict]) -> None:
        p50s = {pid: s["p50"] for pid, s in fleet.items()
                if isinstance(s, dict) and s.get("p50")}
        self._g_hosts.set(len(p50s))
        if not p50s:
            return
        fleet_median = float(np.median(list(p50s.values())))
        self._g_fleet_p50.set(fleet_median)
        local = p50s.get(self.process_id)
        if local is not None:
            self._g_local_p50.set(local)
            self._g_score.set(local / fleet_median if fleet_median else 0.0)
        scores = self.straggler_scores(fleet=fleet)
        if scores:
            self._g_score_max.set(max(scores.values()))
        for pid, score in scores.items():
            if score > self.straggler_threshold:
                self._over[pid] = self._over.get(pid, 0) + 1
            else:
                self._over.pop(pid, None)
                self._escalated.discard(pid)  # recovered: next episode fires
            # >= (not ==) + the per-episode dedup set: a monitor attached
            # AFTER the counter passed the bar (ObsRuntime.attach_monitor
            # runs late in AutoDist.__init__) must still escalate a
            # persistent straggler, exactly once per episode.
            if (self.monitor is not None
                    and self._over.get(pid, 0) >= self.escalate_after
                    and pid not in self._escalated
                    and pid != self.process_id):
                self._escalated.add(pid)
                self._c_escalations.inc()
                logging.warning(
                    "host %d is a straggler (p50 %.1fx fleet median); "
                    "escalating to suspect", pid, score)
                try:
                    self.monitor.escalate(
                        pid, reason=f"straggler x{score:.2f}")
                except Exception:  # noqa: BLE001 - monitor may be stopping
                    logging.warning("straggler escalation failed",
                                    exc_info=True)

    def straggler_scores(
        self, fleet: Optional[Dict[int, dict]] = None
    ) -> Dict[int, float]:
        """``{pid: host_p50 / fleet_median_p50}`` over the last sweep."""
        if fleet is None:
            with self._lock:
                fleet = dict(self._fleet)
        p50s = {pid: s["p50"] for pid, s in fleet.items()
                if isinstance(s, dict) and s.get("p50")}
        if not p50s:
            return {}
        med = float(np.median(list(p50s.values())))
        if not med:
            return {}
        return {pid: p / med for pid, p in p50s.items()}

    def stragglers(self, threshold: Optional[float] = None) -> List[int]:
        th = self.straggler_threshold if threshold is None else threshold
        return sorted(pid for pid, s in self.straggler_scores().items()
                      if s > th)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "HostAggregator":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 - daemon must survive
                    logging.warning("obs aggregator tick failed",
                                    exc_info=True)
                self._stop.wait(self.interval_s)

        self._thread = threading.Thread(
            target=loop, name="obs-aggregator", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(5.0, self.interval_s))
            self._thread = None
