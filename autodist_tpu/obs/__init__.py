"""Unified observability (L1.5): spans, step profiling, metrics export.

The production triad's third leg (after ``serve/`` and ``ft/``): the layer
that tells you *where the time and bytes went* across a multi-host fleet.
Supersedes the earlier islands — ``utils/tracing.py``'s StepTimer (now a
compat shim over :mod:`~autodist_tpu.obs.profiler`), the ad-hoc prometheus
text in serve, and the unexported roofline/metrics plumbing:

- :mod:`~autodist_tpu.obs.spans` — cross-process span tracer: context
  manager/decorator spans into a thread-safe ring, one trace id propagated
  through the launcher's ``AUTODIST_*`` env so launcher → coordinator →
  worker spans stitch into a single chrome-trace/Perfetto JSON.
- :mod:`~autodist_tpu.obs.profiler` — :class:`StepProfiler`: dispatch-gap
  vs device-compute split per run window (one end barrier, bench.py
  discipline), live MFU from the compiled program's own cost analysis,
  roofline position, compile counts, HBM high-water.
- :mod:`~autodist_tpu.obs.exporter` — ONE OpenMetrics renderer for every
  export surface (serve ``GET /metrics`` and the headless
  :class:`FileExporter` are byte-identical), plus the matching parser.
- :mod:`~autodist_tpu.obs.aggregate` — per-host step-time quantiles over
  the ft coordination transports; straggler scores feed the
  HealthMonitor's suspect escalation.
- :mod:`~autodist_tpu.obs.recorder` — the always-on **flight recorder**:
  one compact JSONL record per train/serve step plus sparse events, in a
  crash-safe fsync'd segment ring under ``<ft base>/flight`` — the black
  box every death leaves behind.
- :mod:`~autodist_tpu.obs.sentry` — online anomaly sentry over that
  stream: NaN/Inf, loss spikes, step-time regressions, HBM creep,
  stragglers — stable ``SNT###`` verdict codes, escalated into the ft
  HealthMonitor.
- :mod:`~autodist_tpu.obs.doctor` — the postmortem: stitch flight
  records, heartbeats, snapshot manifests, hang bundles and span parts
  into one timeline and classify the death (``DOC###`` verdicts).
- :mod:`~autodist_tpu.obs.attrib` — measured-wire attribution: the ONE
  xplane reader parses a ``jax.profiler`` capture of a windowed step and
  joins every device op back to the plan's promised wire (per-bucket
  measured overlap, measured-vs-promised payloads, ``SLT###`` conformance
  findings, trace-fed calibration records) — the measured leg of the
  planned → priced → measured loop.

Entry points: ``AutoDist(observability=ObsConfig(...))`` → ``autodist.obs``
(:class:`ObsRuntime`), ``python -m autodist_tpu.obs doctor <ft-dir>``, and
``python -m autodist_tpu.obs --selftest`` — the zero-hardware CPU proof.
See docs/observability.md.
"""
from __future__ import annotations

from autodist_tpu.obs.aggregate import HostAggregator
from autodist_tpu.obs.attrib import MeasuredWire, attribute
from autodist_tpu.obs.config import ObsConfig, ObsRuntime
from autodist_tpu.obs.doctor import Diagnosis, diagnose
from autodist_tpu.obs.exporter import (
    FileExporter,
    parse_openmetrics,
    render_openmetrics,
)
from autodist_tpu.obs.profiler import StepProfiler, StepTimer, detect_peak_flops
from autodist_tpu.obs.recorder import FlightRecorder, read_records
from autodist_tpu.obs.sentry import Finding, Sentry, SentryConfig
from autodist_tpu.obs.slo import SLOSpec, SLOTracker, replay_flight_records
from autodist_tpu.obs.spans import (
    Span,
    SpanTracer,
    add_span,
    current_trace_id,
    enable_trace_out,
    events_for_request,
    get_tracer,
    span,
    stitch,
    traced,
)

__all__ = [
    "Diagnosis",
    "FileExporter",
    "Finding",
    "FlightRecorder",
    "HostAggregator",
    "MeasuredWire",
    "ObsConfig",
    "ObsRuntime",
    "SLOSpec",
    "SLOTracker",
    "Sentry",
    "SentryConfig",
    "Span",
    "SpanTracer",
    "StepProfiler",
    "StepTimer",
    "add_span",
    "attribute",
    "current_trace_id",
    "detect_peak_flops",
    "diagnose",
    "enable_trace_out",
    "events_for_request",
    "get_tracer",
    "parse_openmetrics",
    "read_records",
    "render_openmetrics",
    "replay_flight_records",
    "span",
    "stitch",
    "traced",
]
