"""Serving SLOs: declarative targets, rolling percentiles, burn rates.

Training observability measures *steps* (loss, step wall, HBM); serving
observability measures *requests*. This module is the serving half of the
obs stack's measurement layer: a declarative :class:`SLOSpec` (the
latency/error targets a deployment promises), an :class:`SLOTracker` that
maintains rolling-window percentiles of the request-level signals —
time-to-first-token (TTFT), inter-token latency (ITL), queue wait — plus
good/bad event accounting with **multi-window burn rates** against the
error budget, and one JSON ``slo_report`` every surface renders from:

- the :class:`~autodist_tpu.serve.router.Router` feeds its tracker from
  the delivered (client-visible) stream — TTFT at the first harvested
  token, ITL at completion, queue wait at dispatch — so the SLO measures
  what clients experienced, failovers included;
- the :class:`~autodist_tpu.serve.batcher.ContinuousBatcher` feeds a
  per-replica tracker from its own retire path (single-engine
  deployments get the same report without a router);
- measured percentiles and burn rates publish as ``slo_*`` gauges
  through the ONE :class:`~autodist_tpu.metrics.MetricsRegistry` /
  OpenMetrics exporter, so ``GET /metrics`` scrapes and the headless
  ``FileExporter`` carry the SLO position byte-identically;
- :func:`replay_flight_records` rebuilds a tracker from flight-recorder
  ``serve``/``request`` records, so a postmortem can compute the SLO
  position of a run that is already dead.

Burn rate follows the standard multi-window form: the bad-event fraction
over a window divided by the error budget (1.0 = burning exactly the
budget; >1 = on track to exhaust it). Two windows — fast (paging-speed)
and slow (ticket-speed) — are both reported; the serve sentry's SNT009
fires on the fast window (docs/observability.md § serving SLOs).
"""
from __future__ import annotations

import json
import math
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, Iterable, List, Optional

import numpy as np

from autodist_tpu import metrics as M

__all__ = ["SLOSpec", "SLOTracker", "json_safe", "replay_flight_records"]


@dataclass(frozen=True)
class SLOSpec:
    """Declarative serving SLO: the targets a deployment promises.

    Latency targets are seconds; ``error_budget`` is the allowed bad
    fraction (errors + sheds over all terminal outcomes) the availability
    target implies; windows are seconds of rolling history. Defaults are
    interactive-chat-shaped — deployments pass their own.
    """

    name: str = "serve"
    ttft_p50_s: float = 1.0        # time to first token
    ttft_p99_s: float = 5.0
    itl_p50_s: float = 0.2         # inter-token latency (decode cadence)
    itl_p99_s: float = 1.0
    queue_wait_p99_s: float = 2.0
    availability: float = 0.99     # fraction of requests that must succeed
    window_s: float = 300.0        # rolling percentile window
    burn_fast_window_s: float = 60.0
    burn_slow_window_s: float = 600.0

    @property
    def error_budget(self) -> float:
        return max(1e-9, 1.0 - self.availability)

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, doc: dict) -> "SLOSpec":
        known = {k: doc[k] for k in doc
                 if k in cls.__dataclass_fields__}  # type: ignore[attr-defined]
        return cls(**known)


@dataclass
class _Series:
    """One rolling (t, value, weight) series bounded by time window and
    count. Weights attribute a summary sample to the events it stands
    for — a request's mean ITL carries ``n_tokens - 1`` weight so
    percentiles are per *token*, not per request (multi-token
    speculative-decode steps must not let short requests dominate)."""

    window_s: float
    points: deque = field(default_factory=lambda: deque(maxlen=4096))

    def add(self, t: float, v: float, w: float = 1.0) -> None:
        self.points.append((float(t), float(v), float(w)))

    def values(self, now: float) -> List[tuple]:
        """(value, weight) pairs inside the window."""
        cutoff = now - self.window_s
        return [(v, w) for t, v, w in self.points if t >= cutoff]


class SLOTracker:
    """Streaming SLO accountant (thread-safe; producers on scheduler /
    router threads, readers on HTTP / sentry threads).

    Feed request-level signals with :meth:`observe`; read the position
    with :meth:`report` (the ``slo_report`` JSON), :meth:`percentile`, or
    :meth:`burn_rates`. Gauges ``slo_*`` publish on every report through
    the shared registry.
    """

    def __init__(self, spec: Optional[SLOSpec] = None,
                 registry: Optional[M.MetricsRegistry] = None,
                 clock=time.monotonic):
        self.spec = spec or SLOSpec()
        self.clock = clock
        self._lock = threading.Lock()
        w = self.spec.window_s
        self._ttft = _Series(w)
        # Cached/uncached TTFT split (prefix sharing, serve/prefix.py):
        # a hit-rate shift moves the blended percentile, so the report
        # carries both populations — an uncached (real-prefill)
        # regression stays visible even at a 95% hit rate.
        self._ttft_cached = _Series(w)
        self._ttft_uncached = _Series(w)
        self._itl = _Series(w)
        self._wait = _Series(w)
        # Terminal outcomes: (t, ok, shed) — the burn-rate stream.
        self._events: deque = deque(maxlen=16384)
        self._totals = {"requests": 0, "errors": 0, "sheds": 0}
        # Speculative-decode acceptance: rolling (t, accepted, proposed)
        # — acceptance_rate joins the slo_report so a burn/latency
        # verdict on a spec-decode replica always comes with its
        # acceptance context (ISSUE 15).
        self._spec: deque = deque(maxlen=4096)
        self._spec_totals = {"proposed": 0, "accepted": 0}
        # Acceptance split by temperature bucket (serve/sampling.py's
        # fixed bucket names): stochastic streams legitimately accept
        # fewer draft tokens than greedy ones, so a blended acceptance
        # dip must be attributable to traffic mix before the sentry
        # calls it sickness. Keyed by caller-supplied bucket string —
        # this module never imports the serve layer.
        self._spec_bucket: Dict[str, deque] = {}
        # Terminal-outcome stream mix: sampled (temperature > 0) vs
        # greedy requests, cumulative.
        self._stream_counts = {"sampled": 0, "greedy": 0}

        reg = registry or M.registry
        self._reg = reg
        self._g = {k: reg.gauge(f"slo_{k}") for k in (
            "ttft_p50_s", "ttft_p99_s", "itl_p50_s", "itl_p99_s",
            "queue_wait_p99_s", "availability", "error_rate",
            "acceptance_rate", "prefix_hit_rate",
            "burn_rate_fast", "burn_rate_slow",
            "compliant")}
        self._g_bucket: Dict[str, Any] = {}

    # --------------------------------------------------------------- feeding
    def observe(self, ttft_s: Optional[float] = None,
                itl_s: Optional[float] = None,
                queue_wait_s: Optional[float] = None,
                ok: Optional[bool] = None, shed: bool = False,
                itl_tokens: int = 1,
                spec_proposed: Optional[int] = None,
                spec_accepted: Optional[int] = None,
                spec_bucket: Optional[str] = None,
                cached: Optional[bool] = None,
                temperature: Optional[float] = None,
                t: Optional[float] = None) -> None:
        """Feed any subset of one request's signals. ``ok`` marks a
        terminal outcome (True = served within contract, False = error);
        ``shed`` marks a typed admission rejection (counts against the
        budget — a shed client did not get an answer). ``itl_tokens``
        weights the ITL sample by the inter-token gaps it summarizes
        (the request's token count minus one): ITL percentiles are
        computed per emitted TOKEN, so multi-token speculative-decode
        steps cannot fake latency wins by finishing short requests in
        one burst. ``spec_proposed``/``spec_accepted`` feed the rolling
        draft-acceptance window; with ``spec_bucket`` set the sample
        feeds ONLY that temperature bucket's window (callers feed the
        blended window with a separate un-bucketed call, so one round is
        never double-counted). ``cached`` attributes a TTFT sample to
        the cached-prefix or uncached (full-prefill) population — the
        split percentiles + ``prefix_hit_rate`` in the report; None
        (deployments without a prefix cache) feeds the blended series
        only. ``temperature`` attributes a terminal outcome to the
        sampled (> 0) or greedy stream population. ``t`` overrides the
        clock for replay."""
        now = self.clock() if t is None else float(t)
        with self._lock:
            if ttft_s is not None and math.isfinite(float(ttft_s)):
                self._ttft.add(now, ttft_s)
                if cached is not None:
                    (self._ttft_cached if cached
                     else self._ttft_uncached).add(now, ttft_s)
            if itl_s is not None and math.isfinite(float(itl_s)):
                self._itl.add(now, itl_s, max(int(itl_tokens), 1))
            if queue_wait_s is not None and math.isfinite(float(queue_wait_s)):
                self._wait.add(now, queue_wait_s)
            if spec_proposed is not None and int(spec_proposed) > 0:
                acc = min(max(int(spec_accepted or 0), 0),
                          int(spec_proposed))
                if spec_bucket:
                    self._spec_bucket.setdefault(
                        str(spec_bucket), deque(maxlen=4096)).append(
                            (now, acc, int(spec_proposed)))
                else:
                    self._spec.append((now, acc, int(spec_proposed)))
                    self._spec_totals["proposed"] += int(spec_proposed)
                    self._spec_totals["accepted"] += acc
            if ok is not None or shed:
                good = bool(ok) and not shed
                if temperature is not None:
                    self._stream_counts[
                        "sampled" if float(temperature) > 0.0
                        else "greedy"] += 1
                self._events.append((now, good, bool(shed)))
                self._totals["requests"] += 1
                if shed:
                    self._totals["sheds"] += 1
                elif not good:
                    self._totals["errors"] += 1

    # --------------------------------------------------------------- reading
    @staticmethod
    def _pct(values: List[tuple], p: float) -> float:
        """Weighted percentile over (value, weight) pairs. With all
        weights 1 this is EXACTLY ``np.percentile`` (the pre-weighting
        arithmetic — golden reports unchanged); with real weights each
        sample counts once per event it summarizes (per-token ITL)."""
        if not values:
            return float("nan")
        vs = np.asarray([v for v, _ in values], np.float64)
        ws = np.asarray([w for _, w in values], np.float64)
        if np.all(ws == 1.0):
            return float(np.percentile(vs, p))
        order = np.argsort(vs, kind="stable")
        vs, ws = vs[order], ws[order]
        # Identical to np.percentile('linear') over the weight-expanded
        # array, without materializing it: a sample of weight w is a run
        # of w repeated unit-rank points [left, right]; within a run the
        # value is constant, between adjacent runs interpolation is
        # linear — so each (left, v) and (right, v) pair anchors interp.
        right = np.cumsum(ws) - 1.0
        left = right - (ws - 1.0)
        xs = np.empty(2 * len(vs))
        xs[0::2], xs[1::2] = left, right
        ys = np.repeat(vs, 2)
        rank = (p / 100.0) * (float(np.sum(ws)) - 1.0)
        return float(np.interp(rank, xs, ys))

    def percentile(self, signal: str, p: float,
                   now: Optional[float] = None) -> float:
        """Rolling-window percentile of ``"ttft" | "itl" | "queue_wait"``
        (NaN while the window is empty)."""
        series = {"ttft": self._ttft, "itl": self._itl,
                  "queue_wait": self._wait}[signal]
        with self._lock:
            vals = series.values(self.clock() if now is None else now)
        return self._pct(vals, p)

    def burn_rates(self, now: Optional[float] = None) -> Dict[str, float]:
        """Error-budget burn per window: bad-fraction / budget. 0.0 while
        no terminal outcomes landed in the window."""
        now = self.clock() if now is None else float(now)
        out = {}
        with self._lock:
            events = list(self._events)
        for key, win in (("fast", self.spec.burn_fast_window_s),
                         ("slow", self.spec.burn_slow_window_s)):
            inside = [(good, shed) for t, good, shed in events
                      if t >= now - win]
            if not inside:
                out[key] = 0.0
                continue
            bad = sum(1 for good, _ in inside if not good)
            out[key] = (bad / len(inside)) / self.spec.error_budget
        return out

    def report(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The ``slo_report``: spec, measured position, burn rates,
        per-objective compliance. Publishes the ``slo_*`` gauges as a
        side effect (report IS the render moment)."""
        now = self.clock() if now is None else float(now)
        spec = self.spec
        with self._lock:
            ttft = self._ttft.values(now)
            ttft_cached = self._ttft_cached.values(now)
            ttft_uncached = self._ttft_uncached.values(now)
            itl = self._itl.values(now)
            wait = self._wait.values(now)
            events = list(self._events)
            totals = dict(self._totals)
            spec_win = [(a, p) for t, a, p in self._spec
                        if t >= now - spec.window_s]
            spec_totals = dict(self._spec_totals)
            bucket_win = {b: [(a, p) for t, a, p in dq
                              if t >= now - spec.window_s]
                          for b, dq in self._spec_bucket.items()}
            stream_counts = dict(self._stream_counts)
        win_events = [(g, s) for t, g, s in events
                      if t >= now - spec.window_s]
        good = sum(1 for g, _ in win_events if g)
        availability = good / len(win_events) if win_events else float("nan")
        proposed = sum(p for _, p in spec_win)
        n_split = len(ttft_cached) + len(ttft_uncached)
        measured = {
            "ttft_p50_s": self._pct(ttft, 50.0),
            "ttft_p99_s": self._pct(ttft, 99.0),
            # Prefix-sharing split (NaN without attributed samples — a
            # deployment without a prefix cache says so, not 0).
            "ttft_cached_p50_s": self._pct(ttft_cached, 50.0),
            "ttft_uncached_p50_s": self._pct(ttft_uncached, 50.0),
            "prefix_hit_rate": (len(ttft_cached) / n_split
                                if n_split else float("nan")),
            "itl_p50_s": self._pct(itl, 50.0),
            "itl_p99_s": self._pct(itl, 99.0),
            "queue_wait_p99_s": self._pct(wait, 99.0),
            "availability": availability,
            "error_rate": (1.0 - availability
                           if math.isfinite(availability) else float("nan")),
            # Speculative-decode acceptance over the window (NaN when no
            # drafting happened — a plain replica's report says so rather
            # than claiming 0).
            "acceptance_rate": (
                sum(a for a, _ in spec_win) / proposed
                if proposed else float("nan")),
            # Acceptance split by temperature bucket over the window —
            # only buckets that actually proposed appear (a replica that
            # never saw high-temperature traffic doesn't claim NaN rows).
            "acceptance_by_temperature": {
                b: (sum(a for a, _ in win) / sum(p for _, p in win)
                    if sum(p for _, p in win) else float("nan"))
                for b, win in sorted(bucket_win.items())},
        }
        burn = self.burn_rates(now)

        def _meets(m: float, target: float, higher_is_better=False) -> bool:
            if not math.isfinite(m):
                return True   # no data is not a violation
            return m >= target if higher_is_better else m <= target
        compliant = {
            "ttft_p50": _meets(measured["ttft_p50_s"], spec.ttft_p50_s),
            "ttft_p99": _meets(measured["ttft_p99_s"], spec.ttft_p99_s),
            "itl_p50": _meets(measured["itl_p50_s"], spec.itl_p50_s),
            "itl_p99": _meets(measured["itl_p99_s"], spec.itl_p99_s),
            "queue_wait_p99": _meets(measured["queue_wait_p99_s"],
                                     spec.queue_wait_p99_s),
            "availability": _meets(measured["availability"],
                                   spec.availability, higher_is_better=True),
        }
        compliant["overall"] = all(compliant.values())
        for key, g in self._g.items():
            if key == "compliant":
                g.set(1.0 if compliant["overall"] else 0.0)
            elif key == "burn_rate_fast":
                g.set(burn["fast"])
            elif key == "burn_rate_slow":
                g.set(burn["slow"])
            else:
                v = measured[key]
                g.set(v if math.isfinite(v) else 0.0)
        for b, rate in measured["acceptance_by_temperature"].items():
            gb = self._g_bucket.get(b)
            if gb is None:
                gb = self._reg.gauge(f"slo_acceptance_rate_{b}")
                self._g_bucket[b] = gb
            gb.set(rate if math.isfinite(rate) else 0.0)
        return {
            "slo": spec.to_dict(),
            "measured": measured,
            "burn_rate": {**burn,
                          "windows_s": [spec.burn_fast_window_s,
                                        spec.burn_slow_window_s]},
            "counts": {**totals, "window_requests": len(win_events),
                       "spec_proposed": spec_totals["proposed"],
                       "spec_accepted": spec_totals["accepted"],
                       "sampled_streams": stream_counts["sampled"],
                       "greedy_streams": stream_counts["greedy"]},
            "compliant": compliant,
        }

    def report_json(self, **kw) -> str:
        return json.dumps(json_safe(self.report(**kw)), default=str)


def json_safe(obj):
    """Recursively replace non-finite floats with None: an empty-window
    report carries NaN percentiles, and ``json.dumps`` would emit bare
    ``NaN`` — valid Python, rejected by every RFC-8259 parser. Every
    HTTP/JSON surface renders reports through this."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [json_safe(v) for v in obj]
    return obj


def replay_flight_records(records: Iterable[Dict[str, Any]],
                          spec: Optional[SLOSpec] = None,
                          registry: Optional[M.MetricsRegistry] = None,
                          ) -> SLOTracker:
    """Rebuild an :class:`SLOTracker` from flight records (the batcher's
    ``surface="serve", event="request"`` rows plus ``shed`` events), so
    the SLO position of a dead run is computable postmortem — same spec,
    same arithmetic, fed with the records' own wall clocks."""
    tracker = SLOTracker(spec=spec, registry=registry or M.MetricsRegistry())
    last_t = 0.0
    last_shed: Dict[Any, int] = {}
    for r in records:
        t = float(r.get("t", 0.0))
        if r.get("kind") == "shed":
            # Shed events are rate-limited to one per window (batcher /
            # router `_shed`), with the CUMULATIVE count on the record:
            # replay the per-process deltas, not the event count — else a
            # 100-rejection burst would replay as one bad event. (Sheds
            # after the final window-opening record are lost with the
            # record that was never written; bounded by one window.)
            total = r.get("total_shed")
            # Key deltas by (process, source): an in-process fleet holds
            # the router's AND a batcher's independent cumulative
            # counters under one process id.
            src = (r.get("r", 0), r.get("src"))
            if isinstance(total, (int, float)) and int(total) >= 1:
                n = min(max(1, int(total) - last_shed.get(src, 0)), 100_000)
                last_shed[src] = int(total)
            else:
                n = 1
            for _ in range(n):
                tracker.observe(ok=False, shed=True, t=t)
        elif r.get("kind") == "step" and r.get("event") == "request":
            cached = r.get("cached")
            temp = r.get("temperature")
            tracker.observe(
                ttft_s=r.get("ttft_s"), itl_s=r.get("itl_s"),
                itl_tokens=max(int(r.get("n_tokens") or 2) - 1, 1),
                queue_wait_s=r.get("queue_wait_s"),
                ok=(r.get("state") == "done"),
                cached=None if cached is None else bool(cached),
                temperature=None if temp is None else float(temp), t=t)
        else:
            continue
        last_t = max(last_t, t)
    # The replayed stream's own clock is "now": windows are computed
    # relative to the last record, not this process's monotonic clock.
    tracker.clock = lambda: last_t
    return tracker
