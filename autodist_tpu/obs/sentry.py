"""Online anomaly sentry over the flight-record stream.

The flight recorder (:mod:`autodist_tpu.obs.recorder`) answers "what
happened"; the sentry answers "is something going wrong *right now*". It
watches the same per-step telemetry the recorder persists — loss,
grad/update norms, step wall time, HBM high-water, per-host straggler
scores — and emits a stable, greppable **verdict code** the moment a
stream turns anomalous (mirroring shardlint's SLW/SLM codes,
docs/analysis.md):

======== ==============================================================
Code     Condition
======== ==============================================================
SNT001   non-finite loss (NaN/Inf)
SNT002   non-finite gradient / update norm
SNT003   loss spike: z-score vs the rolling window exceeds threshold
SNT004   step-time regression: consecutive steps above ratio x rolling
         median
SNT005   HBM high-water creep above the post-warmup baseline
SNT006   straggler host: step-time p50 diverges from the fleet median
         (scores from :class:`~autodist_tpu.obs.aggregate.HostAggregator`)
======== ==============================================================

Each finding fires **once per episode** (a NaN'ing loss is one incident,
not one per step; the episode re-arms when the stream recovers), is
logged with its code, appended to the flight record as a ``sentry`` event
(so the postmortem doctor sees it), counted in ``obs_sentry_*`` metrics
through the shared :class:`~autodist_tpu.metrics.MetricsRegistry`, and —
when a :class:`~autodist_tpu.ft.heartbeat.HealthMonitor` is attached —
**escalated**: the offending host is promoted to SUSPECT scrutiny the
same way a silent one is (``HealthMonitor.escalate``), closing the gap
between "beating its heart" and "training correctly".

Wired automatically by :class:`~autodist_tpu.obs.config.ObsRuntime` and
by :class:`~autodist_tpu.obs.profiler.StepProfiler` whenever a flight
recorder is active; ``python -m autodist_tpu.obs --selftest`` proves each
seeded anomaly class trips exactly its code and a clean run trips none.
"""
from __future__ import annotations

import math
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from autodist_tpu import metrics as M
from autodist_tpu.const import ENV
from autodist_tpu.utils import logging

__all__ = ["CODES", "Finding", "Sentry", "SentryConfig"]

#: code -> one-line description (the docs/observability.md table renders
#: from the same source of truth).
CODES: Dict[str, str] = {
    "SNT001": "non-finite loss (NaN/Inf)",
    "SNT002": "non-finite gradient/update norm",
    "SNT003": "loss spike vs rolling window (z-score)",
    "SNT004": "step-time regression vs rolling median",
    "SNT005": "HBM high-water creep above baseline",
    "SNT006": "straggler host: step-time diverges from fleet median",
    "SNT007": "serve TTFT regression vs rolling median (per replica)",
    "SNT008": "serve decode-throughput/ITL regression vs rolling median",
    "SNT009": "serve shed/error burn rate above the SLO budget",
}


@dataclass
class SentryConfig:
    """Detection thresholds. Defaults are deliberately conservative —
    the selftest's clean-run bar ("zero findings on a healthy dryrun")
    is as load-bearing as the seeded-anomaly bar."""

    window: int = 64              # rolling history length (steps)
    min_history: int = 8          # observations before spike checks arm
    loss_z_threshold: float = 8.0     # SNT003: z vs rolling mean/std
    # SNT003 absolute-change floor: a spike must ALSO exceed this fraction
    # of |rolling mean| (min 1e-6) — a flat window's std collapses toward
    # zero and a pure z-score would turn float noise into a verdict.
    loss_spike_min_fraction: float = 0.05
    step_time_ratio: float = 2.0      # SNT004: step > ratio x rolling median
    step_time_consecutive: int = 3    # SNT004: consecutive regressed steps
    hbm_growth_fraction: float = 0.05  # SNT005: growth over baseline
    hbm_min_history: int = 8           # SNT005: baseline sample size
    straggler_threshold: float = 1.5   # SNT006: score bar (aggregate's)
    # Serving codes (docs/observability.md § serving SLOs). TTFT/ITL
    # regressions mirror SNT004's shape — consecutive observations above
    # ratio x the per-replica rolling median — so a single slow request
    # (compile, GC pause) is never a verdict.
    serve_min_history: int = 8         # SNT007/008: per-replica history
    ttft_ratio: float = 2.0            # SNT007: TTFT > ratio x median
    ttft_consecutive: int = 3
    ttft_min_s: float = 0.1            # SNT007: absolute floor (see below)
    itl_ratio: float = 2.0             # SNT008: ITL > ratio x median
    itl_consecutive: int = 3
    # Absolute floors (the SNT003 precedent): a regressed value must ALSO
    # exceed the floor — serving latencies at millisecond scale have
    # ratio-noise (a prefill-heavy tick doubles a 2ms ITL) that is not an
    # incident anyone should be paged for.
    itl_min_s: float = 0.05
    burn_rate_threshold: float = 2.0   # SNT009: fast-window budget burn


@dataclass
class Finding:
    """One tripped verdict."""

    code: str
    message: str
    value: float = 0.0
    step: Optional[int] = None
    process_id: Optional[int] = None
    t: float = field(default_factory=time.time)

    def to_dict(self) -> dict:
        return {
            "code": self.code, "message": self.message, "value": self.value,
            "step": self.step, "process_id": self.process_id, "t": self.t,
        }


def _finite(x: Optional[float]) -> bool:
    return x is not None and math.isfinite(float(x))


class Sentry:
    """Streaming detector: call :meth:`observe_step` with whatever subset
    of signals a step produced; call :meth:`observe_scores` with the
    aggregator's straggler scores. Thread-compat (single producer per
    instance, as with StepProfiler)."""

    def __init__(
        self,
        config: Optional[SentryConfig] = None,
        registry: Optional[M.MetricsRegistry] = None,
        monitor=None,
        recorder=None,
        process_id: Optional[int] = None,
    ):
        self.config = config or SentryConfig()
        self.monitor = monitor
        self.recorder = recorder
        self.process_id = (ENV.AUTODIST_PROCESS_ID.val
                           if process_id is None else int(process_id))
        self.findings: List[Finding] = []
        w = max(4, int(self.config.window))
        self._loss: deque = deque(maxlen=w)
        self._times: deque = deque(maxlen=w)
        self._hbm_baseline: List[float] = []
        self._slow_streak = 0
        self._episodes: set = set()   # active (code[, pid]) incidents
        self._n = 0
        # Serving streams, keyed per replica id (-1 = unattributed):
        # rolling history + regression streaks for SNT007/SNT008.
        self._ttft: Dict[int, deque] = {}
        self._itl: Dict[int, deque] = {}
        self._ttft_streak: Dict[int, int] = {}
        self._itl_streak: Dict[int, int] = {}

        reg = registry or M.registry
        self._reg = reg
        self._c_findings = reg.counter("obs_sentry_findings_total")
        self._g_loss_z = reg.gauge("obs_sentry_loss_z")
        self._g_time_ratio = reg.gauge("obs_sentry_step_time_ratio")
        self._g_hbm_growth = reg.gauge("obs_sentry_hbm_growth")
        self._g_last = reg.gauge("obs_sentry_last_finding_t")

    # ------------------------------------------------------------- emission
    def _emit(self, code: str, message: str, value: float = 0.0,
              step: Optional[int] = None,
              process_id: Optional[int] = None,
              escalate: bool = True) -> Finding:
        pid = self.process_id if process_id is None else int(process_id)
        f = Finding(code=code, message=message, value=float(value),
                    step=step, process_id=pid)
        self.findings.append(f)
        self._c_findings.inc()
        self._reg.counter(f"obs_sentry_{code.lower()}_total").inc()
        self._g_last.set(f.t)
        # The greppable line: `grep SNT0 <log>` finds every verdict.
        logging.warning("%s: %s (value=%.4g, step=%s, host=%d)",
                        code, message, f.value, step, pid)
        if self.recorder is not None:
            try:
                self.recorder.record_event(
                    "sentry", code=code, message=message, value=f.value,
                    step=step, process_id=pid)
            except Exception:  # noqa: BLE001 - telemetry never fatal
                pass
        if self.monitor is not None and escalate:
            try:
                self.monitor.escalate(pid, reason=f"{code}: {message}")
            except Exception:  # noqa: BLE001 - monitor may be stopping
                logging.warning("sentry escalation failed", exc_info=True)
        return f

    def _fire_once(self, key, code: str, message: str, **kw) -> bool:
        """Once-per-episode gate; :meth:`_clear` re-arms on recovery."""
        if key in self._episodes:
            return False
        self._episodes.add(key)
        self._emit(code, message, **kw)
        return True

    def _clear(self, key) -> None:
        self._episodes.discard(key)

    # ------------------------------------------------------------- observing
    def observe_step(
        self,
        step: Optional[int] = None,
        loss: Optional[float] = None,
        step_time_s: Optional[float] = None,
        hbm_bytes: Optional[float] = None,
        grad_norm: Optional[float] = None,
        update_norm: Optional[float] = None,
    ) -> List[Finding]:
        """Feed one step's signals (any subset); returns the findings this
        observation tripped (possibly empty)."""
        cfg = self.config
        before = len(self.findings)
        self._n += 1

        # ---- SNT001 / SNT003: loss stream
        if loss is not None:
            loss = float(loss)
            if not math.isfinite(loss):
                # value keeps the raw non-finite loss (sign included);
                # JSONL round-trips NaN/Infinity through python json.
                self._fire_once("SNT001", "SNT001",
                                f"non-finite loss {loss!r}", value=loss,
                                step=step)
            else:
                self._clear("SNT001")
                if len(self._loss) >= cfg.min_history:
                    hist = np.asarray(self._loss, np.float64)
                    mean, std = float(hist.mean()), float(hist.std())
                    delta = loss - mean
                    floor = max(1e-6,
                                cfg.loss_spike_min_fraction * abs(mean))
                    # Zero-std window (flat/deterministic loss): only a
                    # change past the absolute floor counts as a spike —
                    # never a bare float-noise uptick.
                    z = (delta / std if std > 1e-12
                         else (float("inf") if delta > floor else 0.0))
                    self._g_loss_z.set(min(z, 1e9))
                    if z > cfg.loss_z_threshold and delta > floor:
                        self._fire_once(
                            "SNT003", "SNT003",
                            f"loss spike: {loss:.4g} is z={min(z, 1e9):.1f} "
                            f"above the rolling window (threshold "
                            f"{cfg.loss_z_threshold})",
                            value=min(z, 1e9), step=step)
                    elif z < cfg.loss_z_threshold / 2:
                        self._clear("SNT003")
                self._loss.append(loss)

        # ---- SNT002: gradient / update norms
        norms_seen = [("grad_norm", grad_norm), ("update_norm", update_norm)]
        bad = [(k, v) for k, v in norms_seen
               if v is not None and not math.isfinite(float(v))]
        if bad:
            k, v = bad[0]
            self._fire_once("SNT002", "SNT002",
                            f"non-finite {k} {float(v)!r}", step=step)
        elif any(v is not None for _, v in norms_seen):
            self._clear("SNT002")

        # ---- SNT004: step-time regression
        if step_time_s is not None and step_time_s > 0:
            step_time_s = float(step_time_s)
            if len(self._times) >= cfg.min_history:
                med = float(np.median(np.asarray(self._times, np.float64)))
                ratio = step_time_s / med if med > 0 else 0.0
                self._g_time_ratio.set(ratio)
                if ratio > cfg.step_time_ratio:
                    self._slow_streak += 1
                    if self._slow_streak >= cfg.step_time_consecutive:
                        self._fire_once(
                            "SNT004", "SNT004",
                            f"step time regressed: {step_time_s * 1e3:.1f}ms is "
                            f"{ratio:.2f}x the rolling median "
                            f"({med * 1e3:.1f}ms) for {self._slow_streak} "
                            f"consecutive steps", value=ratio, step=step)
                else:
                    self._slow_streak = 0
                    self._clear("SNT004")
            self._times.append(step_time_s)

        # ---- SNT005: HBM high-water creep
        if hbm_bytes is not None and hbm_bytes > 0:
            hbm_bytes = float(hbm_bytes)
            if len(self._hbm_baseline) < cfg.hbm_min_history:
                self._hbm_baseline.append(hbm_bytes)
            else:
                base = float(np.median(self._hbm_baseline))
                growth = (hbm_bytes - base) / base if base > 0 else 0.0
                self._g_hbm_growth.set(growth)
                if growth > cfg.hbm_growth_fraction:
                    self._fire_once(
                        "SNT005", "SNT005",
                        f"HBM high-water creep: {hbm_bytes / 2**30:.2f} GiB is "
                        f"{growth * 100:.1f}% above the post-warmup baseline "
                        f"({base / 2**30:.2f} GiB)", value=growth, step=step)
                elif growth < cfg.hbm_growth_fraction / 2:
                    self._clear("SNT005")

        return self.findings[before:]

    def observe_scores(self, scores: Dict[int, float],
                       step: Optional[int] = None) -> List[Finding]:
        """Feed the aggregator's per-host straggler scores
        (``HostAggregator.straggler_scores()``); SNT006 fires once per
        host per straggle episode."""
        before = len(self.findings)
        for pid, score in scores.items():
            key = ("SNT006", int(pid))
            if score > self.config.straggler_threshold:
                self._fire_once(
                    key, "SNT006",
                    f"host {pid} is a straggler: step-time p50 is "
                    f"{score:.2f}x the fleet median", value=score, step=step,
                    process_id=pid)
            else:
                self._clear(key)
        return self.findings[before:]

    def observe_serve(
        self,
        step: Optional[int] = None,
        ttft_s: Optional[float] = None,
        itl_s: Optional[float] = None,
        burn_rate: Optional[float] = None,
        replica_id: Optional[int] = None,
    ) -> List[Finding]:
        """Feed one serving observation (any subset): delivered TTFT and
        ITL attributed to ``replica_id`` (SNT007/SNT008 — once per
        episode *per replica*, escalated into the attached monitor so the
        router demotes the replica the way SNT006 demotes hosts), and the
        SLO tracker's fast-window burn rate (SNT009 — escalated only when
        attributed to a replica; a fleet-level burn has no single host to
        demote)."""
        cfg = self.config
        before = len(self.findings)
        rid = -1 if replica_id is None else int(replica_id)
        w = max(4, int(cfg.window))

        def _regress(value, hist: Dict[int, deque],
                     streak: Dict[int, int], code: str, what: str,
                     ratio_bar: float, consecutive: int,
                     min_s: float) -> None:
            series = hist.setdefault(rid, deque(maxlen=w))
            key = (code, rid)
            value = float(value)
            if len(series) >= cfg.serve_min_history:
                med = float(np.median(np.asarray(series, np.float64)))
                ratio = value / med if med > 0 else 0.0
                if ratio > ratio_bar and value > min_s:
                    streak[rid] = streak.get(rid, 0) + 1
                    if streak[rid] >= consecutive:
                        # process_id is ALWAYS rid (-1 when unattributed):
                        # letting it default would stamp the sentry's own
                        # host id (0) on a fleet-level finding, and a
                        # router consumer would demote real replica 0.
                        self._fire_once(
                            key, code,
                            f"replica {rid} {what} regressed: "
                            f"{value * 1e3:.1f}ms is {ratio:.2f}x the rolling "
                            f"median ({med * 1e3:.1f}ms) for {streak[rid]} "
                            f"consecutive requests", value=ratio, step=step,
                            process_id=rid,
                            escalate=replica_id is not None)
                else:
                    streak[rid] = 0
                    self._clear(key)
            series.append(value)

        if ttft_s is not None and ttft_s > 0:
            _regress(ttft_s, self._ttft, self._ttft_streak, "SNT007",
                     "TTFT", cfg.ttft_ratio, cfg.ttft_consecutive,
                     cfg.ttft_min_s)
        if itl_s is not None and itl_s > 0:
            _regress(itl_s, self._itl, self._itl_streak, "SNT008",
                     "inter-token latency", cfg.itl_ratio,
                     cfg.itl_consecutive, cfg.itl_min_s)
        if burn_rate is not None:
            burn_rate = float(burn_rate)
            if replica_id is None:
                # The gauge is the FLEET burn: per-replica calls must not
                # overwrite it (the last replica's 0.0 would mask a
                # fleet-wide 5x burn from every dashboard).
                self._reg.gauge("obs_sentry_burn_rate").set(burn_rate)
            key = ("SNT009", rid)
            if burn_rate > cfg.burn_rate_threshold:
                self._fire_once(
                    key, "SNT009",
                    f"shed/error burn rate {burn_rate:.2f}x the SLO error "
                    f"budget (threshold {cfg.burn_rate_threshold}x"
                    f"{'' if replica_id is None else f', replica {rid}'})",
                    value=burn_rate, step=step, process_id=rid,
                    escalate=replica_id is not None)
            elif burn_rate < cfg.burn_rate_threshold / 2:
                self._clear(key)
        return self.findings[before:]

    def reset_serve_episodes(self, replica_id: int) -> None:
        """Re-arm one replica's serving episodes (SNT007/008/009) and
        streaks. The router calls this when a demotion cooldown expires:
        while demoted the replica served no traffic, so nothing could
        take the recovery path that normally re-arms the episode — and a
        STILL-sick replica would otherwise be re-admitted permanently
        (the episode gate swallowing every later verdict)."""
        rid = int(replica_id)
        for code in ("SNT007", "SNT008", "SNT009"):
            self._clear((code, rid))
        self._ttft_streak.pop(rid, None)
        self._itl_streak.pop(rid, None)

    # --------------------------------------------------------------- queries
    def codes(self) -> List[str]:
        return sorted({f.code for f in self.findings})

    def summary(self) -> dict:
        return {
            "findings": len(self.findings),
            "codes": self.codes(),
            "observed_steps": self._n,
        }
