"""Flight recorder: always-on, bounded-overhead black-box for every run.

BENCH_r04/r05 ended rc=124 with ``parsed: null`` — the fleet died and left
nothing to diagnose. The fix is the aviation answer: a **flight recorder**
that appends one compact record per train/serve step (loss, norms, step
wall time, dispatch gap, exposed-comm fraction, HBM high-water) plus
sparse events (compiles, snapshots, heartbeat transitions, sentry
verdicts, preemptions, errors) to a crash-safe ring of JSONL segments on
disk. After any death — wedge, OOM, NaN, SIGKILL — the surviving segments
are the evidence the postmortem doctor (:mod:`autodist_tpu.obs.doctor`)
classifies.

Design constraints (docs/observability.md § flight recorder):

- **One writer.** All flight-dir writes go through this module
  (``tools/check_patterns.py`` rule 4 bans ``open(``-on-flight-paths
  anywhere else in the package), so the fsync discipline below cannot be
  silently bypassed.
- **Crash-safe.** Each record is one JSON line, written + flushed
  immediately (page cache — survives a process kill); ``fsync`` lands
  every ``fsync_every`` records or ``fsync_interval_s`` seconds, bounding
  loss to seconds of *step* records on a power/host failure, while events
  fsync immediately — they are the rare, load-bearing entries. A
  ``kill -9`` mid-write tears at most the final line, and
  :func:`read_records` skips torn lines by construction.
- **Bounded.** Segments rotate at ``segment_records`` records; the newest
  ``keep_segments`` per process are retained. A month-long run holds a
  fixed-size tail of recent history, which is exactly what a postmortem
  needs.
- **<1% per-step overhead.** Appends are a ``json.dumps`` + buffered
  write; fsyncs amortize across records. The recorder accounts its own
  cost (:meth:`FlightRecorder.stats` ``append_s``) and the obs selftest
  pins ``append_s / window_wall < 1%`` on a dryrun train loop.

The **process-default** recorder turns on automatically when
``AUTODIST_FT_DIR`` is exported (i.e. on every supervised fleet launch):
records land in ``<ft base>/flight/``. ``AUTODIST_FLIGHT_DIR`` enables it
standalone; ``AUTODIST_NO_FLIGHT=1`` opts out. Feeds:
:class:`~autodist_tpu.obs.profiler.StepProfiler` (per-window step
records), ``DistributedTrainStep`` (compile + error events),
``serve.engine`` (admit + sampled decode events), ``ft.snapshot``
(snapshot/preempt events), ``ft.heartbeat`` (peer transitions), and
:mod:`autodist_tpu.obs.sentry` (anomaly verdicts).
"""
from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time
from typing import Any, Dict, Iterable, List, Optional

from autodist_tpu.const import ENV
from autodist_tpu.utils import logging

__all__ = [
    "FLIGHT_SUBDIR",
    "FlightRecorder",
    "disable",
    "enable",
    "flight_dir",
    "get_recorder",
    "read_records",
    "record_event",
    "record_step",
]

#: Subdirectory of the ft base dir the default recorder writes under.
FLIGHT_SUBDIR = "flight"
# Segment naming: flight-r<role>-<seq>.jsonl — per-process files so a
# multi-host fleet on a shared filesystem never interleaves writers.
_SEGMENT_PREFIX = "flight-"
_SEGMENT_SUFFIX = ".jsonl"


def flight_dir(base_dir: str) -> str:
    """The flight-record dir for an ft base dir (ONE naming rule, shared
    with the doctor's bundle reader)."""
    return os.path.join(base_dir, FLIGHT_SUBDIR)


class FlightRecorder:
    """Append-only JSONL ring with the fsync discipline described above.

    Never raises out of a record call: a full disk or revoked mount
    degrades to counted drops (``stats()["errors"]``) — the black box must
    not be able to take down the plane.
    """

    def __init__(
        self,
        directory: str,
        process_id: Optional[int] = None,
        segment_records: int = 1024,
        keep_segments: int = 8,
        fsync_every: int = 64,
        fsync_interval_s: float = 5.0,
        clock=time.time,
    ):
        self.directory = directory
        self.process_id = (ENV.AUTODIST_PROCESS_ID.val
                           if process_id is None else int(process_id))
        self.segment_records = max(1, int(segment_records))
        self.keep_segments = max(1, int(keep_segments))
        self.fsync_every = max(1, int(fsync_every))
        self.fsync_interval_s = float(fsync_interval_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._f = None
        self._seq = 0
        self._n_in_segment = 0
        self._since_fsync = 0
        self._last_fsync = time.monotonic()
        self._closed = False
        self._stats: Dict[str, float] = {
            "records": 0, "events": 0, "bytes": 0, "fsyncs": 0,
            "segments": 0, "pruned_segments": 0, "errors": 0,
            "append_s": 0.0,
        }
        try:
            os.makedirs(directory, exist_ok=True)
            self._seq = self._next_seq()
        except OSError as e:
            self._stats["errors"] += 1
            logging.warning("flight recorder dir unavailable (%s): %s",
                            directory, e)

    # ------------------------------------------------------------- recording
    def record_step(self, **fields: Any) -> None:
        """One per-step (or per-window) record: the dense telemetry row.
        Batched fsync — a crash loses at most ``fsync_every`` steps."""
        self._append({"kind": "step", **fields}, critical=False)

    def record_event(self, kind: str, critical: bool = True,
                     **fields: Any) -> None:
        """One sparse event (compile, snapshot, sentry verdict, error,
        preempt, run_end...). Critical events fsync immediately: they are
        exactly the records a postmortem cannot afford to lose."""
        self._append({"kind": str(kind), **fields}, critical=critical)
        with self._lock:
            self._stats["events"] += 1

    def _append(self, rec: Dict[str, Any], critical: bool) -> None:
        t0 = time.perf_counter()
        try:
            line = json.dumps(
                {"t": self.clock(), "r": self.process_id, **rec},
                separators=(",", ":"), default=str) + "\n"
        except (TypeError, ValueError):
            with self._lock:
                self._stats["errors"] += 1
            return
        with self._lock:
            if self._closed:
                return
            try:
                f = self._ensure_segment()
                f.write(line)
                f.flush()
                self._stats["records"] += 1
                self._stats["bytes"] += len(line)
                self._n_in_segment += 1
                self._since_fsync += 1
                now = time.monotonic()
                if (critical or self._since_fsync >= self.fsync_every
                        or now - self._last_fsync >= self.fsync_interval_s):
                    os.fsync(f.fileno())
                    self._stats["fsyncs"] += 1
                    self._since_fsync = 0
                    self._last_fsync = now
                if self._n_in_segment >= self.segment_records:
                    self._rotate()
            except (OSError, ValueError) as e:
                self._stats["errors"] += 1
                if self._stats["errors"] == 1:  # log the first, count the rest
                    logging.warning("flight record append failed: %s", e)
            finally:
                self._stats["append_s"] += time.perf_counter() - t0

    # -------------------------------------------------------------- segments
    def _segment_path(self, seq: int) -> str:
        return os.path.join(
            self.directory,
            f"{_SEGMENT_PREFIX}r{self.process_id}-{seq:06d}{_SEGMENT_SUFFIX}")

    def _own_segments(self) -> List[str]:
        """This process role's segment names, oldest first."""
        mine = f"{_SEGMENT_PREFIX}r{self.process_id}-"
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        return sorted(n for n in names
                      if n.startswith(mine) and n.endswith(_SEGMENT_SUFFIX))

    def _next_seq(self) -> int:
        segs = self._own_segments()
        if not segs:
            return 0
        tail = segs[-1][len(_SEGMENT_PREFIX):-len(_SEGMENT_SUFFIX)]
        try:
            return int(tail.rsplit("-", 1)[-1]) + 1
        except ValueError:
            return 0

    def _ensure_segment(self):
        if self._f is None:
            self._f = open(self._segment_path(self._seq), "a",
                           encoding="utf-8")
            self._stats["segments"] += 1
            self._n_in_segment = 0
        return self._f

    def _rotate(self) -> None:
        """Close the full segment (fsync'd) and prune the ring. Caller
        holds the lock."""
        f, self._f = self._f, None
        if f is not None:
            try:
                os.fsync(f.fileno())
            except OSError:
                pass
            f.close()
        self._seq += 1
        for name in self._own_segments()[:-self.keep_segments]:
            try:
                os.remove(os.path.join(self.directory, name))
                self._stats["pruned_segments"] += 1
            except OSError:
                pass

    # --------------------------------------------------------------- queries
    def stats(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._stats)

    def close(self, ok: bool = True, **fields: Any) -> None:
        """Flush + fsync + mark the run end. Idempotent; the ``run_end``
        event is what lets the doctor call a run *clean* (a crash never
        writes one)."""
        with self._lock:
            if self._closed:
                return
        self.record_event("run_end", ok=bool(ok), **fields)
        with self._lock:
            self._closed = True
            f, self._f = self._f, None
        if f is not None:
            try:
                os.fsync(f.fileno())
                f.close()
            except OSError:
                pass


# ------------------------------------------------------------------ reading
def read_records(directory: str) -> List[Dict[str, Any]]:
    """Parse every surviving flight record under ``directory``, all
    processes merged, sorted by timestamp. Torn lines (a crash mid-write
    tears at most the final line of a segment) and foreign files are
    skipped, never fatal — this is the reader the doctor trusts on a
    freshly killed run."""
    out: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for name in names:
        if not (name.startswith(_SEGMENT_PREFIX)
                and name.endswith(_SEGMENT_SUFFIX)):
            continue
        try:
            with open(os.path.join(directory, name), encoding="utf-8",
                      errors="replace") as f:
                for ln in f:
                    ln = ln.strip()
                    if not ln:
                        continue
                    try:
                        rec = json.loads(ln)
                    except ValueError:
                        continue  # torn write: skip the fragment
                    if isinstance(rec, dict):
                        out.append(rec)
        except OSError:
            continue
    out.sort(key=lambda r: float(r.get("t", 0.0)))
    return out


def iter_steps(records: Iterable[Dict[str, Any]]):
    """The dense step records of a merged stream (doctor/sentry replay)."""
    return [r for r in records if r.get("kind") == "step"]


# ---------------------------------------------------------- default recorder
_default: Optional[FlightRecorder] = None
_default_lock = threading.Lock()
_resolved = False


def _env_default_dir() -> Optional[str]:
    """Where the always-on default records: AUTODIST_FLIGHT_DIR when set,
    else ``<AUTODIST_FT_DIR>/flight`` on fleet launches; None (disabled)
    otherwise or under AUTODIST_NO_FLIGHT=1."""
    if os.environ.get("AUTODIST_NO_FLIGHT") == "1":
        return None
    explicit = ENV.AUTODIST_FLIGHT_DIR.val
    if explicit:
        return explicit
    base = ENV.AUTODIST_FT_DIR.val
    return flight_dir(base) if base else None


def _install_default(rec: FlightRecorder) -> None:
    """Arm the default recorder's exit paths: at-exit close (the clean
    ``run_end`` marker) AND an excepthook chain — Python runs atexit
    handlers after an uncaught exception too, so without the hook a
    crashed run would still close with ``run_end ok=true`` and the doctor
    would call it clean. The error event lands first (critical fsync) and
    the doctor's precedence (crash/oom/nan beat clean) does the rest.
    Caller holds ``_default_lock``."""
    atexit.register(rec.close)
    prev_hook = sys.excepthook

    def hook(tp, val, tb):
        rec.record_event("error",
                         error=f"uncaught {tp.__name__}: {val}"[:500])
        prev_hook(tp, val, tb)

    sys.excepthook = hook


def get_recorder() -> Optional[FlightRecorder]:
    """The process-default recorder, or None when flight recording is off.
    First call resolves the env contract and arms the exit paths (clean
    ``run_end`` at exit; an uncaught exception records an ``error`` event
    first, so a crash can never read as clean)."""
    global _default, _resolved
    with _default_lock:
        if not _resolved:
            _resolved = True
            d = _env_default_dir()
            if d:
                _default = FlightRecorder(d)
                _install_default(_default)
        return _default


def enable(directory: str, **kwargs: Any) -> FlightRecorder:
    """Install (or replace) the process-default recorder at ``directory``
    — the programmatic form of ``AUTODIST_FLIGHT_DIR``."""
    global _default, _resolved
    with _default_lock:
        old, _default = _default, FlightRecorder(directory, **kwargs)
        _resolved = True
        _install_default(_default)
    if old is not None:
        old.close()
    return _default


def disable(ok: bool = True) -> None:
    """Close and remove the process-default recorder (the inverse of
    :func:`enable`). The next :func:`get_recorder` re-resolves the env
    contract, so scenario harnesses (``autodist_tpu/chaos``) can scope a
    default recorder to one run without leaking it into the next."""
    global _default, _resolved
    with _default_lock:
        old, _default = _default, None
        _resolved = False
    if old is not None:
        old.close(ok=ok)


def record_step(**fields: Any) -> None:
    """Module-level convenience: no-op when no default recorder exists, so
    instrumentation points cost one function call on unconfigured runs."""
    rec = get_recorder()
    if rec is not None:
        rec.record_step(**fields)


def record_event(kind: str, critical: bool = True, **fields: Any) -> None:
    rec = get_recorder()
    if rec is not None:
        rec.record_event(kind, critical=critical, **fields)
