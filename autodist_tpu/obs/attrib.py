"""Measured-wire attribution: per-op device time joined back to the plan.

The repo holds three views of a program's collective wire that, before this
module, never met at op granularity: shardlint diffs the **planned** wire
statically (``analysis/passes.py`` against
:meth:`~autodist_tpu.kernel.lowering.ShardingPlan.promised_wire`),
``plan/calibrate.py`` fits **priced** components from whole-step
regressions, and :class:`~autodist_tpu.obs.profiler.StepProfiler` measures
a single step-level ``exposed_comm_fraction`` from the roofline residue.
This module closes the loop with the **measured** view: capture a
``jax.profiler`` trace of a windowed ``DistributedTrainStep.run``, parse
the device timeline's leaf op events out of the ``xplane.pb``, and join
each measured op back to the plan —

- collectives are recognized through the analysis
  :class:`~autodist_tpu.analysis.inventory.CollectiveInventory` (the ONE
  collective parser) and matched to
  :class:`~autodist_tpu.kernel.lowering.VarWire` entries with the same
  shard-view payload candidates the wire-conformance pass uses;
- ``gradsync.bucket_{i}`` / ``zero1.*`` named scopes (pinned in
  ``kernel/bucketing.py`` — they are the join key) resolve collectives to
  backward-overlap buckets and their variables via the compiled program's
  ``op_name`` metadata;
- the remainder is bucketed into compute categories (the
  ``examples/benchmark/profile_ops.py`` taxonomy, which now delegates
  here).

The result is a :class:`MeasuredWire` report: per-collective and
per-bucket measured seconds, measured-vs-promised payloads, and a
*per-bucket* measured overlap fraction — how much of each bucket's
reduce-scatter interval was actually covered by concurrent compute on the
same device timeline — replacing the single step-level roofline number.
``overlap_measurable`` is False on runtimes that serialize every thunk on
one stream (the CPU thunk executor): a 0.0 overlap there means "cannot
overlap", not "failed to overlap", and the SLT003 lint check stays quiet.

Parsing notes (the ``profile_ops.py`` guards, preserved):

- TPU/GPU device planes (``/device:TPU:*``): ONLY the leaf ``"XLA Ops"``
  line is read — container events (the while loop, the jit region) and the
  async-copy line double-count wall time;
- CPU host plane (``/host:CPU``): the ``tf_XLA*`` client-thread lines are
  the per-device timelines; executor/listener frames
  (``ThunkExecutor::Execute`` …) and container ops (``while.8``) are
  skipped the same way.

This file is the ONE xplane reader in the repo
(``tools/check_patterns.py`` rule 5) — the example CLI and every consumer
delegate here so a dump-format change can never split "what the example
prints" from "what the framework joins".
"""
from __future__ import annotations

import glob
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from autodist_tpu.analysis.inventory import (
    COLLECTIVE_KINDS,
    CollectiveInventory,
)
from autodist_tpu.kernel.bucketing import (
    GRADSYNC_BUCKET_SCOPE,
    ZERO1_ALL_GATHER_SCOPE,
    ZERO1_REDUCE_SCATTER_SCOPE,
)
from autodist_tpu.utils import logging

__all__ = [
    "MeasuredOp",
    "BucketWire",
    "MeasuredWire",
    "ParsedTrace",
    "attribute",
    "capture_trace",
    "category_table",
    "find_xplane",
    "parse_trace",
    "read_capture_meta",
    "write_capture_meta",
]

#: A measured op (collective or compute) whose per-step share of device
#: time exceeds this fraction counts as "large" — an unattributed large
#: row is the attribution failing its job (the selftest pins zero).
LARGE_FRACTION = 0.01

#: Collectives at or below this payload (elements) with no planned
#: counterpart are metric/loss reductions (the scalar loss psum, aux
#: means) — planned in spirit, too small to matter, never flagged.
AUX_REDUCTION_MAX_ELEMENTS = 4096

# Frame/bookkeeping events on the CPU client-thread lines: runtime
# scaffolding around the thunks, not ops.
_FRAME_PREFIXES = (
    "ThunkExecutor", "TfrtCpuExecutable", "ThreadpoolListener",
    "XlaComputation", "BufferAllocations",
)
# Container ops double-count their body's wall time (the profile_ops
# guard): the scanned while loop, conditionals, the jit region.
_CONTAINER_RE = re.compile(r"^%?(while|conditional)(\.\d+)?$|^%?jit[_(]|^0$")

#: Compute categories, checked in order (first match wins). The TPU fusion
#: taxonomy from profile_ops.py rides first; the generic tail covers the
#: CPU thunk names. ``None`` label = container, skip entirely.
CATEGORIES: Tuple[Tuple[str, Optional[str]], ...] = (
    (r"%?convert_reduce_fusion|%?reduce_fusion",
     "stats/grad reductions (+fused producer conv)"),
    (r"%?multiply_add_fusion", "wgrad conv + optimizer update"),
    (r"%?select_and_scatter", "maxpool backward (SelectAndScatter)"),
    (r"%?reduce_window", "pooling forward"),
    (r"%?copy", "layout/loop-boundary copies"),
    (r"%?slice-start|%?slice-done|%?dynamic-slice", "async activation slices"),
    (r"%?dynamic-update-slice", "async activation slices"),
    (r"%?while|^jit_|^0$", None),      # containers: skip, they double-count
    (r"%?dot(\.|$)|%?convolution", "matmul/conv"),
    (r"%?[\w-]*fusion", "conv/elementwise fusions"),
    (r"%?reduce(\.|$)", "reductions"),
    (r"%?(broadcast|transpose|reshape|concatenate|iota|constant|"
     r"convert|select|compare|add|subtract|multiply|divide|maximum|"
     r"minimum|exponential|tanh|rsqrt|sqrt|log|negate|sign|and|or|not|"
     r"xor|clamp|pad|slice|gather|scatter|tuple|get-tuple-element|"
     r"bitcast|rng|sort|abs|power|floor|ceil|round|remainder|is-finite)",
     "elementwise/data movement"),
)


def _category_of(name: str) -> Optional[str]:
    """Category label for a leaf op name; None = container (skip),
    ``"other"`` = nothing matched."""
    for pat, label in CATEGORIES:
        if re.match(pat, name) or re.search(pat, name[:40]):
            return label
    return "other"


def _collective_kind(name: str) -> str:
    """Collective kind a leaf op name spells, '' for compute. Async pair
    halves (``all-reduce-start.3``) fold onto the base kind."""
    stem = name.lstrip("%")
    for kind in COLLECTIVE_KINDS:
        if stem == kind or stem.startswith(kind + ".") or \
                stem.startswith(kind + "-start") or \
                stem.startswith(kind + "-done"):
            return kind
    return ""


# ---------------------------------------------------------------- xplane IO
def find_xplane(trace_dir: str) -> str:
    """Newest ``xplane.pb`` under a ``jax.profiler`` trace dir."""
    paths = glob.glob(
        os.path.join(trace_dir, "plugins", "profile", "*", "*.xplane.pb"))
    if not paths:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    return sorted(paths)[-1]


def write_capture_meta(trace_dir: str, **meta: Any) -> str:
    """Sidecar next to the trace so a later parse normalizes by the window
    the capture actually used (the profile_ops contract)."""
    path = os.path.join(trace_dir, "capture_meta.json")
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(meta, fh)
    return path


def read_capture_meta(trace_dir: str) -> Dict[str, Any]:
    path = os.path.join(trace_dir, "capture_meta.json")
    if not os.path.exists(path):
        return {}
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return {}


@dataclass
class _Event:
    """One leaf op occurrence on one device timeline (absolute ps)."""

    name: str
    t0: int
    t1: int


@dataclass
class ParsedTrace:
    """Leaf device-op events from one xplane, per device timeline.

    ``timelines`` maps a device key (plane name, or plane:line for the CPU
    client threads) to its time-sorted leaf events. ``totals``/``counts``
    aggregate durations (seconds) and occurrence counts per op name across
    all timelines. ``overlap_measurable`` is True when any two leaf events
    on the SAME timeline overlap in time — i.e. the runtime can actually
    run a collective under compute; on a serialized executor the measured
    overlap fraction would read 0.0 for a reason the runtime, not the
    program, chose.
    """

    timelines: Dict[str, List[_Event]] = field(default_factory=dict)
    totals: Dict[str, float] = field(default_factory=dict)
    counts: Dict[str, int] = field(default_factory=dict)
    plane: str = ""
    meta: Dict[str, Any] = field(default_factory=dict)

    @property
    def n_timelines(self) -> int:
        return max(len(self.timelines), 1)

    @property
    def overlap_measurable(self) -> bool:
        for evs in self.timelines.values():
            last_end = 0
            for e in evs:
                if e.t0 < last_end:
                    return True
                last_end = max(last_end, e.t1)
        return False

    def total_device_s(self) -> float:
        return sum(self.totals.values())


def parse_trace(trace_dir: str) -> ParsedTrace:
    """Parse a ``jax.profiler`` trace dir into per-device leaf op events.

    Accelerator traces read the ``/device:*`` planes' leaf ``"XLA Ops"``
    line (containers and the async-copy line are skipped — they
    double-count); CPU traces read the ``/host:CPU`` plane's ``tf_XLA*``
    client-thread lines with the executor frames skipped. Every event is
    keyed by its HLO instruction name (leading ``%`` stripped) — the join
    key into the compiled program's text.
    """
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    xs = xplane_pb2.XSpace()
    with open(find_xplane(trace_dir), "rb") as fh:
        xs.ParseFromString(fh.read())

    out = ParsedTrace(meta=read_capture_meta(trace_dir))

    def add_line(key: str, line, ev_md) -> None:
        evs: List[_Event] = []
        for ev in line.events:
            raw = ev_md[ev.metadata_id].name
            if any(raw.startswith(p) for p in _FRAME_PREFIXES):
                continue
            if _CONTAINER_RE.match(raw):
                continue
            name = raw.lstrip("%")
            t0 = line.timestamp_ns * 1000 + ev.offset_ps
            evs.append(_Event(name=name, t0=t0, t1=t0 + ev.duration_ps))
            out.totals[name] = (out.totals.get(name, 0.0)
                                + ev.duration_ps / 1e12)
            out.counts[name] = out.counts.get(name, 0) + 1
        if evs:
            evs.sort(key=lambda e: (e.t0, e.t1))
            out.timelines[key] = evs

    device_planes = [p for p in xs.planes if p.name.startswith("/device:")]
    if device_planes:
        out.plane = device_planes[0].name
        for plane in device_planes:
            # Leaf op line ONLY: the step/module containers and the async
            # copy line double-count wall time (profile_ops guard).
            for line in plane.lines:
                if line.name == "XLA Ops":
                    add_line(plane.name, line, plane.event_metadata)
        if not out.timelines:
            raise RuntimeError(
                f"no 'XLA Ops' line in device planes "
                f"({[ln.name for p in device_planes for ln in p.lines]})")
        return out

    host = [p for p in xs.planes if p.name == "/host:CPU"]
    if not host:
        raise RuntimeError(
            f"no device plane and no /host:CPU plane in trace "
            f"({[p.name for p in xs.planes]})")
    out.plane = host[0].name
    for line in host[0].lines:
        if line.name.startswith("tf_XLA"):
            add_line(f"{host[0].name}:{line.name}", line,
                     host[0].event_metadata)
    if not out.timelines:
        raise RuntimeError(
            "CPU trace carries no tf_XLA* client-thread lines — was a "
            "program actually executed inside the capture?")
    return out


# ----------------------------------------------------- category table (CLI)
def category_table(parsed: ParsedTrace, window: int,
                   top: int = 0) -> Dict[str, Any]:
    """The profile_ops.py per-kernel-category table, computed from a parsed
    trace: per-step ms by compute category (collectives get their kind as
    the category) plus optionally the N largest individual kernels."""
    agg: Dict[str, float] = {}
    cnt: Dict[str, int] = {}
    for name, secs in parsed.totals.items():
        kind = _collective_kind(name)
        label = kind if kind else _category_of(name)
        if label is None:
            continue
        agg[label] = agg.get(label, 0.0) + secs
        cnt[label] = cnt.get(label, 0) + parsed.counts[name]
    total = sum(agg.values())
    denom = max(window, 1) * parsed.n_timelines
    rows = [
        {
            "category": label,
            "ms_per_step": round(agg[label] * 1e3 / denom, 3),
            "pct": round(100 * agg[label] / max(total, 1e-12), 1),
            "kernels": cnt[label],
        }
        for label in sorted(agg, key=agg.get, reverse=True)
    ]
    out = {
        "total_ms_per_step": round(total * 1e3 / denom, 2),
        "rows": rows,
        "n_timelines": parsed.n_timelines,
    }
    if top:
        out["top_ops"] = [
            {"name": n[:140],
             "ms_per_step": round(parsed.totals[n] * 1e3 / denom, 4)}
            for n in sorted(parsed.totals, key=parsed.totals.get,
                            reverse=True)[:top]
        ]
    return out


# ------------------------------------------------------- HLO scope joining
_DEF_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([A-Za-z0-9_.-]+)\s*=")
_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')
_BUCKET_SCOPE_RE = re.compile(
    re.escape(GRADSYNC_BUCKET_SCOPE) + r"(\d+)")


def hlo_scope_index(hlo_text: str) -> Dict[str, str]:
    """Instruction name → ``op_name`` metadata scope path, for every def
    line of a compiled program dump. The named scopes the lowering pins
    (``gradsync.bucket_{i}``, ``zero1.*`` — kernel/bucketing.py) ride this
    metadata; the measured events join through it."""
    index: Dict[str, str] = {}
    for line in hlo_text.splitlines():
        m = _DEF_RE.match(line)
        if not m:
            continue
        om = _OP_NAME_RE.search(line)
        index[m.group(1)] = om.group(1) if om else ""
    return index


# ------------------------------------------------------------- the report
@dataclass
class MeasuredOp:
    """One measured op, joined (or not) to the plan."""

    name: str                       # HLO instruction name
    kind: str = ""                  # collective kind, "" for compute
    category: str = ""              # compute category / aux label
    scope: str = ""                 # op_name metadata scope path
    seconds_per_step: float = 0.0   # per device timeline
    count: int = 0
    payload_elements: int = 0       # largest array touched (collectives)
    payload_bytes: int = 0
    bucket: Optional[int] = None    # gradsync bucket (scope join)
    vars: Tuple[str, ...] = ()      # plan vars this op syncs
    overlap_fraction: Optional[float] = None   # measured hidden fraction
    matched: bool = False

    def to_json(self) -> Dict[str, Any]:
        return {
            "name": self.name, "kind": self.kind,
            "category": self.category, "scope": self.scope,
            "seconds_per_step": self.seconds_per_step, "count": self.count,
            "payload_elements": self.payload_elements,
            "payload_bytes": self.payload_bytes,
            "bucket": self.bucket, "vars": list(self.vars),
            "overlap_fraction": self.overlap_fraction,
            "matched": self.matched,
        }

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "MeasuredOp":
        return cls(
            name=d["name"], kind=d.get("kind", ""),
            category=d.get("category", ""), scope=d.get("scope", ""),
            seconds_per_step=float(d.get("seconds_per_step", 0.0)),
            count=int(d.get("count", 0)),
            payload_elements=int(d.get("payload_elements", 0)),
            payload_bytes=int(d.get("payload_bytes", 0)),
            bucket=d.get("bucket"), vars=tuple(d.get("vars", ())),
            overlap_fraction=d.get("overlap_fraction"),
            matched=bool(d.get("matched", False)),
        )


@dataclass
class BucketWire:
    """One backward-overlap bucket's measured wire."""

    bucket: int
    vars: Tuple[str, ...] = ()
    measured_s_per_step: float = 0.0
    promised_bytes: int = 0         # full-payload sum of the bucket's vars
    measured_payload_bytes: int = 0  # shard-view payload the ops carried
    overlap_fraction: float = 0.0   # measured hidden fraction [0, 1]
    exposed_s_per_step: float = 0.0

    def to_json(self) -> Dict[str, Any]:
        return {
            "bucket": self.bucket, "vars": list(self.vars),
            "measured_s_per_step": self.measured_s_per_step,
            "promised_bytes": self.promised_bytes,
            "measured_payload_bytes": self.measured_payload_bytes,
            "overlap_fraction": self.overlap_fraction,
            "exposed_s_per_step": self.exposed_s_per_step,
        }

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "BucketWire":
        return cls(
            bucket=int(d["bucket"]), vars=tuple(d.get("vars", ())),
            measured_s_per_step=float(d.get("measured_s_per_step", 0.0)),
            promised_bytes=int(d.get("promised_bytes", 0)),
            measured_payload_bytes=int(d.get("measured_payload_bytes", 0)),
            overlap_fraction=float(d.get("overlap_fraction", 0.0)),
            exposed_s_per_step=float(d.get("exposed_s_per_step", 0.0)),
        )


@dataclass
class MeasuredWire:
    """The measured side of the planned → priced → measured loop.

    Per-collective measured seconds joined to the plan's promised wire,
    per-bucket overlap fractions, compute-category remainder, and the
    roll-ups every consumer reads: ``wire_s_per_step`` (all collective
    time), ``exposed_wire_s_per_step`` (the part NOT covered by concurrent
    same-device compute) and ``exposed_comm_fraction`` (exposed wire over
    total device step time) — the measured replacement for the
    StepProfiler's roofline-residue estimate.
    """

    program: str = ""
    window: int = 1
    n_devices: int = 1
    overlap_measurable: bool = False
    device_total_s_per_step: float = 0.0
    wire_s_per_step: float = 0.0
    exposed_wire_s_per_step: float = 0.0
    ops: List[MeasuredOp] = field(default_factory=list)
    buckets: List[BucketWire] = field(default_factory=list)
    categories: Dict[str, float] = field(default_factory=dict)
    # Promised-wire kinds (per var) with no matching measured op — the
    # SLT002 input; [(var, rendering, op_kind), ...].
    unobserved: List[Tuple[str, str, str]] = field(default_factory=list)
    # Per-var measured-vs-promised payload rows the explain table renders.
    var_table: List[Dict[str, Any]] = field(default_factory=list)

    # ------------------------------------------------------------- queries
    @property
    def collectives(self) -> List[MeasuredOp]:
        return [o for o in self.ops if o.kind]

    @property
    def exposed_comm_fraction(self) -> Optional[float]:
        if self.device_total_s_per_step <= 0:
            return None
        return self.exposed_wire_s_per_step / self.device_total_s_per_step

    @property
    def unattributed_large(self) -> List[MeasuredOp]:
        """Measured rows attribution failed on that are too big to wave
        away: unmatched collectives above the aux-reduction allowance, and
        uncategorized compute, each above LARGE_FRACTION of device time."""
        floor = LARGE_FRACTION * max(self.device_total_s_per_step, 1e-12)
        out = []
        for o in self.ops:
            if o.seconds_per_step < floor:
                continue
            if o.kind and not o.matched and \
                    o.payload_elements > AUX_REDUCTION_MAX_ELEMENTS:
                out.append(o)
            elif not o.kind and o.category == "other":
                out.append(o)
        return out

    def bucket_summed_exposed_fraction(self) -> Optional[float]:
        """Step-level exposed-comm fraction re-derived from the per-bucket
        rows plus the unbucketed collectives — must agree with
        :attr:`exposed_comm_fraction` (the consistency the tests pin)."""
        if self.device_total_s_per_step <= 0:
            return None
        exposed = sum(b.exposed_s_per_step for b in self.buckets)
        for o in self.collectives:
            if o.bucket is None:
                exposed += o.seconds_per_step * (
                    1.0 - (o.overlap_fraction or 0.0))
        return exposed / self.device_total_s_per_step

    def calibration_components(self) -> Dict[str, float]:
        """Measured seconds per plan/calibrate.py component, from the join:
        ``overlap_s`` ← bucketed grad collectives (their full measured
        time — the component the cost model prices as overlappable),
        ``gather_s`` ← zero1 param re-gathers, ``comm_s`` ← every other
        matched grad collective. Components a trace cannot attribute
        (update/latency/act) are absent, not zero."""
        comm = gather = overlap = 0.0
        for o in self.collectives:
            if not o.matched:
                continue
            if o.bucket is not None:
                overlap += o.seconds_per_step
            elif o.kind == "all-gather" and (
                    ZERO1_ALL_GATHER_SCOPE in o.scope or o.vars):
                gather += o.seconds_per_step
            else:
                comm += o.seconds_per_step
        out: Dict[str, float] = {}
        if overlap:
            # The overlap_s coefficient is the measured EXPOSED fraction:
            # report the exposed seconds so Σmeasured/Σpredicted fits it.
            exposed = sum(b.exposed_s_per_step for b in self.buckets)
            out["overlap_s"] = exposed if self.overlap_measurable else overlap
        if gather:
            out["gather_s"] = gather
        if comm:
            out["comm_s"] = comm
        return out

    # -------------------------------------------------------------- serde
    def summary(self) -> Dict[str, Any]:
        """Compact roll-up for JSON lines / recorder events."""
        return {
            "program": self.program,
            "window": self.window,
            "n_devices": self.n_devices,
            "device_ms_per_step": round(
                self.device_total_s_per_step * 1e3, 4),
            "wire_ms_per_step": round(self.wire_s_per_step * 1e3, 4),
            "exposed_comm_fraction": self.exposed_comm_fraction,
            "overlap_measurable": self.overlap_measurable,
            "n_collectives": len(self.collectives),
            "n_matched": sum(1 for o in self.collectives if o.matched),
            "n_buckets": len(self.buckets),
            "bucket_overlap": {
                str(b.bucket): round(b.overlap_fraction, 4)
                for b in self.buckets},
            "unattributed_large": len(self.unattributed_large),
            "unobserved": len(self.unobserved),
        }

    def to_json(self) -> Dict[str, Any]:
        return {
            "program": self.program,
            "window": self.window,
            "n_devices": self.n_devices,
            "overlap_measurable": self.overlap_measurable,
            "device_total_s_per_step": self.device_total_s_per_step,
            "wire_s_per_step": self.wire_s_per_step,
            "exposed_wire_s_per_step": self.exposed_wire_s_per_step,
            "ops": [o.to_json() for o in self.ops],
            "buckets": [b.to_json() for b in self.buckets],
            "categories": dict(self.categories),
            "unobserved": [list(u) for u in self.unobserved],
            "var_table": list(self.var_table),
        }

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "MeasuredWire":
        return cls(
            program=d.get("program", ""),
            window=int(d.get("window", 1)),
            n_devices=int(d.get("n_devices", 1)),
            overlap_measurable=bool(d.get("overlap_measurable", False)),
            device_total_s_per_step=float(
                d.get("device_total_s_per_step", 0.0)),
            wire_s_per_step=float(d.get("wire_s_per_step", 0.0)),
            exposed_wire_s_per_step=float(
                d.get("exposed_wire_s_per_step", 0.0)),
            ops=[MeasuredOp.from_json(o) for o in d.get("ops", [])],
            buckets=[BucketWire.from_json(b) for b in d.get("buckets", [])],
            categories=dict(d.get("categories", {})),
            unobserved=[tuple(u) for u in d.get("unobserved", [])],
            var_table=list(d.get("var_table", [])),
        )

    def save(self, path: str) -> str:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(self.to_json(), fh, indent=2, sort_keys=True,
                      default=float)
        return path

    @classmethod
    def load(cls, path: str) -> "MeasuredWire":
        with open(path, "r", encoding="utf-8") as fh:
            return cls.from_json(json.load(fh))

    def describe(self) -> str:
        lines = [
            f"MeasuredWire({self.program or 'program'}: window "
            f"{self.window} x {self.n_devices} device timeline(s), "
            f"{self.device_total_s_per_step * 1e3:.3f} ms/step device, "
            f"wire {self.wire_s_per_step * 1e3:.3f} ms/step, exposed "
            f"{(self.exposed_comm_fraction or 0.0) * 100:.1f}%"
            + ("" if self.overlap_measurable
               else " [overlap not measurable on this runtime]") + ")"
        ]
        for b in self.buckets:
            lines.append(
                f"  bucket {b.bucket}: {b.measured_s_per_step * 1e3:8.4f} "
                f"ms/step  hidden {b.overlap_fraction * 100:5.1f}%  "
                f"promised {b.promised_bytes / 1e6:.3f} MB  "
                f"vars={','.join(b.vars)[:60]}")
        for o in self.collectives:
            tag = "matched" if o.matched else "UNMATCHED"
            lines.append(
                f"  {o.kind:<19s} {o.name:<24s} "
                f"{o.seconds_per_step * 1e3:8.4f} ms/step  {tag}"
                + (f"  bucket={o.bucket}" if o.bucket is not None else "")
                + (f"  vars={','.join(o.vars)[:48]}" if o.vars else ""))
        return "\n".join(lines)


# ------------------------------------------------------------------ overlap
def _merge_intervals(ivs: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    out: List[Tuple[int, int]] = []
    for a, b in sorted(ivs):
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def _coverage(t0: int, t1: int, merged: List[Tuple[int, int]]) -> float:
    """Fraction of [t0, t1] covered by the merged interval union."""
    if t1 <= t0:
        return 0.0
    covered = 0
    for a, b in merged:
        lo, hi = max(a, t0), min(b, t1)
        if hi > lo:
            covered += hi - lo
        if a >= t1:
            break
    return covered / (t1 - t0)


def _overlap_fractions(parsed: ParsedTrace) -> Dict[str, float]:
    """Duration-weighted hidden fraction per collective op name: how much
    of its occurrences' intervals concurrent NON-collective work on the
    same device timeline covered. 0.0 everywhere on serialized runtimes
    (see :attr:`ParsedTrace.overlap_measurable`)."""
    hidden_ps: Dict[str, float] = {}
    total_ps: Dict[str, float] = {}
    for evs in parsed.timelines.values():
        compute = _merge_intervals(
            [(e.t0, e.t1) for e in evs if not _collective_kind(e.name)])
        for e in evs:
            if not _collective_kind(e.name):
                continue
            dur = e.t1 - e.t0
            total_ps[e.name] = total_ps.get(e.name, 0.0) + dur
            hidden_ps[e.name] = (hidden_ps.get(e.name, 0.0)
                                 + _coverage(e.t0, e.t1, compute) * dur)
    return {n: hidden_ps.get(n, 0.0) / t
            for n, t in total_ps.items() if t > 0}


# --------------------------------------------------------------------- join
def join_to_plan(parsed: ParsedTrace, hlo_text: str, plan,
                 window: int, program: str = "") -> MeasuredWire:
    """Join measured leaf ops to a :class:`ShardingPlan`'s promised wire.

    Three join paths, in precedence order:

    1. **scope**: the compiled program's ``op_name`` metadata carries the
       pinned named scopes — ``gradsync.bucket_{i}`` resolves an op to a
       backward-overlap bucket (and the bucket's variables),
       ``zero1.reduce_scatter_grads`` / ``zero1.all_gather_params`` to the
       shard_update vars;
    2. **payload**: a collective whose payload equals a VarWire's
       storage/bucket elements under one mesh-axis shard division (the
       wire-conformance candidate rule, shared via
       ``analysis.passes.payload_candidates``) joins to that var;
    3. **category**: everything else is compute, bucketed by
       :data:`CATEGORIES`; unmatched small collectives are aux/loss
       reductions.
    """
    from autodist_tpu.analysis.passes import payload_candidates

    inventory = CollectiveInventory.from_hlo(hlo_text, program=program)
    inv_by_name = {c.name: c for c in inventory.collectives if c.name}
    scopes = hlo_scope_index(hlo_text)
    wires = plan.promised_wire()
    trainable = {n: w for n, w in wires.items()
                 if w.rendering != "nontrainable"}
    mesh_sizes = dict(zip(plan.mesh.axis_names, plan.mesh.devices.shape))
    assignment = plan.bucket_assignment()
    bucket_vars = {i: tuple(names) for i, names in enumerate(assignment)}
    su_vars = tuple(n for n, w in trainable.items() if w.shard_update)
    overlap = _overlap_fractions(parsed)
    denom = max(window, 1) * parsed.n_timelines

    report = MeasuredWire(
        program=program, window=max(window, 1),
        n_devices=parsed.n_timelines,
        overlap_measurable=parsed.overlap_measurable,
        device_total_s_per_step=parsed.total_device_s() / denom,
    )

    matched_var_kinds: set = set()
    for name in sorted(parsed.totals, key=parsed.totals.get, reverse=True):
        secs = parsed.totals[name] / denom
        count = parsed.counts[name]
        kind = _collective_kind(name)
        scope = scopes.get(name, "")
        if not kind:
            label = _category_of(name)
            if label is None:
                continue
            report.categories[label] = (
                report.categories.get(label, 0.0) + secs)
            # Only large compute ops get their own row; the category table
            # carries the rest (keeps the report O(categories), not O(ops)).
            if label == "other" or secs >= LARGE_FRACTION * max(
                    report.device_total_s_per_step, 1e-12):
                report.ops.append(MeasuredOp(
                    name=name, category=label, scope=scope,
                    seconds_per_step=secs, count=count, matched=True))
            continue

        inv = inv_by_name.get(name)
        payload = inv.max_payload_elements if inv is not None else 0
        payload_bytes = inv.result_bytes if inv is not None else 0
        op = MeasuredOp(
            name=name, kind=kind, scope=scope, seconds_per_step=secs,
            count=count, payload_elements=payload,
            payload_bytes=payload_bytes,
            overlap_fraction=overlap.get(name),
        )
        # Path 1: named-scope join (the bucket / zero1 keys).
        bm = _BUCKET_SCOPE_RE.search(scope)
        if bm is not None:
            op.bucket = int(bm.group(1))
            op.vars = bucket_vars.get(op.bucket, ())
            op.matched = op.bucket in bucket_vars
        elif ZERO1_REDUCE_SCATTER_SCOPE in scope or \
                ZERO1_ALL_GATHER_SCOPE in scope:
            op.vars = su_vars
            op.matched = bool(su_vars)
        # Path 2: payload match against the promised wire.
        if not op.matched and payload:
            hits = []
            for vn, w in trainable.items():
                if kind not in w.allow and kind not in w.require:
                    continue
                if payload in payload_candidates(w, mesh_sizes):
                    hits.append(vn)
            if hits:
                op.vars = tuple(hits)
                op.matched = True
        # Small unmatched collectives: metric/aux reductions (scalar loss
        # psum, aux means) — attributed as such, never flagged.
        if not op.matched and payload <= AUX_REDUCTION_MAX_ELEMENTS:
            op.category = "aux/loss reductions"
        report.ops.append(op)
        for vn in op.vars:
            matched_var_kinds.add((vn, kind))

    # ------------------------------------------------------------ roll-ups
    report.wire_s_per_step = sum(
        o.seconds_per_step for o in report.collectives)
    report.exposed_wire_s_per_step = sum(
        o.seconds_per_step * (1.0 - (o.overlap_fraction or 0.0))
        for o in report.collectives)

    per_bucket: Dict[int, List[MeasuredOp]] = {}
    for o in report.collectives:
        if o.bucket is not None:
            per_bucket.setdefault(o.bucket, []).append(o)
    for bi in sorted(per_bucket):
        ops = per_bucket[bi]
        total = sum(o.seconds_per_step for o in ops)
        hidden = sum(
            o.seconds_per_step * (o.overlap_fraction or 0.0) for o in ops)
        promised = sum(
            trainable[v].storage_bytes for v in bucket_vars.get(bi, ())
            if v in trainable)
        report.buckets.append(BucketWire(
            bucket=bi, vars=bucket_vars.get(bi, ()),
            measured_s_per_step=total,
            promised_bytes=int(promised),
            measured_payload_bytes=sum(o.payload_bytes for o in ops),
            overlap_fraction=hidden / total if total > 0 else 0.0,
            exposed_s_per_step=total - hidden,
        ))

    # Promised-but-unobserved kinds (the SLT002 input): every require'd op
    # kind of every trainable var must have a measured op joined to it.
    for vn, w in sorted(trainable.items()):
        for kind in w.require:
            if (vn, kind) not in matched_var_kinds:
                report.unobserved.append((vn, w.rendering, kind))

    # Per-var measured-vs-promised table (explain --wire-measured rows).
    per_var_s: Dict[str, float] = {}
    per_var_bytes: Dict[str, int] = {}
    for o in report.collectives:
        if not o.vars:
            continue
        share = o.seconds_per_step / len(o.vars)
        for vn in o.vars:
            per_var_s[vn] = per_var_s.get(vn, 0.0) + share
            per_var_bytes[vn] = (per_var_bytes.get(vn, 0)
                                 + o.payload_bytes // len(o.vars))
    bucket_of: Dict[str, int] = {}
    for bi, names in bucket_vars.items():
        for vn in names:
            bucket_of[vn] = bi
    for vn, w in sorted(trainable.items()):
        elems = int(w.storage_elements)
        row = {
            "var": vn,
            "rendering": w.rendering,
            "promised_bytes": int(w.storage_bytes),
            "measured_s_per_step": per_var_s.get(vn),
            "measured_payload_bytes": per_var_bytes.get(vn),
            "bucket": bucket_of.get(vn),
            "storage_elements": elems,
        }
        report.var_table.append(row)
    return report


# ------------------------------------------------------------ capture + run
def capture_trace(step, state, batch, num_steps: int,
                  trace_dir: Optional[str] = None, stacked: bool = False):
    """Capture a ``jax.profiler`` trace of one windowed ``step.run``.

    Warms the window program first (compile outside the capture), then
    traces exactly one window with the one-end-barrier discipline. Returns
    ``(trace_dir, new_state, metrics)`` — ``run`` may donate ``state``.
    """
    import numpy as np

    from autodist_tpu.utils import tracing

    def barrier(metrics):
        loss = metrics.get("loss") if isinstance(metrics, dict) else None
        if loss is not None:
            float(np.asarray(loss).ravel()[-1])
        else:
            import jax

            jax.block_until_ready(metrics)

    state, metrics = step.run(state, batch, num_steps, stacked=stacked)
    barrier(metrics)
    with tracing.trace("attrib", trace_dir=trace_dir) as td:
        state, metrics = step.run(state, batch, num_steps, stacked=stacked)
        barrier(metrics)
    write_capture_meta(td, window=int(num_steps), stacked=bool(stacked))
    return td, state, metrics


def windowed_hlo(step, state, batch, num_steps: int,
                 stacked: bool = False) -> str:
    """Post-optimization HLO text of the SAME window program a capture
    runs — the text whose instruction names the trace events carry.
    Shapes only (eval_shape): nothing executes, donated buffers untouched.
    Served from the analysis package's compiled-program cache
    (``analysis/inventory.py::compiled_window``) so an ``--attrib`` +
    ``--lint`` run lowers the window program once."""
    from autodist_tpu.analysis import compiled_window

    return compiled_window(step, state, batch, num_steps, stacked)[1]


def attribute(step, state, batch, num_steps: int = 4,
              trace_dir: Optional[str] = None, stacked: bool = False,
              program: str = "train_window"):
    """Capture + parse + join, end to end, for a
    :class:`~autodist_tpu.kernel.lowering.DistributedTrainStep`.

    Returns ``(MeasuredWire, new_state)`` (the window program may donate
    ``state``). ONE XLA compile serves both halves: the AOT-compiled
    window program yields the post-optimization text (the instruction-name
    → scope map, so the join can never drift from what actually ran) AND
    executes the warmup + captured windows directly — on a big TPU model
    a second compile would eat minutes of the watchdog budget
    ``bench.py --attrib`` exists to survive. If this toolchain's AOT
    callable rejects the live arguments, execution falls back to
    ``step.run`` (a second, jit-cached compile) and the text stays from
    the AOT object — same program key, same instruction names.
    """
    import jax
    import numpy as np

    from autodist_tpu.analysis import compiled_window
    from autodist_tpu.utils import tracing

    compiled, hlo = compiled_window(step, state, batch, num_steps, stacked)

    def barrier(metrics):
        loss = metrics.get("loss") if isinstance(metrics, dict) else None
        if loss is not None:
            float(np.asarray(loss).ravel()[-1])
        else:
            jax.block_until_ready(metrics)

    def via_run(st):
        return step.run(st, batch, num_steps, stacked=stacked)

    def via_compiled(st):
        return compiled(st, batch)

    runner = via_compiled
    try:
        state, metrics = runner(state)  # warmup: page in, settle caches
    except (TypeError, ValueError) as e:
        # AOT arg validation rejected the live layout (raises before any
        # donation): run through the jit path instead.
        logging.debug("AOT window call rejected (%s); using step.run", e)
        runner = via_run
        state, metrics = runner(state)
    barrier(metrics)
    with tracing.trace("attrib", trace_dir=trace_dir) as td:
        state, metrics = runner(state)
        barrier(metrics)
    write_capture_meta(td, window=int(num_steps), stacked=bool(stacked))
    parsed = parse_trace(td)
    report = join_to_plan(parsed, hlo, step.plan, num_steps, program=program)
    logging.info("measured-wire attribution: %s",
                 json.dumps(report.summary(), default=float))
    return report, state
