"""Postmortem doctor: stitch a dead run's artifacts into one timeline and
classify the death.

``python -m autodist_tpu.obs doctor <ft-base-dir>`` reads everything a run
leaves behind — flight-record segments (``flight/``), heartbeat files
(``heartbeats/``), snapshot MANIFESTs (``snapshots/``), launcher doctor
bundles (``doctor/``, written by the hang watchdog before it SIGTERMs a
silent fleet), and span part-files (``AUTODIST_TRACE_OUT`` dir or
``<base>/traces``) — merges them into a time-ordered timeline, and returns
a **verdict** with the evidence lines that support it:

======== =============== =================================================
Code     Verdict         Typical cause
======== =============== =================================================
DOC000   clean           ``run_end ok`` recorded; nothing anomalous after
DOC001   nan             sentry SNT001/SNT002, or non-finite loss in tail
DOC002   oom             error event matching RESOURCE_EXHAUSTED / OOM
DOC003   wedge           hang bundle, or heartbeats+records stop
                         mid-stream with no terminal event
DOC004   preemption      SIGTERM preempt event (ft snapshot hook)
DOC005   straggler       hang/abnormal end with SNT006 straggler findings
DOC006   crash           error event that matches no narrower class
DOC007   pool_exhaustion serve died amid KV page-pool pressure: an error
                         carrying the pool-exhausted signature, or the
                         record stream ending abruptly inside a
                         ``pool_pressure`` window
DOC008   failover_storm  replica flap: repeated DEAD transitions in
                         the router journal + flight segments of an
                         abnormal end (reroutes are evidence, not the
                         trigger — one kill reroutes many)
DOC999   unknown         not enough evidence to classify
======== =============== =================================================

Classification is precedence-ordered (strongest causal evidence first):
oom > nan > pool-exhaustion (typed pool-exhausted error) > failover-storm
> hang-bundle (straggler when SNT006 rode along, wedge otherwise) >
preemption > crash > straggler > clean > abrupt-end wedge (pool-exhaustion
when the stream dies inside a pressure window) > unknown. A
watchdog-killed fleet therefore reads as *wedge* even though the chief
also caught SIGTERM — the bundle is the stronger witness; a single
replica death with an orderly failover stays *crash* (DOC006) — the storm
verdict needs repeated flap, never one supervised kill.

The module never raises on malformed artifacts (a postmortem runs over
exactly the files a crash tore) and never needs a device: ``bench.py``
invokes the CLI as a watchdogged subprocess on every abnormal exit so a
BENCH round can no longer end ``parsed: null`` with no classification.
"""
from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from autodist_tpu.obs.recorder import flight_dir, read_records
from autodist_tpu.utils import logging

__all__ = ["Diagnosis", "Evidence", "VERDICT_CODES", "diagnose",
           "render_text"]

#: verdict -> stable greppable code (the docs/observability.md table).
VERDICT_CODES: Dict[str, str] = {
    "clean": "DOC000",
    "nan": "DOC001",
    "oom": "DOC002",
    "wedge": "DOC003",
    "preemption": "DOC004",
    "straggler": "DOC005",
    "crash": "DOC006",
    "pool_exhaustion": "DOC007",
    "failover_storm": "DOC008",
    "unknown": "DOC999",
}

_OOM_RE = re.compile(
    r"RESOURCE[_ ]EXHAUSTED|out of memory|\bOOM\b|allocat\w* failed",
    re.IGNORECASE)
# DOC007: the page-pool-exhausted signature the serve admission path and
# the batcher's pressure/shed events carry (serve/engine.py prose).
# Deliberately narrow — an error merely MENTIONING the pool (accounting
# bug, double free) is a crash, not an exhaustion collapse.
_POOL_RE = re.compile(r"page.pool exhausted|pool exhaust", re.IGNORECASE)
# DOC008 threshold: a storm needs REPEATED death/flap, RECENTLY. Reroute
# count is deliberately NOT a trigger — ONE supervised kill reroutes
# every in-flight request (the chaos replica_death class must stay
# DOC006) — and deaths outside the storm window are history, not the
# cause of THIS death: two recovered single failovers days apart must
# not reclassify a later preemption or crash as a storm.
_STORM_DEAD_TRANSITIONS = 2
_STORM_WINDOW_S = 600.0

# ft directory layout (FTConfig.resolved's literals — mirrored here so the
# doctor stays importable without the ft subsystem's jax-adjacent deps).
_HEARTBEAT_SUBDIR = "heartbeats"
_SNAPSHOT_SUBDIR = "snapshots"
_BUNDLE_SUBDIR = "doctor"
_TRACE_SUBDIR = "traces"


@dataclass
class Evidence:
    """One artifact line supporting the verdict."""

    source: str        # flight | heartbeat | snapshot | bundle | span
    t: float
    detail: str

    def to_dict(self) -> dict:
        return {"source": self.source, "t": self.t, "detail": self.detail}


@dataclass
class Diagnosis:
    verdict: str
    code: str
    evidence: List[Evidence] = field(default_factory=list)
    timeline: List[Dict[str, Any]] = field(default_factory=list)
    stats: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self, timeline_tail: int = 40) -> dict:
        return {
            "verdict": self.verdict,
            "code": self.code,
            "evidence": [e.to_dict() for e in self.evidence[:16]],
            "stats": self.stats,
            "timeline_tail": self.timeline[-timeline_tail:],
        }


# ----------------------------------------------------------------- readers
def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else None
    except (OSError, ValueError):
        return None


def _read_heartbeats(hb_dir: str) -> List[Dict[str, Any]]:
    out = []
    try:
        names = sorted(os.listdir(hb_dir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("hb-") and name.endswith(".json")):
            continue
        doc = _read_json(os.path.join(hb_dir, name))
        if doc is None:
            continue
        try:
            pid = int(name[3:-5])
        except ValueError:
            continue
        out.append({"t": float(doc.get("time", 0.0)), "source": "heartbeat",
                    "kind": "heartbeat", "process_id": pid,
                    "step": doc.get("step")})
    return out


def _read_snapshots(snap_dir: str) -> List[Dict[str, Any]]:
    out = []
    try:
        names = sorted(os.listdir(snap_dir))
    except OSError:
        return out
    for name in names:
        mpath = os.path.join(snap_dir, name, "MANIFEST.json")
        doc = _read_json(mpath)
        if doc is None:
            continue
        try:
            t = os.path.getmtime(mpath)
        except OSError:
            t = 0.0
        out.append({"t": t, "source": "snapshot", "kind": "snapshot_manifest",
                    "step": doc.get("step"), "dir": name})
    return out


def _read_bundles(bundle_dir: str) -> List[Dict[str, Any]]:
    out = []
    try:
        names = sorted(os.listdir(bundle_dir))
    except OSError:
        return out
    for name in names:
        if not name.endswith(".json"):
            continue
        doc = _read_json(os.path.join(bundle_dir, name))
        if doc is None:
            continue
        out.append({"t": float(doc.get("written_at", 0.0)), "source": "bundle",
                    "kind": doc.get("reason", "bundle"), "file": name,
                    "bundle": doc})
    return out


def _read_spans(trace_dir: str, limit: int = 200) -> List[Dict[str, Any]]:
    """Newest span events from chrome-trace part files (obs/spans.py) —
    context for the timeline, rarely verdict-deciding on their own."""
    out: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(trace_dir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("obs-part-") and name.endswith(".json")):
            continue
        doc = _read_json(os.path.join(trace_dir, name))
        if doc is None:
            continue
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") != "X":
                continue
            out.append({
                "t": float(ev.get("ts", 0.0)) / 1e6, "source": "span",
                "kind": "span", "name": ev.get("name"),
                "dur_s": float(ev.get("dur", 0.0)) / 1e6,
                "process_id": ev.get("args", {}).get("process"),
            })
    out.sort(key=lambda e: e["t"])
    return out[-limit:]


def _read_pilot_decisions(base_dir: str) -> List[Dict[str, Any]]:
    """Autopilot decision journal (``<base>/pilot/decisions.jsonl``) —
    every retune the controller attempted, with its trigger evidence and
    canary verdict, so a postmortem reads knob changes next to the sentry
    findings that caused them (docs/autopilot.md). Read-only: the pilot
    package is the ONE writer (check_patterns rule 11)."""
    from autodist_tpu.pilot.journal import decisions_path, read_decisions

    out: List[Dict[str, Any]] = []
    for rec in read_decisions(decisions_path(base_dir)):
        entry: Dict[str, Any] = {
            "t": rec.t, "source": "pilot", "kind": "decision",
            "decision_id": rec.decision_id, "trigger": rec.trigger,
            "action": rec.action, "verdict": rec.verdict,
        }
        if rec.code:
            entry["code"] = rec.code
        if rec.note:
            entry["note"] = rec.note
        out.append(entry)
    return out


# ------------------------------------------------------------ classification
def diagnose(base_dir: str, trace_out: str = "",
             tail_steps: int = 16) -> Diagnosis:
    """Classify whatever died under ``base_dir`` (an ft base: the dir
    ``AUTODIST_FT_DIR`` pointed at). Missing subdirs are just absent
    evidence, never errors."""
    records = read_records(flight_dir(base_dir))
    flight = [{"source": "flight", **r} for r in records]
    heartbeats = _read_heartbeats(os.path.join(base_dir, _HEARTBEAT_SUBDIR))
    snapshots = _read_snapshots(os.path.join(base_dir, _SNAPSHOT_SUBDIR))
    bundles = _read_bundles(os.path.join(base_dir, _BUNDLE_SUBDIR))
    spans = _read_spans(trace_out or os.path.join(base_dir, _TRACE_SUBDIR))
    pilot = _read_pilot_decisions(base_dir)

    timeline = sorted(
        flight + heartbeats + snapshots + bundles + spans + pilot,
        key=lambda e: float(e.get("t", 0.0)))
    stats: Dict[str, Any] = {
        "flight_records": len(flight),
        "heartbeats": len(heartbeats),
        "snapshots": len(snapshots),
        "bundles": len(bundles),
        "spans": len(spans),
        "pilot_decisions": len(pilot),
    }
    steps = [r for r in records if r.get("kind") == "step"]
    if steps:
        stats["first_step_t"] = steps[0].get("t")
        stats["last_step_t"] = steps[-1].get("t")
    snap_steps = [s.get("step") for s in snapshots
                  if isinstance(s.get("step"), int)]
    if snap_steps:
        stats["last_snapshot_step"] = max(snap_steps)

    ev: List[Evidence] = []

    def _ev(source: str, t: Any, detail: str) -> Evidence:
        e = Evidence(source=source, t=float(t or 0.0), detail=detail)
        ev.append(e)
        return e

    def _done(verdict: str) -> Diagnosis:
        stats["verdict"] = verdict
        return Diagnosis(verdict=verdict, code=VERDICT_CODES[verdict],
                         evidence=ev, timeline=_compact(timeline),
                         stats=stats)

    # Gather the classifier's raw signals in one pass over the records.
    run_end = [r for r in records if r.get("kind") == "run_end"]
    errors = [r for r in records if r.get("kind") == "error"]
    preempts = [r for r in records if r.get("kind") == "preempt"]
    sentry = [r for r in records if r.get("kind") == "sentry"]
    nan_sentry = [r for r in sentry if r.get("code") in ("SNT001", "SNT002")]
    straggler_sentry = [r for r in sentry if r.get("code") == "SNT006"]
    hang_bundles = [b for b in bundles
                    if b.get("kind") in ("fleet_hung", "hang")]

    def _nonfinite(x) -> bool:
        if isinstance(x, str):
            return x.lower() in ("nan", "inf", "-inf", "infinity", "-infinity")
        try:
            import math
            return x is not None and not math.isfinite(float(x))
        except (TypeError, ValueError):
            return False

    nan_tail = [r for r in steps[-max(1, tail_steps):]
                if _nonfinite(r.get("loss")) or _nonfinite(r.get("grad_norm"))]

    # ---- precedence ladder (module docstring documents the order) -------
    oom_errors = [r for r in errors if _OOM_RE.search(str(r.get("error", "")))]
    if oom_errors:
        r = oom_errors[-1]
        _ev("flight", r.get("t"),
            f"error event matches OOM signature: {str(r.get('error'))[:200]}")
        return _done("oom")

    if nan_sentry or nan_tail:
        for r in nan_sentry[-3:]:
            _ev("flight", r.get("t"),
                f"sentry {r.get('code')}: {str(r.get('message'))[:160]}")
        for r in nan_tail[-3:]:
            _ev("flight", r.get("t"),
                f"step record carries non-finite loss={r.get('loss')!r}")
        return _done("nan")

    # Serving signals (PR: serve-side SLO observability). Raw streams:
    pool_pressure = [r for r in records if r.get("kind") == "pool_pressure"]
    reroutes = [r for r in records if r.get("kind") == "reroute"]
    dead_transitions = [
        r for r in records if r.get("kind") == "replica_transition"
        and str(r.get("new", "")).lower() == "dead"]
    stats["reroutes"] = len(reroutes)
    stats["replica_dead_transitions"] = len(dead_transitions)
    stats["pool_pressure_windows"] = len(pool_pressure)
    clean_end = any(e.get("ok", True) for e in run_end)

    # DOC007 (typed form): the death itself carries the pool-exhausted
    # signature — the pool, not the code path that tripped over it, is
    # the limiter a postmortem should name.
    pool_errors = [r for r in errors
                   if _POOL_RE.search(str(r.get("error", "")))]
    if pool_errors:
        r = pool_errors[-1]
        _ev("flight", r.get("t"),
            f"error event carries the page-pool-exhausted signature: "
            f"{str(r.get('error'))[:200]}")
        for p in pool_pressure[-3:]:
            _ev("flight", p.get("t"),
                f"pool_pressure window: {str(p.get('reason'))[:120]} "
                f"(free_pages={p.get('free_pages')})")
        return _done("pool_exhaustion")

    # DOC008: repeated replica flap on an abnormal end, inside the storm
    # window ending at the last record. One supervised kill with its
    # orderly failover stays crash (DOC006) — however many in-flight
    # requests it rerouted — and old recovered deaths are history.
    last_record_t = float(records[-1].get("t", 0.0)) if records else 0.0
    recent_dead = [r for r in dead_transitions
                   if last_record_t - float(r.get("t", 0.0))
                   <= _STORM_WINDOW_S]
    if not clean_end and len(recent_dead) >= _STORM_DEAD_TRANSITIONS:
        for r in recent_dead[-3:]:
            _ev("flight", r.get("t"),
                f"replica {r.get('replica')} transitioned "
                f"{r.get('old')} -> dead")
        for r in reroutes[-3:]:
            _ev("flight", r.get("t"),
                f"reroute of {r.get('request_id')} after "
                f"{r.get('delivered')} delivered token(s): "
                f"{str(r.get('reason'))[:120]}")
        _ev("flight", recent_dead[-1].get("t"),
            f"failover storm: {len(recent_dead)} DEAD transition(s) inside "
            f"the {_STORM_WINDOW_S:.0f}s window, {len(reroutes)} "
            f"reroute(s), no clean run_end")
        return _done("failover_storm")

    if hang_bundles:
        b = hang_bundles[-1]
        _ev("bundle", b.get("t"),
            f"launcher hang watchdog bundle {b.get('file')}: fleet "
            f"heartbeats went silent (verdict "
            f"{b['bundle'].get('verdict', '?')})")
        for pid, peer in (b["bundle"].get("heartbeats") or {}).items():
            _ev("bundle", peer.get("last_seen", 0.0),
                f"host {pid}: state={peer.get('state')} last beat at "
                f"t={peer.get('last_seen')}")
        if straggler_sentry:
            for r in straggler_sentry[-3:]:
                _ev("flight", r.get("t"),
                    f"sentry SNT006: {str(r.get('message'))[:160]}")
            return _done("straggler")
        return _done("wedge")

    if preempts:
        r = preempts[-1]
        _ev("flight", r.get("t"),
            f"preemption event (SIGTERM snapshot hook), step "
            f"{r.get('step', '?')}")
        return _done("preemption")

    if errors:
        r = errors[-1]
        _ev("flight", r.get("t"),
            f"error event: {str(r.get('error'))[:200]}")
        return _done("crash")

    if straggler_sentry and not clean_end:
        for r in straggler_sentry[-3:]:
            _ev("flight", r.get("t"),
                f"sentry SNT006: {str(r.get('message'))[:160]}")
        return _done("straggler")

    if clean_end:
        r = run_end[-1]
        _ev("flight", r.get("t"), "run_end event recorded (ok=true)")
        return _done("clean")

    if steps or heartbeats:
        # Records exist but simply stop: nothing wrote a terminal event —
        # the signature of a wedge (or an unattributed SIGKILL, which is
        # operationally the same thing: a silent death). A stream that
        # dies INSIDE a page-pool pressure window is the silent form of a
        # pool-exhaustion collapse: name the pool, not "wedge".
        if pool_pressure and records:
            last_t = float(records[-1].get("t", 0.0))
            tail_pressure = [p for p in pool_pressure
                             if last_t - float(p.get("t", 0.0)) <= 30.0]
            if tail_pressure:
                p = tail_pressure[-1]
                _ev("flight", p.get("t"),
                    f"records end abruptly inside a pool_pressure window: "
                    f"{str(p.get('reason'))[:120]} "
                    f"(free_pages={p.get('free_pages')}, "
                    f"queue_depth={p.get('queue_depth')})")
                return _done("pool_exhaustion")
        if steps:
            r = steps[-1]
            _ev("flight", r.get("t"),
                f"flight records end abruptly at t={r.get('t')} with no "
                f"terminal event (last loss={r.get('loss')})")
        for hb in heartbeats[-3:]:
            _ev("heartbeat", hb.get("t"),
                f"host {hb.get('process_id')} last beat at t={hb.get('t')} "
                f"(step {hb.get('step')})")
        return _done("wedge")

    _ev("flight", 0.0, f"no artifacts found under {base_dir}")
    return _done("unknown")


def _compact(timeline: List[Dict[str, Any]],
             max_entries: int = 400) -> List[Dict[str, Any]]:
    """Bound the timeline: keep the head and tail, drop dense middles
    (step records dominate; the interesting part of a postmortem is the
    beginning and the end)."""
    if len(timeline) <= max_entries:
        return timeline
    head = timeline[: max_entries // 4]
    tail = timeline[-(max_entries - len(head)):]
    return head + [{"kind": "elided",
                    "n": len(timeline) - len(head) - len(tail)}] + tail


# --------------------------------------------------------------------- CLI
def render_text(diag: Diagnosis) -> str:
    lines = [f"verdict: {diag.verdict} [{diag.code}]"]
    for k in sorted(diag.stats):
        lines.append(f"  {k}: {diag.stats[k]}")
    lines.append("evidence:")
    if not diag.evidence:
        lines.append("  (none)")
    for e in diag.evidence:
        lines.append(f"  [{e.source} t={e.t:.3f}] {e.detail}")
    return "\n".join(lines)


def run_cli(base_dir: str, as_json: bool = False,
            trace_out: str = "") -> int:
    """The ``python -m autodist_tpu.obs doctor`` body. Exit code 0 for
    clean, 3 for unknown (no evidence), 1 for every classified failure —
    scriptable like shardlint's exit contract."""
    try:
        diag = diagnose(base_dir, trace_out=trace_out)
    except Exception as e:  # noqa: BLE001 - a postmortem must not crash
        logging.warning("doctor failed over %s", base_dir, exc_info=True)
        if as_json:
            print(json.dumps({"verdict": "unknown",
                              "code": VERDICT_CODES["unknown"],
                              "error": f"{type(e).__name__}: {e}"}))
        else:
            print(f"doctor failed: {type(e).__name__}: {e}")
        return 3
    if as_json:
        print(json.dumps(diag.to_dict(), default=str))
    else:
        print(render_text(diag))
    if diag.verdict == "clean":
        return 0
    return 3 if diag.verdict == "unknown" else 1
