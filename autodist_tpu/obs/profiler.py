"""Step profiler: dispatch-gap vs device-compute split, live MFU, roofline.

:class:`StepProfiler` wraps a :class:`~autodist_tpu.kernel.DistributedTrainStep`
(or any object with the same ``run(state, batch, num_steps)`` contract) and
times each windowed run with the one-end-barrier discipline ``bench.py``
established: ``run`` returns as soon as the window program is *dispatched*
(host latency — the dispatch gap), and a single trailing device→host fetch
of the last loss is the only trustworthy barrier on every platform
(``block_until_ready`` returns early through the axon tunnel). Per window:

- ``dispatch_gap_s`` — time for ``run()`` to return (host dispatch, plus
  XLA compile on a window's first execution);
- ``wall_s`` — dispatch → barrier (the whole window);
- ``device_s`` — ``wall_s - dispatch_gap_s``, the device-side residue.

FLOPs and HBM bytes come from the **compiled program's own cost analysis**
(``DistributedTrainStep.window_cost`` → XLA's per-executable numbers), not
an analytical model, so live MFU is measured-over-measured:
``mfu = flops_per_step / (device_s_per_step × peak_flops)``. Roofline
position reuses :mod:`autodist_tpu.utils.roofline`'s time conversion with
the compiled byte counts, and the same bound yields the
``exposed_comm_fraction`` metric — device time beyond the compute/HBM
roofline, i.e. wire (and scheduling) time NOT hidden under compute — the
before/after signal for bucketed backward-overlap gradient sync
(``GraphConfig.bucket_bytes``, docs/performance.md). Compile counts/times ride the step's
``compile_log`` (fresh-program first-call latencies) and the HBM
high-water mark comes from ``device.memory_stats()`` where the platform
exposes one (TPU; None on CPU).

:class:`StepTimer` (plain wall-clock step timing, previously
``utils/tracing.py``) lives here now; the old import path remains as a
compat shim.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from autodist_tpu import metrics as M
from autodist_tpu.obs import recorder as _flight
from autodist_tpu.obs import spans as _spans
from autodist_tpu.utils import logging

__all__ = ["StepProfiler", "StepTimer", "detect_peak_flops"]

# Peak bf16 FLOPs/s per chip by TPU generation (public figures; the same
# table bench.py matches against Device.device_kind, longest key first).
_PEAK_FLOPS = {
    "v5 lite": 197e12,
    "v5litepod": 197e12,
    "v6e": 918e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
}


def detect_peak_flops(device) -> Optional[float]:
    """Per-chip peak for a recognized accelerator; None when unknown (CPU,
    unlisted generation) — an MFU against a guessed peak misleads."""
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _PEAK_FLOPS.items():
        if key in kind:
            return val
    return None


def _hbm_high_water() -> Optional[int]:
    """Max ``peak_bytes_in_use`` across local devices; None when the
    platform exposes no memory stats (CPU host platform)."""
    import jax

    peaks = []
    for dev in jax.local_devices():
        try:
            stats = dev.memory_stats()
        except Exception:  # noqa: BLE001 - optional platform API
            stats = None
        if stats and "peak_bytes_in_use" in stats:
            peaks.append(int(stats["peak_bytes_in_use"]))
    return max(peaks) if peaks else None


class StepProfiler:
    """Profile windowed train-step execution with near-zero overhead.

    Usage::

        prof = obs.StepProfiler(step)
        for _ in range(n_windows):
            state, metrics = prof.run(state, batch, window)
        print(json.dumps(prof.report()))

    Each profiled window adds one host-side timing pair and one span; the
    device program is untouched (the overhead guard in tests/test_obs.py
    pins enabled-vs-disabled cost). ``registry`` receives ``obs_*`` gauges
    on every window so exporters see live values.
    """

    def __init__(
        self,
        step,
        registry: Optional[M.MetricsRegistry] = None,
        tracer: Optional[_spans.SpanTracer] = None,
        peak_flops_per_chip: Optional[float] = None,
        hbm_bw_bytes_per_s: Optional[float] = None,
        recorder=None,
        sentry=None,
    ):
        import jax

        self.step = step
        self.tracer = tracer or _spans.get_tracer()
        # Black-box feed (docs/observability.md § flight recorder): every
        # profiled window appends one step record, and the sentry watches
        # the same stream online. Defaults follow the always-on contract —
        # the env-gated process recorder, plus a monitor-less sentry so
        # NaN/regression verdicts exist wherever the recorder does.
        self.recorder = (_flight.get_recorder() if recorder is None
                         else recorder)
        if sentry is None and self.recorder is not None:
            from autodist_tpu.obs.sentry import Sentry

            sentry = Sentry(registry=registry, recorder=self.recorder)
        self.sentry = sentry
        # Planned per-step collective payload (sum of the plan's promised
        # wire, docs/analysis.md): a constant of the compiled program,
        # computed once and stamped on every flight record so postmortems
        # can relate wall-time anomalies to wire pressure. None for steps
        # without a plan (foreign step objects).
        self._collective_bytes: Optional[float] = None
        try:
            wire = self.step.plan.promised_wire()
            self._collective_bytes = float(
                sum(w.storage_bytes for w in wire.values()))
        except Exception:  # noqa: BLE001 - telemetry only
            pass
        self._n_devices = jax.device_count()
        self.peak_flops_per_chip = (
            peak_flops_per_chip
            if peak_flops_per_chip is not None
            else detect_peak_flops(jax.devices()[0]))
        self.hbm_bw_bytes_per_s = hbm_bw_bytes_per_s
        self.windows: List[Dict[str, float]] = []
        # Last measured-wire attribution (obs/attrib.py): set by
        # attribute(); when present its trace-measured exposed-comm
        # fraction replaces the roofline-residue estimate in report().
        self.last_attribution = None
        # Cumulative profiled-step counter: stamps flight records and
        # sentry findings with WHICH step an anomaly hit (a proxy for the
        # training step — exact when profiling starts at step 0).
        self._steps_total = 0
        self._cost: Dict[int, Dict[str, float]] = {}
        # Cost analysis runs OFF the training thread: it AOT-compiles the
        # single-step program, which on a big TPU model takes minutes — a
        # synchronous call inside the first profiled window would stall
        # training. report() joins the thread.
        self._cost_thread: Optional[threading.Thread] = None

        reg = registry or M.registry
        self._h_wall = reg.histogram("obs_step_wall_s")
        self._g_dispatch = reg.gauge("obs_dispatch_gap_s")
        self._g_device = reg.gauge("obs_device_compute_s")
        self._g_mfu = reg.gauge("obs_mfu")
        self._g_flops = reg.gauge("obs_flops_per_step")
        self._g_hbm = reg.gauge("obs_hbm_high_water_bytes")
        self._g_compiles = reg.gauge("obs_programs_compiled")
        self._g_exposed = reg.gauge("obs_exposed_comm_fraction")
        self._c_windows = reg.counter("obs_profiled_windows_total")

    # ------------------------------------------------------------------ run
    def run(self, state, batch, num_steps: int, stacked: bool = False):
        """``step.run`` with the window profiled; returns its result."""
        t_wall = time.time()
        t0 = time.perf_counter()
        state, metrics = self.step.run(state, batch, num_steps,
                                       stacked=stacked)
        dispatch = time.perf_counter() - t0
        # ONE end barrier per window (bench.py discipline): a device→host
        # scalar fetch of the final loss.
        loss = metrics.get("loss") if isinstance(metrics, dict) else None
        loss_val = None
        if loss is not None:
            loss_val = float(np.asarray(loss).ravel()[-1])
        else:
            import jax

            jax.block_until_ready(metrics)
        wall = time.perf_counter() - t0
        # Norm scalars (present when the step was built with
        # record_norms=True) ride the same already-barriered metrics tree.
        norms = {}
        if isinstance(metrics, dict):
            for key in ("grad_norm", "update_norm"):
                if key in metrics:
                    norms[key] = float(np.asarray(metrics[key]).ravel()[-1])
        self._record(num_steps, stacked, dispatch, wall, t_wall, state,
                     batch, loss_val, norms)
        return state, metrics

    def _record(self, num_steps, stacked, dispatch, wall, t_wall,
                state, batch, loss_val=None, norms=None) -> None:
        device_s = max(wall - dispatch, 0.0)
        cost = self._step_cost(state, batch, stacked)
        flops_step = cost.get("flops", 0.0)
        rec = {
            "steps": float(num_steps),
            "dispatch_gap_s": dispatch,
            "wall_s": wall,
            "device_s": device_s,
        }
        self.windows.append(rec)
        self._c_windows.inc()
        self._h_wall.observe(wall)
        self._g_dispatch.set(dispatch)
        self._g_device.set(device_s)
        self._g_compiles.set(len(getattr(self.step, "compile_log", ())))
        if flops_step:  # cost analysis may still be compiling in background
            self._g_flops.set(flops_step)
            mfu = self._mfu(flops_step, device_s / max(num_steps, 1))
            if mfu is not None:
                self._g_mfu.set(mfu)
        hbm = _hbm_high_water()
        if hbm is not None:
            self._g_hbm.set(hbm)
        self.tracer.add_span(
            "profiler.window", t_wall, wall, steps=num_steps,
            dispatch_gap_ms=round(dispatch * 1e3, 3),
        )
        # Flight-record + sentry feed: one compact record per window, with
        # per-step derived values (the exposed-comm fraction joins once the
        # background cost analysis lands AND a bandwidth was configured).
        n = max(int(num_steps), 1)
        self._steps_total += n
        exposed = self._window_exposed_fraction(device_s / n, cost)
        if self.recorder is not None:
            rec = {
                "step": self._steps_total,
                "steps": int(num_steps),
                "step_wall_s": wall / n,
                "dispatch_gap_s": dispatch,
                "device_s": device_s,
            }
            if loss_val is not None:
                rec["loss"] = loss_val
            if norms:
                rec.update(norms)
            if hbm is not None:
                rec["hbm_high_water"] = hbm
            if exposed is not None:
                rec["exposed_comm_fraction"] = exposed
            if flops_step:
                rec["flops_per_step"] = flops_step
            if self._collective_bytes:
                rec["collective_bytes_planned"] = self._collective_bytes
            self.recorder.record_step(**rec)
        if self.sentry is not None:
            norms = norms or {}
            self.sentry.observe_step(
                step=self._steps_total, loss=loss_val,
                step_time_s=wall / n, hbm_bytes=hbm,
                grad_norm=norms.get("grad_norm"),
                update_norm=norms.get("update_norm"))

    def _window_exposed_fraction(self, step_device_s: float,
                                 cost) -> Optional[float]:
        """Per-window exposed-comm fraction (same formula as report();
        None until the cost analysis and a bandwidth are both known)."""
        if (not cost or not self.hbm_bw_bytes_per_s
                or not self.peak_flops_per_chip or step_device_s <= 0):
            return None
        from autodist_tpu.utils import roofline

        bounds = {
            "flops": cost.get("flops", 0.0),
            "lower_bytes": cost.get("bytes_accessed", 0.0),
            "upper_bytes": cost.get("bytes_accessed", 0.0),
        }
        times = roofline.roofline_times(
            bounds, self.peak_flops_per_chip, self.hbm_bw_bytes_per_s)
        if not times.get("t_roofline_s"):
            return None
        exposed = max(step_device_s - times["t_roofline_s"], 0.0)
        return exposed / step_device_s

    def _step_cost(self, state, batch, stacked: bool) -> Dict[str, float]:
        """Per-step FLOPs/bytes = the SINGLE-STEP compiled program's cost
        analysis (XLA counts a scan body once regardless of trip count, so
        dividing a window's numbers by its length would under-report — see
        DistributedTrainStep.window_cost; the numbers are PER-DEVICE: cost
        analysis sees the partitioned module). A stacked window's batch
        carries a leading num_steps axis; one slice of it is the per-step
        batch, so costing the whole stack as one step would over-report by
        the window factor.

        Non-blocking: the AOT compile runs on a background thread (first
        call kicks it off; until it lands this returns ``{}`` and the
        flops/mfu gauges stay unset). :meth:`report` joins it."""
        cached = self._cost.get(1)
        if cached is not None:
            return cached
        if self._cost_thread is None:
            wc = getattr(self.step, "window_cost", None)
            if wc is None:
                self._cost[1] = {}
                return self._cost[1]
            import jax

            if stacked:
                batch = jax.tree.map(lambda x: x[0], batch)
            # Abstract shapes captured NOW, on the caller thread: the next
            # profiled window donates the live state's buffers, and the
            # background lower() must never touch them.
            state_shapes = jax.eval_shape(lambda: state)
            batch_shapes = jax.eval_shape(lambda: batch)

            def compute():
                try:
                    self._cost[1] = wc(state_shapes, batch_shapes, 1)
                except Exception as e:  # noqa: BLE001 - never fail training
                    logging.debug("window_cost unavailable: %s", e)
                    self._cost[1] = {}

            self._cost_thread = threading.Thread(
                target=compute, name="obs-step-cost", daemon=True)
            self._cost_thread.start()
        return {}

    # --------------------------------------------------------------- report
    def _mfu(self, flops_per_step: float,
             device_s_per_step: float) -> Optional[float]:
        """Measured MFU. ``flops_per_step`` is PER-DEVICE (XLA's cost
        analysis sees the partitioned module), so the denominator is the
        per-CHIP peak — multiplying by device_count would under-report
        fleet MFU by exactly that factor."""
        if (not flops_per_step or not device_s_per_step
                or self.peak_flops_per_chip is None):
            return None
        return flops_per_step / (device_s_per_step * self.peak_flops_per_chip)

    def report(self) -> Dict[str, Any]:
        """Aggregated profile: median window split, per-step FLOPs, MFU,
        roofline position (with a bandwidth), compile log, HBM high-water.
        Joins the background cost-analysis compile (bounded) so the FLOPs
        fields are final."""
        if self._cost_thread is not None and self._cost_thread.is_alive():
            self._cost_thread.join(timeout=600.0)
        out: Dict[str, Any] = {
            "windows": len(self.windows),
            "n_devices": self._n_devices,
        }
        if not self.windows:
            return out
        med = lambda k: float(np.median([w[k] for w in self.windows]))  # noqa: E731
        steps = self.windows[-1]["steps"] or 1.0
        cost = self._cost.get(1) or {}
        out.update({
            "steps_per_window": steps,
            "dispatch_gap_s": med("dispatch_gap_s"),
            "wall_s": med("wall_s"),
            "device_s": med("device_s"),
            "step_wall_s": med("wall_s") / steps,
            "step_device_s": med("device_s") / steps,
            # Per-device numbers (partitioned module) — see _mfu.
            "flops_per_step": cost.get("flops", 0.0),
            "bytes_per_step": cost.get("bytes_accessed", 0.0),
        })
        if out["flops_per_step"]:
            self._g_flops.set(out["flops_per_step"])
        mfu = self._mfu(out["flops_per_step"], out["step_device_s"])
        if mfu is not None:
            out["mfu"] = mfu
            self._g_mfu.set(mfu)
        if self.hbm_bw_bytes_per_s and self.peak_flops_per_chip:
            from autodist_tpu.utils import roofline

            # Per-device flops/bytes against per-chip peak and per-chip
            # bandwidth: consistent units, so vs_roofline ~ 1 means AT the
            # hardware ceiling on any mesh size.
            bounds = {
                "flops": out["flops_per_step"],
                "lower_bytes": out["bytes_per_step"],
                "upper_bytes": out["bytes_per_step"],
            }
            times = roofline.roofline_times(
                bounds, self.peak_flops_per_chip, self.hbm_bw_bytes_per_s)
            out["roofline"] = {
                **times,
                # >1: measured step above the hardware bound (overhead to
                # hunt); ~1: at the ceiling.
                "vs_roofline": (out["step_device_s"] / times["t_roofline_s"]
                                if times["t_roofline_s"] else float("nan")),
            }
            # Exposed-communication split: device step time BEYOND the
            # compiled program's own compute/HBM roofline bound is time the
            # chip spent neither on the MXU nor on HBM — on real meshes
            # that residue is dominated by collectives NOT hidden under
            # compute (plus scheduling slack), so the fraction is the
            # measurable "did bucketed backward-overlap actually hide the
            # wire" signal (docs/performance.md): it drops when
            # GraphConfig.bucket_bytes moves the grad sync into the
            # backward, and it is what the plan calibration's overlap_s
            # coefficient is fitted against. Upper bound by construction —
            # any non-comm overhead inflates it, never deflates.
            if out["step_device_s"] > 0:
                exposed = max(
                    out["step_device_s"] - times["t_roofline_s"], 0.0)
                out["exposed_comm_s_per_step"] = exposed
                out["exposed_comm_fraction"] = (
                    exposed / out["step_device_s"])
                self._g_exposed.set(out["exposed_comm_fraction"])
        if self.last_attribution is not None:
            # Trace-measured wire beats the roofline residue: the residue
            # is an upper bound (any non-comm overhead inflates it), the
            # attribution measured the collectives themselves.
            wire = self.last_attribution
            out["measured_wire"] = wire.summary()
            frac = wire.exposed_comm_fraction
            if frac is not None:
                out["exposed_comm_s_per_step"] = wire.exposed_wire_s_per_step
                out["exposed_comm_fraction"] = frac
                self._g_exposed.set(frac)
        compile_log = list(getattr(self.step, "compile_log", ()))
        out["compiles"] = {
            "count": len(compile_log),
            "total_first_call_s": round(
                sum(e.get("first_call_s", 0.0) for e in compile_log), 4),
        }
        hbm = _hbm_high_water()
        if hbm is not None:
            out["hbm_high_water_bytes"] = hbm
        return out

    def log_report(self, prefix: str = "profile") -> Dict[str, Any]:
        rep = self.report()
        logging.info("%s: %s", prefix, json.dumps(rep, sort_keys=True,
                                                  default=float))
        return rep

    # ---------------------------------------------------------- attribution
    def attribute(self, state, batch, num_steps: int = 4,
                  trace_dir: Optional[str] = None, stacked: bool = False):
        """Measured-wire attribution of one windowed run (obs/attrib.py):
        capture a ``jax.profiler`` trace, join every device op back to the
        plan's promised wire, and return ``(MeasuredWire, new_state)``
        (``run`` donates ``state``).

        Side effects: the report lands on :attr:`last_attribution`; the
        trace-measured exposed-comm fraction (a direct measurement, unlike
        the roofline residue) updates the ``obs_exposed_comm_fraction``
        gauge and subsequent :meth:`report` calls; an ``attrib`` event
        goes to the flight recorder when one is active."""
        from autodist_tpu.obs import attrib as _attrib

        wire, state = _attrib.attribute(
            self.step, state, batch, num_steps=num_steps,
            trace_dir=trace_dir, stacked=stacked)
        self.last_attribution = wire
        frac = wire.exposed_comm_fraction
        if frac is not None:
            self._g_exposed.set(frac)
        if self.recorder is not None:
            self.recorder.record_event("attrib", critical=False,
                                       **wire.summary())
        return wire, state

    @property
    def exposed_comm_fraction(self) -> Optional[float]:
        """The step-level exposed-communication fraction, best evidence
        first: the trace-measured number when :meth:`attribute` ran (wire
        time not covered by concurrent same-device compute), else the
        roofline-residue estimate from :meth:`report` (device time beyond
        the compiled program's compute/HBM bound), else None."""
        if self.last_attribution is not None:
            frac = self.last_attribution.exposed_comm_fraction
            if frac is not None:
                return frac
        rep = self.report()
        return rep.get("exposed_comm_fraction")

    def calibration_record(self, cost, name: str = ""):
        """This profile as a planner calibration point: pair the measured
        per-step wall split (and the compiled program's FLOPs/bytes) with
        the analytic :class:`~autodist_tpu.strategy.cost_model.StrategyCost`
        of the strategy that ran. Feed the result to
        :func:`autodist_tpu.plan.calibrate.calibrate_from_records` and the
        planner's cost model starts predicting THIS topology
        (docs/planner.md § calibration loop)."""
        from autodist_tpu.plan.calibrate import record_from_profiler

        return record_from_profiler(self.report(), cost, name=name)


# ----------------------------------------------------------------- StepTimer
class StepTimer:
    """Wall-clock step timing + throughput summary.

    ``items_per_step`` (e.g. global batch size, or tokens/step) turns times
    into throughput. First ``warmup`` steps are excluded (compile + cache
    effects). Use as a callable context around each step.
    """

    def __init__(self, items_per_step: float = 0.0, warmup: int = 2):
        self.items_per_step = items_per_step
        self.warmup = warmup
        self.times: List[float] = []
        self._t0: Optional[float] = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        assert self._t0 is not None
        self.times.append(time.perf_counter() - self._t0)
        self._t0 = None
        return False

    @property
    def measured(self) -> List[float]:
        return self.times[self.warmup:] if len(self.times) > self.warmup else []

    def summary(self) -> Dict[str, Any]:
        xs = sorted(self.measured)
        if not xs:
            return {"steps": len(self.times), "measured": 0}
        n = len(xs)
        mean = sum(xs) / n
        out = {
            "steps": len(self.times),
            "measured": n,
            "mean_s": mean,
            "p50_s": xs[n // 2],
            "p90_s": xs[min(n - 1, int(n * 0.9))],
            "min_s": xs[0],
        }
        if self.items_per_step:
            out["items_per_sec"] = self.items_per_step / mean
        return out

    def log_summary(self, prefix: str = "steps") -> Dict[str, Any]:
        s = self.summary()
        logging.info("%s: %s", prefix, json.dumps(s, sort_keys=True))
        return s
