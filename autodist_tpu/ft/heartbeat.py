"""Heartbeat exchange + peer health classification.

The reference's only liveness signal was "the SSH child's exit code"
(``coordinator.py:98-110`` monitor threads): binary, post-mortem, and blind
to hangs. The :class:`HealthMonitor` here is the positive-signal
complement: every process *publishes* a periodic heartbeat and *sweeps*
everyone else's, classifying each peer ``HEALTHY → SUSPECT → DEAD`` with
exponential backoff between escalations so one dropped beat never flaps a
peer. State is exported through
:class:`~autodist_tpu.metrics.MetricsRegistry` gauges
(``ft_peers_{healthy,suspect,dead}``, ``ft_heartbeat_max_age_s``), and the
launcher's supervisor consumes :meth:`HealthMonitor.verdict` instead of
blind exit-code counting (``runtime/launcher.py``).

Heartbeats travel through a pluggable transport:

- :class:`FileTransport` — one atomically-replaced JSON file per process
  under a shared directory. This is the production default: the Saver
  already assumes a shared filesystem for multi-host checkpoints, the
  local-fleet emulation shares ``/tmp``, and — critically — the launcher
  process (which is NOT a jax.distributed member) can observe the fleet
  through the same files.
- :class:`CoordinatorTransport` — rides the jax.distributed
  coordination-service key-value store (the same chief-hosted RPC service
  the async Saver uses for barriers), for fleets without a shared
  filesystem. Best-effort: constructed only when a coordination client
  exists.
- :class:`MemoryTransport` — in-process dict, for tests and the
  single-process degenerate case.

The monitor's classification step is factored into :meth:`HealthMonitor.tick`
(pure function of transport contents + a clock) so tests drive the state
machine deterministically with a synthetic clock; the daemon thread just
calls ``tick`` on a cadence.
"""
from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable, Dict, List, Optional

from autodist_tpu import metrics as M
from autodist_tpu.chaos import hooks as chaos_hooks
from autodist_tpu.ft.config import FTConfig
from autodist_tpu.obs import recorder as obs_recorder
from autodist_tpu.utils import logging, retry

#: Transient transport-publish retry (utils/retry.py — the ONE backoff
#: home): a beat is worth two quick retries, never a blocking stall of
#: the monitor loop.
_PUBLISH_RETRY = retry.RetryPolicy(
    initial_s=0.02, max_s=0.1, multiplier=2.0, jitter=0.5,
    max_attempts=3, deadline_s=1.0)

#: Hard cap on the shutdown join: ``5 * heartbeat_interval_s`` can be
#: minutes with long intervals, and a daemon thread stuck in a slow
#: transport must not block process shutdown that long.
STOP_JOIN_CAP_S = 10.0


class PeerState(Enum):
    HEALTHY = "healthy"
    SUSPECT = "suspect"
    DEAD = "dead"


class FleetVerdict(Enum):
    """Aggregate view the supervisor consumes."""

    HEALTHY = "healthy"    # every known peer healthy
    DEGRADED = "degraded"  # some peers suspect/dead, some alive
    DEAD = "dead"          # every known peer dead (fleet-wide hang/loss)
    UNKNOWN = "unknown"    # no heartbeat ever observed


@dataclass
class PeerInfo:
    """Host-side record for one peer."""

    process_id: int
    state: PeerState = PeerState.HEALTHY
    last_seen: float = 0.0         # transport timestamp of the last beat
    last_payload: dict = field(default_factory=dict)
    misses: int = 0                # consecutive escalation windows missed
    next_check: float = 0.0        # monotonic deadline of the next escalation
    backoff_s: float = 0.0


# ------------------------------------------------------------- transports
class MemoryTransport:
    """In-process heartbeat board (tests, single-process)."""

    def __init__(self):
        self._board: Dict[int, dict] = {}
        self._lock = threading.Lock()

    def publish(self, process_id: int, payload: dict) -> None:
        payload = chaos_hooks.apply(chaos_hooks.SEAM_HB_PUBLISH, payload,
                                    process_id=int(process_id),
                                    transport="memory")
        if payload is None:
            return  # injected transport drop: the beat never lands
        with self._lock:
            self._board[int(process_id)] = dict(payload)

    def sweep(self) -> Dict[int, dict]:
        with self._lock:
            board = {pid: dict(p) for pid, p in self._board.items()}
        return chaos_hooks.apply(chaos_hooks.SEAM_HB_SWEEP, board,
                                 transport="memory")


class FileTransport:
    """One ``hb-<pid>.json`` per process under a shared directory.

    Writes are atomic (tmp + rename) so a sweeping reader never sees a
    torn beat; the payload carries its own ``time`` stamp (``time.time()``
    — wall clock, comparable across hosts to heartbeat precision)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def publish(self, process_id: int, payload: dict) -> None:
        payload = chaos_hooks.apply(chaos_hooks.SEAM_HB_PUBLISH, payload,
                                    process_id=int(process_id),
                                    transport="file")
        if payload is None:
            return  # injected transport drop: the beat never lands
        path = os.path.join(self.directory, f"hb-{int(process_id)}.json")
        tmp = f"{path}.tmp-{os.getpid()}"

        def _write():
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f)
            os.replace(tmp, path)

        # A transient filesystem hiccup (remount, NFS blip) costs a beat
        # only if it outlives the retry budget; the monitor loop's own
        # exception guard catches a final failure.
        retry.retry_call(_write, policy=_PUBLISH_RETRY, retry_on=(OSError,),
                         describe="heartbeat publish")

    def sweep(self) -> Dict[int, dict]:
        out: Dict[int, dict] = {}
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            if not (name.startswith("hb-") and name.endswith(".json")):
                continue
            try:
                pid = int(name[3:-5])
                with open(os.path.join(self.directory, name),
                          encoding="utf-8") as f:
                    out[pid] = json.load(f)
            except (OSError, ValueError):
                continue  # mid-replace / foreign file: catch it next sweep
        return chaos_hooks.apply(chaos_hooks.SEAM_HB_SWEEP, out,
                                 transport="file")


class CoordinatorTransport:
    """Heartbeats through the jax.distributed coordination-service KV store.

    The store is append-oriented, so each beat lands under a fresh
    sequence-suffixed key (``ft/hb/<pid>/<seq>``) and sweeps take the
    newest sequence per peer via ``key_value_dir_get``. Keys are tiny and
    heartbeat cadence is seconds, so growth over a training run is
    negligible next to the service's barrier traffic.
    """

    PREFIX = "ft/hb"

    def __init__(self, client=None):
        if client is None:
            from autodist_tpu.checkpoint.saver import Saver

            client = Saver._coordination_client()
        if client is None:
            raise RuntimeError(
                "CoordinatorTransport needs a jax.distributed coordination "
                "client (no multi-process runtime is initialized)")
        self._client = client
        # Wall-clock-seeded so a RESTARTED process's keys sort after its
        # previous incarnation's (a 0-seeded counter would leave the fresh
        # beats shadowed by stale higher-seq keys forever); sweep()
        # additionally prefers the newest payload timestamp as the tiebreak
        # authority, so even clock skew cannot pin a peer to an old beat.
        self._seq = int(time.time() * 1000)

    def publish(self, process_id: int, payload: dict) -> None:
        payload = chaos_hooks.apply(chaos_hooks.SEAM_HB_PUBLISH, payload,
                                    process_id=int(process_id),
                                    transport="coordinator")
        if payload is None:
            return  # injected transport drop: the beat never lands
        self._seq += 1
        key = f"{self.PREFIX}/{int(process_id)}/{self._seq:012d}"
        try:
            retry.retry_call(
                lambda: self._client.key_value_set(key, json.dumps(payload)),
                policy=_PUBLISH_RETRY, retry_on=(Exception,),
                describe="heartbeat publish (coordination kv)")
        except Exception as e:  # noqa: BLE001 - liveness signal, never fatal
            logging.warning("heartbeat publish failed (%s)", e)

    def sweep(self) -> Dict[int, dict]:
        try:
            entries = self._client.key_value_dir_get(self.PREFIX)
        except Exception:  # noqa: BLE001 - service may be mid-teardown
            return {}
        out: Dict[int, dict] = {}
        for key, value in entries:
            parts = str(key).strip("/").split("/")
            if len(parts) < 2:
                continue
            try:
                pid = int(parts[-2])
                payload = json.loads(value)
            except ValueError:
                continue
            # Newest PAYLOAD TIMESTAMP wins, not the highest key sequence:
            # a restarted peer's fresh beats must never be shadowed by its
            # pre-restart keys.
            if (pid not in out
                    or payload.get("time", 0) > out[pid].get("time", 0)):
                out[pid] = payload
        return out


# ---------------------------------------------------------------- monitor
class HealthMonitor:
    """Per-process health daemon: publish own beat, classify everyone's.

    ``process_id`` identifies this process on the transport;
    ``publish=False`` makes a pure observer (the launcher's fleet watchdog
    — it is not a fleet member and must not appear as a peer).
    ``expected`` optionally names the process ids that SHOULD exist, so a
    peer that never manages a single beat still shows up (as ``DEAD`` once
    the dead window passes from monitor start).

    Thread-safe: ``tick`` may be driven by the daemon thread (``start``)
    or directly by tests with a synthetic clock.
    """

    def __init__(
        self,
        transport,
        process_id: int = 0,
        config: Optional[FTConfig] = None,
        publish: bool = True,
        expected: Optional[List[int]] = None,
        registry: Optional[M.MetricsRegistry] = None,
        clock: Callable[[], float] = time.time,
    ):
        self.transport = transport
        self.process_id = int(process_id)
        self.config = config or FTConfig()
        self.publish = publish
        self.clock = clock
        self._peers: Dict[int, PeerInfo] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._started_at: Optional[float] = None
        self._transitions: List[Callable[[int, PeerState, PeerState], None]] = []
        self._step = 0  # training progress carried in the beat payload

        reg = registry or M.registry
        self._g_healthy = reg.gauge("ft_peers_healthy")
        self._g_suspect = reg.gauge("ft_peers_suspect")
        self._g_dead = reg.gauge("ft_peers_dead")
        self._g_age = reg.gauge("ft_heartbeat_max_age_s")
        self._c_sent = reg.counter("ft_heartbeats_sent_total")
        self._c_trans = reg.counter("ft_peer_transitions_total")

        if expected:
            now = self.clock()
            cfg = self.config
            for pid in expected:
                if publish and int(pid) == self.process_id:
                    continue
                self._peers[int(pid)] = PeerInfo(
                    process_id=int(pid), state=PeerState.HEALTHY,
                    last_seen=now,
                    next_check=now + cfg.suspect_after_misses * cfg.heartbeat_interval_s,
                )

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "HealthMonitor":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._started_at = self.clock()
        self._thread = threading.Thread(
            target=self._loop, name="ft-heartbeat", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            # Bounded shutdown: ``5 * interval`` can be minutes with long
            # heartbeat intervals; a wedged transport must not hold the
            # process exit hostage. Past the cap, warn and detach — the
            # thread is a daemon and dies with the process.
            cap = min(5 * self.config.heartbeat_interval_s, STOP_JOIN_CAP_S)
            self._thread.join(timeout=cap)
            if self._thread.is_alive():
                logging.warning(
                    "heartbeat thread did not exit within %.1fs (transport "
                    "wedged?); detaching without blocking shutdown", cap)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - the monitor must outlive glitches
                logging.warning("health monitor tick failed", exc_info=True)
            self._stop.wait(self.config.heartbeat_interval_s)

    def set_step(self, step: int) -> None:
        """Record training progress; travels in the next beat's payload so
        peers (and the supervisor) can see who is advancing."""
        self._step = int(step)

    def on_transition(
        self, fn: Callable[[int, PeerState, PeerState], None]
    ) -> None:
        """Run ``fn(pid, old_state, new_state)`` on every classification
        change, from the monitor thread (or the tick caller)."""
        self._transitions.append(fn)

    # ---------------------------------------------------------------- tick
    def tick(self, now: Optional[float] = None) -> None:
        """One publish + sweep + classify round (idempotent, reentrant-safe
        under the instance lock)."""
        now = self.clock() if now is None else now
        if self._started_at is None:
            self._started_at = now
        if self.publish:
            self.transport.publish(self.process_id, {
                "time": now, "step": self._step, "pid": os.getpid(),
            })
            self._c_sent.inc()
        beats = self.transport.sweep()
        fired = []
        with self._lock:
            cfg = self.config
            interval = cfg.heartbeat_interval_s
            for pid, payload in beats.items():
                if self.publish and pid == self.process_id:
                    continue
                seen = float(payload.get("time", now))
                peer = self._peers.get(pid)
                if peer is None:
                    peer = self._peers[pid] = PeerInfo(process_id=pid)
                if seen > peer.last_seen:
                    # Fresh beat: whatever the peer was, it is healthy now,
                    # and the escalation backoff resets.
                    if peer.state is not PeerState.HEALTHY:
                        fired.append((pid, peer.state, PeerState.HEALTHY))
                    peer.state = PeerState.HEALTHY
                    peer.last_seen = seen
                    peer.last_payload = payload
                    peer.misses = 0
                    peer.backoff_s = 0.0
                    peer.next_check = now + cfg.suspect_after_misses * interval
            for pid, peer in self._peers.items():
                if peer.state is PeerState.DEAD:
                    continue
                if now < peer.next_check:
                    continue
                # Escalation window expired without a fresh beat.
                peer.misses += 1
                old = peer.state
                if peer.state is PeerState.HEALTHY:
                    peer.state = PeerState.SUSPECT
                if peer.misses >= max(
                        1, cfg.dead_after_misses - cfg.suspect_after_misses):
                    peer.state = PeerState.DEAD
                # Exponential backoff between escalations: a transient miss
                # costs one SUSPECT round; repeated misses wait doubling
                # windows before the next (so flapping can't thrash states).
                peer.backoff_s = min(
                    cfg.backoff_max_s,
                    (peer.backoff_s * 2) if peer.backoff_s
                    else (cfg.backoff_initial_s or interval),
                )
                peer.next_check = now + peer.backoff_s
                if peer.state is not old:
                    fired.append((pid, old, peer.state))
            states = [p.state for p in self._peers.values()]
            self._g_healthy.set(sum(s is PeerState.HEALTHY for s in states))
            self._g_suspect.set(sum(s is PeerState.SUSPECT for s in states))
            self._g_dead.set(sum(s is PeerState.DEAD for s in states))
            ages = [now - p.last_seen for p in self._peers.values()
                    if p.last_seen > 0]
            self._g_age.set(max(ages) if ages else 0.0)
        self._fire(fired)

    def _fire(self, fired, reason: str = "") -> None:
        """Dispatch classification changes: bump the counter, log, run the
        registered callbacks (outside the lock — a callback may query the
        monitor). ONE path for tick() and escalate()."""
        for pid, old, new in fired:
            self._c_trans.inc()
            logging.info("peer %d: %s -> %s%s", pid, old.value, new.value,
                         f" ({reason})" if reason else "")
            # Classification changes are rare and load-bearing for a
            # postmortem ("host 3 went suspect 40s before the wedge") —
            # flight-record each with the immediate-fsync discipline.
            obs_recorder.record_event(
                "peer_transition", peer=pid, old=old.value, new=new.value,
                reason=reason or "")
            for fn in self._transitions:
                try:
                    fn(pid, old, new)
                except Exception:  # noqa: BLE001 - callbacks can't kill the loop
                    logging.warning("peer-transition callback raised",
                                    exc_info=True)

    def _refresh_state_gauges(self) -> None:
        with self._lock:
            states = [p.state for p in self._peers.values()]
        self._g_healthy.set(sum(s is PeerState.HEALTHY for s in states))
        self._g_suspect.set(sum(s is PeerState.SUSPECT for s in states))
        self._g_dead.set(sum(s is PeerState.DEAD for s in states))

    def escalate(self, pid: int, reason: str = "") -> None:
        """External suspicion feed: force peer ``pid`` to SUSPECT scrutiny
        now (obs straggler scores use this — a host can be alive-but-sick
        long before it misses a beat). A DEAD peer stays dead; a healthy
        beat after escalation clears it through the normal tick path. The
        next escalation window opens immediately, so a straggler that also
        stops beating reaches DEAD on the short path."""
        fired = []
        with self._lock:
            peer = self._peers.get(int(pid))
            if peer is None:
                peer = self._peers[int(pid)] = PeerInfo(process_id=int(pid))
            if peer.state is PeerState.HEALTHY:
                fired.append((int(pid), peer.state, PeerState.SUSPECT))
                peer.state = PeerState.SUSPECT
                peer.next_check = self.clock()  # escalate on the next tick
        if fired:
            self._refresh_state_gauges()
        self._fire(fired, reason=reason or "external escalation")

    # ------------------------------------------------------------- queries
    def peers(self) -> Dict[int, PeerInfo]:
        with self._lock:
            return {
                pid: PeerInfo(
                    process_id=p.process_id, state=p.state,
                    last_seen=p.last_seen, last_payload=dict(p.last_payload),
                    misses=p.misses, next_check=p.next_check,
                    backoff_s=p.backoff_s,
                )
                for pid, p in self._peers.items()
            }

    def surviving(self) -> List[int]:
        """Process ids not classified DEAD — the membership an elastic
        restart rebuilds the ResourceSpec from."""
        with self._lock:
            return sorted(pid for pid, p in self._peers.items()
                          if p.state is not PeerState.DEAD)

    def max_observed_step(self) -> int:
        """Highest training step any beat has carried (``set_step``) —
        the supervisor's progress signal."""
        with self._lock:
            peer_max = max(
                (int(p.last_payload.get("step", 0))
                 for p in self._peers.values()), default=0)
        return max(peer_max, self._step)

    def verdict(self, now: Optional[float] = None) -> FleetVerdict:
        """Aggregate classification of everything observed so far."""
        with self._lock:
            states = [p.state for p in self._peers.values()]
        if not states:
            return FleetVerdict.UNKNOWN
        if all(s is PeerState.HEALTHY for s in states):
            return FleetVerdict.HEALTHY
        if all(s is PeerState.DEAD for s in states):
            return FleetVerdict.DEAD
        return FleetVerdict.DEGRADED

    def fleet_hung(self, now: Optional[float] = None) -> bool:
        """Launcher watchdog predicate: at least one beat was ever seen and
        EVERY peer's last beat is older than ``hang_after_misses``
        intervals. Distinct from ``verdict() is DEAD`` only in its longer,
        dedicated window — killing a live-but-slow fleet is worse than
        waiting a few extra intervals."""
        now = self.clock() if now is None else now
        window = self.config.hang_after_misses * self.config.heartbeat_interval_s
        with self._lock:
            seen = [p.last_seen for p in self._peers.values() if p.last_seen > 0]
        if not seen:
            return False
        return all(now - t > window for t in seen)
