"""Fault-tolerance configuration: one dataclass of knobs for the whole
``autodist_tpu.ft`` subsystem.

The reference AutoDist had no fault story beyond "worker death kills the
chief" (``/root/reference/autodist/coordinator.py:98-110``); every knob
here is therefore beyond-reference capability. ``FTConfig`` travels as a
plain value object: :class:`~autodist_tpu.api.AutoDist` accepts
``fault_tolerance=FTConfig(...)``, the launcher's supervisor consumes the
same object, and each ``ft`` component reads only its own fields.

Directory layout (``resolved()``): everything lives under one base dir —
``AUTODIST_FT_DIR`` env, or ``<working-dir>/ft`` — so a restarted process
(same host or a surviving peer on a shared filesystem) finds the previous
incarnation's heartbeats, snapshots and persisted serve queue without any
side-channel.
"""
from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass
from typing import Optional

from autodist_tpu import const
from autodist_tpu.const import ENV


@dataclass
class FTConfig:
    """Knobs for heartbeating, snapshotting, elastic resume and drain.

    Heartbeats (``ft.heartbeat``):

    - ``heartbeat_interval_s``: publish + sweep period of the
      :class:`~autodist_tpu.ft.heartbeat.HealthMonitor` daemon thread.
    - ``suspect_after_misses``: consecutive missed intervals before a peer
      is classified ``SUSPECT`` (transient: the peer recovers to
      ``HEALTHY`` on its next beat).
    - ``dead_after_misses``: escalation bound — total missed intervals
      (counted in backoff windows, see below) before ``DEAD``.
    - ``backoff_initial_s`` / ``backoff_max_s``: after each miss the next
      escalation check waits exponentially longer (doubling, capped), so a
      flapping network cannot ping-pong a peer between states every tick.

    Snapshots (``ft.snapshot``):

    - ``snapshot_every_steps`` / ``snapshot_every_s``: periodic-snapshot
      cadence for :meth:`~autodist_tpu.ft.snapshot.SnapshotManager.maybe_snapshot`
      (0 disables that trigger; both 0 = manual snapshots only).
    - ``keep_snapshots``: ring size — older snapshots are pruned after a
      new one lands, newest-N retained.
    - ``snapshot_on_preempt``: install the SIGTERM hook (the TPU
      preemption signal) that forces a final snapshot before shutdown.

    Serve drain (``ft.drain``):

    - ``drain_deadline_s``: how long in-flight decodes may run after a
      drain begins before undone work is persisted instead.

    Fleet supervision (``runtime.launcher``):

    - ``hang_after_misses``: launcher-side watchdog — when EVERY process's
      heartbeat has been silent this many intervals, the fleet is judged
      hung and the chief is terminated so the restart supervisor can act
      (a wedged fleet otherwise never exits and exit-code supervision
      waits forever).
    """

    # heartbeat
    heartbeat_interval_s: float = 5.0
    suspect_after_misses: int = 2
    dead_after_misses: int = 6
    backoff_initial_s: float = 0.0   # 0 = one interval
    backoff_max_s: float = 60.0
    # snapshot
    snapshot_every_steps: int = 0
    snapshot_every_s: float = 0.0
    keep_snapshots: int = 3
    snapshot_on_preempt: bool = True
    # serve drain
    drain_deadline_s: float = 30.0
    # launcher watchdog
    hang_after_misses: int = 12
    # paths (None = derive from base_dir in resolved())
    base_dir: Optional[str] = None
    heartbeat_dir: Optional[str] = None
    snapshot_dir: Optional[str] = None
    queue_persist_path: Optional[str] = None

    def resolved(self) -> "FTConfig":
        """A copy with every path filled in from ``base_dir`` (explicit, or
        ``AUTODIST_FT_DIR``, or ``<working-dir>/ft``). Explicit per-path
        overrides always win."""
        base = self.base_dir or ENV.AUTODIST_FT_DIR.val or const.DEFAULT_FT_DIR
        return dataclasses.replace(
            self,
            base_dir=base,
            heartbeat_dir=self.heartbeat_dir or os.path.join(base, "heartbeats"),
            snapshot_dir=self.snapshot_dir or os.path.join(base, "snapshots"),
            queue_persist_path=(
                self.queue_persist_path
                or os.path.join(base, "serve_queue.json")
            ),
        )
