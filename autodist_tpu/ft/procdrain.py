"""Graceful subprocess termination: signal, grace period, then kill.

Standalone on purpose — **zero package imports** — because the TPU queue
driver (``examples/benchmark/run_tpu_queue.py``) loads this file by path
(the ``utils/pidlock.py`` pattern): the driver must stay importable with
no framework dependencies. Everything else imports it normally as
``autodist_tpu.ft.procdrain``.

Why this exists: hard-killing a TPU process mid-dispatch is the documented
tunnel-wedge trigger (docs/performance.md r5 notes — a harness timeout
SIGKILL mid-dispatch wedged the tunnel for 27h). SIGTERM first gives the
child its exit path: the ft preemption hook snapshots, the serve drain
persists its queue, and a benchmark's trailing dispatch barrier drains —
then, only if the grace period expires, the process group is SIGKILLed.
"""
from __future__ import annotations

import os
import signal
import subprocess


def signal_group(proc, sig) -> None:
    """Deliver ``sig`` to the child's process group (it was started with
    ``start_new_session=True``), falling back to the child alone."""
    try:
        os.killpg(proc.pid, sig)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            proc.send_signal(sig)
        except (ProcessLookupError, OSError):
            pass


def stop_gracefully(proc, grace_s: float = 60.0, kill_grace_s: float = 10.0):
    """SIGTERM ``proc``'s group, wait up to ``grace_s`` for a clean exit,
    escalate to SIGKILL, and reap.

    Returns ``(stdout, stderr)`` from the final ``communicate()`` (pipes
    captured by the caller's ``Popen``; ``(None, None)`` otherwise). The
    process is guaranteed reaped on return.
    """
    signal_group(proc, signal.SIGTERM)
    try:
        return proc.communicate(timeout=grace_s)
    except subprocess.TimeoutExpired:
        pass
    signal_group(proc, signal.SIGKILL)
    try:
        return proc.communicate(timeout=kill_grace_s)
    except subprocess.TimeoutExpired:
        # Unreapable (e.g. stuck in an uninterruptible syscall): report what
        # we have; the zombie is the kernel's problem now.
        return None, None
