"""Fault tolerance (L1.5): elastic, preemption-aware training + serving.

The reference AutoDist's fault story ended at fail-fast: a worker death
killed the chief (``coordinator.py:98-110``) and a human restarted the
job. This package is the production counterpart the ROADMAP north star
requires — surviving TPU preemptions and host failures without losing
minutes of training or dropping queued inference requests:

- :mod:`~autodist_tpu.ft.heartbeat` — :class:`HealthMonitor`: positive
  liveness signals (vs. exit codes), healthy/suspect/dead classification
  with exponential escalation backoff, metrics-registry gauges, and the
  fleet verdicts the launcher's supervisor consumes.
- :mod:`~autodist_tpu.ft.snapshot` — :class:`SnapshotManager`: async
  ring of integrity-hashed train-state snapshots + the SIGTERM
  (preemption) hook that forces a final one.
- :mod:`~autodist_tpu.ft.elastic` — recompile the Strategy→ShardingPlan
  on the surviving mesh and restore the snapshot through the Saver's
  re-sharding read (GSPMD recompilation-on-resize, arXiv:2105.04663).
- :mod:`~autodist_tpu.ft.drain` — serve-side graceful degradation:
  quiesce → finish in-flight → persist undrained queue → replay on
  restart, zero loss / zero duplicates.
- :mod:`~autodist_tpu.ft.procdrain` — signal-then-grace subprocess
  termination (standalone; the queue driver loads it by path).

Entry point for users: ``AutoDist(fault_tolerance=FTConfig(...))`` — the
returned :class:`FTRuntime` rides on ``autodist.ft``. See
docs/fault_tolerance.md.
"""
from __future__ import annotations

from typing import Optional

from autodist_tpu import metrics as M
from autodist_tpu.ft.config import FTConfig
from autodist_tpu.ft.drain import DrainController, persist_requests, replay_requests
from autodist_tpu.ft.elastic import (
    ElasticController,
    recompile_on,
    resume_from_snapshot,
    surviving_resource_spec,
)
from autodist_tpu.ft.heartbeat import (
    CoordinatorTransport,
    FileTransport,
    FleetVerdict,
    HealthMonitor,
    MemoryTransport,
    PeerState,
)
from autodist_tpu.ft.snapshot import SnapshotManager, latest_snapshot_step

__all__ = [
    "CoordinatorTransport",
    "DrainController",
    "ElasticController",
    "FTConfig",
    "FTRuntime",
    "FileTransport",
    "FleetVerdict",
    "HealthMonitor",
    "MemoryTransport",
    "PeerState",
    "SnapshotManager",
    "latest_snapshot_step",
    "persist_requests",
    "recompile_on",
    "replay_requests",
    "resume_from_snapshot",
    "surviving_resource_spec",
]


class FTRuntime:
    """The per-process bundle ``AutoDist(fault_tolerance=...)`` creates:
    one started :class:`HealthMonitor` (file transport under the resolved
    heartbeat dir), one :class:`SnapshotManager`, and the preemption hook
    when configured. Components stay individually constructible for
    callers that want only one of them."""

    def __init__(self, config: FTConfig,
                 registry: Optional[M.MetricsRegistry] = None,
                 start_monitor: bool = True,
                 install_preempt_hook: Optional[bool] = None):
        import jax

        self.config = config.resolved()
        self.monitor = HealthMonitor(
            FileTransport(self.config.heartbeat_dir),
            process_id=jax.process_index(),
            config=self.config,
            registry=registry,
        )
        if start_monitor:
            self.monitor.start()
        self.snapshots = SnapshotManager.from_config(
            self.config, registry=registry)
        self.elastic = ElasticController(self.monitor, self.snapshots)
        if (self.config.snapshot_on_preempt
                if install_preempt_hook is None else install_preempt_hook):
            try:
                self.snapshots.install_preempt_hook()
            except ValueError:
                # Not the main thread (embedded runtimes): the hook is an
                # optimization, not a correctness requirement.
                pass

    def maybe_snapshot(self, state, step: Optional[int] = None,
                       step_obj=None) -> Optional[str]:
        """Periodic-snapshot hook for training loops; also refreshes the
        heartbeat payload's progress counter."""
        resolved = SnapshotManager._resolve_step(state, step)
        self.monitor.set_step(resolved)
        self.snapshots.register_state_provider(
            lambda: ((step_obj.logical_state(state)
                      if step_obj is not None else state), resolved))
        return self.snapshots.maybe_snapshot(state, step=resolved,
                                             step_obj=step_obj)

    def shutdown(self) -> None:
        self.monitor.stop()
        self.snapshots.wait()
