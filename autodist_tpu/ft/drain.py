"""Graceful serve degradation: drain in-flight work, persist the rest,
replay on restart.

A SIGTERM'd server that hard-stops loses two kinds of work: decodes that
were seconds from finishing, and queued requests nobody started. The
:class:`DrainController` closes both holes around one
:class:`~autodist_tpu.serve.batcher.ContinuousBatcher`:

1. **quiesce** — the batcher stops admitting (new ``submit``s are refused
   with :class:`~autodist_tpu.serve.batcher.Backpressure`, queued entries
   stop being promoted to slots);
2. **finish in-flight** — active decodes keep stepping until done, bounded
   by ``drain_deadline_s``;
3. **persist** — whatever is still undone (the untouched queue + any
   decode the deadline cut off) is written atomically to
   ``queue_persist_path`` and each such request is finished terminally as
   ``PREEMPTED`` (no client ever blocks on work this process will not do);
4. **replay** — a restarted server calls :meth:`DrainController.replay`
   (or :func:`replay_requests`): persisted entries are resubmitted and the
   file consumed, so a request is served exactly once — completed work is
   never persisted, persisted work was never completed.

The persist format is deliberately prompt-level (prompt tokens +
``max_new_tokens`` + remaining deadline), not KV-cache state: replay
re-decodes from scratch on whatever mesh/shardings the restarted server
compiled, which composes with elastic resizes for free. Format version 2
additionally journals the request's **identity and delivery watermark**:
a stable ``request_id``, the ``delivered`` token count, and the delivered
token prefix itself (``tokens``). The id + watermark are what make
multi-journal replay exactly-once: two replicas (or a replica and the
router in front of it, ``serve/router.py``) may both have journaled the
same failed-over request — :func:`merge_journal_entries` dedupes by id,
keeping the entry that delivered furthest, and the prefix lets the
router resume generation from the last delivered token instead of
re-serving from scratch (greedy decode is deterministic, so the resumed
stream is bit-identical to an uninterrupted one).
"""
from __future__ import annotations

import json
import os
import signal
import threading
from typing import List, Optional, Sequence, Union

from autodist_tpu import metrics as M
from autodist_tpu.utils import logging


def persist_requests(path: str, requests) -> int:
    """Atomically write the replay file for ``requests`` (anything with
    ``prompt`` / ``max_new_tokens`` / ``deadline`` — i.e. ``GenRequest``).
    Deadlines are stored as remaining seconds (absolute monotonic times do
    not survive a process restart). Requests carrying a ``request_id`` /
    ``tokens`` surface additionally journal their identity and delivered
    prefix (format version 2) so replay can dedupe across journals and
    resume mid-stream. Returns the entry count."""
    import time

    now = time.monotonic()
    entries = []
    for r in requests:
        entry = {
            "prompt": [int(t) for t in r.prompt],
            "max_new_tokens": int(r.max_new_tokens),
            "timeout_s": (max(0.001, r.deadline - now)
                          if r.deadline is not None else None),
        }
        rid = getattr(r, "request_id", "")
        if rid:
            entry["request_id"] = str(rid)
        tokens = getattr(r, "tokens", None)
        if tokens:
            entry["delivered"] = len(tokens)
            entry["tokens"] = [int(t) for t in tokens]
        samp = getattr(r, "sampling", None)
        if samp is not None:
            # Stochastic params survive the restart with the request: a
            # replayed stream re-derives its counter-based draws from
            # (request_id, seed, position) alone (serve/sampling.py), so
            # the resumed tail is bit-identical to the stream the dead
            # process would have produced.
            entry["sampling"] = samp.to_dict()
        entries.append(entry)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"format_version": 2, "entries": entries}, f)
    os.replace(tmp, path)
    return len(entries)


def _load_entries(path: str) -> Optional[List[dict]]:
    """One journal's entries; None = unreadable (missing is []-like None,
    corrupt is moved aside) — shared by replay and the merge."""
    try:
        with open(path, encoding="utf-8") as f:
            payload = json.load(f)
        return list(payload.get("entries", []))
    except OSError:
        return None
    except ValueError:
        logging.warning("replay file %s is corrupt; moving it aside", path)
        try:
            os.replace(path, path + ".corrupt")
        except OSError:
            pass
        return None


def merge_journal_entries(paths: Sequence[str]) -> List[dict]:
    """Merge entries from several journals, deduping by ``request_id``.

    Exactly-once across a failover: a request that was journaled by two
    replicas (it was draining on one when it failed over to the other)
    must replay ONCE — the entry with the highest ``delivered`` watermark
    wins (it has seen the most client-visible tokens; replaying the lower
    one would re-deliver tokens the client already has). Entries without
    a ``request_id`` (format v1) cannot be identified, so they are all
    kept — v1 journals were always single-writer. Order: first-seen
    journal order, so FIFO fairness survives the merge."""
    merged: List[dict] = []
    by_id: dict = {}
    for path in paths:
        for e in _load_entries(path) or []:
            rid = e.get("request_id")
            if not rid:
                merged.append(e)
                continue
            seen = by_id.get(rid)
            if seen is None:
                by_id[rid] = e
                merged.append(e)
            elif int(e.get("delivered", 0)) > int(seen.get("delivered", 0)):
                merged[merged.index(seen)] = e
                by_id[rid] = e
    return merged


def replay_requests(path: Union[str, Sequence[str]], batcher) -> List:
    """Resubmit every persisted entry to ``batcher``; consume the file(s).

    ``path`` may be one journal or a sequence of them (a restarted fleet
    gathers every replica's drain journal plus the router's): entries are
    merged with :func:`merge_journal_entries`, so a request two journals
    both persisted (a failover raced a drain) replays exactly once — the
    highest ``delivered`` watermark wins.

    Returns the new ``GenRequest`` list (empty when no replay file
    exists). Restart-path hardening — replay must never crash server
    startup or double-serve:

    - a corrupt/unreadable file is renamed aside (``.corrupt``) and
      skipped, not raised;
    - an entry the restarted server can never run (a typed terminal
      ``REJECTED`` from ``submit`` — e.g. an elastic resize shrank the
      engine's ``max_len`` ceiling below the prompt — or a ``ValueError``
      on a malformed entry) is dropped with a warning, since
      re-persisting it would wedge every future restart on the same
      entry;
    - :class:`~autodist_tpu.serve.batcher.Backpressure` (replaying more
      entries than the new queue admits) stops the replay and atomically
      RE-PERSISTS the not-yet-submitted remainder, so already-submitted
      entries are consumed from the file (no duplicates) and the rest
      survive for the next drain cycle (no loss).
    """
    from autodist_tpu.serve.batcher import Backpressure
    from autodist_tpu.serve.sampling import SamplingParams

    paths = [path] if isinstance(path, str) else list(path)
    entries = merge_journal_entries(paths)
    reqs = []
    remainder: List[dict] = []
    for i, e in enumerate(entries):
        try:
            req = batcher.submit(
                e["prompt"], max_new_tokens=e["max_new_tokens"],
                timeout_s=e.get("timeout_s"),
                request_id=e.get("request_id") or None,
                sampling=SamplingParams.from_dict(e.get("sampling")))
            if req.unservable:
                # Typed unservable (e.g. over the restarted engine's
                # max_len ceiling): dropping it is the only move that
                # cannot wedge every future restart on the same entry.
                logging.warning(
                    "dropping unservable persisted entry %r (%s)",
                    e, req.error)
                continue
            reqs.append(req)
        except Backpressure:
            remainder = entries[i:]
            logging.warning(
                "replay hit backpressure after %d of %d entries; "
                "re-persisting the remaining %d", len(reqs), len(entries),
                len(remainder))
            break
        except (ValueError, KeyError) as err:
            logging.warning("dropping unservable persisted entry %r (%s)",
                            e, err)
    # Consume: already-submitted entries must never replay again. The
    # remainder (backpressure cut the replay short) re-persists atomically
    # into the FIRST journal; the others are spent either way.
    if remainder:
        tmp = f"{paths[0]}.tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"format_version": 2, "entries": remainder}, f)
        os.replace(tmp, paths[0])
    for p in paths[1 if remainder else 0:]:
        try:
            os.remove(p)
        except OSError:
            pass
    logging.info("replayed %d persisted serve requests from %s",
                 len(reqs), ", ".join(paths))
    return reqs


class DrainController:
    """SIGTERM-armed drain/persist/replay around one batcher."""

    def __init__(
        self,
        batcher,
        persist_path: str,
        drain_deadline_s: float = 30.0,
        registry: Optional[M.MetricsRegistry] = None,
    ):
        self.batcher = batcher
        self.persist_path = persist_path
        self.drain_deadline_s = drain_deadline_s
        self._prev_handler = None
        self._done = threading.Event()
        reg = registry or M.registry
        self._c_persisted = reg.counter("serve_requests_persisted_total")
        self._c_replayed = reg.counter("serve_requests_replayed_total")
        self._g_drain_s = reg.gauge("serve_last_drain_duration_s")

    # ------------------------------------------------------------- shutdown
    def quiesce(self) -> None:
        """Phase 1 only: stop the batcher admitting (new ``submit``s are
        refused, queued entries stop being promoted) while active decodes
        keep stepping. The rolling-upgrade entry point
        (``serve/router.py``): the router quiesces a replica, lets
        in-flight finish, then :meth:`shutdown` persists the rest."""
        self.batcher.quiesce()

    def shutdown(self) -> dict:
        """Run the full drain sequence; idempotent. Returns
        ``{"drained": n_finished_during_drain, "persisted": n}``."""
        import time

        if self._done.is_set():
            return {"drained": 0, "persisted": 0}
        self._done.set()
        t0 = time.monotonic()
        drained, leftovers = self.batcher.drain(self.drain_deadline_s)
        persisted = 0
        if leftovers:
            persisted = persist_requests(self.persist_path, leftovers)
            self._c_persisted.inc(persisted)
            logging.info(
                "drain: %d in-flight finished, %d undrained persisted -> %s",
                drained, persisted, self.persist_path)
        self._g_drain_s.set(time.monotonic() - t0)
        return {"drained": drained, "persisted": persisted}

    def replay(self) -> List:
        """Resubmit any previously persisted queue (restart path)."""
        reqs = replay_requests(self.persist_path, self.batcher)
        self._c_replayed.inc(len(reqs))
        return reqs

    # --------------------------------------------------------------- signal
    def install_preempt_hook(self, signum: int = signal.SIGTERM) -> None:
        """Arm ``signum`` to run :meth:`shutdown`, then hand the signal
        back — chaining a previous Python handler (a training-side snapshot
        hook on the same signal still fires) or honoring the default
        terminate disposition once the queue is safely persisted.
        Main-thread only (CPython signal rule)."""
        if self._prev_handler is not None:
            return

        def handler(sig, frame):
            logging.info("signal %d: draining serve batcher", sig)
            try:
                self.shutdown()
            except Exception:  # noqa: BLE001 - exit path must not throw
                logging.warning("serve drain failed", exc_info=True)
            from autodist_tpu.ft.snapshot import _chain_signal

            _chain_signal(sig, frame, self._prev_handler)

        self._prev_handler = signal.signal(signum, handler) or signal.SIG_DFL
