"""Elastic restart: recompile the strategy on the surviving mesh, restore
the latest verified snapshot into the new shardings.

GSPMD treats recompilation-on-resize as a first-class operation (GSPMD
§3.5, arXiv:2105.04663; the MPMD pipeline work arXiv:2412.14374 makes the
same move across program boundaries) — the sharded program is a pure
function of (strategy, mesh), so elasticity is: derive a fresh
``ResourceSpec`` from whatever survived, rebuild Strategy → ShardingPlan →
``DistributedTrainStep`` on the shrunken (or re-grown) mesh, and restore
the snapshot through the Saver's re-sharding read. No state migration
protocol: the checkpoint layer's "any sharding in, any sharding out"
contract (``checkpoint/saver.py``) already IS the migration.

Two entry points:

- :func:`recompile_on` + :func:`resume_from_snapshot` — the functional
  pieces (used by the tier-1 kill/resume test directly);
- :class:`ElasticController` — glues a
  :class:`~autodist_tpu.ft.heartbeat.HealthMonitor` to the rebuild: peer
  death flips ``restart_needed``, and ``resume(...)`` performs the
  recompile + restore in one call.

Losses after an elastic resume match the uninterrupted run when the
global batch is unchanged: data-parallel degree is not part of the math
(the mean over the global batch is the same sum in a different shard
order), which is exactly what the tier-1 test pins.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax

from autodist_tpu.ft.heartbeat import HealthMonitor, PeerState
from autodist_tpu.ft.snapshot import SnapshotManager
from autodist_tpu.kernel import DistributedTrainStep, GraphTransformer, build_mesh
from autodist_tpu.model_item import ModelItem, OptimizerSpec
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.utils import logging


def surviving_resource_spec(devices: Sequence[Any],
                            template: Optional[ResourceSpec] = None
                            ) -> ResourceSpec:
    """Re-read the cluster description from the devices that survived.

    The in-process rendering of "re-read ResourceSpec from the surviving
    hosts": group the live devices by owning process and emit a spec with
    one node per surviving process (chief = lowest process index).
    ``template`` donates non-membership fields (accelerator kind,
    bandwidths) so planning constants survive the resize.
    """
    by_proc: dict = {}
    for d in devices:
        by_proc.setdefault(int(getattr(d, "process_index", 0)), []).append(d)
    if not by_proc:
        raise ValueError("no surviving devices to build a ResourceSpec from")
    procs = sorted(by_proc)
    d: dict = {}
    if len(procs) == 1:
        d["nodes"] = [{"address": "localhost",
                       "chips": len(by_proc[procs[0]]), "chief": True}]
    else:
        d["nodes"] = [
            {"address": f"process-{p}", "chips": len(by_proc[p]),
             "chief": p == procs[0]}
            for p in procs
        ]
    if template is not None:
        t = template.to_dict()
        d["tpu"] = t.get("tpu", {})
        # A topology names the ORIGINAL chip count; it no longer applies.
        d["tpu"].pop("topology", None)
    elif devices and getattr(devices[0], "platform", "") == "tpu":
        d["tpu"] = {"accelerator": str(devices[0].device_kind)}
    return ResourceSpec(resource_dict=d)


def recompile_on(
    devices: Sequence[Any],
    loss_fn: Callable,
    params: Any,
    example_batch: Any = None,
    strategy_builder=None,
    optimizer=None,
    mesh_axes: Sequence[str] = ("data",),
    spec_template: Optional[ResourceSpec] = None,
    sparse_names: Sequence[str] = (),
    **step_kwargs,
) -> DistributedTrainStep:
    """Strategy → plan → compiled step on exactly ``devices``.

    The same capture → strategy → compile → transform pipeline as
    ``AutoDist.build``, but against an explicit surviving-device list
    instead of the full runtime — the mesh resize is the whole point.
    """
    from autodist_tpu.strategy import AllReduce, StrategyCompiler

    spec = surviving_resource_spec(devices, template=spec_template)
    mesh = build_mesh(spec, axes=tuple(mesh_axes), devices=list(devices))
    builder = strategy_builder or AllReduce()
    if isinstance(optimizer, OptimizerSpec):
        opt_spec, tx = optimizer, optimizer.make()
    elif optimizer is None:
        opt_spec = OptimizerSpec("sgd", {"learning_rate": 0.01})
        tx = opt_spec.make()
    else:
        opt_spec, tx = OptimizerSpec("custom"), optimizer
    model_item = ModelItem.from_params(
        params, optimizer_spec=opt_spec, loss_fn=loss_fn,
        example_batch=example_batch, sparse_names=sparse_names,
    )
    strategy = builder.build(model_item, spec)
    compiled = StrategyCompiler(model_item).compile(strategy)
    plan = GraphTransformer(compiled, model_item, mesh).transform()
    logging.info(
        "elastic recompile: %d devices, mesh %s, strategy %s",
        len(list(devices)), dict(zip(mesh.axis_names, mesh.devices.shape)),
        type(builder).__name__,
    )
    return DistributedTrainStep(plan, loss_fn, tx, **step_kwargs)


def resume_from_snapshot(step: DistributedTrainStep, params: Any,
                         snapshots: SnapshotManager):
    """Fresh-or-restored state for ``step``, from the newest snapshot that
    passes integrity verification (ring fallback on corruption).

    Exactly ``DistributedTrainStep.init_or_restore`` with the snapshot
    manager's verified restore plugged in: the resharding read is the
    Saver's partial parallel path, so resuming 8→4 devices never
    materializes full arrays on one host.
    """
    return step.init_or_restore(
        params, restore_fn=snapshots.restore_latest_valid)


class ElasticController:
    """Failure detection → drain-the-verdict → recompile → restore.

    Wraps a :class:`HealthMonitor` (peer death sets ``restart_needed``)
    and a :class:`SnapshotManager`; :meth:`resume` performs the elastic
    rebuild on whatever devices the caller says survived (defaulting to
    the runtime's current view).
    """

    def __init__(self, monitor: Optional[HealthMonitor],
                 snapshots: SnapshotManager):
        self.monitor = monitor
        self.snapshots = snapshots
        self.restart_needed = False
        if monitor is not None:
            monitor.on_transition(self._on_transition)

    def _on_transition(self, pid: int, old: PeerState, new: PeerState) -> None:
        if new is PeerState.DEAD:
            logging.warning(
                "peer %d declared dead; flagging elastic restart", pid)
            self.restart_needed = True

    def resume(
        self,
        loss_fn: Callable,
        params: Any,
        example_batch: Any = None,
        devices: Optional[Sequence[Any]] = None,
        **recompile_kwargs,
    ) -> Tuple[DistributedTrainStep, Any]:
        """(recompiled step, restored-or-fresh state) on the surviving
        devices. Clears ``restart_needed``."""
        devices = list(devices) if devices is not None else jax.devices()
        step = recompile_on(devices, loss_fn, params, example_batch,
                            **recompile_kwargs)
        state = resume_from_snapshot(step, params, self.snapshots)
        self.restart_needed = False
        return step, state
