"""Preemption-aware train-state snapshots: async ring + integrity manifest.

Checkpointing (``checkpoint/saver.py``) answers "persist this state";
snapshots answer the *fault-tolerance* question: keep a short ring of
recent states always on disk, written off the critical path, each entry
verifiable, so a preempted or crashed run resumes from seconds-old work —
on the same mesh or a reshaped one (``ft/elastic.py``).

Mechanics per snapshot:

1. **device→host copy on the calling thread** — mandatory before
   returning, because the train step donates its state buffers: the next
   ``step()`` invalidates the device values. The copy itself is cheap
   (the dispatch queue keeps the device busy; the host blocks only on the
   transfer).
2. **background write** through the existing
   :class:`~autodist_tpu.checkpoint.saver.Saver` (atomic stage→swap, one
   file per shard block). One snapshot in flight at a time: if the
   previous write is still running, the new request is *skipped* (counted
   in ``ft_snapshots_skipped_total``) rather than queued — snapshots are
   a freshness ring, not a log.
3. **manifest**: after the swap, ``MANIFEST.json`` inside the snapshot dir
   records the step + a sha256 per file. :meth:`SnapshotManager.verify`
   re-hashes; :meth:`latest_valid` walks the ring newest→oldest skipping
   corrupt entries, so a torn or bit-rotted newest snapshot degrades to
   the previous ring slot instead of a failed restore.
4. **ring prune**: newest ``keep`` snapshots retained.

``install_preempt_hook`` arms SIGTERM — the TPU preemption signal — to
force a final synchronous snapshot from a registered state provider before
the process exits, chaining to any previously-installed handler.

Snapshot dirs use the Saver's ``ckpt-<step>`` naming, so every Saver
facility (``latest_checkpoint``, ``restore``, serving's
``restore_params``) works on a snapshot directory unchanged.
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
import signal
import threading
import time
from typing import Any, Callable, Optional, Tuple

import jax

from autodist_tpu import metrics as M
from autodist_tpu.chaos import hooks as chaos_hooks
from autodist_tpu.checkpoint.saver import Saver, _to_host
from autodist_tpu.ft.config import FTConfig
from autodist_tpu.obs import recorder as obs_recorder
from autodist_tpu.obs import spans as obs_spans
from autodist_tpu.utils import logging, retry

MANIFEST = "MANIFEST.json"

#: Snapshot-write retry (utils/retry.py): a transient unwritable dir
#: (remount, permission flap — the chaos ``snapshot_unwritable`` fault)
#: heals on a quick retry; a persistent failure still surfaces loudly
#: through ``wait()`` within ~2s instead of silently skipping ring slots.
_WRITE_RETRY = retry.RetryPolicy(
    initial_s=0.05, max_s=0.5, multiplier=2.0, jitter=0.5,
    max_attempts=3, deadline_s=2.0)


def _chain_signal(sig, frame, prev) -> None:
    """Hand a caught signal on to whatever was installed before us: call a
    Python handler; re-deliver under ``SIG_DFL`` when the default
    disposition (terminate) was in place; do nothing for ``SIG_IGN``."""
    if callable(prev):
        prev(sig, frame)
    elif prev == signal.SIG_DFL:
        signal.signal(sig, signal.SIG_DFL)
        os.kill(os.getpid(), sig)


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def latest_snapshot_step(directory: str) -> Optional[int]:
    """Step of the newest *manifest-carrying* snapshot under ``directory``,
    or None. Cheap (no hashing) — the supervisor's progress probe."""
    saver = Saver(directory)
    for name in reversed(saver._list_checkpoints()):
        mpath = os.path.join(directory, name, MANIFEST)
        try:
            with open(mpath, encoding="utf-8") as f:
                return int(json.load(f)["step"])
        except (OSError, ValueError, KeyError):
            continue
    return None


class SnapshotManager:
    """Async ring of verified train-state snapshots.

    ``every_steps`` / ``every_s`` drive :meth:`maybe_snapshot`'s cadence
    (either trigger fires it; both 0 = only explicit :meth:`snapshot`
    calls). ``keep`` bounds the ring. All writes go through an internal
    :class:`Saver` rooted at ``directory``.
    """

    def __init__(
        self,
        directory: str,
        keep: int = 3,
        every_steps: int = 0,
        every_s: float = 0.0,
        registry: Optional[M.MetricsRegistry] = None,
    ):
        self.directory = directory
        self.keep = max(1, int(keep))
        self.every_steps = int(every_steps)
        self.every_s = float(every_s)
        self.saver = Saver(directory, max_to_keep=0)  # ring pruned here
        self._worker: Optional[threading.Thread] = None
        self._worker_error: Optional[BaseException] = None
        self._last_step: Optional[int] = None
        self._last_time = 0.0
        self._state_provider: Optional[Callable[[], Tuple[Any, int]]] = None
        self._prev_handler = None
        self._hook_lock = threading.Lock()
        self.preempted = False
        # Signal whose termination was deferred because the provider's state
        # was donated mid-step; re-delivered after the deferred snapshot.
        self._pending_signal: Optional[int] = None

        reg = registry or M.registry
        self._c_taken = reg.counter("ft_snapshots_taken_total")
        self._c_skipped = reg.counter("ft_snapshots_skipped_total")
        self._c_corrupt = reg.counter("ft_snapshots_corrupt_total")
        self._c_preempt = reg.counter("ft_preempt_snapshots_total")
        self._c_write_retries = reg.counter("ft_snapshot_write_retries_total")
        self._g_step = reg.gauge("ft_snapshot_last_step")

    @classmethod
    def from_config(cls, config: FTConfig,
                    registry: Optional[M.MetricsRegistry] = None
                    ) -> "SnapshotManager":
        cfg = config.resolved()
        return cls(
            cfg.snapshot_dir, keep=cfg.keep_snapshots,
            every_steps=cfg.snapshot_every_steps,
            every_s=cfg.snapshot_every_s, registry=registry,
        )

    # ------------------------------------------------------------------ take
    def maybe_snapshot(self, state: Any, step: Optional[int] = None,
                       step_obj: Any = None) -> Optional[str]:
        """Snapshot iff the step/time cadence says one is due (or a
        preemption flag is pending). Returns the target path when a
        snapshot was initiated, else None. Never blocks on file IO."""
        step = self._resolve_step(state, step)
        due = self.preempted
        if self.every_steps > 0 and (
                self._last_step is None
                or step - self._last_step >= self.every_steps):
            due = True
        if self.every_s > 0 and (
                time.monotonic() - self._last_time >= self.every_s):
            due = True
        if not due:
            return None
        path = self.snapshot(state, step=step, step_obj=step_obj,
                             block=self.preempted)
        if self._pending_signal is not None and path is not None:
            # The signal handler deferred termination because its registered
            # state was donated mid-step; THIS state is fresh. The deferred
            # snapshot is on disk — complete the preemption now.
            sig, self._pending_signal = self._pending_signal, None
            self._c_preempt.inc()
            logging.info(
                "deferred preemption snapshot written at step %d; "
                "re-delivering signal %d", step, sig)
            _chain_signal(sig, None, self._prev_handler)
        return path

    def snapshot(self, state: Any, step: Optional[int] = None,
                 step_obj: Any = None, block: bool = False) -> Optional[str]:
        """Take one snapshot now.

        ``step_obj`` (a :class:`~autodist_tpu.kernel.DistributedTrainStep`)
        converts pad-and-mask storage to logical shapes first — the same
        contract as ``step.save``. ``block=True`` waits for the write
        (preemption path); otherwise only the device→host copy happens
        here and the file IO runs on the background worker.
        """
        if self._busy():
            if not block:
                self._c_skipped.inc()
                logging.warning(
                    "snapshot at step %s skipped: previous write still in "
                    "flight", step)
                return None
            # A forced (preemption/final) snapshot must not be skippable:
            # drain the in-flight write first.
            self.wait()
        step = self._resolve_step(state, step)
        tree = step_obj.logical_state(state) if step_obj is not None else state
        # Host materialization on the calling thread — donation safety (the
        # caller's next train step invalidates these device buffers).
        with obs_spans.span("ft.snapshot.device_to_host", step=step):
            host_tree = jax.tree.map(_to_host, tree)
        path = os.path.join(self.directory, f"ckpt-{step}")
        self._last_step, self._last_time = step, time.monotonic()
        self._worker_error = None
        self._worker = threading.Thread(
            target=self._write, args=(host_tree, path, step),
            name="ft-snapshot", daemon=False,
        )
        self._worker.start()
        if block:
            self.wait()
        return path

    def _busy(self) -> bool:
        return self._worker is not None and self._worker.is_alive()

    def wait(self) -> None:
        """Join any in-flight snapshot write; re-raise its failure."""
        if self._worker is not None:
            self._worker.join()
            self._worker = None
        err, self._worker_error = self._worker_error, None
        if err is not None:
            raise RuntimeError("snapshot write failed") from err

    @staticmethod
    def _resolve_step(state: Any, step: Optional[int]) -> int:
        if step is not None:
            return int(step)
        s = getattr(state, "step", None)
        try:
            return int(s) if s is not None else 0
        except TypeError:
            return 0

    def _write(self, host_tree: Any, path: str, step: int) -> None:
        try:
            with obs_spans.span("ft.snapshot.write", step=step):
                def attempt():
                    # Chaos seam: an installed plant may refuse the write
                    # (transient unwritable dir) — exactly what the retry
                    # below must heal.
                    chaos_hooks.fire(chaos_hooks.SEAM_SNAPSHOT_WRITE,
                                     path=path, step=step)
                    if jax.process_count() > 1:
                        # The Saver's own async path runs its stage/swap
                        # barriers on the coordination service (pure RPC —
                        # safe off-thread); its blocking path would enqueue
                        # device collectives from this background thread,
                        # racing the train step's.
                        self.saver.save(host_tree, path=path, step=step,
                                        block=False)
                        self.saver.wait()
                    else:
                        self.saver.save(host_tree, path=path, step=step,
                                        block=True)
                    if jax.process_index() == 0:
                        self._write_manifest(path, step)

                # Re-saving the same path is safe (atomic stage->swap), so
                # a transient OSError costs a jittered retry, not the ring
                # slot.
                retry.retry_call(
                    attempt, policy=_WRITE_RETRY, retry_on=(OSError,),
                    describe=f"snapshot write {path}",
                    on_retry=lambda e, d, a: (
                        self._c_write_retries.inc(),
                        logging.warning(
                            "snapshot write attempt %d failed (%s); "
                            "retrying in %.3fs", a, e, d)))
                if jax.process_index() == 0:
                    self._prune()
            # Post-landing chaos seam: corruption/truncation faults bit-rot
            # the files AFTER the manifest recorded their true hashes —
            # verify()/latest_valid() must catch it.
            chaos_hooks.fire(chaos_hooks.SEAM_SNAPSHOT_WRITTEN,
                             path=path, step=step)
            self._c_taken.inc()
            self._g_step.set(step)
            # Black-box the landed snapshot: the doctor's progress marker
            # ("last good state at step N") and the restart supervisor's
            # progress evidence in one flight event.
            obs_recorder.record_event("snapshot", critical=False,
                                      step=step, path=path)
        except BaseException as e:  # noqa: BLE001 - surfaced via wait()
            self._worker_error = e
            obs_recorder.record_event(
                "error", error=f"snapshot write failed: "
                               f"{type(e).__name__}: {e}"[:500])
            logging.warning("snapshot write to %s failed", path, exc_info=True)

    def _write_manifest(self, path: str, step: int) -> None:
        files = {}
        for root, _, names in os.walk(path):
            for name in names:
                if name == MANIFEST:
                    continue
                full = os.path.join(root, name)
                files[os.path.relpath(full, path)] = _sha256(full)
        manifest = {"step": step, "time": time.time(), "files": files}
        tmp = os.path.join(path, MANIFEST + ".tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(manifest, f, indent=2, sort_keys=True)
        os.replace(tmp, os.path.join(path, MANIFEST))

    def _prune(self) -> None:
        names = self.saver._list_checkpoints()
        for stale in names[: -self.keep]:
            shutil.rmtree(os.path.join(self.directory, stale),
                          ignore_errors=True)

    # ---------------------------------------------------------------- verify
    def verify(self, path: str) -> bool:
        """True iff the snapshot's manifest exists and every listed file
        hashes to its recorded digest (and none is missing)."""
        try:
            with open(os.path.join(path, MANIFEST), encoding="utf-8") as f:
                manifest = json.load(f)
            for rel, digest in manifest["files"].items():
                if _sha256(os.path.join(path, rel)) != digest:
                    return False
        except (OSError, ValueError, KeyError):
            return False
        return True

    def latest_valid(self) -> Optional[str]:
        """Newest snapshot that passes :meth:`verify`, walking the ring
        newest→oldest; corrupt entries are skipped (counted + logged)."""
        self.wait()
        for name in reversed(self.saver._list_checkpoints()):
            path = os.path.join(self.directory, name)
            if self.verify(path):
                return path
            self._c_corrupt.inc()
            logging.warning(
                "snapshot %s failed integrity verification; falling back to "
                "the previous ring entry", path)
        return None

    def restore_latest_valid(self, target: Any = None,
                             shardings: Any = None) -> Optional[Any]:
        """Restore the newest verified snapshot (None when the ring holds
        no valid entry). The sharded-read path is the Saver's — each
        process reads only the regions its devices need, so this is also
        the resharded-resume primitive ``ft/elastic.py`` builds on."""
        path = self.latest_valid()
        if path is None:
            return None
        logging.info("restoring snapshot %s", path)
        return self.saver.restore(path, target=target, shardings=shardings)

    def latest_step(self) -> Optional[int]:
        return latest_snapshot_step(self.directory)

    # --------------------------------------------------------------- preempt
    def register_state_provider(
            self, fn: Callable[[], Tuple[Any, int]]) -> None:
        """``fn() -> (state_tree, step)`` called by the preemption hook to
        get the freshest snapshot-able state. Training loops typically
        register ``lambda: (step.logical_state(state), int(state.step))``
        and refresh the closure each iteration (or use
        :meth:`maybe_snapshot`, which observes state every call)."""
        self._state_provider = fn

    def install_preempt_hook(self, signum: int = signal.SIGTERM) -> None:
        """Arm ``signum`` (default SIGTERM — the TPU preemption notice) to
        force a final blocking snapshot, then hand the signal back: a
        previously installed Python handler is chained; the default
        disposition is HONORED by re-delivering the signal with ``SIG_DFL``
        restored (a preempted process must still die once its snapshot is
        safe — swallowing the signal would just convert the preemption
        notice into the un-notified SIGKILL that follows). Must be called
        from the main thread (CPython signal rule)."""
        if self._prev_handler is not None:
            return

        def handler(sig, frame):
            self.preempted = True
            saved = True
            # First thing, before any snapshot IO that may itself fail: the
            # preemption event is the doctor's DOC004 evidence, fsync'd
            # immediately (critical) so even a botched exit leaves it.
            obs_recorder.record_event("preempt", signal=int(sig),
                                      step=self._last_step)
            with self._hook_lock:
                if self._state_provider is not None:
                    try:
                        state, step = self._state_provider()
                        logging.info(
                            "preemption signal %d: forcing final snapshot at "
                            "step %d", sig, step)
                        self.snapshot(state, step=step, block=True)
                        self._c_preempt.inc()
                    except Exception:  # noqa: BLE001 - exit path must not throw
                        # Dominant cause: the registered state's buffers were
                        # DONATED by the train step that is executing right
                        # now ("Array has been deleted"). Dying here would
                        # lose the final snapshot, so termination is
                        # DEFERRED: the flag below makes the loop's next
                        # maybe_snapshot call — which holds the fresh,
                        # un-donated state — take the forced snapshot and
                        # then re-deliver this signal to finish the exit.
                        saved = False
                        self._pending_signal = sig
                        logging.warning(
                            "preemption snapshot from the signal handler "
                            "failed (state likely donated mid-step); "
                            "deferring to the next maybe_snapshot",
                            exc_info=True)
            if saved:
                _chain_signal(sig, frame, self._prev_handler)

        self._prev_handler = signal.signal(signum, handler) or signal.SIG_DFL
