"""Findings and reports: the analyzer's structured output.

Every defect the passes detect is a :class:`Finding` with a STABLE,
greppable code (the ``SL*`` table below — tests and operators key on these,
so codes are append-only) plus a human message; an :class:`AnalysisReport`
bundles the findings with the informational tables (planned-vs-actual wire,
memory summary) the CLI surfaces render. ``report.ok`` is the gate the
plan cache and the selftest trust: no error-severity findings.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

#: Stable finding codes (append-only). Severity shown is the default the
#: passes emit; see docs/analysis.md for the full catalog with examples.
FINDING_CODES: Dict[str, str] = {
    # wire conformance (inventory vs promised wire)
    "SLW001": "unplanned collective: payload exceeds every planned wire",
    "SLW002": "missing collective: a planned op kind is absent",
    "SLW003": "unattributed large collective (informational)",
    # static memory budget
    "SLM001": "per-chip state overcommits HBM headroom",
    "SLM002": "state + compiled temp/peak overcommits HBM headroom",
    "SLM003": "scheduled peak live bytes overcommit HBM though totals fit",
    # deadlock / ordering / consistency hazards
    "SLH001": "replica-group ordering mismatch across rendezvousing programs",
    "SLH002": "donated/aliased buffer size mismatch",
    "SLH003": "degradation drift: plan flags disagree with the shared predicate",
    "SLH004": "cross-program channel/permute ordering cycle (potential deadlock)",
    # strategy screening (pre-lowering)
    "SLS001": "strategy node cannot lower (screen reject)",
    # measured wire (trace attribution vs the promise — obs/attrib.py;
    # warnings only: traces are optional and the join is heuristic)
    "SLT001": "measured collective with no planned counterpart",
    "SLT002": "promised collective never observed in the trace",
    "SLT003": "per-bucket measured overlap below the priced exposure",
    # schedule passes (analysis/sched.py over the compiled-HLO DAG)
    "SLO001": "gradsync bucket structurally unable to overlap (serialized)",
    "SLO002": "scheduled overlap below the priced hidden fraction",
}

ERROR, WARNING, INFO = "error", "warning", "info"


@dataclass(frozen=True)
class Finding:
    """One defect (or note) from one pass."""

    code: str
    severity: str                 # error | warning | info
    message: str
    var: str = ""
    pass_name: str = ""           # wire | memory | hazard | screen
    details: Dict = field(default_factory=dict)

    def __post_init__(self):
        if self.code not in FINDING_CODES:
            raise ValueError(f"unknown finding code {self.code!r}")
        if self.severity not in (ERROR, WARNING, INFO):
            raise ValueError(f"unknown severity {self.severity!r}")

    def render(self) -> str:
        where = f" var={self.var}" if self.var else ""
        return f"{self.code} [{self.severity}]{where}: {self.message}"


@dataclass
class AnalysisReport:
    """All findings from one analyzer run, plus the informational tables."""

    findings: List[Finding] = field(default_factory=list)
    tables: Dict = field(default_factory=dict)
    program: str = ""

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == WARNING]

    @property
    def ok(self) -> bool:
        """No error-severity findings — the bar cache validation and the
        selftest hold a program to."""
        return not self.errors

    def codes(self) -> Tuple[str, ...]:
        return tuple(f.code for f in self.findings)

    def extend(self, findings: List[Finding]) -> "AnalysisReport":
        self.findings.extend(findings)
        return self

    def summary(self) -> str:
        n_e, n_w = len(self.errors), len(self.warnings)
        label = f" {self.program}" if self.program else ""
        if not self.findings:
            return f"shardlint{label}: clean (0 findings)"
        return (f"shardlint{label}: {n_e} error(s), {n_w} warning(s), "
                f"{len(self.findings) - n_e - n_w} note(s): "
                + "; ".join(f.code for f in self.findings))

    def render(self) -> str:
        lines = [self.summary()]
        for f in self.findings:
            lines.append("  " + f.render())
        return "\n".join(lines)

    def to_json(self) -> Dict:
        return {
            "program": self.program,
            "ok": self.ok,
            "findings": [
                {
                    "code": f.code,
                    "severity": f.severity,
                    "var": f.var,
                    "pass": f.pass_name,
                    "message": f.message,
                    "details": f.details,
                }
                for f in self.findings
            ],
            "tables": self.tables,
        }


class AnalysisError(Exception):
    """Raised where an error-severity report must stop the caller (plan
    cache validation): carries the report so the eviction log can attach
    the findings."""

    def __init__(self, report: AnalysisReport):
        self.report = report
        super().__init__(report.render())


def report_to_text(report: AnalysisReport) -> str:
    """Render a report (findings + tables) for terminal output."""
    out = [report.render()]
    wire = report.tables.get("wire")
    if wire:
        out.append("")
        out.append(f"{'variable':32s} {'rendering':12s} {'planned ops':28s} "
                   f"{'planned':>10s} {'actual':>10s}")
        out.append("-" * 96)
        for row in wire:
            out.append(
                f"{row['var'][:32]:32s} {row['rendering']:12s} "
                f"{','.join(row['planned_ops'])[:28]:28s} "
                f"{row['planned_bytes'] / 1e6:8.3f}MB "
                + (f"{row['actual_bytes'] / 1e6:8.3f}MB"
                   if row.get("actual_bytes") is not None else f"{'—':>10s}")
            )
    sched = report.tables.get("sched_overlap")
    if sched:
        out.append("")
        out.append(f"{'bucket':>6s} {'collectives':>11s} {'wire':>10s} "
                   f"{'window':>10s} {'sched ovl':>9s} {'async':>6s}")
        out.append("-" * 58)
        for row in sched:
            out.append(
                f"{row['bucket']:6d} {row['n_collectives']:11d} "
                f"{row['wire_bytes'] / 1e6:8.3f}MB "
                f"{row['window_compute_bytes'] / 1e6:8.3f}MB "
                f"{row['scheduled_overlap'] * 100:8.1f}% "
                f"{'yes' if row['async_pairs'] else 'no':>6s}")
    smem = report.tables.get("sched_memory")
    if smem and smem.get("n_buffers"):
        top = ", ".join(f"{t['name']} ({t['bytes'] / 1e6:.2f}MB)"
                        for t in smem.get("top_buffers", []))
        out.append(
            f"\nscheduled peak: "
            f"{smem['scheduled_peak_bytes'] / 1e9:.3f} GB/chip live at "
            f"position {smem.get('peak_position', 0)} of "
            f"{smem.get('n_instructions', 0)}"
            + (f" (top: {top})" if top else ""))
    mem = report.tables.get("memory")
    if mem:
        out.append("")
        line = (f"memory: {mem['state_gb_per_chip']:.3f} GB/chip state "
                f"(+{mem.get('temp_gb_per_chip', 0.0):.3f} temp)")
        if mem.get("capacity_gb_per_chip"):
            line += (f" vs {mem['usable_gb_per_chip']:.3f} GB usable "
                     f"({mem['headroom']:.0%} of "
                     f"{mem['capacity_gb_per_chip']:.1f} GB)")
        else:
            line += " — budget unchecked (no ResourceSpec)"
        out.append(line)
    return "\n".join(out)


def dumps(report: AnalysisReport) -> str:
    return json.dumps(report.to_json(), indent=2, sort_keys=True)
