"""CLI: ``python -m autodist_tpu.analysis --selftest``.

The zero-hardware shardlint proof, mirroring ``plan``/``obs --selftest``.
On a CPU mesh it exercises the whole subsystem and **exits nonzero if any
acceptance claim fails**:

1. **family conformance** — every dryrun family the driver gate runs
   (``__graft_entry__``: tensor-parallel, Parallax sparse, PS/ZeRO-3,
   zero1, bucketed backward-overlap, expert, ring, pipeline, PowerSGD,
   TopK+bf16, host offload,
   hybrid DCN) lowers, compiles, and the analyzer re-derives its pinned
   wire from the plan's promise with ZERO error/warning findings — the
   analyzer agrees with every existing wire pin on every family. The
   schedule passes (``analysis/sched.py``) are active throughout, and
   family #12 (bucketed overlap) must additionally report >= 2 gradsync
   buckets with scheduled overlap > 0 from its compiled schedule;
2. **seeded defects trip** — deliberately broken programs each raise the
   intended finding code: a leaked full-table collective (SLW001), a
   zero1 plan whose program re-fused to all-reduce (SLW002+SLW001), an
   HBM-overcommitted plan (SLM001), a plan whose shard_update flags drift
   from the shared predicate (SLH003), rendezvousing programs with
   reordered collectives / permuted replica groups (SLH001), a
   donated-alias size mismatch (SLH002), a structurally serialized
   gradsync bucket (SLO001), a scheduled-peak overcommit the static
   totals miss (SLM003), and a cross-program channel-ordering cycle
   (SLH004);
3. **cache eviction carries the finding** — a plan-cache entry that
   lowers but overcommits the spec's HBM is evicted loudly on ``get``
   (counted invalidated, warning text carries the SLM001 finding), never
   served or crashed on; an entry with a SCHEDULE finding (degenerate
   bucketing, SLO001) is evicted the same way, and the planner's search
   records schedule-screen rejections in provenance
   ``screen_rejected`` before pricing.
"""
from __future__ import annotations

import argparse
import io
import json
import logging as _pylogging
import os
import sys
import tempfile


def _provision_cpu_mesh(n_devices: int = 8) -> None:
    """Force an ``n_devices`` CPU host mesh when no backend exists yet
    (the __graft_entry__ recipe); a live backend is used as-is."""
    try:
        from jax._src import xla_bridge

        if xla_bridge._backends:
            return
    except Exception:  # noqa: BLE001 - internal moved: assume initialized
        return
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def _families():
    """The driver gate's dryrun family runners (``__graft_entry__`` at the
    repo root — run the selftest from a checkout, as CI does)."""
    import __graft_entry__ as g

    return {
        "tensor_parallel": g._dryrun_tensor_parallel,
        "parallax_sparse": g._dryrun_parallax_sparse,
        "ps_zero3": g._dryrun_ps_zero3,
        "zero1": g._dryrun_zero1,
        "bucketed_overlap": g._dryrun_bucketed_overlap,
        "expert_parallel": g._dryrun_expert_parallel,
        "ring_attention": g._dryrun_ring_attention,
        "pipeline_parallel": g._dryrun_pipeline_parallel,
        "compressed_sync": g._dryrun_compressed_sync,
        "topk_bf16": g._dryrun_topk_bf16,
        "host_offload": g._dryrun_host_offload,
        "hybrid_dcn": g._dryrun_hybrid_dcn,
    }


def selftest() -> int:  # noqa: C901 - one linear proof, mirrors plan's
    """Returns a process exit code; prints ONE JSON line."""
    _provision_cpu_mesh()
    import jax

    from autodist_tpu.analysis import (
        CollectiveInventory,
        alias_hazards,
        analyze_plan,
        analyze_program,
        compiled_hlo,
        rendezvous_hazards,
    )
    from autodist_tpu.api import AutoDist
    from autodist_tpu.resource_spec import ResourceSpec

    failures = []
    n = jax.device_count()
    spec = ResourceSpec(resource_dict={
        "nodes": [{"address": "localhost", "chips": n, "chief": True}],
    })

    # ------------------------------------------- 1. family conformance
    family_rows = {}
    sched_buckets_overlapped = 0
    try:
        runners = _families()
    except ImportError as e:
        runners = {}
        failures.append(f"__graft_entry__ unavailable ({e}): run the "
                        f"selftest from a repo checkout")
    for tag, runner in runners.items():
        AutoDist.reset_default()
        try:
            result = runner(n)
            if result is None:
                family_rows[tag] = "skip"  # toolchain/divisor self-skip
                continue
            step, params, batch, _mesh = result
            if not hasattr(step, "plan") or not hasattr(step, "_compile"):
                family_rows[tag] = "no-plan-surface"
                continue
            state = step.init(params)
            hlo = compiled_hlo(step, state, batch)
            report = analyze_program(
                step.plan, hlo, resource_spec=spec, batch=batch,
                program=tag)
            bad = report.errors + report.warnings
            if bad:
                failures.append(
                    f"family {tag}: {len(bad)} false finding(s): "
                    + "; ".join(f.render() for f in bad))
                family_rows[tag] = "FALSE-FINDINGS"
            else:
                family_rows[tag] = "clean"
            # The analyzer must RE-DERIVE the family's pinned wire, not
            # merely stay silent: the promised-wire table has to carry the
            # rendering each family exists to prove.
            renderings = {row["rendering"]
                          for row in report.tables.get("wire", [])}
            expect = {"zero1": "zero1", "parallax_sparse": "sparse",
                      "ps_zero3": "zero3", "tensor_parallel": "partitioned",
                      "expert_parallel": "expert",
                      "bucketed_overlap": "zero1"}.get(tag)
            # Family #12: the analyzer's promised-wire table must carry
            # the bucket attribution (per-bucket allowances in VarWire),
            # and the SCHEDULE pass must see >= 2 buckets whose compiled
            # schedule actually provides overlap (> 0) — the zero-
            # execution face of the family's latency-hiding claim.
            if tag == "bucketed_overlap":
                bucket_ids = {row.get("bucket")
                              for row in report.tables.get("wire", [])
                              if row.get("bucket") is not None}
                if len(bucket_ids) < 2:
                    failures.append(
                        f"family {tag}: wire table attributes "
                        f"{len(bucket_ids)} bucket(s); expected >= 2")
                sched_rows = report.tables.get("sched_overlap", [])
                overlapped = [r for r in sched_rows
                              if r.get("scheduled_overlap", 0) > 0]
                sched_buckets_overlapped = len(overlapped)
                if len(overlapped) < 2:
                    failures.append(
                        f"family {tag}: {len(overlapped)} bucket(s) show "
                        f"scheduled overlap > 0 (rows: {sched_rows}); "
                        f"expected >= 2")
            if expect and expect not in renderings:
                failures.append(
                    f"family {tag}: promised wire lost the {expect!r} "
                    f"rendering (got {sorted(renderings)})")
        except Exception as e:  # noqa: BLE001 - a crash is a failure too
            failures.append(f"family {tag} crashed the analyzer: "
                            f"{type(e).__name__}: {e}")
            family_rows[tag] = "CRASH"
        finally:
            AutoDist.reset_default()

    # ------------------------------------------- 2. seeded defects trip
    defect_rows = {}

    def expect_codes(label, codes, want):
        defect_rows[label] = sorted(set(codes))
        missing = [c for c in want if c not in codes]
        if missing:
            failures.append(
                f"seeded defect {label!r} did not trip {missing} "
                f"(got {sorted(set(codes))})")

    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from autodist_tpu.kernel.lowering import (
        DistributedTrainStep,
        GraphTransformer,
    )
    from autodist_tpu.kernel.mesh import build_mesh
    from autodist_tpu.model_item import ModelItem, OptimizerSpec
    from autodist_tpu.strategy.all_reduce_strategy import AllReduce
    from autodist_tpu.strategy.base import StrategyCompiler
    from autodist_tpu.strategy.zero1_strategy import Zero1

    def _embed_loss(params, batch):
        ids, y = batch
        x = jnp.take(params["embedding"], ids, axis=0)
        return jnp.mean(((x @ params["w"]).squeeze(-1) - y) ** 2)

    k = jax.random.PRNGKey(0)
    vocab, edim = 128 * n, 16
    eparams = {"embedding": jax.random.normal(k, (vocab, edim)),
               "w": jax.random.normal(k, (edim, 1))}
    ebatch = (jax.random.randint(k, (8 * n,), 0, vocab),
              jax.random.normal(k, (8 * n,)))
    sgd = OptimizerSpec("sgd", {"learning_rate": 0.1})
    eitem = ModelItem.from_params(
        eparams, optimizer_spec=sgd, loss_fn=_embed_loss,
        example_batch=ebatch)
    estrategy = StrategyCompiler(eitem).compile(AllReduce().build(eitem, spec))
    mesh = build_mesh(spec)
    good_plan = GraphTransformer(estrategy, eitem, mesh).transform()
    # (a) leaked full-table collective: compile from a plan whose table was
    # forced replicated (the GSPMD-resharding failure mode), analyze
    # against the plan that PROMISES row-sharding.
    bad_plan = GraphTransformer(estrategy, eitem, mesh).transform()
    bad_plan.plan_for("embedding").pspec = P()
    bad_plan.plan_for("embedding").update_pspec = P()
    leaky = DistributedTrainStep(bad_plan, _embed_loss, sgd.make())
    lstate = leaky.init(eparams)
    rep = analyze_program(
        good_plan, compiled_hlo(leaky, lstate, ebatch),
        resource_spec=spec, batch=ebatch, program="defect:leak")
    expect_codes("leaked_all_gather", rep.codes(), ["SLW001"])
    # the clean control must stay clean, or (a) proves nothing
    good = DistributedTrainStep(good_plan, _embed_loss, sgd.make())
    gstate = good.init(eparams)
    grep = analyze_program(
        good_plan, compiled_hlo(good, gstate, ebatch),
        resource_spec=spec, batch=ebatch, program="defect:control")
    if grep.errors or grep.warnings:
        failures.append("leak control program produced findings: "
                        + "; ".join(f.render() for f in grep.findings))

    # (b) zero1 promise vs a program whose wire re-fused to all-reduce
    from autodist_tpu.models import get_model

    model = get_model("mlp", in_dim=8 * n, hidden=(8 * n,), num_classes=4)
    mparams = model.init(jax.random.PRNGKey(0))
    mbatch = model.example_batch(2 * n)
    adam = OptimizerSpec("adam", {"learning_rate": 1e-3})
    mitem = ModelItem.from_params(
        mparams, optimizer_spec=adam, loss_fn=model.loss_fn,
        example_batch=mbatch)
    zstrategy = StrategyCompiler(mitem).compile(Zero1().build(mitem, spec))
    zplan = GraphTransformer(zstrategy, mitem, mesh).transform()
    astrategy = StrategyCompiler(mitem).compile(AllReduce().build(mitem, spec))
    aplan = GraphTransformer(astrategy, mitem, mesh).transform()
    astep = DistributedTrainStep(aplan, model.loss_fn, adam.make())
    astate = astep.init(mparams)
    rep = analyze_program(
        zplan, compiled_hlo(astep, astate, mbatch), resource_spec=spec,
        batch=mbatch, program="defect:refused")
    expect_codes("zero1_refused", rep.codes(), ["SLW002", "SLW001"])

    # (c) HBM overcommit: same plan, a spec whose chips carry ~no HBM
    tiny = ResourceSpec(resource_dict={
        "nodes": [{"address": "localhost", "chips": n, "chief": True}],
        "tpu": {"hbm_gb": 1e-5},
    })
    rep = analyze_plan(zplan, resource_spec=tiny, optimizer="adam",
                       program="defect:overcommit")
    expect_codes("hbm_overcommit", rep.codes(), ["SLM001"])

    # (d) degradation drift: flip shard_update on a var the shared
    # predicate degrades (simulating a lowering rule change within one
    # package version)
    dplan = GraphTransformer(zstrategy, mitem, mesh).transform()
    flipped = False
    for _name, vp in dplan.var_plans.items():
        if vp.degradations:
            vp.shard_update = True
            flipped = True
            break
    if not flipped:
        failures.append("drift defect could not find a degraded var to flip")
    rep = analyze_plan(dplan, strategy=zstrategy, program="defect:drift")
    expect_codes("degradation_drift", rep.codes(), ["SLH003"])

    # (e) rendezvous hazards: same collectives reordered / groups permuted
    prog_a = (
        "%all-reduce.1 = f32[64]{0} all-reduce(f32[64]{0} %x), "
        "channel_id=1, replica_groups={{0,1},{2,3}}, to_apply=%add\n"
        "%all-gather.1 = f32[64]{0} all-gather(f32[8]{0} %y), "
        "channel_id=2, replica_groups={{0,1,2,3}}, dimensions={0}\n")
    reordered = "\n".join(reversed(prog_a.strip().splitlines())) + "\n"
    permuted = prog_a.replace("{{0,1},{2,3}}", "{{1,0},{2,3}}")
    f_order = rendezvous_hazards({
        "stage0": CollectiveInventory.from_hlo(prog_a, "stage0"),
        "stage1": CollectiveInventory.from_hlo(reordered, "stage1")})
    expect_codes("rendezvous_order", [f.code for f in f_order], ["SLH001"])
    f_perm = rendezvous_hazards({
        "stage0": CollectiveInventory.from_hlo(prog_a, "stage0"),
        "stage1": CollectiveInventory.from_hlo(permuted, "stage1")})
    expect_codes("rendezvous_groups", [f.code for f in f_perm], ["SLH001"])
    f_same = rendezvous_hazards({
        "stage0": CollectiveInventory.from_hlo(prog_a, "stage0"),
        "stage1": CollectiveInventory.from_hlo(prog_a, "stage1")})
    if f_same:
        failures.append("identical programs reported a rendezvous hazard")

    # (f) donated-alias size mismatch
    bad_alias = (
        "HloModule jit__step, is_scheduled=true, "
        "input_output_alias={ {0}: (0, {}, may-alias) }, "
        "entry_computation_layout=...\n"
        "ENTRY %main.1 (p0: f32[64,64], p1: f32[32]) -> "
        "(f32[32,64], f32[]) {\n")
    expect_codes("alias_mismatch",
                 [f.code for f in alias_hazards(bad_alias)], ["SLH002"])

    # (g) structurally serialized gradsync bucket: the reduce-scatter's
    # result is consumed by the very next instruction with nothing
    # schedulable in between — the schedule provides zero overlap.
    from autodist_tpu.analysis import (
        ProgramGraph,
        channel_cycle_hazards,
        liveness_check,
        overlap_check,
    )

    serialized = (
        "HloModule serialized, is_scheduled=true\n\n"
        "ENTRY %main (p0: f32[64,64]) -> f32[8,64] {\n"
        "  %p0 = f32[64,64]{1,0} parameter(0)\n"
        "  %rs = f32[8,64]{1,0} reduce-scatter(f32[64,64]{1,0} %p0), "
        "channel_id=1, replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}, "
        "metadata={op_name=\"jit(_step)/transpose(jvp(gradsync.bucket_0))"
        "/reduce_scatter\"}\n"
        "  ROOT %out = f32[8,64]{1,0} copy(f32[8,64]{1,0} %rs)\n"
        "}\n")
    f_ser, _rows = overlap_check(
        ProgramGraph.from_hlo(serialized, "defect:serialized"))
    expect_codes("serialized_bucket", [f.code for f in f_ser], ["SLO001"])
    # the family-#12 control above already proves the clean side: real
    # bucketed programs analyze with zero SLO findings.

    # (h) scheduled-peak overcommit: the schedule materializes two big
    # transients simultaneously; the (tiny-capacity) spec's static totals
    # are not consulted here — liveness judges the schedule itself.
    transient = (
        "HloModule transient, is_scheduled=true\n\n"
        "ENTRY %main (p0: f32[512,512]) -> f32[512,512] {\n"
        "  %p0 = f32[512,512]{1,0} parameter(0)\n"
        "  %g1 = f32[512,512]{1,0} multiply(f32[512,512]{1,0} %p0, "
        "f32[512,512]{1,0} %p0)\n"
        "  %g2 = f32[512,512]{1,0} add(f32[512,512]{1,0} %g1, "
        "f32[512,512]{1,0} %p0)\n"
        "  ROOT %out = f32[512,512]{1,0} add(f32[512,512]{1,0} %g1, "
        "f32[512,512]{1,0} %g2)\n"
        "}\n")
    f_peak, peak_summary = liveness_check(
        ProgramGraph.from_hlo(transient, "defect:transient"),
        resource_spec=tiny, static_totals_ok=True)
    expect_codes("scheduled_overcommit", [f.code for f in f_peak],
                 ["SLM003"])
    if peak_summary.get("scheduled_peak_bytes", 0) != 3 * 512 * 512 * 4:
        failures.append(
            f"scheduled liveness mis-measured the transient peak: "
            f"{peak_summary}")

    # (i) cross-program channel cycle: three stages each order a shared
    # channel pair consistently pairwise, but the union is a cycle — the
    # MPMD deadlock SLH001's pairwise diff cannot see.
    def chan_prog(label, c1, c2):
        return ProgramGraph.from_hlo(
            "HloModule " + label + ", is_scheduled=true\n\n"
            "ENTRY %main (p0: f32[64]) -> f32[64] {\n"
            "  %p0 = f32[64]{0} parameter(0)\n"
            f"  %ar1 = f32[64]{{0}} all-reduce(f32[64]{{0}} %p0), "
            f"channel_id={c1}, replica_groups={{{{0,1}}}}, "
            f"to_apply=%add\n"
            f"  ROOT %ar2 = f32[64]{{0}} all-reduce(f32[64]{{0}} %ar1), "
            f"channel_id={c2}, replica_groups={{{{0,1}}}}, "
            f"to_apply=%add\n"
            "}\n", label)

    f_cycle = channel_cycle_hazards({
        "stage0": chan_prog("s0", 1, 2),
        "stage1": chan_prog("s1", 2, 3),
        "stage2": chan_prog("s2", 3, 1)})
    expect_codes("channel_cycle", [f.code for f in f_cycle], ["SLH004"])
    f_acyclic = channel_cycle_hazards({
        "stage0": chan_prog("s0", 1, 2),
        "stage1": chan_prog("s1", 2, 3),
        "stage2": chan_prog("s2", 1, 3)})
    if f_acyclic:
        failures.append("consistently-ordered programs reported a "
                        "channel cycle")

    # (j) the planner's search screen-rejects a schedule-defective seed
    # BEFORE pricing, recorded in provenance (the acceptance path: a
    # candidate that requests bucketed overlap with zero bucket-eligible
    # vars is structurally serialized — SLO001).
    import importlib

    # NB: `from autodist_tpu.plan import search` resolves to the search()
    # FUNCTION (plan/__init__ rebinds the name); go through sys.modules
    # for the module object (the tests/test_analysis.py convention).
    search_mod = importlib.import_module("autodist_tpu.plan.search")
    from autodist_tpu.strategy.ir import (
        NodeConfig,
        PSSynchronizer,
        Strategy,
    )

    def degenerate_bucketed_strategy(mi, rs):
        from autodist_tpu.strategy.base import reduction_devices

        dest = reduction_devices(rs)[0]
        s = Strategy(id=Strategy.new_id(rs.fingerprint()))
        s.graph_config.bucket_bytes = 1 << 20
        for var in mi.trainable_variables:
            s.node_config.append(NodeConfig(
                var_name=var.name,
                synchronizer=PSSynchronizer(reduction_destination=dest)))
        return s

    class _DegenerateSeed:
        def build(self, mi, rs):
            return degenerate_bucketed_strategy(mi, rs)

    real_slate = search_mod.candidate_slate
    search_mod.candidate_slate = lambda *a, **kw: (
        real_slate(*a, **kw) + [("DegenerateBucketed", _DegenerateSeed())])
    try:
        result = search_mod.PlanSearch(
            mitem, spec,
            search_mod.SearchConfig(generations=1)).run()
    finally:
        search_mod.candidate_slate = real_slate
    rejected = result.provenance.get("screen_rejected", {})
    expect_codes("search_screen_sched",
                 rejected.get("DegenerateBucketed", []), ["SLO001"])
    if "DegenerateBucketed" in result.provenance.get("seeds", {}):
        failures.append("schedule-screened seed was priced anyway")

    # ------------------------------- 3. cache eviction carries the finding
    from autodist_tpu.plan.cache import PlanCache

    tmpdir = tempfile.mkdtemp(prefix="analysis-selftest-")
    cache = PlanCache(cache_dir=os.path.join(tmpdir, "cache"), validate=True)
    # A valid entry round-trips through analyzer-backed validation...
    cache.put(mitem, spec, zstrategy)
    if cache.get(mitem, spec) is None:
        failures.append("clean cache entry failed analyzer validation")
    # ...and an entry that LOWERS but overcommits the (tiny-HBM) spec is
    # evicted with the SLM001 finding in the warning, never served.
    cache.put(mitem, tiny, zstrategy)
    log_buf = io.StringIO()
    handler = _pylogging.StreamHandler(log_buf)
    _pylogging.getLogger("autodist_tpu").addHandler(handler)
    try:
        drifted = cache.get(mitem, tiny)
    finally:
        _pylogging.getLogger("autodist_tpu").removeHandler(handler)
    if drifted is not None:
        failures.append("overcommitted cache entry was served as a hit")
    if cache.stats.get("invalidated", 0) < 1:
        failures.append("overcommitted entry was not counted invalidated")
    if "SLM001" not in log_buf.getvalue():
        failures.append("cache eviction warning carried no SLM001 finding")
    # ...and an entry with a SCHEDULE finding (degenerate bucketing:
    # bucket machinery requested, zero bucket-eligible vars — SLO001) is
    # evicted the same loud way, never trusted.
    cache.put(mitem, spec, degenerate_bucketed_strategy(mitem, spec))
    sched_buf = io.StringIO()
    handler = _pylogging.StreamHandler(sched_buf)
    _pylogging.getLogger("autodist_tpu").addHandler(handler)
    try:
        degenerate = cache.get(mitem, spec)
    finally:
        _pylogging.getLogger("autodist_tpu").removeHandler(handler)
    if degenerate is not None:
        failures.append(
            "cache entry with a schedule finding was served as a hit")
    if "SLO001" not in sched_buf.getvalue():
        failures.append("cache eviction warning carried no SLO001 finding")

    ok = not failures
    line = {
        "selftest": "autodist_tpu.analysis",
        "ok": ok,
        "families": family_rows,
        "n_families_clean": sum(
            1 for v in family_rows.values() if v == "clean"),
        "seeded_defects": defect_rows,
        "cache_eviction_finding": "SLM001" in log_buf.getvalue(),
        "cache_eviction_sched_finding": "SLO001" in sched_buf.getvalue(),
        "sched_buckets_overlapped": sched_buckets_overlapped,
        "device": jax.devices()[0].platform,
        "n_devices": n,
    }
    if failures:
        line["failures"] = failures
    print(json.dumps(line))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m autodist_tpu.analysis",
                                 description=__doc__)
    ap.add_argument("--selftest", action="store_true",
                    help="run the CPU shardlint proof and exit")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
