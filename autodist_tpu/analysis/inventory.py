"""Collective inventory: ONE parser for the collectives in a lowered program.

Every wire pin in the repo — the dryrun families' ``_hlo_wire`` checks, the
sparse/zero1 payload assertions, the analyzer's conformance pass — must read
a program's collectives the same way, or a dump-format change silently
splits "what tests check" from "what the analyzer reports". This module is
that single reading:

- :func:`hlo_contains` / :func:`assert_hlo_wire` / :func:`collective_sizes`
  are the (promoted) ``tests/helpers`` matchers, byte-compatible with their
  previous behavior; the test helper is now a thin re-export of these.
- :class:`CollectiveInventory` is the richer structured view: every
  collective op in a post-optimization HLO dump parsed into op kind, result
  and operand shapes/dtypes, payload bytes, replica groups (explicit
  ``{{0,1},{2,3}}`` and iota ``[2,4]<=[8]`` forms both expanded), channel
  id, and the named-scope ``op_name`` metadata — the substrate the
  analysis passes (``autodist_tpu.analysis.passes``) diff against the
  plan's promised wire.

HLO spells collectives with hyphens (``all-reduce(``), StableHLO with
underscores (``stablehlo.all_reduce``); named-scope metadata rides along as
``metadata={op_name="..."}`` / ``loc("...")`` attachments that must never
satisfy a presence check (a scope named ``zero1.reduce_scatter`` labels
whatever op a regression replaced the real collective with).
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

#: Canonical (hyphenated) collective op kinds in a post-optimization dump.
COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
)

# The payload-size half of wire pinning (the classifier
# tests/test_sparse_wire.py pioneered): op-call spellings with the opening
# paren, the exact needles `collective_sizes` greps.
COLLECTIVE_OPS = tuple(f"{k}(" for k in COLLECTIVE_KINDS)

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s4": 1, "u4": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3": 1,
    "bf16": 2, "f16": 2, "s16": 2, "u16": 2,
    "f32": 4, "s32": 4, "u32": 4,
    "f64": 8, "s64": 8, "u64": 8, "c64": 8,
    "c128": 16,
}


def dtype_bytes(dtype: str) -> int:
    """Bytes per element of an HLO dtype string (unknown kinds read as 4 —
    the conservative f32 default)."""
    return _DTYPE_BYTES.get(dtype, 4)


def _variants(op: str) -> Tuple[str, str]:
    """Both spellings of a collective name: hyphenated (post-optimization
    HLO) and underscored (StableHLO / traced jaxpr)."""
    base = op.strip().rstrip("(")
    return base.replace("_", "-"), base.replace("-", "_")


# jax.named_scope labels ride along as HLO metadata={op_name="..."} and
# StableHLO loc("...") attachments — strip both before matching so a
# present-pin can only be satisfied by an actual op call.
_METADATA_RE = re.compile(r'metadata=\{[^}]*\}|loc\("[^"]*"[^)]*\)')
_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')
_SHAPE_RE = re.compile(r"([a-z][0-9a-z]*)\[([0-9,]*)\]")
_CHANNEL_RE = re.compile(r"channel_id=(\d+)")
_GROUPS_EXPLICIT_RE = re.compile(r"replica_groups=\{(\{[0-9,{} ]*\})\}")
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?")


def hlo_contains(text: str, op: str) -> bool:
    """True when ``op`` (a collective like ``"reduce-scatter"``) appears AS
    AN OP CALL in a lowered/compiled program dump — post-optimization HLO
    (``all-gather(``), StableHLO (``stablehlo.all_gather``), or a traced
    jaxpr (``all_gather(``). Named-scope metadata mentioning the op does
    not count."""
    hyphen, underscore = _variants(op)
    needles = (f"{hyphen}(", f"stablehlo.{underscore}", f"{underscore}(")
    for line in text.splitlines():
        line = _METADATA_RE.sub("", line)
        if any(n in line for n in needles):
            return True
    return False


def assert_hlo_wire(text: str, present: Iterable[str] = (),
                    absent: Iterable[str] = (), label: str = "") -> None:
    """Pin a program's collective wire: every op in ``present`` must appear,
    none in ``absent`` may. Raises AssertionError naming the offender."""
    where = f" [{label}]" if label else ""
    for op in present:
        assert hlo_contains(text, op), (
            f"lowered program{where} carries no {op!r} wire")
    for op in absent:
        assert not hlo_contains(text, op), (
            f"lowered program{where} unexpectedly carries a {op!r} wire")


def collective_sizes(hlo_text: str, ops: Iterable[str] = COLLECTIVE_OPS,
                     ) -> List[int]:
    """Element count of every collective's result/operand array(s) in a
    post-optimization HLO dump (every shape on a collective's def line —
    the historical tests/helpers contract, preserved verbatim)."""
    sizes = []
    for line in hlo_text.splitlines():
        if "=" not in line or not any(op in line for op in ops):
            continue
        # Shapes sit after '=', e.g.
        #   %all-reduce.3 = (f32[4096,16]{1,0}, f32[]) all-reduce(...)
        lhs = line.split("=", 1)[1]
        shapes = re.findall(r"[a-z][0-9a-z]*\[([0-9,]*)\]", lhs)
        for s in shapes:
            dims = [int(d) for d in s.split(",") if d]
            n = 1
            for d in dims:
                n *= d
            sizes.append(n)
    return sizes


# ----------------------------------------------- compiled-program cache
# Lowering + XLA compile is the dominant cost of EVERY analyzer call; one
# bench/lint/attrib run used to re-lower the same program up to three
# times (explain --lint, bench --lint, the attribution capture). The text
# is cached per (step identity, arg shapes/dtypes): the same step object
# with the same abstract signature always lowers to the same program, so
# the cache can never serve a stale dump within a process. Keyed weakly —
# a released step releases its dumps.
_COMPILED_CACHE = None  # weakref.WeakKeyDictionary, created lazily


def _arg_signature(*trees) -> str:
    import jax

    parts = []
    for leaf in jax.tree_util.tree_leaves(trees):
        dtype = getattr(leaf, "dtype", type(leaf).__name__)
        shape = getattr(leaf, "shape", ())
        parts.append(f"{dtype}{tuple(shape)}")
    return "|".join(parts)


def _step_cache(step) -> Optional[Dict]:
    """The per-step cache dict, or None when the step can't be weakly
    referenced (caching silently off — correctness never depends on it)."""
    global _COMPILED_CACHE
    if _COMPILED_CACHE is None:
        import weakref

        _COMPILED_CACHE = weakref.WeakKeyDictionary()
    try:
        return _COMPILED_CACHE.setdefault(step, {})
    except TypeError:
        return None


def compiled_artifacts(step, state, batch) -> Tuple[str, float]:
    """(post-optimization HLO text, compiled temp/peak bytes) of a
    DistributedTrainStep's single-step program, cached per (step, arg
    shapes). The temp figure feeds the SLM002 budget; 0.0 when the
    backend doesn't expose ``memory_analysis``."""
    cache = _step_cache(step)
    key = ("step", _arg_signature(state, batch))
    if cache is not None and key in cache:
        return cache[key]
    compiled = step._compile(state, batch).lower(state, batch).compile()
    text = compiled.as_text()
    temp = 0.0
    try:
        mem = compiled.memory_analysis()
        temp = float(getattr(mem, "temp_size_in_bytes", 0))
    except Exception:  # noqa: BLE001 - optional backend API
        pass
    if cache is not None:
        cache[key] = (text, temp)
    return text, temp


def compiled_hlo(step, state, batch) -> str:
    """Post-optimization HLO of a DistributedTrainStep's single-step
    program — the text every wire pin greps, cached per (step, shapes)
    (StableHLO from ``lower_text`` shows collectives only when they are
    explicit in the traced program; GSPMD-inserted ones exist only
    post-compile.)"""
    return compiled_artifacts(step, state, batch)[0]


def compiled_window(step, state, batch, num_steps: int,
                    stacked: bool = False):
    """(compiled window program, its post-optimization HLO text), cached
    per (step, arg shapes, window) — the one-compile contract the
    measured-wire attribution rides (``obs/attrib.py``): the SAME compile
    serves the instruction-name → scope map and the captured execution.
    Lowered on abstract shapes only; nothing executes here."""
    import jax

    cache = _step_cache(step)
    key = ("window", _arg_signature(state, batch), int(num_steps),
           bool(stacked))
    if cache is not None and key in cache:
        return cache[key]
    fn = step._window_program(state, batch, num_steps, stacked, False)
    compiled = fn.lower(jax.eval_shape(lambda: state),
                        jax.eval_shape(lambda: batch)).compile()
    out = (compiled, compiled.as_text())
    if cache is not None:
        cache[key] = out
    return out


def _expand_iota_groups(num_groups: int, group_size: int,
                        dims: Tuple[int, ...],
                        perm: Optional[Tuple[int, ...]]) -> Tuple[Tuple[int, ...], ...]:
    """Expand HLO's iota replica-group form ``[g,s]<=[dims]T(perm)`` into
    explicit groups (the v2 'iota tile assignment' encoding)."""
    ids = np.arange(int(np.prod(dims))).reshape(dims)
    if perm is not None:
        ids = ids.transpose(perm)
    ids = ids.ravel().reshape(num_groups, group_size)
    return tuple(tuple(int(x) for x in row) for row in ids)


@dataclass(frozen=True)
class Collective:
    """One collective op parsed from a lowered/compiled program."""

    op: str                                   # canonical hyphenated kind
    results: Tuple[Tuple[str, Tuple[int, ...]], ...]   # (dtype, dims)
    operands: Tuple[Tuple[str, Tuple[int, ...]], ...]
    replica_groups: Tuple[Tuple[int, ...], ...] = ()   # expanded groups
    groups_raw: str = ""                      # textual form, "" if absent
    channel_id: Optional[int] = None
    op_name: str = ""                         # metadata op_name scope path
    line: str = ""
    # HLO instruction name from the def line ("reduce-scatter.48", no %):
    # the key device profiles carry per event, so measured-wire attribution
    # (obs/attrib.py) can join a traced op to this inventory entry.
    name: str = ""

    @staticmethod
    def _elems(shapes) -> int:
        total = 0
        for _dt, dims in shapes:
            n = 1
            for d in dims:
                n *= d
            total += n
        return total

    @property
    def result_elements(self) -> int:
        return self._elems(self.results)

    @property
    def operand_elements(self) -> int:
        return self._elems(self.operands)

    @property
    def max_payload_elements(self) -> int:
        """Largest single array this collective touches (result or operand)
        — the figure the payload pins compare against variable sizes."""
        per = [self._elems([s]) for s in self.results + self.operands]
        return max(per) if per else 0

    @property
    def result_bytes(self) -> int:
        return sum(
            self._elems([s]) * dtype_bytes(s[0]) for s in self.results)

    @property
    def group_size(self) -> int:
        return len(self.replica_groups[0]) if self.replica_groups else 0


@dataclass
class CollectiveInventory:
    """Every collective in one program, with per-kind lookups — the
    analyzer's structured view of "what the wire actually is"."""

    collectives: List[Collective] = field(default_factory=list)
    program: str = ""   # label for multi-program (rendezvous) analyses

    @classmethod
    def from_hlo(cls, text: str, program: str = "") -> "CollectiveInventory":
        """Parse a post-optimization HLO dump (``compiled.as_text()``).

        Async pairs (``all-reduce-start``/``-done``) count once, under the
        base kind; named-scope metadata never creates an entry.
        """
        out = []
        for raw in text.splitlines():
            op_name_m = _OP_NAME_RE.search(raw)
            line = _METADATA_RE.sub("", raw).strip()
            if "=" not in line:
                continue
            found = None
            for kind in COLLECTIVE_KINDS:
                for spelled in (f"{kind}(", f"{kind}-start("):
                    idx = line.find(spelled)
                    if idx >= 0:
                        found = (kind, idx)
                        break
                if found:
                    break
            if not found:
                continue
            kind, idx = found
            eq = line.index("=")
            if idx < eq:  # '=' inside the call: not a def line
                continue
            results = tuple(
                (m.group(1), tuple(int(d) for d in m.group(2).split(",") if d))
                for m in _SHAPE_RE.finditer(line[eq + 1:idx])
            )
            operands = tuple(
                (m.group(1), tuple(int(d) for d in m.group(2).split(",") if d))
                for m in _SHAPE_RE.finditer(line[idx:])
            )
            groups: Tuple[Tuple[int, ...], ...] = ()
            groups_raw = ""
            gm = _GROUPS_EXPLICIT_RE.search(line)
            if gm:
                groups_raw = gm.group(0)
                groups = tuple(
                    tuple(int(x) for x in g.split(",") if x.strip())
                    for g in re.findall(r"\{([0-9, ]*)\}", gm.group(1))
                )
            else:
                im = _GROUPS_IOTA_RE.search(line)
                if im:
                    groups_raw = im.group(0)
                    dims = tuple(int(x) for x in im.group(3).split(","))
                    perm = (tuple(int(x) for x in im.group(4).split(","))
                            if im.group(4) else None)
                    groups = _expand_iota_groups(
                        int(im.group(1)), int(im.group(2)), dims, perm)
            cm = _CHANNEL_RE.search(line)
            nm = re.match(r"(?:ROOT\s+)?%?([A-Za-z0-9_.-]+)\s*$",
                          line[:eq].strip())
            out.append(Collective(
                op=kind,
                results=results,
                operands=operands,
                replica_groups=groups,
                groups_raw=groups_raw,
                channel_id=int(cm.group(1)) if cm else None,
                op_name=op_name_m.group(1) if op_name_m else "",
                line=line,
                name=nm.group(1) if nm else "",
            ))
        return cls(collectives=out, program=program)

    # -------------------------------------------------------------- lookups
    def ops(self) -> Tuple[str, ...]:
        """Distinct op kinds present, in :data:`COLLECTIVE_KINDS` order."""
        present = {c.op for c in self.collectives}
        return tuple(k for k in COLLECTIVE_KINDS if k in present)

    def by_op(self, kind: str) -> List[Collective]:
        return [c for c in self.collectives if c.op == kind]

    def has(self, kind: str) -> bool:
        return any(c.op == kind for c in self.collectives)

    def max_payload(self, kind: Optional[str] = None) -> int:
        cs = self.collectives if kind is None else self.by_op(kind)
        return max((c.max_payload_elements for c in cs), default=0)

    def sizes(self, ops: Iterable[str] = COLLECTIVE_KINDS) -> List[int]:
        """Per-array element counts across the selected kinds (results and
        operands, matching the historical :func:`collective_sizes` rule)."""
        kinds = {o.rstrip("(") for o in ops}
        out: List[int] = []
        for c in self.collectives:
            if c.op in kinds:
                out.extend(
                    Collective._elems([s]) for s in c.results + c.operands)
        return out

    def to_json(self) -> List[Dict]:
        return [
            {
                "op": c.op,
                "name": c.name,
                "result_elements": c.result_elements,
                "result_bytes": c.result_bytes,
                "max_payload_elements": c.max_payload_elements,
                "n_groups": len(c.replica_groups),
                "group_size": c.group_size,
                "channel_id": c.channel_id,
                "op_name": c.op_name,
            }
            for c in self.collectives
        ]

    def describe(self) -> str:
        lines = [f"CollectiveInventory({self.program or 'program'}: "
                 f"{len(self.collectives)} collectives)"]
        for c in self.collectives:
            lines.append(
                f"  {c.op:<19s} {c.result_elements:>10d} elems "
                f"{c.result_bytes:>10d} B groups={len(c.replica_groups)}"
                f"x{c.group_size}"
                + (f"  [{c.op_name}]" if c.op_name else "")
            )
        return "\n".join(lines)
