"""shardlint: static sharding/collective/memory analysis of lowered programs.

AutoDist's premise is that the strategy compiler — not the user — is
accountable for what the transformed graph actually does; GSPMD
(arXiv 2105.04663) inserts resharding collectives silently wherever
annotations are inconsistent, so "the strategy said reduce-scatter" and
"the program carries reduce-scatter" are different claims. This subsystem
checks the second claim statically: take (Strategy, ShardingPlan,
ResourceSpec, compiled HLO text) and produce a structured findings report
with no device execution — it runs on CPU under ``JAX_PLATFORMS=cpu``.

Surfaces:

- :func:`analyze_plan` — plan-only passes (degradation drift, static HBM
  budget, optional strategy screen): what ``plan/cache.py`` runs before
  trusting a cached winner;
- :func:`analyze_program` — the above plus wire conformance, alias
  hazards, and the SCHEDULE passes (``analysis/sched.py``: per-gradsync-
  bucket scheduled overlap, scheduled-liveness peak with donation
  folding) against a compiled program's
  :class:`~autodist_tpu.analysis.inventory.CollectiveInventory` and
  :class:`~autodist_tpu.analysis.graph.ProgramGraph`: what
  ``strategy/explain.py --lint``, ``bench.py --lint`` and the tier-1 wire
  pins ride;
- :func:`channel_cycle_hazards` — cross-program channel-ordering cycle
  detection (SLH004), the MPMD groundwork sibling of
  :func:`rendezvous_hazards`;
- ``python -m autodist_tpu.analysis --selftest`` — the CPU proof: every
  dryrun family's pinned wire re-derived with zero findings (schedule
  passes active), plus seeded defects that MUST trip each pass
  (docs/analysis.md).
"""
from __future__ import annotations

from typing import Optional

from autodist_tpu.analysis.inventory import (
    COLLECTIVE_KINDS,
    COLLECTIVE_OPS,
    Collective,
    CollectiveInventory,
    assert_hlo_wire,
    collective_sizes,
    compiled_artifacts,
    compiled_hlo,
    compiled_window,
    hlo_contains,
)
from autodist_tpu.analysis.graph import (
    HloComputation,
    HloInstr,
    ProgramGraph,
)
from autodist_tpu.analysis.report import (
    FINDING_CODES,
    AnalysisError,
    AnalysisReport,
    Finding,
    report_to_text,
)
from autodist_tpu.analysis.passes import (
    DEFAULT_HEADROOM,
    alias_hazards,
    batch_element_count,
    degradation_check,
    hbm_budget,
    measured_wire_check,
    payload_candidates,
    rendezvous_hazards,
    screen_strategy,
    wire_conformance,
)
from autodist_tpu.analysis.sched import (
    channel_cycle_hazards,
    liveness_check,
    overlap_check,
    scheduled_liveness,
    scheduled_overlap,
    screen_schedule,
)


def analyze_plan(
    plan,
    strategy=None,
    resource_spec=None,
    optimizer: str = "",
    headroom: float = DEFAULT_HEADROOM,
    temp_bytes: float = 0.0,
    serve_pool_bytes: float = 0.0,
    serve_shared_fraction: float = 0.0,
    serve_quant_capacity_x: float = 1.0,
    program: str = "",
    model_item=None,
) -> AnalysisReport:
    """Static passes over a lowered :class:`ShardingPlan` (no program text
    needed): degradation drift vs the shared predicate, and — when a
    ``resource_spec`` is given — the per-chip HBM budget
    (``serve_pool_bytes`` accounts a serving engine's static KV page pool
    as a named tenant, ``InferenceEngine.page_pool_bytes`` per chip;
    ``serve_shared_fraction`` — the engine's ``shared_fraction`` — rides
    the memory summary so the report shows how much of the pool's
    logical footprint COW prefix sharing deduplicates). With
    ``model_item`` (and ``strategy``), the pure-arithmetic schedule screen
    (``sched.screen_schedule``: degenerate bucketing SLO001, bucket
    zero-embed transient SLM003) joins in. This is the validation the
    plan cache runs on every hit."""
    report = AnalysisReport(program=program)
    report.extend(degradation_check(plan, strategy))
    mem_findings, mem_summary = hbm_budget(
        plan, resource_spec=resource_spec, optimizer=optimizer,
        headroom=headroom, temp_bytes=temp_bytes,
        serve_pool_bytes=serve_pool_bytes,
        serve_shared_fraction=serve_shared_fraction,
        serve_quant_capacity_x=serve_quant_capacity_x)
    report.extend(mem_findings)
    report.tables["memory"] = mem_summary
    if strategy is not None and model_item is not None:
        report.extend(screen_schedule(
            strategy, model_item, resource_spec=resource_spec,
            headroom=headroom))
    return report


def analyze_program(
    plan,
    hlo_text: str,
    strategy=None,
    resource_spec=None,
    optimizer: str = "",
    headroom: float = DEFAULT_HEADROOM,
    temp_bytes: float = 0.0,
    serve_pool_bytes: float = 0.0,
    serve_shared_fraction: float = 0.0,
    serve_quant_capacity_x: float = 1.0,
    batch=None,
    batch_elements: Optional[int] = None,
    program: str = "",
    model_item=None,
) -> AnalysisReport:
    """Full analysis of one compiled program: everything
    :func:`analyze_plan` checks plus wire conformance (the program's
    collective inventory diffed against the plan's promised wire) and
    donated-buffer alias hazards. ``batch`` (or ``batch_elements``)
    supplies the activation allowance — pass the training batch whenever
    you have one, or token-scale collectives on tiny models read as
    unplanned."""
    report = analyze_plan(
        plan, strategy=strategy, resource_spec=resource_spec,
        optimizer=optimizer, headroom=headroom, temp_bytes=temp_bytes,
        serve_pool_bytes=serve_pool_bytes,
        serve_shared_fraction=serve_shared_fraction,
        serve_quant_capacity_x=serve_quant_capacity_x,
        program=program, model_item=model_item)
    if batch_elements is None and batch is not None:
        batch_elements = batch_element_count(batch)
    inventory = CollectiveInventory.from_hlo(hlo_text, program=program)
    wire_findings, wire_table = wire_conformance(
        plan, inventory, batch_elements=batch_elements)
    report.extend(wire_findings)
    report.extend(alias_hazards(hlo_text))
    report.tables["wire"] = wire_table
    report.tables["inventory"] = inventory.to_json()
    # Schedule passes (schedlint): post-optimization dumps carry the
    # executor's issue order, so static overlap and scheduled liveness run
    # whenever the dump is scheduled — zero extra compiles.
    graph = ProgramGraph.from_hlo(hlo_text, program=program)
    if graph.is_scheduled and graph.entry is not None:
        ov_findings, ov_table = overlap_check(graph)
        report.extend(ov_findings)
        report.tables["sched_overlap"] = ov_table
        static_ok = not any(
            f.code in ("SLM001", "SLM002") for f in report.findings)
        lv_findings, lv_summary = liveness_check(
            graph, resource_spec=resource_spec, headroom=headroom,
            static_totals_ok=static_ok)
        report.extend(lv_findings)
        report.tables["sched_memory"] = lv_summary
    return report


__all__ = [
    "COLLECTIVE_KINDS",
    "COLLECTIVE_OPS",
    "AnalysisError",
    "AnalysisReport",
    "Collective",
    "CollectiveInventory",
    "DEFAULT_HEADROOM",
    "FINDING_CODES",
    "Finding",
    "HloComputation",
    "HloInstr",
    "ProgramGraph",
    "alias_hazards",
    "analyze_plan",
    "analyze_program",
    "assert_hlo_wire",
    "batch_element_count",
    "channel_cycle_hazards",
    "collective_sizes",
    "compiled_artifacts",
    "compiled_hlo",
    "compiled_window",
    "degradation_check",
    "hbm_budget",
    "hlo_contains",
    "liveness_check",
    "measured_wire_check",
    "overlap_check",
    "payload_candidates",
    "rendezvous_hazards",
    "report_to_text",
    "scheduled_liveness",
    "scheduled_overlap",
    "screen_schedule",
    "screen_strategy",
    "wire_conformance",
]
