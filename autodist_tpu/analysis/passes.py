"""shardlint passes: diff a lowered program against what its plan promises.

Three pass families, all static (CPU-only, nothing executes):

- **wire conformance** (:func:`wire_conformance`) — the program's
  :class:`~autodist_tpu.analysis.inventory.CollectiveInventory` against the
  plan's :meth:`~autodist_tpu.kernel.lowering.ShardingPlan.promised_wire`:
  planned op kinds must be present (SLW002), and no collective may carry a
  payload only an UNPLANNED wire explains (SLW001) — the GSPMD resharding
  leak (a full-table collective for a row-sharded sparse var) and the
  zero1 re-fusion regression (a full-gradient all-reduce for a
  shard_update var) both land here. Payload thresholds are deliberately
  conservative: activation-scale traffic (token gathers, TP partial sums,
  expert dispatch) is inherently data-dependent, so the pass only flags
  payloads that exceed EVERY planned source including the activation
  allowance derived from ``batch_elements``.
- **static HBM budget** (:func:`hbm_budget`) — per-chip params + optimizer
  slots (sharded per the plan's update specs — the ``_weight_update_spec``
  accounting) + a full-gradient transient, plus the compiled program's
  temp/peak when given, against the ResourceSpec's per-chip HBM with a
  configurable headroom (SLM001/SLM002): overcommit is a lint error, not
  an OOM at step 1.
- **hazards** — degradation drift between plan flags and the shared
  ``kernel/degrade.py`` predicate (SLH003), replica-group ordering
  mismatches across programs that will rendezvous (SLH001, the
  pipeline/MPMD deadlock mode), and donated-buffer alias size mismatches
  (SLH002). :func:`screen_strategy` is the pre-lowering subset the
  planner's search runs before pricing a candidate (SLS001).
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from autodist_tpu.analysis.inventory import CollectiveInventory
from autodist_tpu.analysis.report import (
    ERROR,
    INFO,
    WARNING,
    Finding,
)

# Default fraction of per-chip HBM the static state may use; matches the
# cost model's HBM_USABLE_FRACTION so lint and pricing agree on "fits".
DEFAULT_HEADROOM = 0.75


def batch_element_count(batch) -> int:
    """Total elements across a batch pytree's leaves — the activation
    allowance input for :func:`wire_conformance` (shapes only; nothing is
    read or transferred)."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(batch):
        shape = np.shape(leaf)
        total += int(np.prod(shape)) if shape else 1
    return total


def payload_candidates(w, mesh_sizes: Dict[str, int]) -> set:
    """Payload element counts a collective for VarWire ``w`` may
    legitimately carry: the var's storage (or its bucket's summed payload
    for backward-overlap buckets), each optionally divided by ONE mesh
    axis at a time (the shard view) — never compounded across axes, which
    would loosen the match for every multi-axis family. Shared by the
    static wire-conformance table and the measured-wire attribution join
    (obs/attrib.py) so "what counts as this var's collective" is one rule."""
    bases = {int(w.storage_elements)}
    if w.bucket is not None and w.bucket_elements:
        bases.add(int(w.bucket_elements))
    candidates = set(bases)
    for k in mesh_sizes.values():
        if k > 1:
            for base in bases:
                candidates.add(-(-base // int(k)))
    return candidates


# ---------------------------------------------------------------------- wire
def wire_conformance(
    plan,
    inventory: CollectiveInventory,
    batch_elements: Optional[int] = None,
) -> Tuple[List[Finding], List[Dict]]:
    """Diff the program's collectives against the plan's promised wire.

    Returns ``(findings, table)`` where ``table`` is the per-variable
    planned-vs-actual rows ``explain --lint`` renders.
    """
    findings: List[Finding] = []
    wires = plan.promised_wire()
    mesh_sizes = dict(zip(plan.mesh.axis_names, plan.mesh.devices.shape))
    n_total = int(np.prod(list(mesh_sizes.values()))) if mesh_sizes else 1
    from autodist_tpu.kernel.mesh import data_axis

    n_data = int(mesh_sizes.get(data_axis(plan.mesh), 1))
    if n_total <= 1:
        # One chip emits no collectives at all (XLA elides them): nothing
        # to conform.
        return findings, []

    trainable = {n: w for n, w in wires.items()
                 if w.rendering != "nontrainable"}

    # Activation allowance: collectives whose payload scales with the batch
    # (token gathers, TP partial sums, ring K/V chunks, expert dispatch)
    # are planned wire too, but their size is data- not plan-dependent.
    # bound = batch elements x the widest TRAILING dim any sharded var can
    # fan a token into (a gather/matmul fans each token into shape[-1]
    # features — never into the row count, which is what a leak moves).
    # Without a batch hint the allowance is zero and the caller accepts a
    # stricter (possibly over-eager on tiny models) check.
    sharded = [w for w in trainable.values()
               if w.rendering in ("sparse", "expert", "partitioned", "zero3")]
    max_fan = 1
    for w in sharded:
        shape = tuple(plan.var_plans[w.var].var.shape) or (1,)
        max_fan = max(max_fan, int(shape[-1]))
    act_allow = int(batch_elements or 0) * int(max_fan)

    # ----------------------------------------------- missing collectives
    for w in trainable.values():
        for op in w.require:
            if not inventory.has(op):
                findings.append(Finding(
                    code="SLW002", severity=ERROR, var=w.var,
                    pass_name="wire",
                    message=(
                        f"plan promises {op!r} for var {w.var!r} "
                        f"({w.rendering} rendering) but the compiled "
                        f"program carries none"),
                    details={"op": op, "rendering": w.rendering},
                ))
    if trainable and n_data > 1 and not (
            inventory.has("all-reduce") or inventory.has("reduce-scatter")):
        findings.append(Finding(
            code="SLW002", severity=ERROR, pass_name="wire",
            message=(
                f"data-parallel degree {n_data} with trainable variables "
                f"but the program carries no gradient-reduction collective "
                f"(no all-reduce, no reduce-scatter)"),
        ))

    # ------------------------------------------------ unplanned payloads
    def allow_sum(op: str, exclude: str = "") -> int:
        return sum(w.storage_elements for w in trainable.values()
                   if op in w.allow and w.var != exclude)

    su = [w for w in trainable.values() if w.shard_update]
    if su:
        min_su = min(w.storage_elements for w in su)
        ar_allow = allow_sum("all-reduce") + act_allow
        for c in inventory.by_op("all-reduce"):
            p = c.max_payload_elements
            if p >= min_su and p > ar_allow:
                findings.append(Finding(
                    code="SLW001", severity=ERROR, pass_name="wire",
                    var=min(
                        (w.var for w in su if w.storage_elements <= p),
                        key=lambda v: wires[v].storage_elements, default=""),
                    message=(
                        f"all-reduce carries a shard_update-sized payload "
                        f"({p} elems >= smallest zero1 var {min_su}): the "
                        f"planned reduce-scatter wire re-fused into "
                        f"all-reduce (docs/zero.md regression)"),
                    details={"payload_elements": p, "min_su": min_su,
                             "allowance": ar_allow},
                ))
    for w in trainable.values():
        if not w.sparse_row_sharded:
            continue
        for c in inventory.collectives:
            p = c.max_payload_elements
            other = allow_sum(c.op, exclude=w.var) + act_allow
            if p >= w.storage_elements and p > other:
                findings.append(Finding(
                    code="SLW001", severity=ERROR, var=w.var,
                    pass_name="wire",
                    message=(
                        f"{c.op} moves a full-table payload ({p} elems >= "
                        f"table {w.storage_elements}) for row-sharded "
                        f"sparse var {w.var!r}: sync wire must scale with "
                        f"touched rows, never the table (GSPMD resharding "
                        f"leak)"),
                    details={"op": c.op, "payload_elements": p,
                             "table_elements": w.storage_elements,
                             "allowance": other},
                ))

    # Informational: payloads no planned source (incl. the activation
    # allowance) accounts for — GSPMD resharding worth a look, below the
    # error bar because attribution under op fusion is heuristic.
    for op in inventory.ops():
        bound = allow_sum(op) + act_allow
        p = inventory.max_payload(op)
        if p > bound:
            findings.append(Finding(
                code="SLW003", severity=INFO, pass_name="wire",
                message=(
                    f"{op} payload of {p} elems exceeds the summed planned "
                    f"{op} wire ({bound} elems incl. activation allowance) "
                    f"— possible GSPMD resharding"),
                details={"op": op, "payload_elements": p, "allowance": bound},
            ))

    # --------------------------------------------- planned-vs-actual table
    table: List[Dict] = []
    for name, w in sorted(wires.items()):
        if w.rendering == "nontrainable":
            continue
        planned_ops = tuple(w.require) or tuple(w.allow)
        matched = []
        for c in inventory.collectives:
            for _dt, dims in c.results:
                elems = int(np.prod(dims)) if dims else 1
                # Backward-overlap bucketing (VarWire.bucket): a combined
                # collective for this var's bucket legitimately carries the
                # bucket's SUMMED payload — the per-bucket allowance. The
                # candidate rule (one mesh-axis shard division at a time)
                # lives in payload_candidates, shared with the measured-
                # wire attribution join.
                if elems in payload_candidates(w, mesh_sizes) and (
                        c.op in w.allow or c.op in w.require):
                    matched.append(c)
                    break
        row = {
            "var": name,
            "rendering": w.rendering,
            "planned_ops": list(planned_ops),
            "planned_bytes": int(w.storage_bytes),
            "actual_ops": sorted({c.op for c in matched}),
            "actual_bytes": (sum(c.result_bytes for c in matched)
                             if matched else None),
            "degradations": list(w.degradations),
        }
        if w.bucket is not None:
            row["bucket"] = int(w.bucket)
        table.append(row)
    return findings, table


# -------------------------------------------------------------------- memory
def hbm_budget(
    plan,
    resource_spec=None,
    optimizer: str = "",
    headroom: float = DEFAULT_HEADROOM,
    temp_bytes: float = 0.0,
    serve_pool_bytes: float = 0.0,
    serve_shared_fraction: float = 0.0,
    serve_quant_capacity_x: float = 1.0,
) -> Tuple[List[Finding], Dict]:
    """Static per-chip HBM budget from the lowered plan.

    State = params (sharded per ``pspec``, padded storage shapes) +
    optimizer slots (sharded per ``update_pspec`` — the
    ``_weight_update_spec`` accounting the cost model prices) + one
    full-gradient transient per trainable var; ``temp_bytes`` adds the
    compiled program's own temp/peak figure when the caller has one
    (``DistributedTrainStep.window_cost``). ``serve_pool_bytes`` adds a
    serving engine's static KV page pool (per-chip bytes —
    ``InferenceEngine.page_pool_bytes`` over the data degree), so a
    serving plan's resident state is accounted by the same SLM passes as
    a training plan's: the pool is a named tenant (``serve.page_pool``)
    that can head the overcommit blame line. Host-offloaded vars live in
    pinned host memory and are excluded from the HBM sum.

    ``serve_shared_fraction`` (0..1) annotates the pool tenant with how
    much of its LOGICAL footprint is deduplicated by COW prefix sharing
    (``serve/prefix.py``; ``1 - physical/logical`` — the engine's
    ``shared_fraction``). The pool tenant's bytes are the pool's STATIC
    physical allocation, so shared bytes are already counted exactly
    once and the number never changes the SLM001/002 verdict — it rides
    the summary so an overcommit report shows how hard sharing is
    already working (a 0.6 shared fraction means re-sharding, not a
    bigger pool, is the fix).

    ``serve_quant_capacity_x`` (>= 1) annotates the pool tenant with the
    int8-KV effective-capacity multiplier (the engine's
    ``quant_capacity_x``: fp-equivalent bytes per physical pool byte —
    ~3.76x for fp32 models at head_dim 64, including the f32 scale
    planes). ``serve_pool_bytes`` stays the PHYSICAL quantized
    allocation — that is what SLM001 must account, and it is how the
    analyzer "sees" the real capacity win: at equal fp-equivalent KV
    capacity a quantized pool contributes capacity_x fewer bytes to the
    overcommit sum. The multiplier rides the summary so a report reader
    can tell a small-because-quantized pool from a small-because-starved
    one.
    """
    from autodist_tpu.strategy.cost_model import OPTIMIZER_SLOT_FACTOR

    findings: List[Finding] = []
    mesh_sizes = dict(zip(plan.mesh.axis_names, plan.mesh.devices.shape))
    slot_factor = OPTIMIZER_SLOT_FACTOR.get(optimizer, 2.0)

    def shards_of(pspec) -> int:
        k = 1
        for e in tuple(pspec):
            if e is None:
                continue
            for name in (e if isinstance(e, tuple) else (e,)):
                k *= int(mesh_sizes.get(name, 1))
        return max(k, 1)

    state = 0.0
    per_var: Dict[str, float] = {}
    for name, p in plan.var_plans.items():
        elems = int(np.prod(p.storage_shape or tuple(p.var.shape) or (1,)))
        b = float(elems) * np.dtype(p.var.dtype).itemsize
        if p.offload:
            continue  # pinned-host residency: not an HBM tenant
        contrib = b / shards_of(p.pspec)
        if p.var.trainable:
            contrib += slot_factor * b / shards_of(p.update_pspec)
            contrib += b  # transient full-gradient buffer
        state += contrib
        per_var[name] = contrib
    if serve_pool_bytes:
        state += float(serve_pool_bytes)
        per_var["serve.page_pool"] = float(serve_pool_bytes)
    capacity = float(resource_spec.tpu.hbm_bytes) if resource_spec else 0.0
    usable = capacity * headroom
    n_chips = max(int(resource_spec.num_chips), 1) if resource_spec else 1
    top_vars = sorted(per_var, key=per_var.get, reverse=True)[:5]
    summary = {
        "state_gb_per_chip": state / 1e9,
        "temp_gb_per_chip": float(temp_bytes) / 1e9,
        "serve_pool_gb_per_chip": float(serve_pool_bytes) / 1e9,
        "serve_shared_fraction": min(max(
            float(serve_shared_fraction), 0.0), 1.0),
        "serve_quant_capacity_x": max(float(serve_quant_capacity_x), 1.0),
        "serve_pool_fp_equiv_gb_per_chip": (
            float(serve_pool_bytes)
            * max(float(serve_quant_capacity_x), 1.0) / 1e9),
        "capacity_gb_per_chip": capacity / 1e9,
        "usable_gb_per_chip": usable / 1e9,
        "headroom": headroom,
        "n_chips": n_chips,
        "top_vars": top_vars,
    }
    # An overcommit is actionable only if it names the tenants: the top-3
    # contributing variables (param + slots + grad transient, per-chip)
    # ride the message so the fix needs no debugger rerun.
    top3 = ", ".join(
        f"{name} ({per_var[name] / 1e9:.3f} GB)" for name in top_vars[:3])
    if resource_spec is None:
        return findings, summary
    if state > usable:
        findings.append(Finding(
            code="SLM001", severity=ERROR, pass_name="memory",
            message=(
                f"static state {state / 1e9:.3f} GB/chip overcommits "
                f"{usable / 1e9:.3f} GB usable "
                f"({headroom:.0%} headroom of {capacity / 1e9:.2f} GB "
                f"HBM): OOM at step 1, re-shard or offload"
                + (f" — top contributors: {top3}" if top3 else "")),
            details=summary,
        ))
    elif temp_bytes and state + float(temp_bytes) > usable:
        findings.append(Finding(
            code="SLM002", severity=ERROR, pass_name="memory",
            message=(
                f"state {state / 1e9:.3f} GB + compiled temp "
                f"{float(temp_bytes) / 1e9:.3f} GB/chip overcommits "
                f"{usable / 1e9:.3f} GB usable"
                + (f" — top state contributors: {top3}" if top3 else "")),
            details=summary,
        ))
    return findings, summary


# ------------------------------------------------------------------- hazards
def degradation_check(plan, strategy=None) -> List[Finding]:
    """Plan flags vs the ONE shared degradation predicate (SLH003).

    With ``strategy`` given, each node's shard_update REQUEST is replayed
    through ``kernel.degrade.zero1_degradation_reasons`` on this mesh and
    compared against what the plan actually flags — the check that catches
    a lowering rule drifting away from pricing/analysis within one package
    version. Degradations themselves are declared (info), never errors.
    """
    from autodist_tpu import const
    from autodist_tpu.kernel.degrade import (
        DEGRADATION_REASONS,
        zero1_degradation_reasons,
    )
    from autodist_tpu.kernel.mesh import data_axis
    from autodist_tpu.strategy.ir import AllReduceSynchronizer

    findings: List[Finding] = []
    mesh_sizes = dict(zip(plan.mesh.axis_names, plan.mesh.devices.shape))
    n_data = int(mesh_sizes.get(data_axis(plan.mesh), 1))
    n_model = int(mesh_sizes.get(const.MESH_AXIS_MODEL, 1))
    n_expert = int(mesh_sizes.get(const.MESH_AXIS_EXPERT, 1))

    nodes = {}
    if strategy is not None:
        nodes = {n.var_name: n for n in strategy.node_config}

    for name, p in plan.var_plans.items():
        unknown = [r for r in p.degradations if r not in DEGRADATION_REASONS]
        if unknown:
            findings.append(Finding(
                code="SLH003", severity=ERROR, var=name, pass_name="hazard",
                message=(f"plan declares unknown degradation reason(s) "
                         f"{unknown}: not in the shared predicate's "
                         f"vocabulary"),
            ))
        if p.shard_update and p.degradations:
            findings.append(Finding(
                code="SLH003", severity=ERROR, var=name, pass_name="hazard",
                message=("plan flags shard_update ACTIVE while declaring "
                         f"degradations {list(p.degradations)}"),
            ))
        node = nodes.get(name)
        if node is None or not isinstance(
                node.synchronizer, AllReduceSynchronizer):
            continue
        requested = bool(node.synchronizer.shard_update)
        if not requested and not p.shard_update:
            continue
        try:
            part_axis = node.active_partition_axis
        except ValueError:
            part_axis = None
        reasons = zero1_degradation_reasons(
            p.var.shape,
            sparse_update=p.var.sparse_update,
            expert=p.var.expert,
            part_axis=part_axis,
            compressor=p.compressor,
            n_data=n_data, n_model=n_model, n_expert=n_expert,
        )
        expect_active = requested and not reasons
        if p.shard_update != expect_active:
            findings.append(Finding(
                code="SLH003", severity=ERROR, var=name, pass_name="hazard",
                message=(
                    f"strategy requests shard_update={requested} and the "
                    f"shared predicate says "
                    f"{'active' if expect_active else 'degrade'}"
                    f"{' (' + ', '.join(reasons) + ')' if reasons else ''}, "
                    f"but the plan rendered "
                    f"shard_update={p.shard_update} — lowering has drifted "
                    f"from kernel/degrade.py"),
                details={"reasons": list(reasons)},
            ))
        elif requested and reasons and tuple(p.degradations) != reasons:
            findings.append(Finding(
                code="SLH003", severity=WARNING, var=name,
                pass_name="hazard",
                message=(
                    f"quiet degradation is undeclared: predicate says "
                    f"{list(reasons)}, plan declares "
                    f"{list(p.degradations)}"),
            ))
    return findings


def rendezvous_hazards(
    inventories: Dict[str, CollectiveInventory]) -> List[Finding]:
    """Cross-program collective-ordering check (SLH001) for programs that
    will rendezvous (pipeline/MPMD stages lowered separately): each pair
    must issue the same collectives, over the same replica groups in the
    same device order, in the same sequence — anything else deadlocks or
    silently mis-reduces at runtime."""
    findings: List[Finding] = []
    names = sorted(inventories)

    def seq(inv: CollectiveInventory, exact: bool):
        out = []
        for c in inv.collectives:
            if not c.replica_groups:
                continue
            groups = (tuple(c.replica_groups) if exact else
                      tuple(sorted(tuple(sorted(g))
                                   for g in c.replica_groups)))
            out.append((c.op, groups))
        return out

    for i, a in enumerate(names):
        for b in names[i + 1:]:
            norm_a, norm_b = (seq(inventories[a], False),
                              seq(inventories[b], False))
            exact_a, exact_b = (seq(inventories[a], True),
                                seq(inventories[b], True))
            if sorted(norm_a) != sorted(norm_b):
                findings.append(Finding(
                    code="SLH001", severity=ERROR, pass_name="hazard",
                    message=(
                        f"programs {a!r} and {b!r} issue different "
                        f"collective sets ({len(norm_a)} vs {len(norm_b)} "
                        f"group-carrying collectives): they cannot "
                        f"rendezvous"),
                    details={"a": a, "b": b},
                ))
            elif norm_a != norm_b:
                findings.append(Finding(
                    code="SLH001", severity=ERROR, pass_name="hazard",
                    message=(
                        f"programs {a!r} and {b!r} issue matching "
                        f"collectives in DIFFERENT ORDER: rendezvous "
                        f"deadlock hazard"),
                    details={"a": a, "b": b},
                ))
            elif exact_a != exact_b:
                findings.append(Finding(
                    code="SLH001", severity=ERROR, pass_name="hazard",
                    message=(
                        f"programs {a!r} and {b!r} order replica groups "
                        f"differently for matching collectives: "
                        f"mis-rendezvous (wrong pairing) hazard"),
                    details={"a": a, "b": b},
                ))
    return findings


_ALIAS_PAIR_RE = re.compile(
    r"\{([0-9, ]*)\}:\s*\((\d+),\s*\{([0-9, ]*)\}")


def alias_hazards(hlo_text: str) -> List[Finding]:
    """Donated-buffer aliasing check (SLH002): every input/output alias
    pair declared by the module must connect equal-sized buffers. A
    mismatched pair is a program XLA will reject at runtime (or worse,
    silently mis-donate) — statically checkable from the dump's ENTRY
    signature."""
    findings: List[Finding] = []
    alias_line = next(
        (ln for ln in hlo_text.splitlines() if "input_output_alias=" in ln),
        "")
    if not alias_line:
        return findings
    alias_blob = alias_line.split("input_output_alias=", 1)[1]
    entry = next(
        (ln for ln in hlo_text.splitlines() if ln.startswith("ENTRY ")), "")
    if "->" not in entry:
        return findings
    params_part, result_part = entry.split("->", 1)
    param_shapes = re.findall(
        r"[\w.]+:\s*([a-z][0-9a-z]*\[[0-9,]*\])", params_part)
    result_shapes = re.findall(r"([a-z][0-9a-z]*\[[0-9,]*\])", result_part)

    def nbytes(shape: str) -> int:
        from autodist_tpu.analysis.inventory import dtype_bytes

        dt, dims = shape.split("[", 1)
        dims = dims.rstrip("]")
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        return n * dtype_bytes(dt)

    for pair in _ALIAS_PAIR_RE.finditer(alias_blob):
        out_ix = [int(x) for x in pair.group(1).split(",") if x.strip()]
        param_no = int(pair.group(2))
        if param_no >= len(param_shapes):
            continue
        oi = out_ix[0] if out_ix else 0
        if oi >= len(result_shapes):
            continue
        pb, ob = nbytes(param_shapes[param_no]), nbytes(result_shapes[oi])
        if pb != ob:
            findings.append(Finding(
                code="SLH002", severity=ERROR, pass_name="hazard",
                message=(
                    f"input_output_alias pairs parameter {param_no} "
                    f"({param_shapes[param_no]}, {pb} B) with output "
                    f"{oi} ({result_shapes[oi]}, {ob} B): donated buffer "
                    f"sizes differ"),
                details={"param": param_no, "output": oi,
                         "param_bytes": pb, "output_bytes": ob},
            ))
    return findings


# ------------------------------------------------------------- measured wire
def measured_wire_check(
    plan,
    measured,
    priced_exposed_fraction: Optional[float] = None,
    overlap_tolerance: float = 0.10,
) -> List[Finding]:
    """Diff a **measured** wire (an ``obs.attrib.MeasuredWire``) against
    the plan's promise — the trace-side sibling of :func:`wire_conformance`.

    All findings are WARNINGS, never errors: traces are optional, capture
    windows are short, and a fused/renamed op is a heuristic miss, not
    proof of a broken program. Codes:

    - **SLT001** — a measured collective joined to nothing the plan
      promises (above the aux-reduction allowance): either a GSPMD
      resharding leak actually executing, or the join losing an op;
    - **SLT002** — a promised (``require``'d) collective kind never
      observed for its variable in the trace;
    - **SLT003** — a backward-overlap bucket whose measured hidden
      fraction falls short of what pricing assumed
      (``1 - priced_exposed_fraction``, default the cost model's
      OVERLAP_EXPOSED_FRACTION prior): the wire was priced as hidden but
      measured exposed. Emitted only when the runtime can overlap at all
      (``measured.overlap_measurable``) — a serialized executor reads 0
      overlap for a reason the program didn't choose.
    """
    findings: List[Finding] = []
    if priced_exposed_fraction is None:
        from autodist_tpu.strategy.cost_model import OVERLAP_EXPOSED_FRACTION

        priced_exposed_fraction = OVERLAP_EXPOSED_FRACTION

    from autodist_tpu.obs.attrib import AUX_REDUCTION_MAX_ELEMENTS

    for op in measured.collectives:
        if op.matched or op.payload_elements <= AUX_REDUCTION_MAX_ELEMENTS:
            continue
        findings.append(Finding(
            code="SLT001", severity=WARNING, pass_name="measured",
            message=(
                f"measured {op.kind} {op.name!r} "
                f"({op.payload_elements} elems, "
                f"{op.seconds_per_step * 1e3:.4f} ms/step) joins to no "
                f"promised wire entry — unplanned collective actually "
                f"executing, or an attribution miss"),
            details={"name": op.name, "kind": op.kind,
                     "payload_elements": op.payload_elements,
                     "seconds_per_step": op.seconds_per_step},
        ))
    for var, rendering, kind in measured.unobserved:
        findings.append(Finding(
            code="SLT002", severity=WARNING, var=var, pass_name="measured",
            message=(
                f"plan promises {kind!r} for var {var!r} ({rendering} "
                f"rendering) but no measured op in the trace joined to it"),
            details={"op": kind, "rendering": rendering},
        ))
    if measured.overlap_measurable:
        want_hidden = 1.0 - float(priced_exposed_fraction)
        for b in measured.buckets:
            if b.overlap_fraction + overlap_tolerance < want_hidden:
                findings.append(Finding(
                    code="SLT003", severity=WARNING, pass_name="measured",
                    message=(
                        f"bucket {b.bucket}: measured overlap "
                        f"{b.overlap_fraction:.0%} is below the priced "
                        f"{want_hidden:.0%} hidden fraction "
                        f"({b.exposed_s_per_step * 1e3:.4f} ms/step of "
                        f"supposedly-hidden wire exposed) — recalibrate "
                        f"overlap_s or revisit bucket_bytes"),
                    details={"bucket": b.bucket,
                             "overlap_fraction": b.overlap_fraction,
                             "priced_hidden": want_hidden,
                             "exposed_s_per_step": b.exposed_s_per_step},
                ))
    return findings


# -------------------------------------------------------------------- screen
def screen_strategy(strategy, model_item, resource_spec) -> List[Finding]:
    """Pre-lowering strategy screen (SLS001): defects that make a candidate
    unlowerable or meaningless, cheap enough to run on every search seed
    before any pricing. Mirrors the hard errors ``_fold_part_config`` /
    ``StrategyCompiler`` raise, as findings instead of exceptions."""
    from autodist_tpu.kernel.lowering import GraphTransformer
    from autodist_tpu.strategy.ir import PSSynchronizer

    findings: List[Finding] = []
    for node in strategy.node_config:
        try:
            var = model_item.var(node.var_name)
        except KeyError:
            findings.append(Finding(
                code="SLS001", severity=ERROR, var=node.var_name,
                pass_name="screen",
                message=f"strategy names unknown variable "
                        f"{node.var_name!r}"))
            continue
        try:
            axis = node.active_partition_axis
        except ValueError as e:
            findings.append(Finding(
                code="SLS001", severity=ERROR, var=node.var_name,
                pass_name="screen",
                message=f"invalid partitioner: {e}"))
            continue
        if axis is not None:
            if axis >= len(var.shape):
                findings.append(Finding(
                    code="SLS001", severity=ERROR, var=node.var_name,
                    pass_name="screen",
                    message=(f"partition axis {axis} out of range for "
                             f"shape {tuple(var.shape)}")))
            elif node.num_shards > max(int(var.shape[axis]), 1):
                findings.append(Finding(
                    code="SLS001", severity=ERROR, var=node.var_name,
                    pass_name="screen",
                    message=(f"{node.num_shards} shards exceed axis "
                             f"{axis} size {var.shape[axis]}")))
        sync = node.synchronizer
        if isinstance(sync, PSSynchronizer) and not sync.sync:
            findings.append(Finding(
                code="SLS001", severity=ERROR, var=node.var_name,
                pass_name="screen",
                message="async PS (sync=False) has no SPMD rendering"))
        try:
            GraphTransformer._fold_part_config(node)
        except ValueError as e:
            findings.append(Finding(
                code="SLS001", severity=ERROR, var=node.var_name,
                pass_name="screen", message=str(e)))
    return findings
