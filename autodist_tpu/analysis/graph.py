"""Def-use DAG over a post-optimization HLO dump (the schedule substrate).

``inventory.py`` reads a dump one op line at a time — enough for payload
and presence pins, blind to *order*. The schedule passes
(``analysis/sched.py``) need more: post-optimization dumps are emitted in
schedule order (``is_scheduled=true``), so the textual instruction
sequence IS the executor's issue order, and def→use edges over it give
liveness intervals and overlap windows with zero execution. This module
is the second (and last) HLO reader in the parser home — the same
single-parser policy as ``CollectiveInventory``
(``tools/check_patterns.py`` rule 7 bans ``.as_text()`` parsing anywhere
else).

Reading rules, shared with the inventory:

- named-scope metadata (``metadata={op_name=...}``) is attached to the
  node but never creates one;
- result shapes sit between ``=`` and the op token, operands after it;
  names that resolve to no instruction in the same computation
  (``to_apply=%region``, ``calls=%fused_computation``, ``body=``/
  ``condition=`` computation refs) are dropped, so data edges never point
  at computations;
- ``tuple`` / ``get-tuple-element`` / ``bitcast`` define views, not
  buffers (their ``result_bytes`` reads 0 for liveness purposes via
  :attr:`HloInstr.is_view`).
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from autodist_tpu.analysis.inventory import (
    COLLECTIVE_KINDS,
    _CHANNEL_RE,
    _GROUPS_EXPLICIT_RE,
    _GROUPS_IOTA_RE,
    _METADATA_RE,
    _OP_NAME_RE,
    _SHAPE_RE,
    _expand_iota_groups,
    dtype_bytes,
)

#: Ops that define a *view* of an existing buffer, not a new one — they
#: contribute zero bytes to scheduled liveness (XLA's buffer assignment
#: aliases them).
VIEW_OPS = frozenset({"tuple", "get-tuple-element", "bitcast"})

#: Async-pair spellings: ``<kind>-start`` / ``<kind>-done`` (TPU dumps),
#: plus the generic ``async-start``/``async-done`` wrappers.
_ASYNC_START_SUFFIX = "-start"
_ASYNC_DONE_SUFFIX = "-done"

_DEF_RE = re.compile(r"^(ROOT\s+)?%?([A-Za-z0-9_.-]+)\s*=\s*(.*)$")
# First `name(` token after the result type — the opcode. Hyphenated HLO
# op names (reduce-scatter, dynamic-update-slice, all-reduce-start).
_OP_TOKEN_RE = re.compile(r"(?<![\w.%-])([a-z][a-z0-9-]*(?:-[a-z0-9]+)*)\(")
_OPERAND_NAME_RE = re.compile(r"%([A-Za-z0-9_.-]+)")
_COMPUTATION_RE = re.compile(r"^(ENTRY\s+)?%?([A-Za-z0-9_.-]+)\s*\(")
_SOURCE_TARGET_RE = re.compile(r"source_target_pairs=\{((?:\{\d+,\d+\},?)*)\}")
_PAIR_RE = re.compile(r"\{(\d+),(\d+)\}")


@dataclass
class HloInstr:
    """One instruction in one computation of a post-optimization dump."""

    name: str
    op: str
    index: int                                # schedule position
    results: Tuple[Tuple[str, Tuple[int, ...]], ...] = ()
    operands: Tuple[str, ...] = ()            # resolved same-computation defs
    op_name: str = ""                         # metadata named-scope path
    channel_id: Optional[int] = None
    replica_groups: Tuple[Tuple[int, ...], ...] = ()
    source_target_pairs: Tuple[Tuple[int, int], ...] = ()
    is_root: bool = False
    line: str = ""

    @property
    def result_bytes(self) -> int:
        """Bytes this instruction's result buffer(s) occupy; 0 for views."""
        if self.is_view:
            return 0
        total = 0
        for dt, dims in self.results:
            n = 1
            for d in dims:
                n *= d
            total += n * dtype_bytes(dt)
        return total

    @property
    def is_view(self) -> bool:
        return self.op in VIEW_OPS

    @property
    def is_parameter(self) -> bool:
        return self.op == "parameter"

    @property
    def collective_kind(self) -> Optional[str]:
        """Canonical collective kind when this is (any spelling of) a
        collective op — ``all-reduce-start`` and the ``async-start``
        wrapper both read as their base kind; None otherwise."""
        op = self.op
        for suffix in (_ASYNC_START_SUFFIX, _ASYNC_DONE_SUFFIX):
            if op.endswith(suffix):
                op = op[: -len(suffix)]
                break
        if op in COLLECTIVE_KINDS:
            return op
        if self.op in ("async-start", "async-done"):
            for kind in COLLECTIVE_KINDS:
                if kind in self.line:
                    return kind
        return None

    @property
    def is_collective(self) -> bool:
        return self.collective_kind is not None

    @property
    def is_async_start(self) -> bool:
        return self.is_collective and self.op.endswith(_ASYNC_START_SUFFIX)

    @property
    def is_async_done(self) -> bool:
        return self.is_collective and self.op.endswith(_ASYNC_DONE_SUFFIX)


@dataclass
class HloComputation:
    """One computation's instructions, in schedule (textual) order."""

    name: str
    is_entry: bool = False
    instrs: List[HloInstr] = field(default_factory=list)
    _by_name: Dict[str, HloInstr] = field(default_factory=dict)
    _users: Optional[Dict[str, List[HloInstr]]] = None

    def instr(self, name: str) -> Optional[HloInstr]:
        return self._by_name.get(name)

    @property
    def root(self) -> Optional[HloInstr]:
        for i in reversed(self.instrs):
            if i.is_root:
                return i
        return self.instrs[-1] if self.instrs else None

    def users(self, name: str) -> List[HloInstr]:
        """Instructions consuming ``name``'s result (def→use edges)."""
        if self._users is None:
            users: Dict[str, List[HloInstr]] = {}
            for instr in self.instrs:
                for op_name in instr.operands:
                    users.setdefault(op_name, []).append(instr)
            self._users = users
        return self._users.get(name, [])

    def first_use(self, name: str) -> Optional[int]:
        us = self.users(name)
        return min(u.index for u in us) if us else None

    def last_use(self, name: str) -> Optional[int]:
        us = self.users(name)
        return max(u.index for u in us) if us else None


@dataclass
class ProgramGraph:
    """A whole dump: module attributes + every computation's DAG."""

    module_name: str = ""
    is_scheduled: bool = False
    computations: Dict[str, HloComputation] = field(default_factory=dict)
    #: ``input_output_alias`` pairs as (output_index, parameter_number).
    alias_pairs: Tuple[Tuple[int, int], ...] = ()
    program: str = ""

    @property
    def entry(self) -> Optional[HloComputation]:
        for comp in self.computations.values():
            if comp.is_entry:
                return comp
        return None

    @classmethod
    def from_hlo(cls, text: str, program: str = "") -> "ProgramGraph":
        graph = cls(program=program)
        comp: Optional[HloComputation] = None
        for raw in text.splitlines():
            stripped = raw.strip()
            if raw.startswith("HloModule"):
                header = raw
                m = re.match(r"HloModule\s+([\w.-]+)", header)
                graph.module_name = m.group(1) if m else ""
                graph.is_scheduled = "is_scheduled=true" in header
                graph.alias_pairs = _parse_alias_pairs(header)
                continue
            if not stripped:
                continue
            # Computation header: column-0 `%name (params) -> type {` or
            # `ENTRY %name (...) -> type {` (instructions are indented).
            if not raw[:1].isspace() and stripped.endswith("{"):
                m = _COMPUTATION_RE.match(stripped)
                if m:
                    comp = HloComputation(
                        name=m.group(2), is_entry=bool(m.group(1)))
                    graph.computations[comp.name] = comp
                continue
            if stripped == "}":
                comp = None
                continue
            if comp is None:
                continue
            instr = _parse_instr(raw, index=len(comp.instrs))
            if instr is not None:
                comp.instrs.append(instr)
                comp._by_name[instr.name] = instr
        # Resolve operands against same-computation defs (drops refs to
        # called computations / regions).
        for comp in graph.computations.values():
            for instr in comp.instrs:
                instr.operands = tuple(
                    n for n in instr.operands if n in comp._by_name
                    and n != instr.name)
        return graph

    # ------------------------------------------------------------- summaries
    def describe(self) -> str:
        entry = self.entry
        lines = [
            f"ProgramGraph({self.program or self.module_name}: "
            f"{len(self.computations)} computations, "
            f"scheduled={self.is_scheduled})"]
        if entry:
            n_coll = sum(1 for i in entry.instrs if i.is_collective)
            n_edges = sum(len(i.operands) for i in entry.instrs)
            lines.append(
                f"  entry {entry.name}: {len(entry.instrs)} instructions, "
                f"{n_edges} def-use edges, {n_coll} collectives")
        return "\n".join(lines)


def _parse_alias_pairs(header: str) -> Tuple[Tuple[int, int], ...]:
    """``input_output_alias={ {1}: (1, {}, must-alias), ... }`` →
    ((output_index, param_no), ...) — the same pair grammar
    ``passes.alias_hazards`` checks for size mismatches."""
    if "input_output_alias=" not in header:
        return ()
    blob = header.split("input_output_alias=", 1)[1]
    pairs = []
    for m in re.finditer(r"\{([0-9, ]*)\}:\s*\((\d+)", blob):
        out_ix = [int(x) for x in m.group(1).split(",") if x.strip()]
        pairs.append((out_ix[0] if out_ix else 0, int(m.group(2))))
    return tuple(pairs)


def _parse_instr(raw: str, index: int) -> Optional[HloInstr]:
    op_name_m = _OP_NAME_RE.search(raw)
    line = _METADATA_RE.sub("", raw).strip()
    m = _DEF_RE.match(line)
    if not m:
        return None
    is_root, name, rhs = bool(m.group(1)), m.group(2), m.group(3)
    op_m = _OP_TOKEN_RE.search(rhs)
    if not op_m:
        return None
    op = op_m.group(1)
    results = tuple(
        (sm.group(1), tuple(int(d) for d in sm.group(2).split(",") if d))
        for sm in _SHAPE_RE.finditer(rhs[: op_m.start()])
    )
    # Data operands live INSIDE the op's argument parens; everything after
    # the closing paren is attributes — and attributes like
    # ``control-predecessors={%x}`` (standard in TPU scheduled dumps)
    # reference same-computation instructions, so the name-resolution
    # filter below would NOT drop them. Walk to the balanced close.
    depth, end = 0, len(rhs)
    for i in range(op_m.end() - 1, len(rhs)):
        ch = rhs[i]
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                end = i
                break
    operands = tuple(_OPERAND_NAME_RE.findall(rhs[op_m.end():end]))
    groups: Tuple[Tuple[int, ...], ...] = ()
    gm = _GROUPS_EXPLICIT_RE.search(line)
    if gm:
        groups = tuple(
            tuple(int(x) for x in g.split(",") if x.strip())
            for g in re.findall(r"\{([0-9, ]*)\}", gm.group(1)))
    else:
        im = _GROUPS_IOTA_RE.search(line)
        if im:
            dims = tuple(int(x) for x in im.group(3).split(","))
            perm = (tuple(int(x) for x in im.group(4).split(","))
                    if im.group(4) else None)
            groups = _expand_iota_groups(
                int(im.group(1)), int(im.group(2)), dims, perm)
    st_pairs: Tuple[Tuple[int, int], ...] = ()
    sm = _SOURCE_TARGET_RE.search(line)
    if sm:
        st_pairs = tuple(
            (int(a), int(b)) for a, b in _PAIR_RE.findall(sm.group(1)))
    cm = _CHANNEL_RE.search(line)
    return HloInstr(
        name=name,
        op=op,
        index=index,
        results=results,
        operands=operands,
        op_name=op_name_m.group(1) if op_name_m else "",
        channel_id=int(cm.group(1)) if cm else None,
        replica_groups=groups,
        source_target_pairs=st_pairs,
        is_root=is_root,
        line=line,
    )
