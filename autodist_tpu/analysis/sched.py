"""schedlint: schedule, liveness, and overlap passes over the compiled DAG.

The wire passes (``analysis/passes.py``) prove WHAT a program moves; these
passes prove WHEN. Post-optimization dumps are emitted in schedule order
(``is_scheduled=true``), so a :class:`~autodist_tpu.analysis.graph.ProgramGraph`
carries the executor's issue order — enough to decide, with zero device
execution, whether the latency hiding the cost model priced is
*structurally possible* and whether the schedule's transient buffers fit.

Pass families:

- **static overlap** (:func:`overlap_check`) — per gradsync bucket
  (collectives under a ``gradsync.bucket_{i}`` named scope,
  ``kernel/bucketing.py``), the compute scheduled inside each collective's
  overlap window. For a TPU-style async pair the window is the
  instructions strictly between ``-start`` and ``-done``; for a
  synchronous spelling (CPU dumps) it is the span from the collective to
  its first consumer — the slack an async runtime would stretch the wire
  over. ``SLO001`` (error) fires when a bucket's windows contain NO
  compute at all (its done is consumed immediately, or only other
  collectives sit between start and done): the bucket is structurally
  unable to overlap and the per-bucket machinery is pure overhead.
  ``SLO002`` (warning) fires — only on programs that actually carry async
  pairs, i.e. a latency-hiding schedule — when a bucket's scheduled
  overlap falls below the fraction the cost model priced as hidden
  (``1 - OVERLAP_EXPOSED_FRACTION``), catching at compile time what
  SLT003 only catches from a device trace. The per-collective fraction is
  ``min(1, window compute bytes / wire bytes)`` — a structural
  bytes-touched proxy, not a time model: 0 is exact (nothing can hide),
  1 means the schedule provides at least wire-sized compute to hide
  under.
- **scheduled liveness** (:func:`liveness_check`) — walk the entry
  schedule with each buffer born at its producer and dying after its last
  consumer; parameters are live from program start, module outputs to
  program end, and ``input_output_alias``/donation pairs are folded (an
  aliased output writes into its donor parameter's buffer and contributes
  no new bytes). ``SLM003`` (error) fires when the scheduled peak
  exceeds the ResourceSpec's HBM × headroom even though SLM001/002's
  static totals passed — the transient overcommit (gradient + zero-embed
  double-buffers co-live at a sync boundary) the totals bound cannot see.
  Fusion-internal temps are invisible to the entry walk, so the peak is a
  LOWER bound on the true footprint: exceeding it statically is always
  real.
- **cross-program channel cycles** (:func:`channel_cycle_hazards`) — the
  SLH001 rendezvous pass generalized over the DAG for the MPMD world:
  each program contributes its channel issue order (channel-carrying
  collectives, including collective-permute send/recv chains) as ordering
  edges over channel ids; a cycle in the union — two programs ordering a
  shared pair inconsistently, or a longer loop through three stages —
  is a potential deadlock no pairwise sequence diff can see (``SLH004``).
- **schedule screen** (:func:`screen_schedule`) — the pre-lowering,
  pure-arithmetic projection of SLO001/SLM003 the planner's search runs
  on every candidate before pricing: a candidate that requests bucketed
  overlap with zero bucket-eligible variables is structurally serialized
  (SLO001), and one whose bucket zero-embed transient pushes a fitting
  static state over the HBM headroom is a scheduled-peak overcommit
  (SLM003) — both rejected before a single cost-model evaluation.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from autodist_tpu.analysis.graph import HloComputation, HloInstr, ProgramGraph
from autodist_tpu.analysis.report import ERROR, WARNING, Finding

#: Wire-volume factor per collective kind: all-reduce moves ~2x the
#: payload of a one-way reshard (reduce+broadcast halves); the others move
#: ~1x. A structural proxy shared by the overlap fraction's denominator.
_WIRE_FACTOR = {"all-reduce": 2.0}

_BUCKET_SCOPE_RE = re.compile(r"gradsync\.bucket_(\d+)")

#: Tolerance on the scheduled-overlap fraction before SLO002 fires —
#: the byte proxy is structural, not a clock.
OVERLAP_TOLERANCE = 0.10


def _bucket_of(instr: HloInstr) -> Optional[int]:
    m = _BUCKET_SCOPE_RE.search(instr.op_name)
    return int(m.group(1)) if m else None


def _payload_bytes(instr: HloInstr, comp: HloComputation) -> int:
    """Largest single array a collective touches (result or operand),
    in bytes — the wire-volume base, mirroring
    ``Collective.max_payload_elements``."""
    best = 0
    for dt, dims in instr.results:
        n = 1
        for d in dims:
            n *= d
        best = max(best, n * _dtype_b(dt))
    for name in instr.operands:
        op = comp.instr(name)
        if op is not None:
            best = max(best, op.result_bytes if not op.is_view
                       else _raw_bytes(op))
    return best


def _dtype_b(dt: str) -> int:
    from autodist_tpu.analysis.inventory import dtype_bytes

    return dtype_bytes(dt)


def _raw_bytes(instr: HloInstr) -> int:
    total = 0
    for dt, dims in instr.results:
        n = 1
        for d in dims:
            n *= d
        total += n * _dtype_b(dt)
    return total


def _compute_weight(instr: HloInstr, comp: HloComputation) -> int:
    """Bytes-touched proxy for one schedulable compute op: result bytes +
    resolved operand bytes. Collectives, parameters and views weigh 0 —
    they are not compute the wire can hide under."""
    if instr.is_collective or instr.is_parameter or instr.is_view:
        return 0
    total = _raw_bytes(instr)
    for name in instr.operands:
        op = comp.instr(name)
        if op is not None:
            total += _raw_bytes(op)
    return total


# ------------------------------------------------------------------ overlap
@dataclass
class BucketOverlap:
    """Scheduled-overlap summary for one gradsync bucket."""

    bucket: int
    n_collectives: int = 0
    wire_bytes: int = 0
    window_compute_bytes: int = 0
    #: wire-weighted mean of per-collective min(1, compute/wire).
    overlap_fraction: float = 0.0
    async_pairs: bool = False

    def to_json(self) -> Dict:
        return {
            "bucket": self.bucket,
            "n_collectives": self.n_collectives,
            "wire_bytes": self.wire_bytes,
            "window_compute_bytes": self.window_compute_bytes,
            "scheduled_overlap": round(self.overlap_fraction, 4),
            "async_pairs": self.async_pairs,
        }


def _overlap_window(instr: HloInstr, comp: HloComputation,
                    ) -> Tuple[int, int, bool]:
    """(start, end) schedule positions (exclusive bounds) of the span the
    collective's wire may overlap, and whether it came from an async pair.

    Async pair: strictly between ``-start`` and its ``-done``. Sync
    spelling: strictly between the collective and its first consumer
    (end of schedule when unconsumed)."""
    if instr.is_async_start:
        done = next((u for u in comp.users(instr.name) if u.is_async_done),
                    None)
        if done is not None:
            return instr.index, done.index, True
    first = comp.first_use(instr.name)
    return instr.index, (first if first is not None
                         else len(comp.instrs)), False


def scheduled_overlap(graph: ProgramGraph) -> List[BucketOverlap]:
    """Per-gradsync-bucket scheduled overlap over the entry schedule.

    Programs without bucket scopes return ``[]`` — unbucketed gradient
    sync never promised overlap, so there is nothing to judge."""
    comp = graph.entry
    if comp is None:
        return []
    buckets: Dict[int, BucketOverlap] = {}
    for instr in comp.instrs:
        if not instr.is_collective or instr.is_async_done:
            continue
        b = _bucket_of(instr)
        if b is None:
            continue
        row = buckets.setdefault(b, BucketOverlap(bucket=b))
        lo, hi, is_async = _overlap_window(instr, comp)
        window = sum(_compute_weight(comp.instrs[i], comp)
                     for i in range(lo + 1, hi))
        wire = int(_payload_bytes(instr, comp)
                   * _WIRE_FACTOR.get(instr.collective_kind or "", 1.0))
        wire = max(wire, 1)
        row.n_collectives += 1
        row.wire_bytes += wire
        row.window_compute_bytes += window
        row.async_pairs = row.async_pairs or is_async
        # incremental wire-weighted mean of min(1, compute/wire)
        frac = min(1.0, window / wire)
        prev_wire = row.wire_bytes - wire
        row.overlap_fraction = (
            (row.overlap_fraction * prev_wire + frac * wire)
            / row.wire_bytes)
    return sorted(buckets.values(), key=lambda r: r.bucket)


def overlap_check(
    graph: ProgramGraph,
    priced_exposed_fraction: Optional[float] = None,
) -> Tuple[List[Finding], List[Dict]]:
    """SLO001/SLO002 over one scheduled program; returns
    ``(findings, per-bucket table)``."""
    if priced_exposed_fraction is None:
        from autodist_tpu.strategy.cost_model import OVERLAP_EXPOSED_FRACTION

        priced_exposed_fraction = OVERLAP_EXPOSED_FRACTION
    findings: List[Finding] = []
    rows = scheduled_overlap(graph)
    want_hidden = 1.0 - float(priced_exposed_fraction)
    for row in rows:
        if row.window_compute_bytes == 0:
            findings.append(Finding(
                code="SLO001", severity=ERROR, pass_name="sched",
                message=(
                    f"bucket {row.bucket}: structurally unable to overlap "
                    f"— {row.n_collectives} collective(s), "
                    f"{row.wire_bytes} wire bytes, and ZERO compute "
                    f"scheduled inside any overlap window (done consumed "
                    f"immediately / only collectives between start and "
                    f"done); the bucketed emission is pure overhead here"),
                details=row.to_json(),
            ))
        elif row.async_pairs and (
                row.overlap_fraction + OVERLAP_TOLERANCE < want_hidden):
            findings.append(Finding(
                code="SLO002", severity=WARNING, pass_name="sched",
                message=(
                    f"bucket {row.bucket}: scheduled overlap "
                    f"{row.overlap_fraction:.0%} is below the priced "
                    f"{want_hidden:.0%} hidden fraction — the schedule "
                    f"cannot deliver the latency hiding the cost model "
                    f"charged for (the compile-time face of SLT003)"),
                details=row.to_json(),
            ))
    return findings, [r.to_json() for r in rows]


# ----------------------------------------------------------------- liveness
def scheduled_liveness(graph: ProgramGraph) -> Dict:
    """Walk the entry schedule; return the scheduled peak summary.

    Buffers are born at their producer's position, die after their last
    consumer; parameters are live from position 0; module outputs (root
    operands) to the end; donated (``input_output_alias``) outputs write
    into their parameter's buffer and contribute no new bytes."""
    comp = graph.entry
    if comp is None or not comp.instrs:
        return {"scheduled_peak_bytes": 0, "n_buffers": 0, "top_buffers": []}
    n = len(comp.instrs)
    root = comp.root
    # Producers of aliased outputs: root operand at each aliased output
    # index reuses its donor parameter's buffer.
    aliased_producers = set()
    if root is not None and graph.alias_pairs:
        for out_ix, _param_no in graph.alias_pairs:
            if out_ix < len(root.operands):
                aliased_producers.add(root.operands[out_ix])
    out_names = set(root.operands) if root is not None else set()
    # A buffer read through a chain of views (tuple / get-tuple-element /
    # bitcast) lives until the LAST view use — propagate deaths through
    # views in reverse schedule order so a view chain cannot shorten its
    # underlying buffer's life.
    death: Dict[str, int] = {}
    for instr in comp.instrs:
        last = comp.last_use(instr.name)
        death[instr.name] = last if last is not None else instr.index
    for instr in reversed(comp.instrs):
        if instr.is_view:
            for op_name in instr.operands:
                death[op_name] = max(death[op_name], death[instr.name])
    births: List[int] = [0] * (n + 1)   # +bytes at position
    deaths: List[int] = [0] * (n + 2)   # -bytes after position
    sized: List[Tuple[str, int, int, int]] = []  # (name, bytes, born, die)
    for instr in comp.instrs:
        nbytes = instr.result_bytes
        if nbytes <= 0 or instr.name in aliased_producers:
            continue
        born = 0 if instr.is_parameter else instr.index
        die = death[instr.name]
        if instr.name in out_names or instr.is_root or (
                instr.is_parameter and _is_donor(instr, graph, comp)):
            die = n
        births[born] += nbytes
        deaths[die + 1] += nbytes
        sized.append((instr.name, nbytes, born, die))
    live, peak, peak_pos = 0, 0, 0
    for pos in range(n + 1):
        live += births[pos] - deaths[pos]
        if live > peak:
            peak, peak_pos = live, pos
    at_peak = sorted(
        ((name, b) for name, b, born, die in sized
         if born <= peak_pos <= die),
        key=lambda x: x[1], reverse=True)
    return {
        "scheduled_peak_bytes": int(peak),
        "peak_position": int(peak_pos),
        "n_buffers": len(sized),
        "n_instructions": n,
        "top_buffers": [
            {"name": name, "bytes": int(b)} for name, b in at_peak[:3]],
    }


def _is_donor(instr: HloInstr, graph: ProgramGraph,
              comp: HloComputation) -> bool:
    """True when this parameter is the donor side of an alias pair (its
    buffer is rewritten in place and stays resident to the end)."""
    if not graph.alias_pairs:
        return False
    m = re.search(r"parameter\((\d+)\)", instr.line)
    if not m:
        return False
    param_no = int(m.group(1))
    return any(p == param_no for _o, p in graph.alias_pairs)


def liveness_check(
    graph: ProgramGraph,
    resource_spec=None,
    headroom: float = 0.75,
    static_totals_ok: bool = True,
) -> Tuple[List[Finding], Dict]:
    """SLM003 over one scheduled program. ``static_totals_ok`` suppresses
    the finding when SLM001/SLM002 already reported the overcommit — the
    scheduled pass exists for the transients the totals bound misses, not
    to restate a failure the totals already caught."""
    summary = scheduled_liveness(graph)
    findings: List[Finding] = []
    if resource_spec is None:
        return findings, summary
    capacity = float(resource_spec.tpu.hbm_bytes)
    usable = capacity * headroom
    summary["usable_bytes"] = int(usable)
    peak = summary["scheduled_peak_bytes"]
    if static_totals_ok and usable > 0 and peak > usable:
        top = ", ".join(
            f"{t['name']} ({t['bytes'] / 1e6:.2f} MB)"
            for t in summary["top_buffers"])
        findings.append(Finding(
            code="SLM003", severity=ERROR, pass_name="sched",
            message=(
                f"scheduled peak live bytes {peak / 1e9:.3f} GB/chip "
                f"overcommit {usable / 1e9:.3f} GB usable "
                f"({headroom:.0%} of {capacity / 1e9:.2f} GB HBM) even "
                f"though the static totals fit — transient buffers at "
                f"schedule position {summary['peak_position']} "
                f"(top: {top}); re-bucket, remat, or offload"),
            details=summary,
        ))
    return findings, summary


# ----------------------------------------------------------- channel cycles
def channel_cycle_hazards(
    graphs: Dict[str, ProgramGraph]) -> List[Finding]:
    """SLH004: cross-program channel/permute ordering cycles.

    Each program's entry schedule contributes its channel issue order
    (first occurrence per channel id) as directed edges over channel ids;
    a cycle in the union of those orders means no global issue order can
    satisfy every program — a potential rendezvous deadlock. Catches the
    3-stage loop (A: c1<c2, B: c2<c3, C: c3<c1) the pairwise SLH001
    sequence diff structurally cannot see."""
    order: Dict[str, List[int]] = {}
    participants: Dict[int, set] = {}
    for name, graph in sorted(graphs.items()):
        comp = graph.entry
        if comp is None:
            continue
        seen: List[int] = []
        for instr in comp.instrs:
            if instr.channel_id is None or not (
                    instr.is_collective or instr.source_target_pairs):
                continue
            if instr.is_async_done:
                continue
            cid = int(instr.channel_id)
            if cid not in seen:
                seen.append(cid)
            devs = participants.setdefault(cid, set())
            for g in instr.replica_groups:
                devs.update(g)
            for a, b in instr.source_target_pairs:
                devs.update((a, b))
        if seen:
            order[name] = seen
    # Union digraph over channel ids; remember which program asserts each
    # edge so the finding can name the disagreeing stages.
    edges: Dict[int, Dict[int, str]] = {}
    for prog, seq in order.items():
        for i, a in enumerate(seq):
            for b in seq[i + 1:]:
                edges.setdefault(a, {}).setdefault(b, prog)
    cycle = _find_cycle(edges)
    if cycle is None:
        return []
    progs = sorted({edges[a][b] for a, b in zip(cycle, cycle[1:])})
    return [Finding(
        code="SLH004", severity=ERROR, pass_name="hazard",
        message=(
            f"cross-program channel cycle "
            f"{' -> '.join(str(c) for c in cycle)}: programs "
            f"{progs} order these channels inconsistently — no global "
            f"issue order satisfies all of them (potential rendezvous "
            f"deadlock; the MPMD hazard SLH001's pairwise diff cannot "
            f"see)"),
        details={
            "cycle": list(cycle),
            "programs": progs,
            "participants": {
                str(c): sorted(participants.get(c, ()))
                for c in cycle},
        },
    )]


def _find_cycle(edges: Dict[int, Dict[int, str]]) -> Optional[List[int]]:
    """First cycle in the channel digraph as [c0, c1, ..., c0]; None if
    acyclic. Recursive white/grey/black DFS — channel counts are tiny."""
    WHITE, GREY, BLACK = 0, 1, 2
    color: Dict[int, int] = {}

    def visit(node: int, path: List[int]) -> Optional[List[int]]:
        color[node] = GREY
        path.append(node)
        for nxt in sorted(edges.get(node, ())):
            c = color.get(nxt, WHITE)
            if c == GREY:
                return path[path.index(nxt):] + [nxt]
            if c == WHITE:
                found = visit(nxt, path)
                if found is not None:
                    return found
        path.pop()
        color[node] = BLACK
        return None

    for start in sorted(edges):
        if color.get(start, WHITE) == WHITE:
            found = visit(start, [])
            if found is not None:
                return found
    return None


# ------------------------------------------------------------------- screen
@dataclass
class ScheduleScreen:
    """Pure-arithmetic projection of the schedule passes onto an unlowered
    Strategy — what the planner's search can afford on every candidate."""

    findings: List[Finding] = field(default_factory=list)
    state_bytes: float = 0.0
    transient_bytes: float = 0.0
    n_buckets: int = 0
    n_eligible: int = 0


def screen_schedule(
    strategy,
    model_item,
    resource_spec=None,
    headroom: float = 0.75,
) -> List[Finding]:
    """Pre-lowering SLO001/SLM003 screen (no jax, no lowering, no compile).

    - SLO001: the candidate sets ``bucket_bytes > 0`` but NO variable is
      bucket-eligible (every gradient rides a PS / sparse / expert /
      partitioned / compressed wire): the per-bucket custom_vjp machinery
      is emitted with nothing to overlap — structurally serialized.
    - SLM003: static state fits the HBM headroom but the bucketed
      zero-embed transient (each bucketed zero1 gradient co-lives with a
      full-shape zero-fill buffer at its sync boundary —
      ``kernel/bucketing.py`` shape note) pushes the scheduled peak over:
      the overcommit SLM001's totals cannot see.
    """
    return _screen_schedule(
        strategy, model_item, resource_spec, headroom).findings


def _screen_schedule(
    strategy,
    model_item,
    resource_spec=None,
    headroom: float = 0.75,
) -> ScheduleScreen:
    import numpy as np

    from autodist_tpu.kernel.bucketing import (
        assign_buckets,
        bucket_exclusion_reasons,
    )
    from autodist_tpu.strategy.cost_model import OPTIMIZER_SLOT_FACTOR
    from autodist_tpu.strategy.ir import (
        AllReduceSynchronizer,
        PSSynchronizer,
    )

    out = ScheduleScreen()
    bucket_bytes = int(getattr(
        strategy.graph_config, "bucket_bytes", 0) or 0)
    mesh = resource_spec.mesh_shape(("data", "model")) if resource_spec \
        else {"data": 1, "model": 1}
    n_data = max(int(mesh.get("data", 1)), 1)
    n_model = max(int(mesh.get("model", 1)), 1)
    slot_factor = OPTIMIZER_SLOT_FACTOR.get(
        getattr(model_item.optimizer_spec, "name", ""), 2.0)

    eligible: List[Tuple[str, int]] = []
    bucketed_su: Dict[str, int] = {}
    state = 0.0
    for node in strategy.node_config:
        try:
            var = model_item.var(node.var_name)
        except KeyError:
            continue  # screen_strategy's SLS001 owns unknown vars
        b = float(int(np.prod(tuple(var.shape) or (1,)))
                  * np.dtype(var.dtype).itemsize)
        sync = node.synchronizer
        try:
            part_axis = node.active_partition_axis
        except ValueError:
            part_axis = None
        shards = max(int(node.num_shards), 1) if part_axis is not None else 1
        shard_update = bool(isinstance(sync, AllReduceSynchronizer)
                            and sync.shard_update)
        contrib = b / shards
        if var.trainable:
            contrib += slot_factor * b / (n_data if shard_update else shards)
            contrib += b  # full-gradient transient (the SLM001 accounting)
        state += contrib
        if not var.trainable:
            continue
        reasons = bucket_exclusion_reasons(
            var.shape,
            trainable=var.trainable,
            is_ps=isinstance(sync, PSSynchronizer),
            sparse_update=var.sparse_update,
            expert=var.expert,
            part_axis=part_axis,
            compressor=getattr(sync, "compressor", "") or "NoneCompressor",
            n_data=n_data, n_model=n_model,
        )
        if not reasons:
            eligible.append((node.var_name, int(b)))
            if shard_update:
                bucketed_su[node.var_name] = int(b)
    out.state_bytes = state
    out.n_eligible = len(eligible)

    if bucket_bytes > 0:
        buckets = assign_buckets(eligible, bucket_bytes)
        out.n_buckets = len(buckets)
        if not eligible:
            out.findings.append(Finding(
                code="SLO001", severity=ERROR, pass_name="sched",
                message=(
                    f"candidate requests bucketed overlap "
                    f"(bucket_bytes={bucket_bytes}) but NO variable is "
                    f"bucket-eligible — every gradient rides a "
                    f"PS/sparse/expert/partitioned/compressed wire, so "
                    f"the bucket machinery is structurally unable to "
                    f"overlap anything"),
                details={"bucket_bytes": bucket_bytes, "n_eligible": 0},
            ))
        else:
            sizes = dict(eligible)
            # Zero-embed transient: each bucketed shard_update gradient
            # co-lives with its full-shape zero-fill buffer at the
            # bucket's sync boundary — the largest bucket bounds the
            # simultaneous extra bytes.
            out.transient_bytes = max(
                (sum(bucketed_su.get(nm, 0) for nm in bucket)
                 for bucket in buckets), default=0.0)
    if resource_spec is not None and out.transient_bytes > 0:
        usable = float(resource_spec.tpu.hbm_bytes) * headroom
        if usable > 0 and state <= usable < state + out.transient_bytes:
            out.findings.append(Finding(
                code="SLM003", severity=ERROR, pass_name="sched",
                message=(
                    f"scheduled-peak estimate {state / 1e6:.3f} MB state "
                    f"+ {out.transient_bytes / 1e6:.3f} MB bucket "
                    f"zero-embed transient overcommits "
                    f"{usable / 1e6:.3f} MB usable even though the static "
                    f"state alone fits — shrink bucket_bytes or drop the "
                    f"bucketed rendering for this topology"),
                details={
                    "state_bytes": state,
                    "transient_bytes": out.transient_bytes,
                    "usable_bytes": usable,
                    "n_buckets": out.n_buckets,
                },
            ))
    return out
