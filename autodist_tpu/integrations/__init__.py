"""Framework adapters: bring flax/haiku models into the AutoDist contract.

The reference monkey-patched Keras so ``model.fit`` ran through its
distributed session (``/root/reference/autodist/patch.py:96-198``). JAX
module systems need no patching — an adapter just extracts the
(params, loss_fn) pair the user API consumes.
"""
from autodist_tpu.integrations.flax_adapter import from_flax
from autodist_tpu.integrations.haiku_adapter import from_haiku

__all__ = ["from_flax", "from_haiku"]
