"""Flax (linen) adapter — the Keras-integration analog.

Where the reference intercepted Keras' session plumbing
(``/root/reference/autodist/patch.py:96-198``, swapping
``GraphExecutionFunction`` internals so ``model.fit`` hit the distributed
session), a flax ``nn.Module`` is already a pure init/apply pair — the
adapter binds a loss around ``module.apply`` and hands back exactly what
``AutoDist.build`` consumes.

Usage::

    import flax.linen as nn

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(1)(x)

    spec = from_flax(Net(), loss=lambda pred, batch: ((pred - batch["y"]) ** 2).mean(),
                     example_inputs=lambda b: b["x"])
    params = spec.init(jax.random.PRNGKey(0))
    step = autodist.build(spec.loss_fn, params, batch)
"""
from __future__ import annotations

from typing import Any, Callable, Optional

from autodist_tpu.models.spec import ModelSpec


def from_flax(
    module,
    loss: Callable[[Any, Any], Any],
    example_inputs: Callable[[Any], Any],
    example_batch: Optional[Callable[[int], Any]] = None,
    name: Optional[str] = None,
    mutable: bool = False,
) -> ModelSpec:
    """Wrap a flax linen module as a :class:`ModelSpec`.

    ``loss(prediction, batch)`` maps module output + batch to a scalar;
    ``example_inputs(batch)`` extracts the module's positional input from a
    batch pytree. ``mutable=False`` keeps the adapter to pure modules
    (batch-stats style mutable collections need an explicit train loop).
    """

    def init(rng):
        batch = example_batch(2) if example_batch is not None else None
        if batch is None:
            raise ValueError(
                "from_flax needs example_batch to trace initialization; "
                "pass example_batch=lambda b: {...}"
            )
        variables = module.init(rng, example_inputs(batch))
        params = variables["params"] if "params" in variables else variables
        extra = [k for k in getattr(variables, "keys", lambda: [])() if k != "params"]
        if extra and not mutable:
            raise ValueError(
                f"module has mutable collections {extra}; from_flax supports "
                f"pure modules (pass the train state explicitly for batch stats)"
            )
        return params

    def loss_fn(params, batch):
        pred = module.apply({"params": params}, example_inputs(batch))
        return loss(pred, batch)

    return ModelSpec(
        name=name or f"flax_{type(module).__name__}",
        init=init,
        loss_fn=loss_fn,
        example_batch=example_batch or (lambda b: None),
        apply=lambda params, inputs: module.apply({"params": params}, inputs),
    )
