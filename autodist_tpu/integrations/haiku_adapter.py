"""Haiku adapter: ``hk.transform``'d functions as ModelSpecs."""
from __future__ import annotations

from typing import Any, Callable, Optional

from autodist_tpu.models.spec import ModelSpec


def from_haiku(
    transformed,
    loss: Callable[[Any, Any], Any],
    example_inputs: Callable[[Any], Any],
    example_batch: Optional[Callable[[int], Any]] = None,
    name: Optional[str] = None,
) -> ModelSpec:
    """Wrap a ``hk.transform`` (or ``transform_with_state``-free) pair.

    ``transformed`` must expose ``init(rng, inputs)`` / ``apply(params,
    rng, inputs)`` — the standard stateless haiku contract.
    """

    def init(rng):
        if example_batch is None:
            raise ValueError("from_haiku needs example_batch to trace init")
        return transformed.init(rng, example_inputs(example_batch(2)))

    def loss_fn(params, batch):
        pred = transformed.apply(params, None, example_inputs(batch))
        return loss(pred, batch)

    return ModelSpec(
        name=name or "haiku_model",
        init=init,
        loss_fn=loss_fn,
        example_batch=example_batch or (lambda b: None),
        apply=lambda params, inputs: transformed.apply(params, None, inputs),
    )
