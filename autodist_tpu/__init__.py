"""autodist_tpu: a TPU-native distributed training strategy compiler.

A ground-up JAX/XLA rebuild of the capabilities of AutoDist (reference at
``/root/reference``): the user brings a single-device model; a pluggable
``StrategyBuilder`` analyzes (model × cluster resources) and emits an explicit,
serializable ``Strategy`` (per-variable synchronization/partitioning choice);
a lowering layer turns the strategy into ``jax.sharding`` annotations +
collective plans over a TPU device mesh; and a thin multi-controller runtime
(``jax.distributed``) replaces the reference's SSH + TF-server launcher.

Where the reference rewired TF graphs op-by-op
(``/root/reference/autodist/kernel/``), this framework annotates shardings and
lets XLA GSPMD insert the collectives — the idiomatic TPU mechanism with the
same user-visible contract (single-device model in, distributed execution out).
"""
from autodist_tpu import checkpoint, const, ft, metrics, obs, plan, runtime, serve, strategy
from autodist_tpu.api import AutoDist, get_default_autodist
from autodist_tpu.ft import FTConfig
from autodist_tpu.obs import ObsConfig
from autodist_tpu.kernel import DistributedTrainStep, TrainState
from autodist_tpu.model_item import ModelItem, OptimizerSpec
from autodist_tpu.resource_spec import ResourceSpec

__version__ = "0.1.0"

__all__ = [
    "AutoDist",
    "DistributedTrainStep",
    "FTConfig",
    "ModelItem",
    "ObsConfig",
    "OptimizerSpec",
    "ResourceSpec",
    "TrainState",
    "checkpoint",
    "const",
    "ft",
    "get_default_autodist",
    "obs",
    "plan",
    "runtime",
    "serve",
    "strategy",
    "__version__",
]
