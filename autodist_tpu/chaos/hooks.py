"""Chaos injection seams: the ONE hook registry production code consults.

Every fault the chaos subsystem can inject enters the stack through a
**named seam** — a single ``hooks.fire(...)`` / ``hooks.apply(...)`` call
placed in the production module that owns the behavior (snapshot writes,
heartbeat transports, the train-step window, the serve engine, the obs
aggregator sweep). With no hook installed the seams are a dict lookup —
zero-cost and inert in production; with a :class:`~autodist_tpu.chaos.
schedule.ChaosPlant` installed they become deterministic fault injectors.

Contract per seam style:

- ``apply(seam, value, **ctx) -> value`` — *filter* seams: the hook may
  transform or replace the value (poison a batch, drop a heartbeat
  payload by returning None, scale a straggler's quantiles). No hook ⇒
  the value passes through untouched.
- ``fire(seam, **ctx) -> result`` — *event* seams: the hook may RAISE the
  injected fault (an OSError for an unwritable snapshot dir, an
  :class:`~autodist_tpu.serve.engine.EngineDeadError` mid-decode) or
  return a directive the seam interprets (``"defer"`` for admission).
  No hook ⇒ returns None and nothing happens.

This module is deliberately stdlib-only (no jax, no package imports) so
every subsystem can import it without cycles or cost. Only ONE plant may
hold the registry at a time (:func:`install` enforces it) — overlapping
chaos schedules would make injection traces ambiguous.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "SEAM_AGG_SWEEP",
    "SEAM_HB_PUBLISH",
    "SEAM_HB_SWEEP",
    "SEAM_PILOT_REFIT",
    "SEAM_SERVE_ADMIT",
    "SEAM_SERVE_DRAFT",
    "SEAM_SERVE_PAGES",
    "SEAM_SERVE_STEP",
    "SEAM_SNAPSHOT_WRITE",
    "SEAM_SNAPSHOT_WRITTEN",
    "SEAM_TRAIN_BATCH",
    "SEAM_TRAIN_METRICS",
    "active",
    "apply",
    "clear",
    "fire",
    "install",
    "installed",
    "uninstall",
]

# Seam names are part of the chaos schedule format (docs/chaos.md) — keep
# them stable.
SEAM_TRAIN_BATCH = "kernel.train_step.batch"       # apply(batch)
SEAM_TRAIN_METRICS = "kernel.train_step.metrics"   # apply(metrics)
SEAM_SNAPSHOT_WRITE = "ft.snapshot.write"          # fire (may raise OSError)
SEAM_SNAPSHOT_WRITTEN = "ft.snapshot.written"      # fire (corrupts files)
SEAM_HB_PUBLISH = "ft.heartbeat.publish"           # apply(payload) -> None=drop
SEAM_HB_SWEEP = "ft.heartbeat.sweep"               # apply(board)
SEAM_AGG_SWEEP = "obs.aggregate.sweep"             # apply(fleet summaries)
SEAM_SERVE_ADMIT = "serve.engine.admit"            # fire -> "defer" | raise
SEAM_SERVE_STEP = "serve.engine.step"              # fire (may raise)
SEAM_SERVE_PAGES = "serve.pages.alloc"             # fire -> "exhaust"
SEAM_SERVE_DRAFT = "serve.spec.draft"              # fire -> "garbage"
SEAM_PILOT_REFIT = "pilot.calibrate.refit"         # apply(live records)

_lock = threading.Lock()
_hooks: Dict[str, Callable] = {}
_owner: Optional[object] = None


def active() -> bool:
    """Fast inertness check for hot paths (the train-step window)."""
    return bool(_hooks)


def installed() -> List[str]:
    with _lock:
        return sorted(_hooks)


def install(seam: str, fn: Callable, owner: Optional[object] = None) -> None:
    """Register ``fn`` on ``seam``. A second owner trying to install while
    another plant holds any seam is a harness bug — refused loudly."""
    global _owner
    with _lock:
        if _hooks and owner is not None and _owner is not None \
                and owner is not _owner:
            raise RuntimeError(
                "chaos hooks are already installed by another plant; "
                "remove it first (one schedule at a time)")
        if owner is not None:
            _owner = owner
        _hooks[seam] = fn


def uninstall(seam: str) -> None:
    with _lock:
        _hooks.pop(seam, None)


def clear(owner: Optional[object] = None) -> None:
    """Drop every hook (and the owner claim)."""
    global _owner
    with _lock:
        if owner is None or owner is _owner or not _hooks:
            _hooks.clear()
            _owner = None


def apply(seam: str, value: Any, **ctx: Any) -> Any:
    """Filter seam: run the hook over ``value`` (or pass it through)."""
    fn = _hooks.get(seam)
    if fn is None:
        return value
    return fn(value, **ctx)


def fire(seam: str, **ctx: Any) -> Any:
    """Event seam: invoke the hook (which may raise the injected fault);
    returns its directive, or None when no hook is installed."""
    fn = _hooks.get(seam)
    if fn is None:
        return None
    return fn(**ctx)
