"""Deterministic fault injection + soak harness (docs/chaos.md).

The fault-tolerance and observability stack (``ft/``, ``obs/``, the serve
drain path, the launcher supervisor) claims to survive preemptions,
corruption, partitions, stragglers and engine death. This package proves
it: a seeded, replayable fault-injection subsystem
(:mod:`~autodist_tpu.chaos.schedule` + :mod:`~autodist_tpu.chaos.faults`)
whose injectors enter the stack through explicit seams
(:mod:`~autodist_tpu.chaos.hooks` — inert dict lookups in production),
and a CPU-runnable soak harness (:mod:`~autodist_tpu.chaos.harness`,
``python -m autodist_tpu.chaos --selftest``) asserting, per fault class:
detection with exactly the promised SNT*/DOC* code, recovery within a
step budget or a typed graceful degradation (never a hang), and a
post-recovery loss trajectory matching the uninterrupted control run.

This ``__init__`` stays import-light on purpose: production seams import
``autodist_tpu.chaos.hooks`` from hot paths (the train-step window), so
nothing heavier than the hooks registry may load here.
"""
from __future__ import annotations

from autodist_tpu.chaos import hooks

__all__ = ["CATALOG", "ChaosEvent", "ChaosPlant", "ChaosSchedule",
           "FaultSpec", "hooks"]


def __getattr__(name):
    if name in ("ChaosEvent", "ChaosPlant", "ChaosSchedule"):
        from autodist_tpu.chaos import schedule

        return getattr(schedule, name)
    if name in ("CATALOG", "FaultSpec"):
        from autodist_tpu.chaos import faults

        return getattr(faults, name)
    raise AttributeError(name)
