"""CLI: ``python -m autodist_tpu.chaos [--selftest | --list | --faults ...]``.

- ``--selftest`` — the zero-hardware chaos proof (docs/chaos.md), wired
  into CI's fast lane: provision an 8-device CPU host mesh, run the full
  soak matrix (:mod:`autodist_tpu.chaos.harness` — every catalog fault
  class injected against the real ft/obs/serve/runtime stack), assert
  each was detected with exactly its promised ``SNT###``/``DOC###`` code
  and recovered within budget (or degraded typed — never a hang), verify
  the no-chaos control run trips nothing, and prove schedule replay
  determinism (same seed ⇒ byte-identical injection trace). Exits
  non-zero on any contract violation.

- ``--faults nan_loss,engine_death`` — run a subset of the matrix
  (debugging one seam without paying for the rest).

- ``--list`` — print the fault catalog (kind, seam, expected detection,
  recovery contract) as JSON.
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def _provision_cpu_mesh(n_devices: int = 8) -> None:
    """Force an ``n_devices`` CPU host mesh when no backend exists yet
    (the __graft_entry__ recipe); a live backend is used as-is."""
    try:
        from jax._src import xla_bridge

        if xla_bridge._backends:
            return
    except Exception:  # noqa: BLE001 - internal moved: assume initialized
        return
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")


def _cmd_list() -> int:
    from autodist_tpu.chaos.faults import CATALOG

    doc = {k: {"seam": s.seam, "description": s.description,
               "detects": s.detects, "recovery": s.recovery}
           for k, s in sorted(CATALOG.items())}
    print(json.dumps(doc, indent=2))
    return 0


def _cmd_soak(faults, selftest: bool) -> int:
    _provision_cpu_mesh()
    from autodist_tpu.chaos import harness
    from autodist_tpu.chaos.faults import CATALOG

    try:
        results = harness.run_soak(faults=faults)
    except harness.SoakFailure as e:
        print(f"chaos soak FAILED: {e}", file=sys.stderr)
        return 1

    summary = {"results": [r.to_dict() for r in results]}
    if selftest:
        covered = {r.fault for r in results if r.injected > 0}
        missing = sorted(set(CATALOG) - covered)
        if missing:
            print(f"chaos selftest FAILED: catalog fault class(es) never "
                  f"injected: {missing}", file=sys.stderr)
            return 1
        # Replay determinism: one RNG-using scenario (the corrupt injector
        # draws the victim file and byte offset from the seeded RNG) and
        # one windowed transport scenario.
        for fault in ("snapshot_corrupt", "heartbeat_drop"):
            if not harness.replay_is_deterministic(fault):
                print(f"chaos selftest FAILED: {fault} replay produced a "
                      f"different injection trace (nondeterminism)",
                      file=sys.stderr)
                return 1
        summary["replay_deterministic"] = ["snapshot_corrupt",
                                           "heartbeat_drop"]
    print(json.dumps(summary, indent=2))
    print("chaos soak ok" if not selftest else "chaos selftest ok")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m autodist_tpu.chaos",
        description="Deterministic fault injection + soak harness "
                    "(docs/chaos.md)")
    p.add_argument("--selftest", action="store_true",
                   help="run the full soak matrix + determinism proof")
    p.add_argument("--faults", default="",
                   help="comma-separated scenario subset (see --list)")
    p.add_argument("--list", action="store_true", dest="list_catalog",
                   help="print the fault catalog as JSON")
    args = p.parse_args(argv)

    if args.list_catalog:
        return _cmd_list()
    faults = [f for f in args.faults.split(",") if f] or None
    if args.selftest or faults:
        return _cmd_soak(faults, selftest=args.selftest and not faults)
    p.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
