"""Declarative, seeded, replayable chaos schedules.

A :class:`ChaosSchedule` is pure data — a seed plus a list of
:class:`ChaosEvent` rows ("at step N, inject fault F on host H, for K
steps") — serializable to canonical JSON so a chaos run is an artifact
you can attach to a bug report and replay. A :class:`ChaosPlant`
instantiates the schedule against the live stack: it installs the fault
catalog's hook closures (:func:`autodist_tpu.chaos.faults.make_handlers`)
into the seam registry (:mod:`autodist_tpu.chaos.hooks`), owns the seeded
RNG every injector draws from, and appends each injection to a **trace**
whose bytes are a pure function of (schedule, driven steps) — no wall
clock, no process ids, no ``Date.now``-style nondeterminism. Replaying
the same schedule over the same scenario yields byte-identical traces
(pinned by ``tests/test_chaos.py``).

Step semantics are scenario-local: for training faults the plant's step
counter advances with each train window (the metrics seam); for
heartbeat/aggregator/serve scenarios the harness drives
:meth:`ChaosPlant.advance` at its own tick boundaries.
"""
from __future__ import annotations

import json
import random
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from autodist_tpu.chaos import hooks

__all__ = ["ChaosEvent", "ChaosSchedule", "ChaosPlant"]


@dataclass(frozen=True)
class ChaosEvent:
    """One scheduled injection window (``until_step`` exclusive; None =
    a single step)."""

    fault: str
    at_step: int = 0
    until_step: Optional[int] = None
    host: int = 0
    params: Tuple[Tuple[str, Any], ...] = ()

    @property
    def end_step(self) -> int:
        return self.at_step + 1 if self.until_step is None else self.until_step

    def active(self, step: int) -> bool:
        return self.at_step <= step < self.end_step

    def param(self, key: str, default: Any = None) -> Any:
        for k, v in self.params:
            if k == key:
                return v
        return default

    def to_dict(self) -> dict:
        d: Dict[str, Any] = {"fault": self.fault, "at_step": self.at_step,
                             "host": self.host}
        if self.until_step is not None:
            d["until_step"] = self.until_step
        if self.params:
            d["params"] = {k: v for k, v in self.params}
        return d

    @staticmethod
    def from_dict(d: dict) -> "ChaosEvent":
        return ChaosEvent(
            fault=str(d["fault"]),
            at_step=int(d.get("at_step", 0)),
            until_step=(None if d.get("until_step") is None
                        else int(d["until_step"])),
            host=int(d.get("host", 0)),
            params=tuple(sorted((str(k), v) for k, v in
                                (d.get("params") or {}).items())),
        )


@dataclass(frozen=True)
class ChaosSchedule:
    """Seed + events. Unknown fault kinds are rejected at construction
    time (a typo'd schedule must not silently inject nothing)."""

    seed: int = 0
    events: Tuple[ChaosEvent, ...] = ()

    def __post_init__(self):
        from autodist_tpu.chaos.faults import CATALOG

        unknown = sorted({e.fault for e in self.events} - set(CATALOG))
        if unknown:
            raise ValueError(
                f"unknown fault kind(s) {unknown}; catalog: "
                f"{sorted(CATALOG)}")

    def to_json(self) -> str:
        doc = {"seed": self.seed,
               "events": [e.to_dict() for e in self.events]}
        return json.dumps(doc, sort_keys=True, indent=2)

    @staticmethod
    def from_json(text: str) -> "ChaosSchedule":
        doc = json.loads(text)
        return ChaosSchedule(
            seed=int(doc.get("seed", 0)),
            events=tuple(ChaosEvent.from_dict(e)
                         for e in doc.get("events", [])))

    @staticmethod
    def from_file(path: str) -> "ChaosSchedule":
        with open(path, encoding="utf-8") as f:
            return ChaosSchedule.from_json(f.read())


class ChaosPlant:
    """A schedule armed against the live stack (context manager).

    ``install()`` registers the catalog's hook closures for every seam
    the schedule touches; ``remove()`` (or context exit) clears them. The
    injection trace accumulates one dict per injection —
    :meth:`trace_bytes` renders it as canonical JSONL, the replay-
    determinism artifact.
    """

    def __init__(self, schedule: ChaosSchedule):
        self.schedule = schedule
        self.rng = random.Random(schedule.seed)
        self.step = 0
        self.trace: List[Dict[str, Any]] = []
        self.state: Dict[Any, Any] = {}
        self._once: set = set()
        self._installed = False

    # ------------------------------------------------------------ lifecycle
    def install(self) -> "ChaosPlant":
        from autodist_tpu.chaos.faults import make_handlers

        if self._installed:
            return self
        for seam, fn in make_handlers(self).items():
            hooks.install(seam, fn, owner=self)
        self._installed = True
        return self

    def remove(self) -> None:
        if self._installed:
            hooks.clear(owner=self)
            self._installed = False

    def __enter__(self) -> "ChaosPlant":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.remove()

    # ------------------------------------------------------------- stepping
    def advance(self, n: int = 1) -> int:
        self.step += int(n)
        return self.step

    # -------------------------------------------------------------- tracing
    def record(self, fault: str, **detail: Any) -> Dict[str, Any]:
        entry = {"i": len(self.trace), "step": self.step, "fault": fault,
                 **detail}
        self.trace.append(entry)
        return entry

    def record_once(self, key: Any, fault: str, **detail: Any) -> bool:
        """Record at most once per ``key`` (events whose hook fires from a
        scheduler thread record per-activation, keeping the trace
        independent of thread timing)."""
        if key in self._once:
            return False
        self._once.add(key)
        self.record(fault, **detail)
        return True

    def injected(self, fault: Optional[str] = None) -> int:
        return sum(1 for e in self.trace
                   if fault is None or e["fault"] == fault)

    def trace_lines(self) -> List[str]:
        return [json.dumps(e, sort_keys=True) for e in self.trace]

    def trace_bytes(self) -> bytes:
        return ("\n".join(self.trace_lines()) + "\n").encode("utf-8") \
            if self.trace else b""
