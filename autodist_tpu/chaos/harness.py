"""CPU-runnable chaos soak harness: one scenario per catalog fault class.

Each scenario arms a seeded :class:`~autodist_tpu.chaos.schedule.ChaosPlant`
against the REAL stack (the production ``DistributedTrainStep``, the real
``SnapshotManager`` ring, live ``HealthMonitor``/``HostAggregator``
instances, a compiled serve engine, real supervised subprocesses) and
asserts the :data:`~autodist_tpu.chaos.faults.CATALOG` contract for its
fault class:

- the fault was **injected** (the plant's trace is non-empty),
- the stack **detected** it with exactly the promised ``SNT###`` sentry
  code / ``DOC###`` doctor verdict / typed degradation — no more, no less,
- the run **recovered** within its step budget or degraded gracefully
  (typed rejection, never a hang), and
- for training faults, the committed post-recovery **loss trajectory is
  identical to the uninterrupted control run** (the elastic-resume
  tolerance, ``tests/test_ft.py``).

Scenario step budgets and schedules are constants here, so a soak run is a
pure function of the code under test — replaying a scenario yields a
byte-identical injection trace (:func:`replay_is_deterministic`, pinned by
``tests/test_chaos.py``).

Run it: ``python -m autodist_tpu.chaos --selftest`` (docs/chaos.md).
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from autodist_tpu import metrics as M
from autodist_tpu.chaos.faults import CATALOG
from autodist_tpu.chaos.schedule import ChaosEvent, ChaosPlant, ChaosSchedule
from autodist_tpu.utils import logging, retry

__all__ = ["SoakResult", "SCENARIOS", "run_soak", "replay_is_deterministic"]

#: Train-scenario geometry: N total steps, injection at INJECT_AT. The
#: sentry needs ``min_history`` clean losses before spike checks arm, so
#: the injection sits past the warmup window.
TRAIN_STEPS = 10
TRAIN_INJECT_AT = 6

#: Loss-trajectory match tolerance — the elastic-resume bar
#: (tests/test_ft.py::test_kill_resume_on_smaller_mesh_matches_uninterrupted).
LOSS_RTOL, LOSS_ATOL = 1e-5, 1e-6


@dataclass
class SoakResult:
    """One scenario's verdict against its catalog contract."""

    fault: str
    ok: bool
    injected: int                      # injection-trace entries
    detected: List[str] = field(default_factory=list)
    expected: str = ""                 # CATALOG[fault].detects
    recovery_steps: int = -1           # steps from detection to recovered
    notes: str = ""
    trace: bytes = b""

    def to_dict(self) -> dict:
        return {"fault": self.fault, "ok": self.ok,
                "injected": self.injected, "detected": self.detected,
                "expected": self.expected,
                "recovery_steps": self.recovery_steps, "notes": self.notes}


class SoakFailure(AssertionError):
    """A scenario's contract assertion failed (message says which)."""


def _check(cond: bool, fault: str, what: str) -> None:
    if not cond:
        raise SoakFailure(f"[{fault}] {what}")


# --------------------------------------------------------------- train rig
def _build_train_step(n_chips: int = 8):
    """Tiny linear-regression train step over the full production stack
    (strategy → compile → transform → DistributedTrainStep), the same rig
    as tests/test_ft.py — small enough that a 10-step soak run costs
    milliseconds after compile."""
    import jax
    import jax.numpy as jnp
    import optax

    from autodist_tpu.kernel import (
        DistributedTrainStep, GraphTransformer, build_mesh)
    from autodist_tpu.model_item import ModelItem, OptimizerSpec
    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.strategy import AllReduce, StrategyCompiler

    def loss_fn(params, batch):
        x, y = batch
        return jnp.mean((x @ params["w"] + params["b"] - y) ** 2)

    k1, k2, k3, k4 = jax.random.split(jax.random.PRNGKey(3), 4)
    params = {"w": jax.random.normal(k1, (8, 4)),
              "b": jax.random.normal(k2, (4,))}
    batch = (jax.random.normal(k3, (16, 8)),
             jax.random.normal(k4, (16, 4)))
    spec = ResourceSpec(resource_dict={
        "nodes": [{"address": "localhost", "chips": n_chips, "chief": True}]})
    mesh = build_mesh(spec, axes=("data",))
    mi = ModelItem.from_params(
        params, optimizer_spec=OptimizerSpec("sgd", {"learning_rate": 0.1}))
    strategy = AllReduce().build(mi, spec)
    compiled = StrategyCompiler(mi).compile(strategy)
    plan = GraphTransformer(compiled, mi, mesh).transform()
    step = DistributedTrainStep(plan, loss_fn, optax.sgd(0.1))
    return step, params, batch


def _control_losses(n_steps: int = TRAIN_STEPS) -> List[float]:
    """The uninterrupted reference trajectory (no plant installed)."""
    step, params, batch = _build_train_step()
    state = step.init(params)
    out = []
    for _ in range(n_steps):
        state, m = step(state, batch)
        out.append(float(m["loss"]))
    return out


def _sentry_rig(base: str, registry: M.MetricsRegistry, monitor=None):
    """A flight recorder + sentry pair rooted at ``base`` (the ft-style
    base dir the doctor later diagnoses)."""
    from autodist_tpu.obs import recorder as obs_recorder
    from autodist_tpu.obs.sentry import Sentry, SentryConfig

    rec = obs_recorder.FlightRecorder(obs_recorder.flight_dir(base))
    sentry = Sentry(
        SentryConfig(min_history=4, loss_z_threshold=4.0),
        registry=registry, recorder=rec, monitor=monitor, process_id=0)
    return rec, sentry


def _train_fault_scenario(fault: str, base: str) -> SoakResult:
    """Shared rig for ``nan_loss`` and ``loss_spike``: inject at step
    ``TRAIN_INJECT_AT``, expect immediate sentry detection, recover by
    restoring the newest verified snapshot and replaying clean steps, and
    require the committed loss trajectory to equal the control run's."""
    from autodist_tpu.ft.elastic import resume_from_snapshot
    from autodist_tpu.ft.snapshot import SnapshotManager
    from autodist_tpu.obs import doctor

    expect_snt = "SNT001" if fault == "nan_loss" else "SNT003"
    expect_doc = "DOC001" if fault == "nan_loss" else "DOC000"
    ref = _control_losses()

    schedule = ChaosSchedule(seed=7, events=(
        ChaosEvent(fault, at_step=TRAIN_INJECT_AT,
                   params=(("scale", 32.0),)),))
    step, params, batch = _build_train_step()
    reg = M.MetricsRegistry()
    rec, sentry = _sentry_rig(base, reg)
    mgr = SnapshotManager(os.path.join(base, "snapshots"), keep=3,
                          registry=reg)

    committed: List[float] = []
    detected_at: Optional[int] = None
    with ChaosPlant(schedule) as plant:
        state = step.init(params)
        mgr.snapshot(state, step_obj=step, block=True)  # step-0 baseline
        i, calls = 0, 0
        while i < TRAIN_STEPS:
            calls += 1
            _check(calls <= 2 * TRAIN_STEPS, fault, "soak loop failed to "
                   "converge (recovery re-poisoned?)")
            state, m = step(state, batch)
            loss = float(m["loss"])
            rec.record_step(step=i, loss=loss)
            findings = sentry.observe_step(step=i, loss=loss)
            if any(f.code in ("SNT001", "SNT003") for f in findings):
                # Detection: roll back to the newest verified snapshot and
                # replay. The plant's step cursor has already advanced past
                # the injection window, so the replayed batch is clean.
                _check(detected_at is None, fault,
                       "sentry fired twice (episode failed to close)")
                detected_at = i
                state = resume_from_snapshot(step, params, mgr)
                _check(int(state.step) == i, fault,
                       f"restored snapshot is at step {int(state.step)}, "
                       f"expected {i}")
                continue
            committed.append(loss)
            mgr.snapshot(state, step_obj=step, block=True)
            i += 1
        injected = plant.injected()
        trace = plant.trace_bytes()

    _check(injected >= 1, fault, "schedule never injected")
    _check(detected_at == TRAIN_INJECT_AT, fault,
           f"detected at step {detected_at}, injected at {TRAIN_INJECT_AT} "
           f"(budget: same-step detection)")
    recovery_steps = TRAIN_STEPS - detected_at
    _check(sorted({f.code for f in sentry.findings}) == [expect_snt], fault,
           f"sentry codes {sorted({f.code for f in sentry.findings})}, "
           f"expected exactly [{expect_snt!r}]")
    ok_traj = np.allclose(committed, ref, rtol=LOSS_RTOL, atol=LOSS_ATOL)
    _check(ok_traj, fault,
           "post-recovery loss trajectory diverged from the control run")
    rec.close(ok=True)
    diag = doctor.diagnose(base)
    _check(diag.code == expect_doc, fault,
           f"doctor said {diag.code}, expected {expect_doc}")

    return SoakResult(
        fault=fault, ok=True, injected=injected,
        detected=[expect_snt, diag.code],
        expected=CATALOG[fault].detects,
        recovery_steps=TRAIN_STEPS - detected_at,
        notes=f"detected at step {detected_at}; trajectory matches control "
              f"(rtol={LOSS_RTOL:g})",
        trace=trace)


def scenario_nan_loss(base: str) -> SoakResult:
    return _train_fault_scenario("nan_loss", base)


def scenario_loss_spike(base: str) -> SoakResult:
    return _train_fault_scenario("loss_spike", base)


# ----------------------------------------------------------- control run
def scenario_control(base: str) -> SoakResult:
    """No plant installed: the sentry must stay silent and the doctor must
    call the run clean — the zero-findings bar is as load-bearing as the
    seeded-fault bars."""
    from autodist_tpu.obs import doctor

    reg = M.MetricsRegistry()
    rec, sentry = _sentry_rig(base, reg)
    step, params, batch = _build_train_step()
    state = step.init(params)
    for i in range(TRAIN_STEPS):
        state, m = step(state, batch)
        loss = float(m["loss"])
        rec.record_step(step=i, loss=loss)
        sentry.observe_step(step=i, loss=loss)
    rec.close(ok=True)
    _check(not sentry.findings, "control",
           f"clean run tripped {sorted({f.code for f in sentry.findings})}")
    diag = doctor.diagnose(base)
    _check(diag.code == "DOC000", "control",
           f"doctor said {diag.code} on a clean run")
    return SoakResult(fault="control", ok=True, injected=0,
                      detected=["DOC000"], expected="zero findings + DOC000",
                      recovery_steps=0, notes="no chaos, no findings")


# ------------------------------------------------------------- straggler
def scenario_straggler(base: str) -> SoakResult:
    from autodist_tpu.ft.heartbeat import (
        HealthMonitor, MemoryTransport, PeerState)
    from autodist_tpu.obs.aggregate import HostAggregator
    from autodist_tpu.obs.sentry import Sentry, SentryConfig

    fault = "straggler"
    reg = M.MetricsRegistry()
    monitor = HealthMonitor(MemoryTransport(), publish=False,
                            expected=[0, 1, 2, 3], registry=reg)
    sentry = Sentry(SentryConfig(), registry=reg, monitor=monitor,
                    process_id=0)
    transport = MemoryTransport()
    aggs = [HostAggregator(transport, process_id=p, registry=reg)
            for p in range(4)]
    for agg in aggs:
        for k in range(16):
            agg.observe_step(0.1 + 0.001 * (k % 3))

    # Two windows, same victim: the second proves the episode re-armed.
    schedule = ChaosSchedule(seed=11, events=(
        ChaosEvent(fault, at_step=1, until_step=3, host=1,
                   params=(("scale", 4.0),)),
        ChaosEvent(fault, at_step=5, until_step=6, host=1,
                   params=(("scale", 4.0),)),
    ))

    def sweep_and_observe():
        for agg in aggs[1:]:
            agg.tick()
        fleet = aggs[0].tick()
        scores = aggs[0].straggler_scores(fleet)
        sentry.observe_scores(scores)
        return scores

    with ChaosPlant(schedule) as plant:
        scores = sweep_and_observe()                      # step 0: clean
        _check(not sentry.findings, fault,
               f"clean sweep tripped {[f.code for f in sentry.findings]}")
        plant.advance(1)                                  # window 1 opens
        scores = sweep_and_observe()
        _check([f.code for f in sentry.findings] == ["SNT006"], fault,
               "SNT006 did not fire on the slowed host")
        _check(sentry.findings[0].process_id == 1, fault,
               f"SNT006 blamed host {sentry.findings[0].process_id}, "
               f"victim is 1")
        _check(monitor.peers()[1].state is PeerState.SUSPECT, fault,
               "HealthMonitor did not escalate the straggler to SUSPECT")
        plant.advance(1)                                  # still open
        sweep_and_observe()
        _check(len(sentry.findings) == 1, fault,
               "episode fired more than once inside one window")
        plant.advance(1)                                  # window 1 closes
        scores = sweep_and_observe()
        _check(abs(scores[1] - 1.0) < 0.2, fault,
               f"score did not renormalize after the window ({scores[1]:.2f})")
        plant.advance(2)                                  # window 2 opens
        sweep_and_observe()
        _check(len(sentry.findings) == 2, fault,
               "episode did not re-arm for the second window")
        trace = plant.trace_bytes()

    return SoakResult(
        fault=fault, ok=True, injected=2, detected=["SNT006", "SUSPECT"],
        expected=CATALOG[fault].detects, recovery_steps=1,
        notes="score renormalized after each window; one finding per episode",
        trace=trace)


# ------------------------------------------------------- heartbeat faults
def scenario_heartbeat_drop(base: str) -> SoakResult:
    from autodist_tpu.ft.config import FTConfig
    from autodist_tpu.ft.heartbeat import (
        HealthMonitor, MemoryTransport, PeerState)

    fault = "heartbeat_drop"
    reg = M.MetricsRegistry()
    cfg = FTConfig(heartbeat_interval_s=1.0, suspect_after_misses=2,
                   dead_after_misses=4, backoff_initial_s=1.0)
    transport = MemoryTransport()
    monitor = HealthMonitor(transport, process_id=0, config=cfg,
                            publish=True, registry=reg)
    transitions: List[tuple] = []
    monitor.on_transition(
        lambda pid, old, new: transitions.append((pid, old, new)))

    # Synthetic clock with a nonzero base: PeerInfo.last_seen starts at 0
    # and freshness is strictly "seen > last_seen", so a t=0 beat would
    # never register.
    t0 = 100.0
    schedule = ChaosSchedule(seed=5, events=(
        ChaosEvent(fault, at_step=1, until_step=2, host=1),))
    with ChaosPlant(schedule) as plant:
        transport.publish(1, {"time": t0, "step": 0})
        monitor.tick(now=t0)
        _check(monitor.peers()[1].state is PeerState.HEALTHY, fault,
               "peer 1 not HEALTHY after its first beat")
        plant.advance(1)                                  # drop window opens
        for dt in (1.0, 2.0, 3.0):
            transport.publish(1, {"time": t0 + dt, "step": int(dt)})
            monitor.tick(now=t0 + dt)
        _check(monitor.peers()[1].state is PeerState.DEAD, fault,
               f"peer 1 is {monitor.peers()[1].state} after the drop "
               f"window, expected DEAD")
        plant.advance(1)                                  # window closes
        transport.publish(1, {"time": t0 + 4.0, "step": 4})
        monitor.tick(now=t0 + 4.0)
        trace = plant.trace_bytes()

    peer = monitor.peers()[1]
    _check(peer.state is PeerState.HEALTHY, fault,
           "first fresh beat did not return the peer to HEALTHY")
    _check(peer.backoff_s == 0.0 and peer.misses == 0, fault,
           "escalation backoff did not reset on recovery")
    seq = [(p, o.name, n.name) for p, o, n in transitions if p == 1]
    _check(seq == [(1, "HEALTHY", "SUSPECT"), (1, "SUSPECT", "DEAD"),
                   (1, "DEAD", "HEALTHY")], fault,
           f"transition sequence {seq}")
    return SoakResult(
        fault=fault, ok=True, injected=3,
        detected=["HEALTHY->SUSPECT", "SUSPECT->DEAD", "DEAD->HEALTHY"],
        expected=CATALOG[fault].detects, recovery_steps=1,
        notes="3 dropped beats -> SUSPECT -> DEAD; first fresh beat heals",
        trace=trace)


def scenario_heartbeat_partition(base: str) -> SoakResult:
    import time as _time

    from autodist_tpu.ft.config import FTConfig
    from autodist_tpu.ft.heartbeat import FileTransport
    from autodist_tpu.obs import doctor
    from autodist_tpu.runtime.launcher import _FleetWatch

    fault = "heartbeat_partition"
    watch = _FleetWatch(FTConfig(base_dir=base, heartbeat_interval_s=1.0,
                                 hang_after_misses=3))
    transport = FileTransport(watch.config.heartbeat_dir)
    t0 = _time.time()

    schedule = ChaosSchedule(seed=3, events=(
        ChaosEvent(fault, at_step=1, until_step=2),))
    with ChaosPlant(schedule) as plant:
        for pid in (0, 1):
            transport.publish(pid, {"time": t0, "step": 5})
        watch.monitor.tick(now=t0)
        _check(len(watch.monitor.peers()) == 2, fault,
               "watchdog did not see the fleet before the partition")
        _check(not watch.monitor.fleet_hung(now=t0), fault,
               "fleet read as hung before the partition")
        plant.advance(1)                                  # partition opens
        for k in range(1, 5):
            watch.monitor.tick(now=t0 + k)
        _check(watch.monitor.fleet_hung(now=t0 + 4), fault,
               "fleet_hung never fired under a full partition")
        bundle = watch.write_bundle()
        _check(bundle is not None and os.path.exists(bundle), fault,
               "hang bundle was not written")
        trace = plant.trace_bytes()

    diag = doctor.diagnose(base)
    _check(diag.code == "DOC003", fault,
           f"doctor said {diag.code}, expected DOC003 (wedge)")
    return SoakResult(
        fault=fault, ok=True, injected=1,
        detected=["fleet_hung", "DOC003"],
        expected=CATALOG[fault].detects, recovery_steps=0,
        notes=f"bundle {os.path.basename(bundle)} attributes the kill",
        trace=trace)


# -------------------------------------------------------- snapshot faults
def _snapshot_state():
    return {"w": np.arange(32, dtype=np.float32),
            "b": np.ones((4,), np.float32)}


def _snapshot_damage_scenario(fault: str, base: str) -> SoakResult:
    """Shared rig for ``snapshot_corrupt`` / ``snapshot_partial``: damage
    the SECOND ring entry after it lands; the ring must fall back to the
    first and restore from it."""
    from autodist_tpu.ft.snapshot import SnapshotManager

    reg = M.MetricsRegistry()
    mgr = SnapshotManager(os.path.join(base, "snapshots"), keep=3,
                          registry=reg)
    state = _snapshot_state()

    schedule = ChaosSchedule(seed=13, events=(
        ChaosEvent(fault, at_step=1),))
    with ChaosPlant(schedule) as plant:
        p1 = mgr.snapshot(state, step=1, block=True)      # clean entry
        plant.advance(1)
        p2 = mgr.snapshot(state, step=2, block=True)      # damaged entry
        trace = plant.trace_bytes()

    _check(mgr.verify(p1), fault, "the clean ring entry failed verify()")
    _check(not mgr.verify(p2), fault,
           "verify() passed on the damaged snapshot")
    _check(mgr.latest_valid() == p1, fault,
           "latest_valid() did not fall back to the previous ring entry")
    _check(reg.counter("ft_snapshots_corrupt_total").value >= 1, fault,
           "ft_snapshots_corrupt_total did not increment")
    restored = mgr.restore_latest_valid(target=_snapshot_state())
    _check(restored is not None
           and np.array_equal(np.asarray(restored["w"]), state["w"]), fault,
           "restore from the fallback entry did not round-trip")
    return SoakResult(
        fault=fault, ok=True, injected=1,
        detected=["verify_failed", "ft_snapshots_corrupt_total"],
        expected=CATALOG[fault].detects, recovery_steps=0,
        notes="ring fell back to the previous entry and restored",
        trace=trace)


def scenario_snapshot_corrupt(base: str) -> SoakResult:
    return _snapshot_damage_scenario("snapshot_corrupt", base)


def scenario_snapshot_partial(base: str) -> SoakResult:
    return _snapshot_damage_scenario("snapshot_partial", base)


def scenario_snapshot_unwritable(base: str) -> SoakResult:
    from autodist_tpu.ft.snapshot import SnapshotManager

    fault = "snapshot_unwritable"
    reg = M.MetricsRegistry()
    mgr = SnapshotManager(os.path.join(base, "snapshots"), keep=3,
                          registry=reg)
    state = _snapshot_state()

    schedule = ChaosSchedule(seed=17, events=(
        ChaosEvent(fault, at_step=0, params=(("times", 2),)),))
    with ChaosPlant(schedule) as plant:
        path = mgr.snapshot(state, step=1, block=True)    # heals on retry
        trace = plant.trace_bytes()

    _check(mgr.verify(path), fault,
           "snapshot did not land despite the retry budget covering the "
           "transient failures")
    _check(reg.counter("ft_snapshot_write_retries_total").value == 2, fault,
           f"expected exactly 2 write retries, saw "
           f"{reg.counter('ft_snapshot_write_retries_total').value}")
    _check(mgr.latest_valid() == path, fault, "ring slot was skipped")
    return SoakResult(
        fault=fault, ok=True, injected=2,
        detected=["retry_healed", "ft_snapshot_write_retries_total=2"],
        expected=CATALOG[fault].detects, recovery_steps=0,
        notes="2 refused write attempts healed by utils/retry within "
              "policy; no ring slot skipped",
        trace=trace)


# ------------------------------------------------------------ serve faults
_ENGINE = None


def _serve_engine():
    """One compiled CPU inference engine shared by the serve scenarios
    (the compile dominates scenario cost; the faults are injected per-run
    through the seams, so sharing is sound)."""
    global _ENGINE
    if _ENGINE is not None:
        return _ENGINE
    import jax
    import jax.numpy as jnp

    from autodist_tpu.api import AutoDist
    from autodist_tpu.models.transformer import (
        TransformerConfig, decode_model, init_params)
    from autodist_tpu.strategy import AllReduce

    cfg = TransformerConfig(
        vocab_size=97, num_layers=1, d_model=32, num_heads=2, d_ff=64,
        max_seq_len=32, causal=True, dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    AutoDist.reset_default()
    try:
        autodist = AutoDist(strategy_builder=AllReduce())
        # Small pool: the page_exhaustion scenario's seam forces the
        # exhaustion deterministically, but a modest pool keeps the
        # scenario's accounting assertions legible.
        _ENGINE = autodist.build_inference(
            params, decode_model=decode_model(cfg),
            n_slots=4, page_len=8, n_pages=9, prefill_chunk=8, max_len=16)
    finally:
        AutoDist.reset_default()
    return _ENGINE


def scenario_serve_admission(base: str) -> SoakResult:
    from autodist_tpu.obs import doctor
    from autodist_tpu.obs import recorder as obs_recorder
    from autodist_tpu.serve.batcher import ContinuousBatcher, RequestState

    fault = "serve_admission"
    obs_recorder.enable(obs_recorder.flight_dir(base))
    batcher = ContinuousBatcher(_serve_engine(), max_queue=4,
                                registry=M.MetricsRegistry())
    prompt = np.arange(4, dtype=np.int32)

    schedule = ChaosSchedule(seed=23, events=(
        ChaosEvent(fault, at_step=0),))
    try:
        with ChaosPlant(schedule) as plant:
            queued = [batcher.submit(prompt, max_new_tokens=4)
                      for _ in range(4)]
            batcher.start()
            retry.wait_until(lambda: plant.injected(fault) > 0, 5.0)
            _check(plant.injected(fault) > 0, fault,
                   "admission seam never fired")
            _check(all(r.state is RequestState.QUEUED for r in queued),
                   fault, "requests progressed during the admission stall")
            shed = [batcher.try_submit(prompt, max_new_tokens=4)
                    for _ in range(2)]
            _check(all(r.state is RequestState.REJECTED for r in shed),
                   fault, "overflow was not shed with typed REJECTED")
            _check(all("queue full" in r.error for r in shed), fault,
                   f"rejection reason untyped: {[r.error for r in shed]}")
            plant.advance(1)                              # window closes
            done = [r.wait(30.0).state for r in queued]
            _check(all(s is RequestState.DONE for s in done), fault,
                   f"queued work did not complete after the window: {done}")
            trace = plant.trace_bytes()
        batcher.stop()
    finally:
        obs_recorder.disable(ok=True)

    records = obs_recorder.read_records(obs_recorder.flight_dir(base))
    sheds = [r for r in records if r.get("kind") == "shed"]
    _check(len(sheds) >= 1, fault,
           "no shed flight event — the doctor timeline cannot show the "
           "shed-load window")
    diag = doctor.diagnose(base)
    _check(diag.code == "DOC000", fault,
           f"doctor said {diag.code} after graceful recovery")
    return SoakResult(
        fault=fault, ok=True, injected=1,
        detected=["REJECTED(queue full)", "shed event", "DOC000"],
        expected=CATALOG[fault].detects, recovery_steps=0,
        notes="overflow shed at the edge; queued work completed after the "
              "window; shed window on the doctor timeline",
        trace=trace)


def scenario_page_exhaustion(base: str) -> SoakResult:
    """Burst past KV page-pool capacity: while the pool reports exhausted,
    admissions defer typed (requests stay queued, nothing hangs), queue
    overflow sheds typed REJECTED at the edge with a shed flight event on
    the doctor timeline, and once pages recycle every queued request
    completes — the acceptance contract the paged serving engine must
    keep under burst (docs/serving.md § admission)."""
    from autodist_tpu.obs import doctor
    from autodist_tpu.obs import recorder as obs_recorder
    from autodist_tpu.serve.batcher import ContinuousBatcher, RequestState

    fault = "page_exhaustion"
    obs_recorder.enable(obs_recorder.flight_dir(base))
    engine = _serve_engine()
    free_before = engine.pool.free_pages
    batcher = ContinuousBatcher(engine, max_queue=4,
                                registry=M.MetricsRegistry())
    prompt = np.arange(1, 5, dtype=np.int32)

    schedule = ChaosSchedule(seed=41, events=(
        ChaosEvent(fault, at_step=0),))
    try:
        with ChaosPlant(schedule) as plant:
            queued = [batcher.submit(prompt, max_new_tokens=4)
                      for _ in range(4)]
            batcher.start()
            retry.wait_until(lambda: plant.injected(fault) > 0, 5.0)
            _check(plant.injected(fault) > 0, fault,
                   "page-pool seam never fired")
            _check(all(r.state is RequestState.QUEUED for r in queued),
                   fault, "requests progressed while the pool was exhausted")
            _check(engine.pool.used_pages == 0, fault,
                   "pages were allocated during the exhaustion window")
            shed = [batcher.try_submit(prompt, max_new_tokens=4)
                    for _ in range(2)]
            _check(all(r.state is RequestState.REJECTED for r in shed),
                   fault, "burst overflow was not shed with typed REJECTED")
            _check(all("queue full" in r.error for r in shed), fault,
                   f"rejection reason untyped: {[r.error for r in shed]}")
            plant.advance(1)                              # window closes
            done = [r.wait(30.0).state for r in queued]
            _check(all(s is RequestState.DONE for s in done), fault,
                   f"queued work did not complete after the window: {done}")
            trace = plant.trace_bytes()
        batcher.stop()
    finally:
        obs_recorder.disable(ok=True)

    _check(engine.pool.free_pages == free_before, fault,
           f"pages leaked: {engine.pool.free_pages} free, expected "
           f"{free_before}")
    records = obs_recorder.read_records(obs_recorder.flight_dir(base))
    sheds = [r for r in records if r.get("kind") == "shed"]
    _check(len(sheds) >= 1, fault,
           "no shed flight event — the doctor timeline cannot show the "
           "pool-pressure shed window")
    diag = doctor.diagnose(base)
    _check(diag.code == "DOC000", fault,
           f"doctor said {diag.code} after graceful recovery")
    return SoakResult(
        fault=fault, ok=True, injected=1,
        detected=["QUEUED(deferred)", "REJECTED(queue full)", "shed event",
                  "DOC000"],
        expected=CATALOG[fault].detects, recovery_steps=0,
        notes="burst shed typed at the edge; pages recycled and the queue "
              "drained after the window; no hang, no OOM",
        trace=trace)


_PREFIX_ENGINE = None


def _prefix_engine():
    """A prefix-cache engine for the eviction_storm scenario, compiled
    once. SEPARATE from :func:`_serve_engine` on purpose: the
    page_exhaustion scenario asserts ``used_pages == 0`` during its
    window, and a radix cache legitimately keeps cold pages allocated —
    the shared engine must stay cache-free."""
    global _PREFIX_ENGINE
    if _PREFIX_ENGINE is not None:
        return _PREFIX_ENGINE
    import jax
    import jax.numpy as jnp

    from autodist_tpu.api import AutoDist
    from autodist_tpu.models.transformer import (
        TransformerConfig, decode_model, init_params)
    from autodist_tpu.strategy import AllReduce

    cfg = TransformerConfig(
        vocab_size=97, num_layers=1, d_model=32, num_heads=2, d_ff=64,
        max_seq_len=32, causal=True, dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    AutoDist.reset_default()
    try:
        autodist = AutoDist(strategy_builder=AllReduce())
        _PREFIX_ENGINE = autodist.build_inference(
            params, decode_model=decode_model(cfg),
            n_slots=4, page_len=8, n_pages=17, prefill_chunk=8,
            max_len=24, prefix_cache=True)
    finally:
        AutoDist.reset_default()
    return _PREFIX_ENGINE


def scenario_eviction_storm(base: str) -> SoakResult:
    """Sustained pool pressure against a WARM prefix cache: every
    allocation in the window reports exhausted, so the engine's
    evict-retry loop churns the radix tree down to empty (cold
    refcount-0 leaves reclaimed, LRU-first) before admission degrades to
    typed QUEUED — eviction never touches a live request's pages. When
    the window closes, the queued work recomputes the evicted prefixes
    (bit-identical streams — no request ever read another's KV),
    re-populates the tree, and every page leak-checks back to the pool
    (docs/chaos.md)."""
    from autodist_tpu.obs import doctor
    from autodist_tpu.obs import recorder as obs_recorder
    from autodist_tpu.serve.batcher import ContinuousBatcher, RequestState

    fault = "eviction_storm"
    obs_recorder.enable(obs_recorder.flight_dir(base))
    engine = _prefix_engine()
    cache = engine.prefix_cache
    free_before = engine.pool.free_pages + cache.cached_pages
    # System-prompt-heavy workload, TWO prefix families of 16 shared
    # tokens (2 full blocks) + unique 4-token suffixes: the storm
    # requests lead with family A (whose leased blocks eviction must
    # never touch), so the pressure loop can only reclaim family B's
    # cold chain — which the trailing B requests then have to RECOMPUTE.
    rng = np.random.default_rng(5)
    fam_a, fam_b = (rng.integers(1, 97, size=16) for _ in range(2))
    prompts = [np.concatenate([fam, rng.integers(1, 97, size=4)])
               .astype(np.int32)
               for fam in (fam_a, fam_a, fam_a, fam_a, fam_b, fam_b)]
    # Warm phase (no chaos): expected streams AND a populated tree.
    expected = [engine.generate(p, 4) for p in prompts]
    warm = engine.prefix_stats()
    _check(warm["inserts"] > 0 and cache.cached_pages > 0, fault,
           "warm-up did not populate the radix tree")

    batcher = ContinuousBatcher(engine, max_queue=8,
                                registry=M.MetricsRegistry())
    schedule = ChaosSchedule(seed=43, events=(
        ChaosEvent(fault, at_step=0),))
    try:
        with ChaosPlant(schedule) as plant:
            reqs = [batcher.submit(p, max_new_tokens=4) for p in prompts]
            batcher.start()
            retry.wait_until(lambda: plant.injected(fault) > 0, 5.0)
            _check(plant.injected(fault) > 0, fault,
                   "page-pool seam never fired")
            retry.wait_until(
                lambda: engine.prefix_stats()["evictions"]
                > warm["evictions"], 5.0)
            storm = engine.prefix_stats()
            _check(storm["evictions"] > warm["evictions"], fault,
                   "sustained pressure forced no evictions")
            _check(all(r.state is RequestState.QUEUED for r in reqs),
                   fault, "admissions did not degrade to typed QUEUED "
                   "once the evictable tree was drained")
            _check(engine.pool.used_pages == cache.cached_pages, fault,
                   "pages used beyond the surviving cache during the "
                   "storm — evicted pages were not reclaimed")
            plant.advance(1)                              # window closes
            done = [r.wait(30.0).state for r in reqs]
            _check(all(s is RequestState.DONE for s in done), fault,
                   f"queued work did not complete after the window: {done}")
            _check([r.tokens for r in reqs] == expected, fault,
                   "post-eviction recompute streams diverged from the "
                   "warm-cache streams (cross-request KV or COW bug)")
            after = engine.prefix_stats()
            _check(after["inserts"] > storm["inserts"], fault,
                   "the evicted family-B prefix was not recomputed and "
                   "re-inserted")
            trace = plant.trace_bytes()
        batcher.stop()
    finally:
        obs_recorder.disable(ok=True)

    _check(cache.live_refcount == 0, fault,
           f"refcounts unbalanced at drain: {cache.live_refcount}")
    cache.purge()
    _check(engine.pool.used_pages == 0
           and engine.pool.free_pages == free_before, fault,
           f"pages leaked: {engine.pool.free_pages} free after purge, "
           f"expected {free_before}")
    records = obs_recorder.read_records(obs_recorder.flight_dir(base))
    pressure = [r for r in records if r.get("kind") == "pool_pressure"]
    _check(len(pressure) >= 1, fault,
           "no pool_pressure flight event — the doctor timeline cannot "
           "show the eviction-storm window")
    diag = doctor.diagnose(base)
    _check(diag.code == "DOC000", fault,
           f"doctor said {diag.code} after graceful recovery")
    return SoakResult(
        fault=fault, ok=True, injected=1,
        detected=[f"evictions={storm['evictions']}", "QUEUED(deferred)",
                  "bit-identical recompute", "pool_pressure event",
                  "DOC000"],
        expected=CATALOG[fault].detects, recovery_steps=0,
        notes="pressure evicted only the cold refcount-0 family; leased "
              "blocks survived, admissions degraded typed and the evicted "
              "family recomputed bit-identically; zero leaked pages",
        trace=trace)


def scenario_engine_death(base: str) -> SoakResult:
    from autodist_tpu.obs import doctor
    from autodist_tpu.obs import recorder as obs_recorder
    from autodist_tpu.serve.batcher import (
        Backpressure, ContinuousBatcher, RequestState)

    fault = "engine_death"
    obs_recorder.enable(obs_recorder.flight_dir(base))
    batcher = ContinuousBatcher(_serve_engine(), max_queue=8,
                                registry=M.MetricsRegistry())
    prompt = np.arange(4, dtype=np.int32)

    schedule = ChaosSchedule(seed=29, events=(
        ChaosEvent(fault, at_step=0),))
    try:
        with ChaosPlant(schedule) as plant:
            reqs = [batcher.submit(prompt, max_new_tokens=4)
                    for _ in range(3)]
            batcher.start()
            states = [r.wait(30.0).state for r in reqs]
            _check(all(s is RequestState.REJECTED for s in states), fault,
                   f"in-flight/queued work not typed-REJECTED: {states}")
            _check(all("engine died" in r.error for r in reqs), fault,
                   f"rejection reason untyped: {[r.error for r in reqs]}")
            # Post-death admission degrades typed, never hangs.
            late = batcher.try_submit(prompt, max_new_tokens=4)
            _check(late.state is RequestState.REJECTED, fault,
                   "post-death try_submit did not return typed REJECTED")
            try:
                batcher.submit(prompt, max_new_tokens=4)
                _check(False, fault, "post-death submit did not raise")
            except Backpressure:
                pass
            trace = plant.trace_bytes()
        batcher.stop()
    finally:
        obs_recorder.disable(ok=True)

    diag = doctor.diagnose(base)
    _check(diag.code == "DOC006", fault,
           f"doctor said {diag.code}, expected DOC006 (crash)")
    return SoakResult(
        fault=fault, ok=True, injected=1,
        detected=["REJECTED(engine died)", "DOC006"],
        expected=CATALOG[fault].detects, recovery_steps=0,
        notes="all load shed with explicit rejections; no client hung",
        trace=trace)


_SPEC_PAIR = None


def _spec_engines():
    """Compiled-once (spec engine, plain control) pair on one checkpoint
    and plan, with a different-seed draft — the speculative-decode
    scenario's substrate. The fault enters per-run through the
    SEAM_SERVE_DRAFT hook, so sharing is sound (counters are cumulative;
    the scenario measures deltas)."""
    global _SPEC_PAIR
    if _SPEC_PAIR is not None:
        return _SPEC_PAIR
    import jax
    import jax.numpy as jnp

    from autodist_tpu.models.transformer import (
        TransformerConfig, decode_model, init_params)
    from autodist_tpu.serve.engine import InferenceEngine
    from autodist_tpu.serve.spec import SpecDecodeEngine, build_draft_plan

    cfg = TransformerConfig(
        vocab_size=97, num_layers=1, d_model=32, num_heads=2, d_ff=64,
        max_seq_len=32, causal=True, dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    draft_params = init_params(jax.random.PRNGKey(5), cfg)
    plain = InferenceEngine.build(
        params, decode_model=decode_model(cfg),
        n_slots=4, page_len=8, n_pages=17, prefill_chunk=8, max_len=16)
    spec = SpecDecodeEngine(
        params, plain.plan, draft_params,
        build_draft_plan(draft_params, plain.plan.mesh),
        decode_model=decode_model(cfg),
        draft_decode_model=decode_model(cfg),
        spec_k=4, draft_n_pages=17,
        n_slots=4, page_len=8, n_pages=17, prefill_chunk=8, max_len=16)
    _SPEC_PAIR = (spec, plain)
    return _SPEC_PAIR


def scenario_draft_divergence(base: str) -> SoakResult:
    """Garble every draft proposal for the whole run: the verify program
    must reject the garbage and keep emitting the target's own greedy
    tokens — delivered streams bit-identical to plain decode, acceptance
    collapses toward 0, cadence stays bounded (~1 token/round), page
    accounting balances, and the run classifies clean (DOC000)."""
    from autodist_tpu.obs import doctor
    from autodist_tpu.obs import recorder as obs_recorder
    from autodist_tpu.serve.batcher import ContinuousBatcher, RequestState

    fault = "draft_divergence"
    spec, plain = _spec_engines()
    rng = np.random.RandomState(11)
    prompts = [rng.randint(1, 96, size=rng.randint(3, 7)).astype(np.int32)
               for _ in range(6)]
    expected = [plain.generate(p, 6) for p in prompts]

    obs_recorder.enable(obs_recorder.flight_dir(base))
    batcher = ContinuousBatcher(spec, max_queue=16,
                                registry=M.MetricsRegistry())
    acc0, prop0 = spec.accepted_total, spec.proposed_total
    schedule = ChaosSchedule(seed=31, events=(
        ChaosEvent(fault, at_step=0),))
    try:
        with ChaosPlant(schedule) as plant:
            batcher.start()
            reqs = [batcher.submit(p, max_new_tokens=6) for p in prompts]
            states = [r.wait(60.0).state for r in reqs]
            _check(all(s is RequestState.DONE for s in states), fault,
                   f"requests did not complete under garbled drafts: "
                   f"{states}")
            _check(plant.injected(fault) > 0, fault,
                   "draft seam never fired")
            trace = plant.trace_bytes()
        batcher.stop()
    finally:
        obs_recorder.disable(ok=True)

    _check(all(r.tokens == expected[i] for i, r in enumerate(reqs)),
           fault, "delivered streams diverged from plain greedy — a "
                  "garbage draft must never change output")
    proposed = spec.proposed_total - prop0
    accepted = spec.accepted_total - acc0
    _check(proposed > 0, fault, "no spec rounds ran")
    rate = accepted / proposed
    _check(rate <= 0.25, fault,
           f"acceptance {rate:.2f} under garbled drafts (expected ~0)")
    _check(spec.pool.used_pages == 0 and spec.draft_pool.used_pages == 0,
           fault, "pages leaked after the divergence window")
    diag = doctor.diagnose(base)
    _check(diag.code == "DOC000", fault,
           f"doctor said {diag.code} after graceful degradation")
    return SoakResult(
        fault=fault, ok=True, injected=1,
        detected=[f"acceptance {rate:.2f} (~0)", "streams bit-identical",
                  "DOC000"],
        expected=CATALOG[fault].detects, recovery_steps=0,
        notes="verify rejected every garbled proposal; output stayed "
              "plain-greedy bit-identical at ~1 token/round; zero leaked "
              "pages",
        trace=trace)


# ------------------------------------------------------- router scenarios
def _router_fleet(base: str, registry=None, config=None,
                  kv_quant: bool = False):
    """A 3-replica in-process router fleet + lone control engine, rooted
    at ``base`` (journals under ``base/journals``). Shares the
    byte-identical plan across replicas the way a production factory
    shares the persistent plan cache. ``kv_quant=True`` serves the whole
    fleet (control included) from int8 quantized KV pages."""
    from autodist_tpu.serve.router import build_test_fleet

    return build_test_fleet(
        n_replicas=3, journal_dir=os.path.join(base, "journals"),
        registry=registry or M.MetricsRegistry(), config=config,
        kv_quant=kv_quant)


def scenario_replica_death(base: str) -> SoakResult:
    """Kill one of 3 replicas mid-decode (host-targeted EngineDeadError
    through the serve step seam): the replica self-reports DEAD, the
    router fails every in-flight request over to the survivors, and every
    request completes EXACTLY ONCE with its delivered stream bit-identical
    to an uninterrupted control run; the death is DOC006-attributed."""
    from autodist_tpu.obs import doctor
    from autodist_tpu.obs import recorder as obs_recorder
    from autodist_tpu.serve.batcher import RequestState
    from autodist_tpu.serve.replica import ReplicaState

    fault = "replica_death"
    obs_recorder.enable(obs_recorder.flight_dir(base))
    reg = M.MetricsRegistry()
    router, control = _router_fleet(base, registry=reg)
    rng = np.random.default_rng(101)
    prompts = [rng.integers(1, 127, size=int(rng.integers(3, 10)))
               .astype(np.int32) for _ in range(12)]
    expected = [control.generate(p, 6) for p in prompts]

    schedule = ChaosSchedule(seed=47, events=(
        ChaosEvent(fault, at_step=0, host=1),))
    try:
        with ChaosPlant(schedule) as plant:
            router.start()
            for rep in router.replicas.values():
                rep.wait_ready(120.0)
            fronts = [router.submit(p, max_new_tokens=6) for p in prompts]
            states = [f.wait(120.0).state for f in fronts]
            _check(all(s is RequestState.DONE for s in states), fault,
                   f"not every request completed on the survivors: "
                   f"{[s.value for s in states]}")
            _check(plant.injected(fault) == 1, fault,
                   "the targeted decode-step seam never fired")
            _check(retry.wait_until(
                lambda: router.replica_state(1) is ReplicaState.DEAD, 10.0),
                fault, "router never classified the killed replica DEAD")
            trace = plant.trace_bytes()
        streams_ok = all(f.tokens == expected[i]
                         for i, f in enumerate(fronts))
        _check(streams_ok, fault,
               "a failed-over stream diverged from the uninterrupted "
               "control run (prefix resume broke bit-identity)")
        ledger = router.ledger()
        _check(len(ledger) == len(prompts)
               and all(v == 1 for v in ledger.values()), fault,
               f"exactly-once violated: ledger {ledger}")
        rerouted = int(reg.counter(
            "serve_router_requests_rerouted_total").value)
        _check(rerouted >= 1, fault,
               "no request was actually in flight on the killed replica")
        router.stop(drain=False)
    finally:
        obs_recorder.disable(ok=True)

    diag = doctor.diagnose(base)
    _check(diag.code == "DOC006", fault,
           f"doctor said {diag.code}, expected DOC006 (crash)")
    return SoakResult(
        fault=fault, ok=True, injected=1,
        detected=["DEAD", "exactly_once", "DOC006"],
        expected=CATALOG[fault].detects, recovery_steps=0,
        notes=f"{rerouted} in-flight rerouted to survivors; streams "
              f"bit-identical to control; no duplicate, no drop",
        trace=trace)


def scenario_kill_mid_stochastic_stream(base: str) -> SoakResult:
    """Kill one of 3 replicas mid-decode while the fleet serves
    STOCHASTIC streams (mixed temperatures/top-p, per-request seeds):
    the router fails the sampled streams over to survivors and every
    delivered stream is bit-identical to an uninterrupted control run —
    the counter-based draws (serve/sampling.py) depend only on
    (request_id, seed, position), so failover resume re-derives the
    identical randomness on whichever replica picks the work up."""
    from autodist_tpu.obs import doctor
    from autodist_tpu.obs import recorder as obs_recorder
    from autodist_tpu.serve.batcher import RequestState
    from autodist_tpu.serve.replica import ReplicaState
    from autodist_tpu.serve.sampling import SamplingParams

    fault = "kill_mid_stochastic_stream"
    obs_recorder.enable(obs_recorder.flight_dir(base))
    reg = M.MetricsRegistry()
    router, control = _router_fleet(base, registry=reg)
    rng = np.random.default_rng(211)
    temps = (0.5, 0.8, 1.3)
    jobs = []
    for i in range(12):
        p = (rng.integers(1, 127, size=int(rng.integers(3, 10)))
             .astype(np.int32))
        sp = SamplingParams(temperature=temps[i % len(temps)], top_k=24,
                            top_p=0.95, seed=i)
        jobs.append((f"stoch-{i}", p, sp))
    expected = [control.generate(p, 6, request_id=rid, sampling=sp)
                for rid, p, sp in jobs]
    greedy = [control.generate(p, 6) for _, p, _ in jobs]
    _check(any(e != g for e, g in zip(expected, greedy)), fault,
           "every sampled control stream equals greedy — sampling never "
           "engaged, the scenario would prove nothing")

    schedule = ChaosSchedule(seed=53, events=(
        ChaosEvent(fault, at_step=0, host=1),))
    try:
        with ChaosPlant(schedule) as plant:
            router.start()
            for rep in router.replicas.values():
                rep.wait_ready(120.0)
            fronts = [router.submit(p, max_new_tokens=6, request_id=rid,
                                    sampling=sp)
                      for rid, p, sp in jobs]
            states = [f.wait(120.0).state for f in fronts]
            _check(all(s is RequestState.DONE for s in states), fault,
                   f"not every sampled request completed on the "
                   f"survivors: {[s.value for s in states]}")
            _check(plant.injected(fault) == 1, fault,
                   "the targeted decode-step seam never fired")
            _check(retry.wait_until(
                lambda: router.replica_state(1) is ReplicaState.DEAD, 10.0),
                fault, "router never classified the killed replica DEAD")
            trace = plant.trace_bytes()
        streams_ok = all(f.tokens == expected[i]
                         for i, f in enumerate(fronts))
        _check(streams_ok, fault,
               "a failed-over SAMPLED stream diverged from the "
               "uninterrupted control run — the counter-based draws "
               "leaked replica/slot/cache state into the randomness")
        ledger = router.ledger()
        _check(len(ledger) == len(jobs)
               and all(v == 1 for v in ledger.values()), fault,
               f"exactly-once violated: ledger {ledger}")
        rerouted = int(reg.counter(
            "serve_router_requests_rerouted_total").value)
        _check(rerouted >= 1, fault,
               "no request was actually in flight on the killed replica")
        router.stop(drain=False)
    finally:
        obs_recorder.disable(ok=True)

    diag = doctor.diagnose(base)
    _check(diag.code == "DOC006", fault,
           f"doctor said {diag.code}, expected DOC006 (crash)")
    return SoakResult(
        fault=fault, ok=True, injected=1,
        detected=["DEAD", "sampled_bit_identity", "DOC006"],
        expected=CATALOG[fault].detects, recovery_steps=0,
        notes=f"{rerouted} in-flight sampled stream(s) rerouted to "
              f"survivors; every delivered stream bit-identical to its "
              f"uninterrupted control; exactly-once held",
        trace=trace)


def scenario_kill_mid_quantized_stream(base: str) -> SoakResult:
    """Kill one of 3 replicas mid-decode while the whole fleet (control
    included) serves from int8 QUANTIZED KV pages: the router fails the
    streams over to survivors and every delivered stream is bit-identical
    to the uninterrupted quantized control — quantize-on-scatter is
    deterministic (amax/127 per (position, head)), so the survivor's
    journal-replay re-prefill reproduces the dead replica's pages
    bit-exactly, and the documented logit-drift bound (vs the fp oracle)
    holds trivially across the failover because both sides of it ran the
    same quantized math."""
    from autodist_tpu.obs import doctor
    from autodist_tpu.obs import recorder as obs_recorder
    from autodist_tpu.serve.batcher import RequestState
    from autodist_tpu.serve.replica import ReplicaState

    fault = "kill_mid_quantized_stream"
    obs_recorder.enable(obs_recorder.flight_dir(base))
    reg = M.MetricsRegistry()
    router, control = _router_fleet(base, registry=reg, kv_quant=True)
    _check(getattr(control, "kv_quant", False), fault,
           "the control engine is not serving quantized pages — the "
           "scenario would compare fp to fp and prove nothing")
    rng = np.random.default_rng(223)
    prompts = [rng.integers(1, 127, size=int(rng.integers(3, 10)))
               .astype(np.int32) for _ in range(12)]
    expected = [control.generate(p, 6) for p in prompts]

    schedule = ChaosSchedule(seed=59, events=(
        ChaosEvent(fault, at_step=0, host=1),))
    try:
        with ChaosPlant(schedule) as plant:
            router.start()
            for rep in router.replicas.values():
                rep.wait_ready(120.0)
            fronts = [router.submit(p, max_new_tokens=6,
                                    request_id=f"quant-{i}")
                      for i, p in enumerate(prompts)]
            states = [f.wait(120.0).state for f in fronts]
            _check(all(s is RequestState.DONE for s in states), fault,
                   f"not every quantized-stream request completed on the "
                   f"survivors: {[s.value for s in states]}")
            _check(plant.injected(fault) == 1, fault,
                   "the targeted decode-step seam never fired")
            _check(retry.wait_until(
                lambda: router.replica_state(1) is ReplicaState.DEAD, 10.0),
                fault, "router never classified the killed replica DEAD")
            trace = plant.trace_bytes()
        streams_ok = all(f.tokens == expected[i]
                         for i, f in enumerate(fronts))
        _check(streams_ok, fault,
               "a failed-over QUANTIZED stream diverged from the "
               "uninterrupted quantized control — quantize-on-scatter "
               "re-prefill was not deterministic")
        ledger = router.ledger()
        _check(len(ledger) == len(prompts)
               and all(v == 1 for v in ledger.values()), fault,
               f"exactly-once violated: ledger {ledger}")
        rerouted = int(reg.counter(
            "serve_router_requests_rerouted_total").value)
        _check(rerouted >= 1, fault,
               "no request was actually in flight on the killed replica")
        router.stop(drain=False)
    finally:
        obs_recorder.disable(ok=True)

    diag = doctor.diagnose(base)
    _check(diag.code == "DOC006", fault,
           f"doctor said {diag.code}, expected DOC006 (crash)")
    return SoakResult(
        fault=fault, ok=True, injected=1,
        detected=["DEAD", "quantized_bit_identity", "DOC006"],
        expected=CATALOG[fault].detects, recovery_steps=0,
        notes=f"{rerouted} in-flight quantized stream(s) rerouted to "
              f"survivors; every delivered stream bit-identical to its "
              f"uninterrupted quantized control; exactly-once held",
        trace=trace)


def scenario_replica_partition(base: str) -> SoakResult:
    """Drop one replica's control-plane beats (the replica keeps
    serving): the router marks it SUSPECT and routes new work around it,
    its in-flight work keeps progressing and delivers exactly once (no
    spurious failover), and when beats resume the replica rejoins and
    receives new work again."""
    from autodist_tpu.serve.batcher import RequestState
    from autodist_tpu.serve.replica import ReplicaState
    from autodist_tpu.serve.router import RouterConfig

    fault = "replica_partition"
    reg = M.MetricsRegistry()
    # DEAD needs a long silence: the partition must pin SUSPECT routing,
    # not decay into a failover.
    router, control = _router_fleet(base, registry=reg, config=RouterConfig(
        heartbeat_interval_s=0.05, health_interval_s=0.02,
        suspect_after_misses=2, dead_after_misses=60))
    rng = np.random.default_rng(103)
    prompts = [rng.integers(1, 127, size=int(rng.integers(3, 8)))
               .astype(np.int32) for _ in range(15)]
    expected = [control.generate(p, 24 if i < 9 else 6)
                for i, p in enumerate(prompts)]

    schedule = ChaosSchedule(seed=59, events=(
        ChaosEvent(fault, at_step=1, host=1),))
    with ChaosPlant(schedule) as plant:
        router.start()
        for rep in router.replicas.values():
            rep.wait_ready(120.0)
        # Long-running requests spread across the fleet (beats flowing).
        fronts = [router.submit(p, max_new_tokens=24) for p in prompts[:9]]

        def on_victim() -> bool:
            with router._lock:
                return any(f.replica_id == 1 and len(f.front.tokens) > 0
                           for f in router._flights.values())

        _check(retry.wait_until(on_victim, 60.0, interval_s=0.005), fault,
               "no in-flight work landed on the victim before the window")
        plant.advance(1)                                  # partition opens
        _check(retry.wait_until(
            lambda: router.replica_state(1) is ReplicaState.SUSPECT, 10.0),
            fault, "router never classified the partitioned replica "
                   "SUSPECT")
        d_before = router.dispatch_counts()[1]
        late = [router.submit(p, max_new_tokens=6) for p in prompts[9:]]
        late_states = [f.wait(120.0).state for f in late]
        _check(all(s is RequestState.DONE for s in late_states), fault,
               f"new work did not complete on the non-suspect replicas: "
               f"{[s.value for s in late_states]}")
        _check(router.dispatch_counts()[1] == d_before, fault,
               "new work was routed TO the suspect replica")
        states = [f.wait(120.0).state for f in fronts]
        _check(all(s is RequestState.DONE for s in states), fault,
               f"in-flight work on the partitioned replica was lost: "
               f"{[s.value for s in states]}")
        plant.advance(1)                                  # window closes
        _check(retry.wait_until(
            lambda: router.replica_state(1) is ReplicaState.READY, 10.0),
            fault, "replica did not rejoin READY after the partition")
        rejoin = [router.submit(p, max_new_tokens=6) for p in prompts[:6]]
        _check(all(f.wait(120.0).state is RequestState.DONE
                   for f in rejoin), fault, "post-rejoin work failed")
        _check(retry.wait_until(
            lambda: router.dispatch_counts()[1] > d_before, 5.0), fault,
            "the rejoined replica never received new work")
        trace = plant.trace_bytes()

    streams_ok = all(f.tokens == expected[i]
                     for i, f in enumerate(fronts + late))
    _check(streams_ok, fault,
           "a stream forked during the partition (duplicate or dropped "
           "token)")
    rerouted = int(reg.counter("serve_router_requests_rerouted_total").value)
    _check(rerouted == 0, fault,
           f"a SUSPECT-only partition triggered {rerouted} spurious "
           f"failover(s)")
    router.stop(drain=False)
    return SoakResult(
        fault=fault, ok=True, injected=1,
        detected=["SUSPECT", "routed around", "rejoined"],
        expected=CATALOG[fault].detects, recovery_steps=0,
        notes="suspect excluded from new work, in-flight delivered "
              "exactly once, zero spurious failovers, rejoined on first "
              "fresh beat",
        trace=trace)


def scenario_rolling_upgrade_under_load(base: str) -> SoakResult:
    """Drain + restart every replica in turn while a background loader
    keeps submitting: zero dropped requests (typed shed only — and at
    this load, none), every request completes exactly once, p99 stays
    bounded, and every replica cycles through exactly one restart."""
    import threading

    from autodist_tpu.serve.batcher import Backpressure, RequestState
    from autodist_tpu.serve.replica import ReplicaState

    fault = "rolling_upgrade_under_load"
    reg = M.MetricsRegistry()
    router, _control = _router_fleet(base, registry=reg)
    rng = np.random.default_rng(107)
    prompts = [rng.integers(1, 127, size=int(rng.integers(3, 8)))
               .astype(np.int32) for _ in range(200)]

    schedule = ChaosSchedule(seed=61, events=(
        ChaosEvent(fault, at_step=0),))
    plant = ChaosPlant(schedule)  # no hooks: the "fault" is the upgrade
    router.start()
    for rep in router.replicas.values():
        rep.wait_ready(120.0)

    fronts: List = []
    shed = [0]
    stop_load = threading.Event()

    def loader():
        i = 0
        while not stop_load.is_set() and i < len(prompts):
            try:
                fronts.append(router.submit(prompts[i], max_new_tokens=5))
                i += 1
            except Backpressure:
                shed[0] += 1  # typed shed at the edge is allowed, a drop
                #               is not — nothing here ever hangs
            stop_load.wait(0.01)

    thread = threading.Thread(target=loader, daemon=True)
    thread.start()
    try:
        results = router.rolling_upgrade(deadline_s=30.0,
                                         ready_timeout_s=120.0)
    finally:
        stop_load.set()
        thread.join(timeout=10.0)
    for r in results:
        plant.record(fault, replica=int(r["replica"]))

    _check(len(results) == 3, fault, "not every replica was upgraded")
    _check(all(rep.restarts == 1 for rep in router.replicas.values()),
           fault, "a replica did not restart exactly once")
    # A straggler escalation can hold a just-restarted replica SUSPECT
    # for one beat (alive-but-sick scrutiny, by design); it heals on the
    # next fresh beat — bound the wait instead of racing it.
    _check(retry.wait_until(
        lambda: all(router.replica_state(rid) is ReplicaState.READY
                    for rid in router.replicas), 15.0, interval_s=0.02),
        fault, "fleet not fully READY after the upgrade")
    states = [f.wait(120.0).state for f in fronts]
    n_done = sum(1 for s in states if s is RequestState.DONE)
    _check(n_done == len(fronts), fault,
           f"{len(fronts) - n_done} of {len(fronts)} requests dropped "
           f"during the rolling upgrade")
    ledger = router.ledger()
    _check(all(v == 1 for rid_, v in ledger.items()), fault,
           "exactly-once violated during the upgrade")
    p99 = reg.snapshot().get("serve_router_request_latency_s",
                             {}).get("p99", float("inf"))
    _check(p99 < 60.0, fault, f"p99 unbounded during the upgrade "
           f"({p99:.1f}s)")
    rerouted = int(reg.counter("serve_router_requests_rerouted_total").value)
    router.stop(drain=False)
    return SoakResult(
        fault=fault, ok=True, injected=3,
        detected=["zero drops", "exactly_once", "p99 bounded"],
        expected=CATALOG[fault].detects, recovery_steps=0,
        notes=f"{len(fronts)} requests served across 3 drain/restart "
              f"cycles, {rerouted} failed over from drains, {shed[0]} "
              f"typed sheds, p99 {p99:.2f}s",
        trace=plant.trace_bytes())


def scenario_poisoned_calibration(base: str) -> SoakResult:
    """An adversarial live window at the pilot's refit intake: the plant
    scales one record's ``measured_s`` x1000 before the fit runs. The
    pilot's trusted-set fit-error gate must reject the refit (decision
    journal shows trigger -> rejected), the rollout path must never run,
    the persisted calibration must stay byte-identical — and the
    keep-best guard inside ``plan/calibrate.py`` must independently
    refuse the same poisoned window when handed it directly (two belts,
    either alone stops the deploy)."""
    from dataclasses import replace as _dc_replace

    from autodist_tpu.pilot import (
        Controller,
        ControllerConfig,
        DecisionJournal,
        FunctionRollout,
        PilotContext,
        PilotState,
        PilotStateStore,
        build_actions,
    )
    from autodist_tpu.plan.calibrate import (
        CalibrationRecord,
        TopologyCalibration,
        calibrate_from_records,
        topology_key,
    )
    from autodist_tpu.resource_spec import ResourceSpec

    fault = "poisoned_calibration"
    spec = ResourceSpec(resource_dict={
        "nodes": [{"address": "localhost", "chips": 8, "chief": True}]})
    calib_dir = os.path.join(base, "calib")
    # Replayed profile: a fixed linear world (wire at 50% efficiency, a
    # 2 ms compute floor) over enough points for the component fit.
    rng = np.random.default_rng(13)
    records = []
    for i in range(10):
        comm, upd, lat, act = (float(x) for x in rng.uniform(1e-4, 5e-3, 4))
        measured = 2e-3 + 2.0 * comm + 1.25 * upd + 1.5 * lat + 1.0 * act
        records.append(CalibrationRecord(
            comm_s=comm, update_s=upd, latency_s=lat, act_sync_s=act,
            measured_s=measured, name=f"rec{i}"))
    calibrate_from_records(records, spec, device_kind="cpu",
                           directory=calib_dir)
    key = topology_key(spec, "cpu")
    calib_path = os.path.join(calib_dir, f"calibration-{key}.json")
    with open(calib_path, "rb") as f:
        bytes_before = f.read()

    pdir = os.path.join(base, "pilot")
    store = PilotStateStore(os.path.join(pdir, "state.json"))
    store.save(PilotState())
    journal = DecisionJournal(os.path.join(pdir, "decisions.jsonl"))
    deploys = [0]
    ctrl = Controller(
        store, journal,
        build_actions(PilotContext(
            resource_spec=spec, device_kind="cpu",
            calibration_dir=calib_dir, pilot_dir=pdir,
            live_records=lambda: list(records))),
        FunctionRollout(
            lambda old, new: deploys.__setitem__(0, deploys[0] + 1),
            lambda n: {}),
        config=ControllerConfig(cooldown_s=0.0))

    schedule = ChaosSchedule(seed=29, events=(ChaosEvent(fault, at_step=0),))
    plant = ChaosPlant(schedule)
    with plant:
        rec = ctrl.ingest_measured_wire(measured_s=1.0, priced_s=0.5)
    _check(plant.injected(fault) == 1, fault,
           "the plant never corrupted a live record")
    _check(rec is not None and rec.verdict == "rejected", fault,
           f"poisoned refit not rejected "
           f"(verdict {rec.verdict if rec else None!r})")
    _check(rec is not None and "poisoned_calibration" in rec.note, fault,
           f"rejection not attributed to the fit-error gate: "
           f"{rec.note if rec else None!r}")
    _check(deploys[0] == 0, fault,
           "a rejected refit still reached the rollout path")
    with open(calib_path, "rb") as f:
        _check(f.read() == bytes_before, fault,
               "persisted calibration changed under a rejected refit")

    # Second belt: hand the poisoned window straight to
    # calibrate_from_records — keep-best must keep the prior coefficients
    # and record the losing fit in the file's rejected_fits provenance.
    poisoned = list(records)
    poisoned[3] = _dc_replace(poisoned[3],
                              measured_s=poisoned[3].measured_s * 1000.0)
    prior = TopologyCalibration.load(calib_path)
    kept = calibrate_from_records(poisoned, spec, device_kind="cpu",
                                  directory=calib_dir)
    _check(kept.coefficients == prior.coefficients
           and kept.base_s == prior.base_s, fault,
           "keep-best persisted a fit that regressed the merged set")
    with open(calib_path, encoding="utf-8") as f:
        doc = json.load(f)
    _check(bool(doc.get("rejected_fits")), fault,
           "the rejected fit left no rejected_fits provenance")
    return SoakResult(
        fault=fault, ok=True, injected=plant.injected(fault),
        detected=["refit rejected", "journal trigger -> rejected",
                  "keep-best held"],
        expected=CATALOG[fault].detects, recovery_steps=0,
        notes="poisoned live window rejected by the trusted-set gate "
              "(coefficients byte-identical, rollout never ran); direct "
              "calibrate_from_records refused the same window via "
              "keep-best with rejected_fits provenance",
        trace=plant.trace_bytes())


# -------------------------------------------------------- supervised kill
_KILL_CHILD = """\
import json, os, signal, sys
base = sys.argv[1]
if os.environ.get("AUTODIST_PROCESS_ID", "0") != "0":
    sys.exit(0)  # worker peer: the chief is the victim
cnt = os.path.join(base, "attempts.txt")
n = int(open(cnt).read()) if os.path.exists(cnt) else 0
with open(cnt, "w") as f:
    f.write(str(n + 1))
# Real snapshot progress every attempt: the supervisor's budget AND
# backoff must reset on it (runtime/launcher.py).
snap = os.path.join(base, "ft", "snapshots", f"ckpt-{n + 1}")
os.makedirs(snap, exist_ok=True)
with open(os.path.join(snap, "MANIFEST.json"), "w") as f:
    json.dump({"step": n + 1, "files": {}}, f)
if n < 2:
    os.kill(os.getpid(), signal.SIGKILL)
sys.exit(0)
"""


def scenario_worker_kill(base: str) -> SoakResult:
    """SIGKILL a REAL supervised fleet chief twice; the supervisor must
    restart it with jittered exponential backoff, reset both the restart
    budget and the backoff on the snapshot progress each attempt makes,
    and the third attempt must complete. ``max_restarts=1`` makes the
    reset the load-bearing part: without it the second kill would exhaust
    the budget."""
    from autodist_tpu.ft.config import FTConfig
    from autodist_tpu.runtime import launcher

    fault = "worker_kill"
    script = os.path.join(base, "victim.py")
    with open(script, "w", encoding="utf-8") as f:
        f.write(_KILL_CHILD)
    initial_s = 0.05
    delays: List[float] = []
    schedule = ChaosSchedule(seed=31, events=(
        ChaosEvent(fault, at_step=0),))
    plant = ChaosPlant(schedule)  # no hooks: the fault IS the dying process
    rc = launcher.launch_supervised(
        None, [sys.executable, script, base],
        num_local_processes=2,
        max_restarts=1,
        restart_backoff_s=initial_s,
        restart_backoff_max_s=1.0,
        backoff_seed=1234,
        restart_sleep=delays.append,   # capture; no real sleep
        ft_config=FTConfig(base_dir=os.path.join(base, "ft")),
    )
    attempts = int(open(os.path.join(base, "attempts.txt")).read())
    for k in range(attempts - 1):
        plant.record(fault, kill=k + 1, detail="chief SIGKILLed")

    _check(rc == 0, fault, f"supervised run did not complete (rc={rc})")
    _check(attempts == 3, fault, f"expected 3 attempts (2 kills), saw "
           f"{attempts}")
    _check(len(delays) == 2, fault,
           f"expected 2 restart delays, saw {len(delays)}")
    _check(all(0.0 < d <= initial_s + 1e-9 for d in delays), fault,
           f"backoff did not reset on snapshot progress (delays {delays}; "
           f"an unreset second delay would exceed {initial_s}s)")
    _check(delays[0] != delays[1], fault,
           "restart delays identical — jitter is not being applied")
    return SoakResult(
        fault=fault, ok=True, injected=attempts - 1,
        detected=["supervised restart", "budget+backoff reset on progress"],
        expected=CATALOG[fault].detects, recovery_steps=0,
        notes=f"2 SIGKILLs survived with max_restarts=1 (reset proof); "
              f"jittered delays {['%.3f' % d for d in delays]}",
        trace=plant.trace_bytes())


# ---------------------------------------------------------------- driver
SCENARIOS: Dict[str, Callable[[str], SoakResult]] = {
    "control": scenario_control,
    "nan_loss": scenario_nan_loss,
    "loss_spike": scenario_loss_spike,
    "straggler": scenario_straggler,
    "heartbeat_drop": scenario_heartbeat_drop,
    "heartbeat_partition": scenario_heartbeat_partition,
    "snapshot_corrupt": scenario_snapshot_corrupt,
    "snapshot_partial": scenario_snapshot_partial,
    "snapshot_unwritable": scenario_snapshot_unwritable,
    "serve_admission": scenario_serve_admission,
    "page_exhaustion": scenario_page_exhaustion,
    "eviction_storm": scenario_eviction_storm,
    "engine_death": scenario_engine_death,
    "draft_divergence": scenario_draft_divergence,
    "worker_kill": scenario_worker_kill,
    "replica_death": scenario_replica_death,
    "kill_mid_stochastic_stream": scenario_kill_mid_stochastic_stream,
    "kill_mid_quantized_stream": scenario_kill_mid_quantized_stream,
    "replica_partition": scenario_replica_partition,
    "rolling_upgrade_under_load": scenario_rolling_upgrade_under_load,
    "poisoned_calibration": scenario_poisoned_calibration,
}


def run_soak(faults: Optional[List[str]] = None,
             workdir: Optional[str] = None,
             verbose: bool = True) -> List[SoakResult]:
    """Run the soak matrix (every scenario, or the named subset). Each
    scenario gets a fresh subdirectory; a :class:`SoakFailure` from any
    scenario propagates after the matrix is reported."""
    names = list(faults) if faults else list(SCENARIOS)
    unknown = sorted(set(names) - set(SCENARIOS))
    if unknown:
        raise ValueError(f"unknown scenario(s) {unknown}; "
                         f"have {sorted(SCENARIOS)}")
    own_tmp = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="chaos-soak-")
    results: List[SoakResult] = []
    failures: List[str] = []
    try:
        for name in names:
            base = os.path.join(workdir, name)
            os.makedirs(base, exist_ok=True)
            try:
                res = SCENARIOS[name](base)
            except SoakFailure as e:
                res = SoakResult(fault=name, ok=False, injected=0,
                                 expected=CATALOG.get(name).detects
                                 if name in CATALOG else "", notes=str(e))
                failures.append(str(e))
            results.append(res)
            if verbose:
                mark = "ok " if res.ok else "FAIL"
                logging.info("chaos soak [%s] %-22s injected=%d %s",
                             mark, res.fault, res.injected, res.notes)
    finally:
        if own_tmp:
            shutil.rmtree(workdir, ignore_errors=True)
    if failures:
        raise SoakFailure("; ".join(failures))
    return results


def replay_is_deterministic(fault: str = "nan_loss") -> bool:
    """Run ``fault``'s scenario twice in fresh directories and compare the
    injection traces byte-for-byte — the replay-determinism acceptance
    bar (same seed ⇒ identical trace)."""
    traces = []
    for _ in range(2):
        tmp = tempfile.mkdtemp(prefix="chaos-replay-")
        try:
            traces.append(SCENARIOS[fault](os.path.join(tmp, fault)).trace)
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    return bool(traces[0]) and traces[0] == traces[1]
